/*
 * mxtpu flat C API — the native runtime ABI.
 *
 * Rebuild of the reference's include/mxnet/c_api.h role for the
 * TPU-native stack: opaque handles, int return codes (0 = success,
 * nonzero = failure with MXTPUGetLastError()), per-thread error string.
 *
 * Scope note (deliberate design split, SURVEY.md §7): the *compute*
 * path — arrays, operators, autograd, collectives — compiles through
 * XLA and is driven from the Python layer; this C ABI covers what is
 * native in this framework, mirroring what was native in the
 * reference's runtime:
 *   - the dependency engine (threaded_engine.{h,cc} analog)
 *   - the pooled host storage manager (storage/ analog)
 *   - the RecordIO scanner (io/ analog)
 *   - the runtime-discoverable op registry (MXSymbolListAtomicSymbol-
 *     Creators / MXSymbolGetAtomicSymbolInfo analog), populated by the
 *     host frontend at import so thin in-process language bindings can
 *     generate op wrappers at runtime.
 */

#ifndef MXTPU_C_API_H_
#define MXTPU_C_API_H_

#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

/* ---- error handling (src/c_api/c_api_error.cc analog) ---- */
/* Message of the last failure on this thread; empty string if none. */
const char* MXTPUGetLastError(void);
void MXTPUSetLastError(const char* msg);

/* ---- dependency engine (include/mxnet/engine.h analog) ---- */
typedef void* EngineHandle;
typedef void* VarHandle;
typedef void (*MXTPUOpCallback)(void* payload);

EngineHandle MXTPUEngineCreate(int num_workers, int num_io_workers);
void MXTPUEngineFree(EngineHandle engine);
VarHandle MXTPUEngineNewVar(EngineHandle engine);
/* Push fn(payload) with read deps const_vars and write deps
 * mutable_vars; prop: 0 = normal worker pool, 1 = prioritized/IO pool
 * (FnProperty analog). Returns immediately; execution is async. */
void MXTPUEnginePush(EngineHandle engine, MXTPUOpCallback fn, void* payload,
                     VarHandle* const_vars, int n_const,
                     VarHandle* mutable_vars, int n_mutable, int prop);
/* As MXTPUEnginePush with a scheduling priority: among READY ops in a
 * worker lane, larger priority dispatches sooner (FIFO within a level) —
 * the reference's threaded_engine_pooled priority queue, which makes
 * kvstore priority=-key order gradient comm the way the next forward
 * consumes weights (python/mxnet/model.py:87-97). */
void MXTPUEnginePushPriority(EngineHandle engine, MXTPUOpCallback fn,
                             void* payload, VarHandle* const_vars,
                             int n_const, VarHandle* mutable_vars,
                             int n_mutable, int prop, int priority);
void MXTPUEngineWaitForAll(EngineHandle engine);
void MXTPUEngineWaitForVar(EngineHandle engine, VarHandle var);
int64_t MXTPUEnginePending(EngineHandle engine);

/* ---- pooled host storage (include/mxnet/storage.h analog) ---- */
/* Size-bucketed free-list pool; Alloc may return a recycled buffer. */
void* MXTPUStorageAlloc(uint64_t size);
void MXTPUStorageFree(void* ptr, uint64_t size);
/* Return all pooled buffers to the OS (release-on-pressure). */
void MXTPUStorageReleaseAll(void);
void MXTPUStorageStats(uint64_t* allocated, uint64_t* pooled,
                       uint64_t* allocs, uint64_t* hits);

/* ---- RecordIO scanner (src/io recordio analog) ---- */
/* Build an offset index of a .rec file: returns a handle and writes the
 * record count to *out_count; NULL on failure. */
void* MXTPURecordIOIndex(const char* path, int64_t* out_count);
void MXTPURecordIOIndexGet(void* index, int64_t i, uint64_t* out_offset,
                           uint32_t* out_length);
void MXTPURecordIOIndexFree(void* index);
/* Read records [begin, begin+n) payloads into buf (capacity buf_len);
 * writes each record's length into out_lengths; returns bytes written
 * or -1 on failure. */
int64_t MXTPURecordIOReadBatch(const char* path, void* index, int64_t* order,
                               int64_t n, uint8_t* buf, int64_t buf_len,
                               uint32_t* out_lengths);

/* ---- runtime op registry (c_api.cc op-discovery analog) ---- */
/* Register/replace op metadata. Arrays are parallel; param_types follow
 * the reference's "type, optional, default=..." style strings. */
int MXTPURegisterOp(const char* name, const char* doc,
                    const char** arg_names, int n_args,
                    const char** param_names, const char** param_types,
                    const char** param_docs, int n_params);
/* Enumerate op names; pointers valid until the next MXTPUListOps call. */
int MXTPUListOps(int* out_size, const char*** out_names);
/* Fetch one op's metadata; pointers valid until re-registration. */
/* ---- predict-only mini API (reference include/mxnet/c_predict_api.h:
 * create from symbol JSON + param blob, set named inputs, forward, copy
 * outputs; the binding surface for non-Python frontends).  Implemented
 * over an embedded CPython interpreter driving the JAX predictor. */
typedef void* PredictorHandle;

int MXTPUPredCreate(const char* symbol_json, const void* param_bytes,
                    uint64_t param_size, int dev_type, int dev_id,
                    uint32_t num_input_nodes, const char** input_keys,
                    const uint32_t* input_shape_indptr,
                    const uint32_t* input_shape_data,
                    PredictorHandle* out);
int MXTPUPredSetInput(PredictorHandle handle, const char* key,
                      const float* data, uint32_t size);
int MXTPUPredForward(PredictorHandle handle);
/* Pass shape_data == NULL to query ndim first. */
int MXTPUPredGetOutputShape(PredictorHandle handle, uint32_t index,
                            uint32_t* shape_data, uint32_t* shape_ndim);
int MXTPUPredGetOutput(PredictorHandle handle, uint32_t index, float* data,
                       uint32_t size);
int MXTPUPredFree(PredictorHandle handle);

int MXTPUGetOpInfo(const char* name, const char** out_doc, int* out_n_args,
                   const char*** out_arg_names, int* out_n_params,
                   const char*** out_param_names,
                   const char*** out_param_types,
                   const char*** out_param_docs);

/* ==== training surface =====================================================
 * Rebuild of the reference's full training C API (include/mxnet/c_api.h;
 * src/c_api/c_api.cc:410-1250): NDArray CRUD + imperative invoke, Symbol
 * create/compose/infer, Executor bind/forward/backward, KVStore, DataIter.
 * Conventions: 0 = ok, -1 = failure (MXTPUGetLastError()); op/iter/optimizer
 * parameters travel as parallel key/value C-string arrays; dtype codes are
 * the mshadow TypeFlag order (0=f32 1=f64 2=f16 3=u8 4=i32 5=i8 6=i64) plus
 * 7=bf16 and 8=bool; dev_type: 1=cpu 2=gpu 3=cpu_pinned 4=tpu.
 * Pointer outputs (name lists, shape buffers, JSON) live in per-handle
 * snapshots and stay valid until the next call on the same handle. */

#define MXTPU_MAX_NDIM 8

typedef void* NDArrayHandle;
typedef void* SymbolHandle;
typedef void* ExecutorHandle;
typedef void* KVStoreHandle;
typedef void* DataIterHandle;

/* ---- NDArray (MXNDArray* analogs) ---- */
int MXTPUNDArrayCreate(const uint32_t* shape, uint32_t ndim, int dtype,
                       int dev_type, int dev_id, NDArrayHandle* out);
int MXTPUNDArraySyncCopyFromCPU(NDArrayHandle handle, const void* data,
                                uint64_t nbytes);
int MXTPUNDArraySyncCopyToCPU(NDArrayHandle handle, void* data,
                              uint64_t nbytes);
/* out_shape must have capacity MXTPU_MAX_NDIM. */
int MXTPUNDArrayGetShape(NDArrayHandle handle, uint32_t* out_ndim,
                         uint32_t* out_shape);
int MXTPUNDArrayGetDType(NDArrayHandle handle, int* out_dtype);
int MXTPUNDArrayWaitAll(void);
int MXTPUNDArrayFree(NDArrayHandle handle);
/* keys may be NULL for a nameless list save. */
int MXTPUNDArraySave(const char* fname, int num, NDArrayHandle* handles,
                     const char** keys);
/* out_names entries stay valid as long as their array handle lives;
 * *out_named is 1 when the file carried a name dict. */
int MXTPUNDArrayLoad(const char* fname, int cap, NDArrayHandle* out_handles,
                     const char** out_names, int* out_num, int* out_named);
/* Imperative op invoke on NDArrays (MXImperativeInvoke analog). */
int MXTPUFuncInvoke(const char* op_name, int n_in, NDArrayHandle* inputs,
                    int n_param, const char** keys, const char** vals,
                    int cap, NDArrayHandle* outputs, int* out_num);

/* ---- Symbol (MXSymbol* analogs) ---- */
int MXTPUSymbolCreateVariable(const char* name, SymbolHandle* out);
int MXTPUSymbolCreateAtomicSymbol(const char* op_name, int n_param,
                                  const char** keys, const char** vals,
                                  SymbolHandle* out);
/* Mutates sym in place (reference Compose semantics). keys == NULL means
 * positional inputs. */
int MXTPUSymbolCompose(SymbolHandle sym, const char* name, int n_args,
                       const char** keys, SymbolHandle* args);
int MXTPUSymbolCreateFromJSON(const char* json, SymbolHandle* out);
int MXTPUSymbolSaveToJSON(SymbolHandle sym, const char** out_json);
int MXTPUSymbolListArguments(SymbolHandle sym, int* out_size,
                             const char*** out);
int MXTPUSymbolListOutputs(SymbolHandle sym, int* out_size,
                           const char*** out);
int MXTPUSymbolListAuxiliaryStates(SymbolHandle sym, int* out_size,
                                   const char*** out);
int MXTPUSymbolCopy(SymbolHandle sym, SymbolHandle* out);
int MXTPUSymbolGetInternals(SymbolHandle sym, SymbolHandle* out);
int MXTPUSymbolGetOutput(SymbolHandle sym, uint32_t index, SymbolHandle* out);
int MXTPUSymbolGetAttr(SymbolHandle sym, const char* key, const char** out);
int MXTPUSymbolSetAttr(SymbolHandle sym, const char* key, const char* value);
/* MXSymbolInferShape-shaped: known input shapes arrive CSR-style
 * (keys + arg_ind_ptr[num_args+1] + arg_shape_data); results come back as
 * three groups (arg/out/aux) of (count, ndim array, shape-data pointer
 * array), owned by the handle snapshot. *complete is 0 when inference is
 * underdetermined (partial variant only). */
int MXTPUSymbolInferShape(SymbolHandle sym, uint32_t num_args,
                          const char** keys, const uint32_t* arg_ind_ptr,
                          const uint32_t* arg_shape_data, uint32_t* in_size,
                          const uint32_t** in_ndim, const uint32_t*** in_data,
                          uint32_t* out_size, const uint32_t** out_ndim,
                          const uint32_t*** out_data, uint32_t* aux_size,
                          const uint32_t** aux_ndim,
                          const uint32_t*** aux_data, int* complete);
int MXTPUSymbolInferShapePartial(
    SymbolHandle sym, uint32_t num_args, const char** keys,
    const uint32_t* arg_ind_ptr, const uint32_t* arg_shape_data,
    uint32_t* in_size, const uint32_t** in_ndim, const uint32_t*** in_data,
    uint32_t* out_size, const uint32_t** out_ndim, const uint32_t*** out_data,
    uint32_t* aux_size, const uint32_t** aux_ndim, const uint32_t*** aux_data,
    int* complete);
int MXTPUSymbolFree(SymbolHandle sym);

/* ---- Executor (MXExecutor* analogs) ---- */
/* arg_grads entries may be NULL (no gradient buffer); grad_reqs per arg:
 * 0 = null, 1 = write, 2 = add (NULL means all-write). */
int MXTPUExecutorBind(SymbolHandle sym, int dev_type, int dev_id,
                      uint32_t n_args, NDArrayHandle* args,
                      NDArrayHandle* arg_grads, const uint32_t* grad_reqs,
                      uint32_t n_aux, NDArrayHandle* aux,
                      ExecutorHandle* out);
int MXTPUExecutorForward(ExecutorHandle handle, int is_train);
/* head_grads may be n == 0 for loss-op heads (SoftmaxOutput etc.). */
int MXTPUExecutorBackward(ExecutorHandle handle, uint32_t n,
                          NDArrayHandle* head_grads);
/* Writes up to cap fresh NDArray handles (caller frees each). */
int MXTPUExecutorOutputs(ExecutorHandle handle, int cap, NDArrayHandle* out,
                        int* out_num);
int MXTPUExecutorFree(ExecutorHandle handle);

/* ---- KVStore (MXKVStore* analogs) ---- */
int MXTPUKVStoreCreate(const char* type, KVStoreHandle* out);
int MXTPUKVStoreInit(KVStoreHandle handle, int num, const int* keys,
                     NDArrayHandle* vals);
int MXTPUKVStorePush(KVStoreHandle handle, int num, const int* keys,
                     NDArrayHandle* vals, int priority);
int MXTPUKVStorePull(KVStoreHandle handle, int num, const int* keys,
                     NDArrayHandle* outs, int priority);
/* Server-side/local optimizer from name + string params (the C analog of
 * MXKVStoreSetUpdater: the optimizer zoo lives in the runtime). */
int MXTPUKVStoreSetOptimizer(KVStoreHandle handle, const char* name,
                             int n_param, const char** keys,
                             const char** vals);
int MXTPUKVStoreGetType(KVStoreHandle handle, const char** out);
int MXTPUKVStoreGetRank(KVStoreHandle handle, int* out);
int MXTPUKVStoreGetGroupSize(KVStoreHandle handle, int* out);
int MXTPUKVStoreBarrier(KVStoreHandle handle);
int MXTPUKVStoreFree(KVStoreHandle handle);

/* ---- DataIter (MXDataIter* analogs) ---- */
int MXTPUListDataIters(int* out_size, const char*** out_names);
int MXTPUDataIterCreate(const char* name, int n_param, const char** keys,
                        const char** vals, DataIterHandle* out);
int MXTPUDataIterNext(DataIterHandle handle, int* out);
int MXTPUDataIterBeforeFirst(DataIterHandle handle);
int MXTPUDataIterGetData(DataIterHandle handle, NDArrayHandle* out);
int MXTPUDataIterGetLabel(DataIterHandle handle, NDArrayHandle* out);
int MXTPUDataIterGetPadNum(DataIterHandle handle, int* out);
int MXTPUDataIterFree(DataIterHandle handle);

/* ---- extended NDArray views / metadata ----
 * COPY SEMANTICS (deliberate design shift from MXNDArraySlice/At/
 * Reshape, which alias the parent's memory): XLA arrays are immutable,
 * so Slice/At/Reshape return independent snapshot arrays — writing
 * through the result does NOT modify the parent.  To update a region of
 * an array, SyncCopyToCPU the whole buffer, edit, SyncCopyFromCPU. */
int MXTPUNDArraySlice(NDArrayHandle handle, uint32_t begin, uint32_t end,
                      NDArrayHandle* out);
/* Index along axis 0, dropping it. */
int MXTPUNDArrayAt(NDArrayHandle handle, uint32_t idx, NDArrayHandle* out);
int MXTPUNDArrayReshape(NDArrayHandle handle, uint32_t ndim,
                        const uint32_t* shape, NDArrayHandle* out);
int MXTPUNDArrayGetContext(NDArrayHandle handle, int* out_dev_type,
                           int* out_dev_id);
int MXTPUNDArrayCopyTo(NDArrayHandle src, NDArrayHandle dst);

/* ---- extended Symbol surface ---- */
/* Flattened [k0, v0, k1, v1, ...] attribute pairs (MXSymbolListAttr). */
int MXTPUSymbolListAttr(SymbolHandle sym, int recursive, int* out_size,
                        const char*** out);
int MXTPUSymbolGetNumOutputs(SymbolHandle sym, uint32_t* out);
/* Gradient-graph symbol wrt the named arguments (MXSymbolGrad). */
int MXTPUSymbolGrad(SymbolHandle sym, uint32_t n_wrt, const char** wrt,
                    SymbolHandle* out);
/* Human-readable executor graph dump (MXExecutorPrint). */
int MXTPUExecutorPrint(ExecutorHandle handle, const char** out);

/* ---- extended KVStore surface ---- */
/* C-side updater (MXKVStoreSetUpdater): called as
 * updater(key, recv_grad, local_weight, updater_handle); the callback
 * must update local_weight IN PLACE (SyncCopyFromCPU works) and may use
 * any NDArray entry points on the temporary handles it receives. */
typedef void (*MXTPUKVUpdater)(int key, NDArrayHandle recv,
                               NDArrayHandle local, void* updater_handle);
int MXTPUKVStoreSetUpdater(KVStoreHandle handle, MXTPUKVUpdater updater,
                           void* updater_handle);
int MXTPUKVStoreSaveOptimizerStates(KVStoreHandle handle, const char* fname);
int MXTPUKVStoreLoadOptimizerStates(KVStoreHandle handle, const char* fname);
int MXTPUKVStoreSendCommandToServers(KVStoreHandle handle, int head,
                                     const char* body);
int MXTPUKVStoreGetNumDeadNode(KVStoreHandle handle, int node_id, int* out);

/* ---- NDArray raw/blocking tail ---- */
int MXTPUNDArrayWaitToRead(NDArrayHandle handle);
int MXTPUNDArrayWaitToWrite(NDArrayHandle handle);
/* Self-describing single-array blob; buffer owned by the handle until
 * the next call on it. */
int MXTPUNDArraySaveRawBytes(NDArrayHandle handle, uint64_t* out_size,
                             const char** out_buf);
int MXTPUNDArrayLoadFromRawBytes(const void* buf, uint64_t size,
                                 int dev_type, int dev_id,
                                 NDArrayHandle* out);

/* ---- Symbol tail ---- */
int MXTPUSymbolCreateFromFile(const char* path, SymbolHandle* out);
int MXTPUSymbolCreateGroup(uint32_t n, SymbolHandle* symbols,
                           SymbolHandle* out);
int MXTPUSymbolGetName(SymbolHandle sym, const char** out);
/* Dtype inference: codes as in the dtype table above, -1 = unknown. */
int MXTPUSymbolInferType(SymbolHandle sym, uint32_t num_args,
                         const char** keys, const int* arg_types,
                         uint32_t* in_size, const int** in_types,
                         uint32_t* out_size, const int** out_types,
                         uint32_t* aux_size, const int** aux_types,
                         int* complete);
/* Non-recursive attribute pairs [k0, v0, ...]. */
int MXTPUSymbolListAttrShallow(SymbolHandle sym, int* out_size,
                               const char*** out);

/* ---- DataIter tail ---- */
int MXTPUDataIterGetIndex(DataIterHandle handle, uint64_t* out_size,
                          const uint64_t** out_index);

/* ---- imperative optimizer (MXOptimizer*) ---- */
typedef void* OptimizerHandle;
int MXTPUOptimizerCreateOptimizer(const char* name, int n_param,
                                  const char** keys, const char** vals,
                                  OptimizerHandle* out);
/* Stateful in-place weight update; per-index optimizer state lives in
 * the handle. */
int MXTPUOptimizerUpdate(OptimizerHandle handle, int index,
                         NDArrayHandle weight, NDArrayHandle grad);
int MXTPUOptimizerFree(OptimizerHandle handle);

/* ---- RecordIO reader/writer (MXRecordIO*) ---- */
typedef void* RecordIOHandle;
int MXTPURecordIOWriterCreate(const char* path, RecordIOHandle* out);
int MXTPURecordIOReaderCreate(const char* path, RecordIOHandle* out);
int MXTPURecordIOWriterWriteRecord(RecordIOHandle handle, const void* buf,
                                   uint64_t size);
int MXTPURecordIOWriterTell(RecordIOHandle handle, uint64_t* out);
/* Next record payload; *out_size == 0 at end of file; buffer owned by
 * the handle until the next call. */
int MXTPURecordIOReaderReadRecord(RecordIOHandle handle, uint64_t* out_size,
                                  const char** out_buf);
/* Rewind to the first record. */
int MXTPURecordIOReaderSeek(RecordIOHandle handle);
int MXTPURecordIOClose(RecordIOHandle handle);

/* ---- PS roles / lifecycle ---- */
int MXTPUKVStoreIsWorkerNode(int* out);
int MXTPUKVStoreIsServerNode(int* out);
int MXTPUKVStoreIsSchedulerNode(int* out);
/* Enter the blocking server loop when launched in the server role. */
int MXTPUKVStoreRunServer(KVStoreHandle handle);
int MXTPUInitPSEnv(int num, const char** keys, const char** vals);
/* Drain the host engine before process teardown (MXNotifyShutdown). */
int MXTPUNotifyShutdown(void);

/* ---- profiler / misc ---- */
int MXTPUProfilerStart(const char* logdir);
int MXTPUProfilerStop(void);
int MXTPUGetVersion(const char** out);
int MXTPURandomSeed(int seed);

#ifdef __cplusplus
}  /* extern "C" */
#endif

#endif  /* MXTPU_C_API_H_ */
