// mxtpu-cpp: header-only C++ frontend over the flat C ABI.
//
// The second-language frontend proof for this framework — the role the
// reference's cpp-package (include/mxnet-cpp/*.hpp, header-only classes
// over include/mxnet/c_api.h) and its R/Scala bindings play: every
// operation below reaches the runtime exclusively through the C entry
// points in mxtpu/c_api.h, never through Python headers, so any
// language with a C FFI can replicate this layer.
//
// RAII value types with shared-handle semantics: copying an NDArray /
// Symbol / Executor copies a reference to the same underlying handle
// (reference mxnet-cpp has the same contract).
//
//   using namespace mxtpu::cpp;
//   Symbol data = Symbol::Variable("data");
//   Symbol fc = Op("FullyConnected", {{"num_hidden", "10"}}, {data}, "fc");
//   auto shapes = fc.InferShape({{"data", {32, 64}}});
//   ...

#ifndef MXTPU_CPP_MXTPU_HPP_
#define MXTPU_CPP_MXTPU_HPP_

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <map>
#include <memory>
#include <random>
#include <stdexcept>
#include <string>
#include <utility>
#include <vector>

#include "../c_api.h"

namespace mxtpu {
namespace cpp {

class Error : public std::runtime_error {
 public:
  explicit Error(const std::string& where)
      : std::runtime_error(where + ": " + MXTPUGetLastError()) {}
};

inline void Check(int rc, const char* where) {
  if (rc != 0) throw Error(where);
}

using KwArgs = std::map<std::string, std::string>;

// Split a kwargs map into parallel C-string arrays (valid while the
// map is alive).
struct KwView {
  std::vector<const char*> keys, vals;
  explicit KwView(const KwArgs& kw) {
    for (const auto& it : kw) {
      keys.push_back(it.first.c_str());
      vals.push_back(it.second.c_str());
    }
  }
  int n() const { return static_cast<int>(keys.size()); }
};

// ---- NDArray ---------------------------------------------------------------

class NDArray {
 public:
  NDArray() = default;

  explicit NDArray(const std::vector<uint32_t>& shape, int dtype = 0,
                   int dev_type = 1, int dev_id = 0) {
    NDArrayHandle h = nullptr;
    Check(MXTPUNDArrayCreate(shape.data(),
                             static_cast<uint32_t>(shape.size()), dtype,
                             dev_type, dev_id, &h),
          "NDArrayCreate");
    Reset(h);
  }

  NDArray(const std::vector<float>& data, const std::vector<uint32_t>& shape)
      : NDArray(shape) {
    SyncCopyFromCPU(data);
  }

  // adopt an existing C handle (takes ownership)
  static NDArray Adopt(NDArrayHandle h) {
    NDArray a;
    a.Reset(h);
    return a;
  }

  bool IsNone() const { return handle_ == nullptr; }
  NDArrayHandle handle() const { return handle_ ? handle_->h : nullptr; }

  void SyncCopyFromCPU(const std::vector<float>& data) {
    Check(MXTPUNDArraySyncCopyFromCPU(handle(), data.data(),
                                      data.size() * sizeof(float)),
          "NDArraySyncCopyFromCPU");
  }

  std::vector<float> SyncCopyToCPU() const {
    std::vector<float> out(Size());
    Check(MXTPUNDArraySyncCopyToCPU(handle(), out.data(),
                                    out.size() * sizeof(float)),
          "NDArraySyncCopyToCPU");
    return out;
  }

  std::vector<uint32_t> Shape() const {
    uint32_t ndim = 0, buf[MXTPU_MAX_NDIM];
    Check(MXTPUNDArrayGetShape(handle(), &ndim, buf), "NDArrayGetShape");
    return std::vector<uint32_t>(buf, buf + ndim);
  }

  uint64_t Size() const {
    uint64_t n = 1;
    for (uint32_t d : Shape()) n *= d;
    return n;
  }

  int DType() const {
    int dt = 0;
    Check(MXTPUNDArrayGetDType(handle(), &dt), "NDArrayGetDType");
    return dt;
  }

  static void WaitAll() { Check(MXTPUNDArrayWaitAll(), "NDArrayWaitAll"); }

 private:
  struct Owner {
    explicit Owner(NDArrayHandle hh) : h(hh) {}
    Owner(const Owner&) = delete;
    Owner& operator=(const Owner&) = delete;
    NDArrayHandle h;
    ~Owner() {
      if (h) MXTPUNDArrayFree(h);
    }
  };
  void Reset(NDArrayHandle h) { handle_ = std::make_shared<Owner>(h); }
  std::shared_ptr<Owner> handle_;
};

// ---- Symbol ----------------------------------------------------------------

class Symbol {
 public:
  Symbol() = default;

  static Symbol Variable(const std::string& name) {
    SymbolHandle h = nullptr;
    Check(MXTPUSymbolCreateVariable(name.c_str(), &h), "SymbolCreateVariable");
    return Symbol(h);
  }

  static Symbol FromJSON(const std::string& json) {
    SymbolHandle h = nullptr;
    Check(MXTPUSymbolCreateFromJSON(json.c_str(), &h),
          "SymbolCreateFromJSON");
    return Symbol(h);
  }

  std::string ToJSON() const {
    const char* js = nullptr;
    Check(MXTPUSymbolSaveToJSON(handle(), &js), "SymbolSaveToJSON");
    return js;
  }

  std::vector<std::string> ListArguments() const {
    return ListStrs(&MXTPUSymbolListArguments);
  }
  std::vector<std::string> ListOutputs() const {
    return ListStrs(&MXTPUSymbolListOutputs);
  }
  std::vector<std::string> ListAuxiliaryStates() const {
    return ListStrs(&MXTPUSymbolListAuxiliaryStates);
  }

  Symbol GetInternals() const {
    SymbolHandle out = nullptr;
    Check(MXTPUSymbolGetInternals(handle(), &out), "SymbolGetInternals");
    return Symbol(out);
  }

  Symbol operator[](uint32_t i) const {
    SymbolHandle out = nullptr;
    Check(MXTPUSymbolGetOutput(handle(), i, &out), "SymbolGetOutput");
    return Symbol(out);
  }

  struct InferredShapes {
    bool complete = false;
    std::vector<std::vector<uint32_t>> arg, out, aux;
  };

  InferredShapes InferShape(
      const std::map<std::string, std::vector<uint32_t>>& known,
      bool partial = false) const {
    std::vector<const char*> keys;
    std::vector<uint32_t> indptr{0}, data;
    for (const auto& kv : known) {
      keys.push_back(kv.first.c_str());
      for (uint32_t d : kv.second) data.push_back(d);
      indptr.push_back(static_cast<uint32_t>(data.size()));
    }
    uint32_t sizes[3];
    const uint32_t* ndims[3];
    const uint32_t** shapes[3];
    int complete = 0;
    auto fn = partial ? &MXTPUSymbolInferShapePartial : &MXTPUSymbolInferShape;
    Check(fn(handle(), static_cast<uint32_t>(keys.size()), keys.data(),
             indptr.data(), data.data(), &sizes[0], &ndims[0], &shapes[0],
             &sizes[1], &ndims[1], &shapes[1], &sizes[2], &ndims[2],
             &shapes[2], &complete),
          "SymbolInferShape");
    InferredShapes r;
    r.complete = complete != 0;
    std::vector<std::vector<uint32_t>>* groups[3] = {&r.arg, &r.out, &r.aux};
    for (int g = 0; g < 3; ++g)
      for (uint32_t i = 0; i < sizes[g]; ++i)
        groups[g]->emplace_back(shapes[g][i], shapes[g][i] + ndims[g][i]);
    return r;
  }

  std::string GetAttr(const std::string& key) const {
    const char* out = nullptr;
    Check(MXTPUSymbolGetAttr(handle(), key.c_str(), &out), "SymbolGetAttr");
    return out;
  }

  void SetAttr(const std::string& key, const std::string& value) {
    Check(MXTPUSymbolSetAttr(handle(), key.c_str(), value.c_str()),
          "SymbolSetAttr");
  }

  SymbolHandle handle() const { return handle_ ? handle_->h : nullptr; }

  explicit Symbol(SymbolHandle h)
      : handle_(std::make_shared<Owner>(h)) {}

 private:
  template <typename Fn>
  std::vector<std::string> ListStrs(Fn fn) const {
    int n = 0;
    const char** strs = nullptr;
    Check(fn(handle(), &n, &strs), "SymbolList*");
    return std::vector<std::string>(strs, strs + n);
  }

  struct Owner {
    explicit Owner(SymbolHandle hh) : h(hh) {}
    Owner(const Owner&) = delete;
    Owner& operator=(const Owner&) = delete;
    SymbolHandle h;
    ~Owner() {
      if (h) MXTPUSymbolFree(h);
    }
  };
  std::shared_ptr<Owner> handle_;
};

// Atomic-create + compose in one expression — the mxnet-cpp Operator
// builder equivalent.
inline Symbol Op(const std::string& op_name, const KwArgs& params,
                 const std::vector<Symbol>& inputs,
                 const std::string& name = "") {
  KwView kw(params);
  SymbolHandle h = nullptr;
  Check(MXTPUSymbolCreateAtomicSymbol(op_name.c_str(), kw.n(),
                                      kw.keys.data(), kw.vals.data(), &h),
        "SymbolCreateAtomicSymbol");
  std::vector<SymbolHandle> args;
  for (const Symbol& s : inputs) args.push_back(s.handle());
  int rc = MXTPUSymbolCompose(h, name.c_str(),
                              static_cast<int>(args.size()), nullptr,
                              args.data());
  if (rc != 0) {
    MXTPUSymbolFree(h);
    throw Error("SymbolCompose");
  }
  return Symbol(h);
}

// ---- Executor --------------------------------------------------------------

enum class GradReq : uint32_t { kNull = 0, kWrite = 1, kAdd = 2 };

class Executor {
 public:
  Executor(const Symbol& sym, const std::vector<NDArray>& args,
           const std::vector<NDArray>& arg_grads,
           const std::vector<GradReq>& reqs,
           const std::vector<NDArray>& aux = {}, int dev_type = 1,
           int dev_id = 0) {
    if (arg_grads.size() != args.size() || reqs.size() != args.size())
      throw std::invalid_argument(
          "Executor: args, arg_grads and reqs must be the same length");
    std::vector<NDArrayHandle> a, g, x;
    std::vector<uint32_t> r;
    for (const auto& nd : args) a.push_back(nd.handle());
    for (const auto& nd : arg_grads) g.push_back(nd.handle());
    for (const auto& req : reqs) r.push_back(static_cast<uint32_t>(req));
    for (const auto& nd : aux) x.push_back(nd.handle());
    ExecutorHandle h = nullptr;
    Check(MXTPUExecutorBind(sym.handle(), dev_type, dev_id,
                            static_cast<uint32_t>(a.size()), a.data(),
                            g.data(), r.data(),
                            static_cast<uint32_t>(x.size()),
                            x.empty() ? nullptr : x.data(), &h),
          "ExecutorBind");
    handle_ = std::make_shared<Owner>(h);
  }

  void Forward(bool is_train) {
    Check(MXTPUExecutorForward(handle(), is_train ? 1 : 0),
          "ExecutorForward");
  }

  void Backward(const std::vector<NDArray>& head_grads = {}) {
    std::vector<NDArrayHandle> hg;
    for (const auto& nd : head_grads) hg.push_back(nd.handle());
    Check(MXTPUExecutorBackward(handle(),
                                static_cast<uint32_t>(hg.size()),
                                hg.empty() ? nullptr : hg.data()),
          "ExecutorBackward");
  }

  std::vector<NDArray> Outputs() const {
    NDArrayHandle buf[64];
    int n = 0;
    Check(MXTPUExecutorOutputs(handle(), 64, buf, &n), "ExecutorOutputs");
    std::vector<NDArray> outs;
    for (int i = 0; i < n; ++i) outs.push_back(NDArray::Adopt(buf[i]));
    return outs;
  }

  ExecutorHandle handle() const { return handle_ ? handle_->h : nullptr; }

 private:
  struct Owner {
    explicit Owner(ExecutorHandle hh) : h(hh) {}
    Owner(const Owner&) = delete;
    Owner& operator=(const Owner&) = delete;
    ExecutorHandle h;
    ~Owner() {
      if (h) MXTPUExecutorFree(h);
    }
  };
  std::shared_ptr<Owner> handle_;
};

// ---- KVStore ---------------------------------------------------------------

class KVStore {
 public:
  explicit KVStore(const std::string& type = "local") {
    KVStoreHandle h = nullptr;
    Check(MXTPUKVStoreCreate(type.c_str(), &h), "KVStoreCreate");
    handle_ = std::make_shared<Owner>(h);
  }

  void SetOptimizer(const std::string& name, const KwArgs& params) {
    KwView kw(params);
    Check(MXTPUKVStoreSetOptimizer(handle(), name.c_str(), kw.n(),
                                   kw.keys.data(), kw.vals.data()),
          "KVStoreSetOptimizer");
  }

  void Init(int key, const NDArray& val) {
    NDArrayHandle h = val.handle();
    Check(MXTPUKVStoreInit(handle(), 1, &key, &h), "KVStoreInit");
  }

  void Push(int key, const NDArray& val, int priority = 0) {
    NDArrayHandle h = val.handle();
    Check(MXTPUKVStorePush(handle(), 1, &key, &h, priority), "KVStorePush");
  }

  void Pull(int key, NDArray* out, int priority = 0) {
    NDArrayHandle h = out->handle();
    Check(MXTPUKVStorePull(handle(), 1, &key, &h, priority), "KVStorePull");
  }

  int Rank() const {
    int r = 0;
    Check(MXTPUKVStoreGetRank(handle(), &r), "KVStoreGetRank");
    return r;
  }

  int NumWorkers() const {
    int r = 0;
    Check(MXTPUKVStoreGetGroupSize(handle(), &r), "KVStoreGetGroupSize");
    return r;
  }

  std::string Type() const {
    const char* t = nullptr;
    Check(MXTPUKVStoreGetType(handle(), &t), "KVStoreGetType");
    return t;
  }

  KVStoreHandle handle() const { return handle_ ? handle_->h : nullptr; }

 private:
  struct Owner {
    explicit Owner(KVStoreHandle hh) : h(hh) {}
    Owner(const Owner&) = delete;
    Owner& operator=(const Owner&) = delete;
    KVStoreHandle h;
    ~Owner() {
      if (h) MXTPUKVStoreFree(h);
    }
  };
  std::shared_ptr<Owner> handle_;
};

// ---- DataIter --------------------------------------------------------------

class DataIter {
 public:
  DataIter(const std::string& name, const KwArgs& params) {
    KwView kw(params);
    DataIterHandle h = nullptr;
    Check(MXTPUDataIterCreate(name.c_str(), kw.n(), kw.keys.data(),
                              kw.vals.data(), &h),
          "DataIterCreate");
    handle_ = std::make_shared<Owner>(h);
  }

  static std::vector<std::string> List() {
    int n = 0;
    const char** names = nullptr;
    Check(MXTPUListDataIters(&n, &names), "ListDataIters");
    return std::vector<std::string>(names, names + n);
  }

  bool Next() {
    int more = 0;
    Check(MXTPUDataIterNext(handle(), &more), "DataIterNext");
    return more != 0;
  }

  void Reset() {
    Check(MXTPUDataIterBeforeFirst(handle()), "DataIterBeforeFirst");
  }

  NDArray Data() const {
    NDArrayHandle h = nullptr;
    Check(MXTPUDataIterGetData(handle(), &h), "DataIterGetData");
    return NDArray::Adopt(h);
  }

  NDArray Label() const {
    NDArrayHandle h = nullptr;
    Check(MXTPUDataIterGetLabel(handle(), &h), "DataIterGetLabel");
    return NDArray::Adopt(h);
  }

  int PadNum() const {
    int p = 0;
    Check(MXTPUDataIterGetPadNum(handle(), &p), "DataIterGetPadNum");
    return p;
  }

  DataIterHandle handle() const { return handle_ ? handle_->h : nullptr; }

 private:
  struct Owner {
    explicit Owner(DataIterHandle hh) : h(hh) {}
    Owner(const Owner&) = delete;
    Owner& operator=(const Owner&) = delete;
    DataIterHandle h;
    ~Owner() {
      if (h) MXTPUDataIterFree(h);
    }
  };
  std::shared_ptr<Owner> handle_;
};

inline void RandomSeed(int seed) {
  Check(MXTPURandomSeed(seed), "RandomSeed");
}

// ---- op registry discovery -------------------------------------------------
// The frontend does not hard-code the operator set: names and per-op
// metadata come from the runtime registry (reference
// MXSymbolListAtomicSymbolCreators + MXSymbolGetAtomicSymbolInfo, the
// machinery cpp-package's OpWrapperGenerator.py consumes).  Op() above
// composes any discovered name.

inline std::vector<std::string> ListOps() {
  int n = 0;
  const char** names = nullptr;
  Check(MXTPUListOps(&n, &names), "ListOps");
  return std::vector<std::string>(names, names + n);
}

struct OpInfo {
  std::string doc;
  std::vector<std::string> arg_names;                  // data inputs
  std::vector<std::string> param_names, param_types, param_docs;
};

inline OpInfo GetOpInfo(const std::string& name) {
  const char* doc = nullptr;
  int n_args = 0, n_params = 0;
  const char **arg_names = nullptr, **param_names = nullptr,
             **param_types = nullptr, **param_docs = nullptr;
  Check(MXTPUGetOpInfo(name.c_str(), &doc, &n_args, &arg_names, &n_params,
                       &param_names, &param_types, &param_docs),
        "GetOpInfo");
  OpInfo info;
  info.doc = doc ? doc : "";
  for (int i = 0; i < n_args; ++i) info.arg_names.emplace_back(arg_names[i]);
  for (int i = 0; i < n_params; ++i) {
    info.param_names.emplace_back(param_names[i]);
    info.param_types.emplace_back(param_types[i] ? param_types[i] : "");
    info.param_docs.emplace_back(param_docs[i] ? param_docs[i] : "");
  }
  return info;
}

// ---- Optimizer -------------------------------------------------------------
// Imperative worker-side optimizer over the C handle (reference
// MXOptimizerCreateOptimizer/MXOptimizerUpdate); per-index state
// (momentum etc.) lives behind the handle.

class Optimizer {
 public:
  Optimizer(const std::string& name, const KwArgs& params) {
    KwView kw(params);
    OptimizerHandle h = nullptr;
    Check(MXTPUOptimizerCreateOptimizer(name.c_str(), kw.n(),
                                        kw.keys.data(), kw.vals.data(), &h),
          "OptimizerCreateOptimizer");
    handle_ = std::make_shared<Owner>(h);
  }

  void Update(int index, const NDArray& weight, const NDArray& grad) {
    Check(MXTPUOptimizerUpdate(handle(), index, weight.handle(),
                               grad.handle()),
          "OptimizerUpdate");
  }

  OptimizerHandle handle() const { return handle_ ? handle_->h : nullptr; }

 private:
  struct Owner {
    explicit Owner(OptimizerHandle hh) : h(hh) {}
    Owner(const Owner&) = delete;
    Owner& operator=(const Owner&) = delete;
    OptimizerHandle h;
    ~Owner() {
      if (h) MXTPUOptimizerFree(h);
    }
  };
  std::shared_ptr<Owner> handle_;
};

// ---- initializers ----------------------------------------------------------
// Client-side like the reference cpp-package (initializers run in the
// frontend, only the filled arrays cross the ABI).

class Initializer {
 public:
  virtual ~Initializer() = default;
  virtual void operator()(const std::string& name, NDArray* arr) = 0;
};

class Xavier : public Initializer {
 public:
  explicit Xavier(double magnitude = 3.0, unsigned seed = 0)
      : magnitude_(magnitude), rng_(seed) {}

  void operator()(const std::string& name, NDArray* arr) override {
    auto shape = arr->Shape();
    std::vector<float> buf(arr->Size(), 0.0f);
    const bool is_weight =
        name.size() >= 6 && name.compare(name.size() - 6, 6, "weight") == 0;
    const bool is_gamma =
        name.size() >= 5 && name.compare(name.size() - 5, 5, "gamma") == 0;
    if (is_weight && !shape.empty()) {
      double fan_out = shape[0], fan_in = 1.0;
      for (size_t i = 1; i < shape.size(); ++i) fan_in *= shape[i];
      double scale = std::sqrt(magnitude_ * 2.0 / (fan_in + fan_out));
      std::uniform_real_distribution<float> dist(
          static_cast<float>(-scale), static_cast<float>(scale));
      for (auto& v : buf) v = dist(rng_);
    } else if (is_gamma) {
      for (auto& v : buf) v = 1.0f;     // BN scale starts at identity
    }  // biases/betas zero (reference initializer contract)
    arr->SyncCopyFromCPU(buf);
  }

 private:
  double magnitude_;
  std::mt19937 rng_;
};

// ---- Module ----------------------------------------------------------------
// The high-level training loop (reference module/module.py shape, via
// the executor): bind from shapes, init params, fit over a DataIter
// with an imperative optimizer, score, save/load params.  User code is
// symbol -> Module -> Fit, same as the Python frontend.

class Module {
 public:
  explicit Module(Symbol net) : net_(std::move(net)) {}

  void Bind(const std::map<std::string, std::vector<uint32_t>>& data_shapes) {
    arg_names_ = net_.ListArguments();
    aux_names_ = net_.ListAuxiliaryStates();
    auto shapes = net_.InferShape(data_shapes);
    if (!shapes.complete || shapes.arg.size() != arg_names_.size())
      throw std::runtime_error("Module::Bind: shape inference incomplete");
    args_.clear();
    grads_.clear();
    reqs_.clear();
    aux_.clear();
    for (size_t i = 0; i < arg_names_.size(); ++i) {
      args_.emplace_back(shapes.arg[i]);
      if (data_shapes.count(arg_names_[i])) {
        grads_.emplace_back();
        reqs_.push_back(GradReq::kNull);
      } else {
        grads_.emplace_back(shapes.arg[i]);
        reqs_.push_back(GradReq::kWrite);
      }
    }
    for (const auto& s : shapes.aux) aux_.emplace_back(s);
    exec_ = std::make_shared<Executor>(net_, args_, grads_, reqs_, aux_);
  }

  void InitParams(Initializer& init) {
    EnsureBound();
    for (size_t i = 0; i < args_.size(); ++i)
      if (reqs_[i] == GradReq::kWrite) init(arg_names_[i], &args_[i]);
    // aux states have fixed semantics, not initializer-drawn ones:
    // variance-like states start at 1, means/counters at 0 (the
    // Python executor applies the same contract)
    for (size_t i = 0; i < aux_.size(); ++i) {
      const std::string& n = aux_names_[i];
      const bool ones =
          n.size() >= 4 && (n.find("_var") != std::string::npos ||
                            n.find("gamma") != std::string::npos);
      aux_[i].SyncCopyFromCPU(
          std::vector<float>(aux_[i].Size(), ones ? 1.0f : 0.0f));
    }
  }

  void InitOptimizer(const std::string& name, const KwArgs& params) {
    opt_ = std::make_shared<Optimizer>(name, params);
  }

  // One pass over the iterator; returns training accuracy of the pass
  // (argmax of outputs[0] vs the label input, pad-aware).
  double FitEpoch(DataIter& it, const std::string& data_name = "data",
                  const std::string& label_name = "softmax_label") {
    EnsureBound();
    if (!opt_) throw std::runtime_error("Module: InitOptimizer first");
    long correct = 0, total = 0;
    it.Reset();
    while (it.Next()) {
      FeedBatch(it, data_name, label_name);
      exec_->Forward(true);
      exec_->Backward();
      for (size_t i = 0; i < args_.size(); ++i)
        if (reqs_[i] == GradReq::kWrite)
          opt_->Update(static_cast<int>(i), args_[i], grads_[i]);
      Accumulate(it, label_name, &correct, &total);
    }
    return total ? static_cast<double>(correct) / total : 0.0;
  }

  double Fit(DataIter& train, int epochs,
             const std::string& data_name = "data",
             const std::string& label_name = "softmax_label") {
    double acc = 0.0;
    for (int e = 0; e < epochs; ++e) acc = FitEpoch(train, data_name,
                                                    label_name);
    return acc;
  }

  double Score(DataIter& it, const std::string& data_name = "data",
               const std::string& label_name = "softmax_label") {
    EnsureBound();
    long correct = 0, total = 0;
    it.Reset();
    while (it.Next()) {
      FeedBatch(it, data_name, label_name);
      exec_->Forward(false);
      Accumulate(it, label_name, &correct, &total);
    }
    return total ? static_cast<double>(correct) / total : 0.0;
  }

  // Single-batch inference on caller data (shape = bound data shape).
  std::vector<float> Predict(const std::vector<float>& data,
                             const std::string& data_name = "data") {
    EnsureBound();
    args_[InputIdx(data_name)].SyncCopyFromCPU(data);
    exec_->Forward(false);
    return exec_->Outputs()[0].SyncCopyToCPU();
  }

  // Reference .params naming: "arg:<name>" / "aux:<name>" prefixes, so
  // the file carries the full model state (BatchNorm moving stats
  // included) and interoperates with the Python loader's convention.
  void SaveParams(const std::string& fname) {
    EnsureBound();
    std::vector<std::string> key_store;
    std::vector<NDArrayHandle> hs;
    for (size_t i = 0; i < args_.size(); ++i)
      if (reqs_[i] == GradReq::kWrite) {
        hs.push_back(args_[i].handle());
        key_store.push_back("arg:" + arg_names_[i]);
      }
    for (size_t i = 0; i < aux_.size(); ++i) {
      hs.push_back(aux_[i].handle());
      key_store.push_back("aux:" + aux_names_[i]);
    }
    std::vector<const char*> keys;
    for (const auto& k : key_store) keys.push_back(k.c_str());
    Check(MXTPUNDArraySave(fname.c_str(), static_cast<int>(hs.size()),
                           hs.data(), keys.data()),
          "NDArraySave");
  }

  void LoadParams(const std::string& fname) {
    EnsureBound();
    // 4096 covers any model this frontend binds in one executor; the C
    // entry fails loudly ("capacity too small") rather than truncating
    std::vector<NDArrayHandle> buf(4096);
    std::vector<const char*> names(4096);
    int n = 0, named = 0;
    Check(MXTPUNDArrayLoad(fname.c_str(), static_cast<int>(buf.size()),
                           buf.data(), names.data(), &n, &named),
          "NDArrayLoad");
    // adopt everything FIRST so every handle is owned (and freed) no
    // matter which validation below throws
    std::map<std::string, NDArray> loaded;
    for (int i = 0; i < n; ++i)
      loaded.emplace(named ? names[i] : std::to_string(i),
                     NDArray::Adopt(buf[i]));
    if (!named) throw std::runtime_error("Module::LoadParams: nameless file");

    auto fetch = [&](const std::string& prefixed) -> const NDArray* {
      auto it = loaded.find(prefixed);
      if (it != loaded.end()) return &it->second;
      // tolerate prefixless saves (e.g. hand-written files)
      auto bare = loaded.find(prefixed.substr(prefixed.find(':') + 1));
      return bare != loaded.end() ? &bare->second : nullptr;
    };
    for (size_t i = 0; i < args_.size(); ++i) {
      if (reqs_[i] != GradReq::kWrite) continue;
      const NDArray* src = fetch("arg:" + arg_names_[i]);
      if (!src)
        throw std::runtime_error("Module::LoadParams: missing " +
                                 arg_names_[i]);
      args_[i].SyncCopyFromCPU(src->SyncCopyToCPU());
    }
    for (size_t i = 0; i < aux_.size(); ++i) {
      const NDArray* src = fetch("aux:" + aux_names_[i]);
      if (!src)
        throw std::runtime_error("Module::LoadParams: missing aux " +
                                 aux_names_[i]);
      aux_[i].SyncCopyFromCPU(src->SyncCopyToCPU());
    }
  }

  const std::vector<std::string>& ArgNames() const { return arg_names_; }
  NDArray& Arg(const std::string& name) { return args_[InputIdx(name)]; }
  Executor& Exec() { return *exec_; }

 private:
  void EnsureBound() const {
    if (!exec_) throw std::runtime_error("Module: call Bind first");
  }

  int InputIdx(const std::string& name) const {
    for (size_t i = 0; i < arg_names_.size(); ++i)
      if (arg_names_[i] == name) return static_cast<int>(i);
    throw std::runtime_error("Module: unknown argument " + name);
  }

  void FeedBatch(DataIter& it, const std::string& data_name,
                 const std::string& label_name) {
    args_[InputIdx(data_name)].SyncCopyFromCPU(it.Data().SyncCopyToCPU());
    last_labels_ = it.Label().SyncCopyToCPU();
    args_[InputIdx(label_name)].SyncCopyFromCPU(last_labels_);
  }

  void Accumulate(DataIter& it, const std::string& /*label_name*/,
                  long* correct, long* total) {
    // labels cached host-side by FeedBatch: no device round-trip here
    const std::vector<float>& labels = last_labels_;
    auto probs = exec_->Outputs()[0].SyncCopyToCPU();
    const long batch = static_cast<long>(labels.size());
    const long classes = batch ? static_cast<long>(probs.size()) / batch : 0;
    const long live = batch - it.PadNum();     // round-pad tail excluded
    for (long b = 0; b < live; ++b) {
      auto row = probs.begin() + b * classes;
      long best = std::max_element(row, row + classes) - row;
      *correct += best == static_cast<long>(labels[b]);
      ++*total;
    }
  }

  Symbol net_;
  std::vector<std::string> arg_names_, aux_names_;
  std::vector<NDArray> args_, grads_, aux_;
  std::vector<GradReq> reqs_;
  std::vector<float> last_labels_;
  std::shared_ptr<Executor> exec_;
  std::shared_ptr<Optimizer> opt_;
};

}  // namespace cpp
}  // namespace mxtpu

#endif  // MXTPU_CPP_MXTPU_HPP_
