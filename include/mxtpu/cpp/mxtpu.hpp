// mxtpu-cpp: header-only C++ frontend over the flat C ABI.
//
// The second-language frontend proof for this framework — the role the
// reference's cpp-package (include/mxnet-cpp/*.hpp, header-only classes
// over include/mxnet/c_api.h) and its R/Scala bindings play: every
// operation below reaches the runtime exclusively through the C entry
// points in mxtpu/c_api.h, never through Python headers, so any
// language with a C FFI can replicate this layer.
//
// RAII value types with shared-handle semantics: copying an NDArray /
// Symbol / Executor copies a reference to the same underlying handle
// (reference mxnet-cpp has the same contract).
//
//   using namespace mxtpu::cpp;
//   Symbol data = Symbol::Variable("data");
//   Symbol fc = Op("FullyConnected", {{"num_hidden", "10"}}, {data}, "fc");
//   auto shapes = fc.InferShape({{"data", {32, 64}}});
//   ...

#ifndef MXTPU_CPP_MXTPU_HPP_
#define MXTPU_CPP_MXTPU_HPP_

#include <cstdint>
#include <map>
#include <memory>
#include <stdexcept>
#include <string>
#include <utility>
#include <vector>

#include "../c_api.h"

namespace mxtpu {
namespace cpp {

class Error : public std::runtime_error {
 public:
  explicit Error(const std::string& where)
      : std::runtime_error(where + ": " + MXTPUGetLastError()) {}
};

inline void Check(int rc, const char* where) {
  if (rc != 0) throw Error(where);
}

using KwArgs = std::map<std::string, std::string>;

// Split a kwargs map into parallel C-string arrays (valid while the
// map is alive).
struct KwView {
  std::vector<const char*> keys, vals;
  explicit KwView(const KwArgs& kw) {
    for (const auto& it : kw) {
      keys.push_back(it.first.c_str());
      vals.push_back(it.second.c_str());
    }
  }
  int n() const { return static_cast<int>(keys.size()); }
};

// ---- NDArray ---------------------------------------------------------------

class NDArray {
 public:
  NDArray() = default;

  explicit NDArray(const std::vector<uint32_t>& shape, int dtype = 0,
                   int dev_type = 1, int dev_id = 0) {
    NDArrayHandle h = nullptr;
    Check(MXTPUNDArrayCreate(shape.data(),
                             static_cast<uint32_t>(shape.size()), dtype,
                             dev_type, dev_id, &h),
          "NDArrayCreate");
    Reset(h);
  }

  NDArray(const std::vector<float>& data, const std::vector<uint32_t>& shape)
      : NDArray(shape) {
    SyncCopyFromCPU(data);
  }

  // adopt an existing C handle (takes ownership)
  static NDArray Adopt(NDArrayHandle h) {
    NDArray a;
    a.Reset(h);
    return a;
  }

  bool IsNone() const { return handle_ == nullptr; }
  NDArrayHandle handle() const { return handle_ ? handle_->h : nullptr; }

  void SyncCopyFromCPU(const std::vector<float>& data) {
    Check(MXTPUNDArraySyncCopyFromCPU(handle(), data.data(),
                                      data.size() * sizeof(float)),
          "NDArraySyncCopyFromCPU");
  }

  std::vector<float> SyncCopyToCPU() const {
    std::vector<float> out(Size());
    Check(MXTPUNDArraySyncCopyToCPU(handle(), out.data(),
                                    out.size() * sizeof(float)),
          "NDArraySyncCopyToCPU");
    return out;
  }

  std::vector<uint32_t> Shape() const {
    uint32_t ndim = 0, buf[MXTPU_MAX_NDIM];
    Check(MXTPUNDArrayGetShape(handle(), &ndim, buf), "NDArrayGetShape");
    return std::vector<uint32_t>(buf, buf + ndim);
  }

  uint64_t Size() const {
    uint64_t n = 1;
    for (uint32_t d : Shape()) n *= d;
    return n;
  }

  int DType() const {
    int dt = 0;
    Check(MXTPUNDArrayGetDType(handle(), &dt), "NDArrayGetDType");
    return dt;
  }

  static void WaitAll() { Check(MXTPUNDArrayWaitAll(), "NDArrayWaitAll"); }

 private:
  struct Owner {
    explicit Owner(NDArrayHandle hh) : h(hh) {}
    Owner(const Owner&) = delete;
    Owner& operator=(const Owner&) = delete;
    NDArrayHandle h;
    ~Owner() {
      if (h) MXTPUNDArrayFree(h);
    }
  };
  void Reset(NDArrayHandle h) { handle_ = std::make_shared<Owner>(h); }
  std::shared_ptr<Owner> handle_;
};

// ---- Symbol ----------------------------------------------------------------

class Symbol {
 public:
  Symbol() = default;

  static Symbol Variable(const std::string& name) {
    SymbolHandle h = nullptr;
    Check(MXTPUSymbolCreateVariable(name.c_str(), &h), "SymbolCreateVariable");
    return Symbol(h);
  }

  static Symbol FromJSON(const std::string& json) {
    SymbolHandle h = nullptr;
    Check(MXTPUSymbolCreateFromJSON(json.c_str(), &h),
          "SymbolCreateFromJSON");
    return Symbol(h);
  }

  std::string ToJSON() const {
    const char* js = nullptr;
    Check(MXTPUSymbolSaveToJSON(handle(), &js), "SymbolSaveToJSON");
    return js;
  }

  std::vector<std::string> ListArguments() const {
    return ListStrs(&MXTPUSymbolListArguments);
  }
  std::vector<std::string> ListOutputs() const {
    return ListStrs(&MXTPUSymbolListOutputs);
  }
  std::vector<std::string> ListAuxiliaryStates() const {
    return ListStrs(&MXTPUSymbolListAuxiliaryStates);
  }

  Symbol GetInternals() const {
    SymbolHandle out = nullptr;
    Check(MXTPUSymbolGetInternals(handle(), &out), "SymbolGetInternals");
    return Symbol(out);
  }

  Symbol operator[](uint32_t i) const {
    SymbolHandle out = nullptr;
    Check(MXTPUSymbolGetOutput(handle(), i, &out), "SymbolGetOutput");
    return Symbol(out);
  }

  struct InferredShapes {
    bool complete = false;
    std::vector<std::vector<uint32_t>> arg, out, aux;
  };

  InferredShapes InferShape(
      const std::map<std::string, std::vector<uint32_t>>& known,
      bool partial = false) const {
    std::vector<const char*> keys;
    std::vector<uint32_t> indptr{0}, data;
    for (const auto& kv : known) {
      keys.push_back(kv.first.c_str());
      for (uint32_t d : kv.second) data.push_back(d);
      indptr.push_back(static_cast<uint32_t>(data.size()));
    }
    uint32_t sizes[3];
    const uint32_t* ndims[3];
    const uint32_t** shapes[3];
    int complete = 0;
    auto fn = partial ? &MXTPUSymbolInferShapePartial : &MXTPUSymbolInferShape;
    Check(fn(handle(), static_cast<uint32_t>(keys.size()), keys.data(),
             indptr.data(), data.data(), &sizes[0], &ndims[0], &shapes[0],
             &sizes[1], &ndims[1], &shapes[1], &sizes[2], &ndims[2],
             &shapes[2], &complete),
          "SymbolInferShape");
    InferredShapes r;
    r.complete = complete != 0;
    std::vector<std::vector<uint32_t>>* groups[3] = {&r.arg, &r.out, &r.aux};
    for (int g = 0; g < 3; ++g)
      for (uint32_t i = 0; i < sizes[g]; ++i)
        groups[g]->emplace_back(shapes[g][i], shapes[g][i] + ndims[g][i]);
    return r;
  }

  std::string GetAttr(const std::string& key) const {
    const char* out = nullptr;
    Check(MXTPUSymbolGetAttr(handle(), key.c_str(), &out), "SymbolGetAttr");
    return out;
  }

  void SetAttr(const std::string& key, const std::string& value) {
    Check(MXTPUSymbolSetAttr(handle(), key.c_str(), value.c_str()),
          "SymbolSetAttr");
  }

  SymbolHandle handle() const { return handle_ ? handle_->h : nullptr; }

  explicit Symbol(SymbolHandle h)
      : handle_(std::make_shared<Owner>(h)) {}

 private:
  template <typename Fn>
  std::vector<std::string> ListStrs(Fn fn) const {
    int n = 0;
    const char** strs = nullptr;
    Check(fn(handle(), &n, &strs), "SymbolList*");
    return std::vector<std::string>(strs, strs + n);
  }

  struct Owner {
    explicit Owner(SymbolHandle hh) : h(hh) {}
    Owner(const Owner&) = delete;
    Owner& operator=(const Owner&) = delete;
    SymbolHandle h;
    ~Owner() {
      if (h) MXTPUSymbolFree(h);
    }
  };
  std::shared_ptr<Owner> handle_;
};

// Atomic-create + compose in one expression — the mxnet-cpp Operator
// builder equivalent.
inline Symbol Op(const std::string& op_name, const KwArgs& params,
                 const std::vector<Symbol>& inputs,
                 const std::string& name = "") {
  KwView kw(params);
  SymbolHandle h = nullptr;
  Check(MXTPUSymbolCreateAtomicSymbol(op_name.c_str(), kw.n(),
                                      kw.keys.data(), kw.vals.data(), &h),
        "SymbolCreateAtomicSymbol");
  std::vector<SymbolHandle> args;
  for (const Symbol& s : inputs) args.push_back(s.handle());
  int rc = MXTPUSymbolCompose(h, name.c_str(),
                              static_cast<int>(args.size()), nullptr,
                              args.data());
  if (rc != 0) {
    MXTPUSymbolFree(h);
    throw Error("SymbolCompose");
  }
  return Symbol(h);
}

// ---- Executor --------------------------------------------------------------

enum class GradReq : uint32_t { kNull = 0, kWrite = 1, kAdd = 2 };

class Executor {
 public:
  Executor(const Symbol& sym, const std::vector<NDArray>& args,
           const std::vector<NDArray>& arg_grads,
           const std::vector<GradReq>& reqs,
           const std::vector<NDArray>& aux = {}, int dev_type = 1,
           int dev_id = 0) {
    if (arg_grads.size() != args.size() || reqs.size() != args.size())
      throw std::invalid_argument(
          "Executor: args, arg_grads and reqs must be the same length");
    std::vector<NDArrayHandle> a, g, x;
    std::vector<uint32_t> r;
    for (const auto& nd : args) a.push_back(nd.handle());
    for (const auto& nd : arg_grads) g.push_back(nd.handle());
    for (const auto& req : reqs) r.push_back(static_cast<uint32_t>(req));
    for (const auto& nd : aux) x.push_back(nd.handle());
    ExecutorHandle h = nullptr;
    Check(MXTPUExecutorBind(sym.handle(), dev_type, dev_id,
                            static_cast<uint32_t>(a.size()), a.data(),
                            g.data(), r.data(),
                            static_cast<uint32_t>(x.size()),
                            x.empty() ? nullptr : x.data(), &h),
          "ExecutorBind");
    handle_ = std::make_shared<Owner>(h);
  }

  void Forward(bool is_train) {
    Check(MXTPUExecutorForward(handle(), is_train ? 1 : 0),
          "ExecutorForward");
  }

  void Backward(const std::vector<NDArray>& head_grads = {}) {
    std::vector<NDArrayHandle> hg;
    for (const auto& nd : head_grads) hg.push_back(nd.handle());
    Check(MXTPUExecutorBackward(handle(),
                                static_cast<uint32_t>(hg.size()),
                                hg.empty() ? nullptr : hg.data()),
          "ExecutorBackward");
  }

  std::vector<NDArray> Outputs() const {
    NDArrayHandle buf[64];
    int n = 0;
    Check(MXTPUExecutorOutputs(handle(), 64, buf, &n), "ExecutorOutputs");
    std::vector<NDArray> outs;
    for (int i = 0; i < n; ++i) outs.push_back(NDArray::Adopt(buf[i]));
    return outs;
  }

  ExecutorHandle handle() const { return handle_ ? handle_->h : nullptr; }

 private:
  struct Owner {
    explicit Owner(ExecutorHandle hh) : h(hh) {}
    Owner(const Owner&) = delete;
    Owner& operator=(const Owner&) = delete;
    ExecutorHandle h;
    ~Owner() {
      if (h) MXTPUExecutorFree(h);
    }
  };
  std::shared_ptr<Owner> handle_;
};

// ---- KVStore ---------------------------------------------------------------

class KVStore {
 public:
  explicit KVStore(const std::string& type = "local") {
    KVStoreHandle h = nullptr;
    Check(MXTPUKVStoreCreate(type.c_str(), &h), "KVStoreCreate");
    handle_ = std::make_shared<Owner>(h);
  }

  void SetOptimizer(const std::string& name, const KwArgs& params) {
    KwView kw(params);
    Check(MXTPUKVStoreSetOptimizer(handle(), name.c_str(), kw.n(),
                                   kw.keys.data(), kw.vals.data()),
          "KVStoreSetOptimizer");
  }

  void Init(int key, const NDArray& val) {
    NDArrayHandle h = val.handle();
    Check(MXTPUKVStoreInit(handle(), 1, &key, &h), "KVStoreInit");
  }

  void Push(int key, const NDArray& val, int priority = 0) {
    NDArrayHandle h = val.handle();
    Check(MXTPUKVStorePush(handle(), 1, &key, &h, priority), "KVStorePush");
  }

  void Pull(int key, NDArray* out, int priority = 0) {
    NDArrayHandle h = out->handle();
    Check(MXTPUKVStorePull(handle(), 1, &key, &h, priority), "KVStorePull");
  }

  int Rank() const {
    int r = 0;
    Check(MXTPUKVStoreGetRank(handle(), &r), "KVStoreGetRank");
    return r;
  }

  int NumWorkers() const {
    int r = 0;
    Check(MXTPUKVStoreGetGroupSize(handle(), &r), "KVStoreGetGroupSize");
    return r;
  }

  std::string Type() const {
    const char* t = nullptr;
    Check(MXTPUKVStoreGetType(handle(), &t), "KVStoreGetType");
    return t;
  }

  KVStoreHandle handle() const { return handle_ ? handle_->h : nullptr; }

 private:
  struct Owner {
    explicit Owner(KVStoreHandle hh) : h(hh) {}
    Owner(const Owner&) = delete;
    Owner& operator=(const Owner&) = delete;
    KVStoreHandle h;
    ~Owner() {
      if (h) MXTPUKVStoreFree(h);
    }
  };
  std::shared_ptr<Owner> handle_;
};

// ---- DataIter --------------------------------------------------------------

class DataIter {
 public:
  DataIter(const std::string& name, const KwArgs& params) {
    KwView kw(params);
    DataIterHandle h = nullptr;
    Check(MXTPUDataIterCreate(name.c_str(), kw.n(), kw.keys.data(),
                              kw.vals.data(), &h),
          "DataIterCreate");
    handle_ = std::make_shared<Owner>(h);
  }

  static std::vector<std::string> List() {
    int n = 0;
    const char** names = nullptr;
    Check(MXTPUListDataIters(&n, &names), "ListDataIters");
    return std::vector<std::string>(names, names + n);
  }

  bool Next() {
    int more = 0;
    Check(MXTPUDataIterNext(handle(), &more), "DataIterNext");
    return more != 0;
  }

  void Reset() {
    Check(MXTPUDataIterBeforeFirst(handle()), "DataIterBeforeFirst");
  }

  NDArray Data() const {
    NDArrayHandle h = nullptr;
    Check(MXTPUDataIterGetData(handle(), &h), "DataIterGetData");
    return NDArray::Adopt(h);
  }

  NDArray Label() const {
    NDArrayHandle h = nullptr;
    Check(MXTPUDataIterGetLabel(handle(), &h), "DataIterGetLabel");
    return NDArray::Adopt(h);
  }

  int PadNum() const {
    int p = 0;
    Check(MXTPUDataIterGetPadNum(handle(), &p), "DataIterGetPadNum");
    return p;
  }

  DataIterHandle handle() const { return handle_ ? handle_->h : nullptr; }

 private:
  struct Owner {
    explicit Owner(DataIterHandle hh) : h(hh) {}
    Owner(const Owner&) = delete;
    Owner& operator=(const Owner&) = delete;
    DataIterHandle h;
    ~Owner() {
      if (h) MXTPUDataIterFree(h);
    }
  };
  std::shared_ptr<Owner> handle_;
};

inline void RandomSeed(int seed) {
  Check(MXTPURandomSeed(seed), "RandomSeed");
}

}  // namespace cpp
}  // namespace mxtpu

#endif  // MXTPU_CPP_MXTPU_HPP_
