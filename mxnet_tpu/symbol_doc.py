"""Symbol documentation helpers (rebuild of python/mxnet/symbol_doc.py).

The reference attaches extended doc/examples to auto-generated ops and
exposes ``SymbolDoc.get_output_shape`` as a teaching utility.  Ops here
carry their docs in the registry (OpDef docstrings + typed Params with
per-field doc), so this module provides the utility surface: shape
lookup, and a ``build_doc`` that renders an op's signature the way the
reference's C-API docstring generator did.
"""

from __future__ import annotations

from .ops.op import OP_REGISTRY

__all__ = ["SymbolDoc", "build_doc", "list_ops",
           "ActivationDoc", "DropoutDoc", "EmbeddingDoc", "FlattenDoc",
           "FullyConnectedDoc", "ConcatDoc", "BroadcastPlusDoc"]


class SymbolDoc:
    """Doc/demo helpers (reference symbol_doc.py SymbolDoc)."""

    @staticmethod
    def get_output_shape(sym, **input_shapes):
        """Map output names to inferred shapes for given input shapes."""
        _, s_outputs, _ = sym.infer_shape(**input_shapes)
        return dict(zip(sym.list_outputs(), s_outputs))


def list_ops():
    """All registered operator names (discovery surface parity with
    MXSymbolListAtomicSymbolCreators)."""
    return sorted(OP_REGISTRY.list())


def build_doc(op_name: str) -> str:
    """Render an op's docstring + parameter table from the registry,
    the way the reference generated Python docstrings from the C API's
    key/type/description triples."""
    op = OP_REGISTRY.get(op_name)
    lines = [f"{op_name}", ""]
    doc = (getattr(op, "__doc__", None)
           or getattr(type(op), "__doc__", None) or "")
    if doc:
        lines += [doc.strip(), ""]
    param_cls = getattr(op, "param_cls", None)
    if param_cls is not None:
        lines.append("Parameters")
        lines.append("----------")
        for fname, fld in getattr(param_cls, "_fields", {}).items():
            typ = getattr(fld, "type", None)
            tname = getattr(typ, "__name__", str(typ))
            default = getattr(fld, "default", None)
            req = getattr(fld, "required", False)
            spec = f"{fname} : {tname}"
            spec += ", required" if req else f", optional, default={default!r}"
            lines.append(spec)
            fdoc = getattr(fld, "doc", None)
            if fdoc:
                lines.append(f"    {fdoc}")
    # the reference hook: a ``<Op>Doc`` subclass of SymbolDoc in this
    # module contributes its docstring (Examples etc.) to the op's docs.
    # Lookup is case/underscore-insensitive so snake_case op names
    # (broadcast_plus) find their CamelCase doc class (BroadcastPlusDoc)
    target = op_name.replace("_", "").lower() + "doc"
    for key, extra in globals().items():
        if (key.replace("_", "").lower() == target
                and isinstance(extra, type) and issubclass(extra, SymbolDoc)
                and extra.__doc__):
            lines += ["", extra.__doc__.strip()]
            break
    return "\n".join(lines)


# -- per-op extended doc classes (reference symbol_doc.py pattern) ----------
# The reference attaches extra examples to generated ops by writing a
# ``<Op>Doc`` class whose docstring is appended to the op's docs.  The
# same hook exists here: subclass SymbolDoc, name it after the op.


class ActivationDoc(SymbolDoc):
    """
    Examples
    --------
    >>> x = mx.sym.Variable('x')
    >>> h = mx.sym.FullyConnected(x, num_hidden=64, name='proj')
    >>> h = mx.sym.Activation(h, act_type='relu', name='act')

    act_type is one of relu / sigmoid / tanh / softrelu; the lowering is
    one fused XLA elementwise op either way.
    """


class DropoutDoc(SymbolDoc):
    """
    Examples
    --------
    >>> h = mx.sym.Dropout(h, p=0.5)

    Active only under ``forward(is_train=True)``; the mask is drawn from
    the executor's threefry key chain, so a seeded run replays exactly
    (and identically across CPU/TPU backends).
    """


class EmbeddingDoc(SymbolDoc):
    """
    Examples
    --------
    >>> ids = mx.sym.Variable('ids')       # (batch, seq) token ids
    >>> emb = mx.sym.Embedding(ids, input_dim=50000, output_dim=256)

    Integer inputs are welcome (int32 ids are the TPU-friendly form);
    the output takes the TABLE's float dtype.  Backward is a native XLA
    scatter-add.
    """


class FlattenDoc(SymbolDoc):
    """
    Examples
    --------
    >>> conv = mx.sym.Convolution(x, kernel=(3, 3), num_filter=32)
    >>> fc = mx.sym.FullyConnected(mx.sym.Flatten(conv), num_hidden=10)

    Collapses all trailing axes: (N, C, H, W) -> (N, C*H*W).
    """


class FullyConnectedDoc(SymbolDoc):
    """
    Examples
    --------
    >>> fc = mx.sym.FullyConnected(x, num_hidden=128, name='fc')
    >>> fc.list_arguments()
    ['x', 'fc_weight', 'fc_bias']

    Weight layout is (num_hidden, input_dim) — the reference
    convention, preserved so checkpoints interchange; the MXU matmul
    absorbs the transpose.
    """


class ConcatDoc(SymbolDoc):
    """
    Examples
    --------
    >>> out = mx.sym.Concat(a, b, c, dim=1)

    ``num_args`` is inferred from the positional count
    (key_var_num_args); pass ``dim`` to pick the axis (default 1).
    """


class BroadcastPlusDoc(SymbolDoc):
    """
    Examples
    --------
    >>> out = mx.sym.broadcast_plus(x, bias)   # numpy-style broadcasting

    Size-1 axes broadcast; the gradient sums over broadcast axes.
    """
