"""Symbol documentation helpers (rebuild of python/mxnet/symbol_doc.py).

The reference attaches extended doc/examples to auto-generated ops and
exposes ``SymbolDoc.get_output_shape`` as a teaching utility.  Ops here
carry their docs in the registry (OpDef docstrings + typed Params with
per-field doc), so this module provides the utility surface: shape
lookup, and a ``build_doc`` that renders an op's signature the way the
reference's C-API docstring generator did.
"""

from __future__ import annotations

from .ops.op import OP_REGISTRY

__all__ = ["SymbolDoc", "build_doc", "list_ops"]


class SymbolDoc:
    """Doc/demo helpers (reference symbol_doc.py SymbolDoc)."""

    @staticmethod
    def get_output_shape(sym, **input_shapes):
        """Map output names to inferred shapes for given input shapes."""
        _, s_outputs, _ = sym.infer_shape(**input_shapes)
        return dict(zip(sym.list_outputs(), s_outputs))


def list_ops():
    """All registered operator names (discovery surface parity with
    MXSymbolListAtomicSymbolCreators)."""
    return sorted(OP_REGISTRY.list())


def build_doc(op_name: str) -> str:
    """Render an op's docstring + parameter table from the registry,
    the way the reference generated Python docstrings from the C API's
    key/type/description triples."""
    op = OP_REGISTRY.get(op_name)
    lines = [f"{op_name}", ""]
    doc = (getattr(op, "__doc__", None)
           or getattr(type(op), "__doc__", None) or "")
    if doc:
        lines += [doc.strip(), ""]
    param_cls = getattr(op, "param_cls", None)
    if param_cls is not None:
        lines.append("Parameters")
        lines.append("----------")
        for fname, fld in getattr(param_cls, "_fields", {}).items():
            typ = getattr(fld, "type", None)
            tname = getattr(typ, "__name__", str(typ))
            default = getattr(fld, "default", None)
            req = getattr(fld, "required", False)
            spec = f"{fname} : {tname}"
            spec += ", required" if req else f", optional, default={default!r}"
            lines.append(spec)
            fdoc = getattr(fld, "doc", None)
            if fdoc:
                lines.append(f"    {fdoc}")
    return "\n".join(lines)
