"""Module: single-symbol data-parallel training module.

Rebuild of python/mxnet/module/module.py: owns a DataParallelExecutorGroup
over a list of contexts, CPU-resident master params, and the
kvstore-mediated update paths (``_update_params_on_kvstore`` /
``_update_params``, reference model.py:87-115) with per-key priority
hints for comm/compute overlap.
"""

from __future__ import annotations

import logging

import numpy as np

from .. import context as ctx_mod
from .. import ndarray as nd
from .. import optimizer as opt
from ..base import MXNetError, env_flag
from ..initializer import Uniform
from ..kvstore import KVStore
from ..model import (_create_kvstore, _initialize_kvstore, _update_params,
                     _update_params_on_kvstore, load_checkpoint, save_checkpoint)
from .base_module import BaseModule
from .executor_group import DataParallelExecutorGroup
from .fused_step import FusedTrainStep

__all__ = ["Module"]


class Module(BaseModule):
    def __init__(self, symbol, data_names=("data",), label_names=("softmax_label",),
                 logger=logging, context=None, work_load_list=None,
                 fixed_param_names=None):
        super().__init__(logger=logger)
        if context is None:
            context = [ctx_mod.current_context()]
        if isinstance(context, ctx_mod.Context):
            context = [context]
        self._context = context
        self._work_load_list = work_load_list or [1] * len(context)
        self._symbol = symbol
        self._data_names = list(data_names or [])
        self._label_names = list(label_names or [])
        self._fixed_param_names = list(fixed_param_names or [])

        arg_names = symbol.list_arguments()
        self._param_names = [n for n in arg_names
                             if n not in self._data_names
                             and n not in self._label_names]
        self._aux_names = symbol.list_auxiliary_states()
        self._output_names = symbol.list_outputs()

        self._arg_params = None
        self._aux_params = None
        # dirty = device arrays newer than the CPU master dicts.  Held
        # in a one-element list so modules sharing one set of params
        # (shared_module) share ONE flag: an update through any of them
        # makes get_params on all of them resync
        self._dirty_ref = [False]
        self._exec_group = None
        self._optimizer = None
        self._kvstore = None
        self._update_on_kvstore = None
        self._updater = None
        self._fused = None

    @property
    def _params_dirty(self):
        return self._dirty_ref[0]

    @_params_dirty.setter
    def _params_dirty(self, value):
        self._dirty_ref[0] = value

    @property
    def data_names(self):
        return self._data_names

    @property
    def label_names(self):
        return self._label_names

    @property
    def output_names(self):
        return self._output_names

    @property
    def data_shapes(self):
        return self._exec_group.data_shapes

    @property
    def label_shapes(self):
        return self._exec_group.label_shapes

    @property
    def output_shapes(self):
        _, out_shapes, _ = self._symbol.infer_shape(
            **{d.name: d.shape for d in self.data_shapes})
        return list(zip(self._output_names, out_shapes))

    # -- bind --------------------------------------------------------------
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        if force_rebind:
            self._exec_group = None
            self._fused = None
            self.binded = False
        if self.binded:
            self.logger.warning("Already binded, ignoring bind()")
            return
        shared_group = None
        arg_params = aux_params = None
        if shared_module is not None:
            # the reference requires both (module.py:260-261); an
            # uninitialized donor would let two modules write divergent
            # random inits into the SAME aliased arrays.  Validate (and
            # sync a dirty donor) BEFORE mutating any state so a raise
            # leaves this module cleanly unbound
            if not (shared_module.binded and shared_module.params_initialized):
                raise MXNetError(
                    "shared_module must be binded and params-initialized")
            missing = [n for n in self._param_names + self._aux_names
                       if n not in shared_module._arg_params
                       and n not in shared_module._aux_params]
            if missing:
                raise MXNetError(
                    f"shared_module does not hold parameters {missing}: "
                    "every param/aux of a sharing module must exist in "
                    "the donor (the shared master dicts would otherwise "
                    "have no entry to sync them into)")
            shared_module.get_params()   # device->master sync if dirty
            shared_group = shared_module._exec_group
            arg_params = shared_module._arg_params
            aux_params = shared_module._aux_params
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self.binded = True
        self._fused = None  # executor changes: stale fused program
        self._exec_group = DataParallelExecutorGroup(
            self._symbol, self._context, self._work_load_list, data_shapes,
            label_shapes, self._param_names, for_training, inputs_need_grad,
            shared_group=shared_group, logger=self.logger,
            fixed_param_names=self._fixed_param_names, grad_req=grad_req)
        if shared_module is not None:
            # share the master param dicts AND the dirty flag (reference
            # module.py:285-288) — both modules see every update.  Every
            # param/aux array ALIASES the donor's (simple_bind raises on
            # any name/shape/dtype/ctx mismatch and the donor-coverage
            # check above rejects extras), so no set_params push is
            # needed — the aliased arrays already hold the live values
            self.params_initialized = True
            self._arg_params = arg_params
            self._aux_params = aux_params
            self._dirty_ref = shared_module._dirty_ref
        elif self._arg_params is not None:
            # params from a previous bind/init: push into new executors
            self._exec_group.set_params(self._arg_params, self._aux_params)
        if shared_module is not None and shared_module.optimizer_initialized:
            self.borrow_optimizer(shared_module)

    def borrow_optimizer(self, shared_module):
        """Share the optimizer/updater/kvstore of an already-initialized
        module so update counts and state are one (reference
        module.py:362-370)."""
        if not shared_module.optimizer_initialized:
            raise MXNetError("optimizer of shared_module is not initialized")
        self._optimizer = shared_module._optimizer
        self._kvstore = shared_module._kvstore
        self._update_on_kvstore = shared_module._update_on_kvstore
        self._updater = shared_module._updater
        self._fused = None
        self.optimizer_initialized = True

    # -- params ------------------------------------------------------------
    def init_params(self, initializer=Uniform(0.01), arg_params=None,
                    aux_params=None, allow_missing=False, force_init=False):
        if self.params_initialized and not force_init:
            return
        if not self.binded:
            raise MXNetError("call bind before init_params")
        if self._arg_params is None:
            self._arg_params = {
                name: nd.zeros(exe_arr.shape, dtype=exe_arr.dtype)
                for name, exe_arr in zip(
                    self._param_names,
                    [self._exec_group.execs[0].arg_dict[n]
                     for n in self._param_names])}
            self._aux_params = {
                name: nd.zeros(exe_arr.shape, dtype=exe_arr.dtype)
                for name, exe_arr in zip(
                    self._aux_names,
                    [self._exec_group.execs[0].aux_dict[n]
                     for n in self._aux_names])}

        def _impl(name, arr, cache):
            if cache is not None and name in cache:
                cache_arr = cache[name]
                if cache_arr is not arr:
                    arr[:] = cache_arr
            elif not allow_missing and initializer is None:
                raise MXNetError(f"{name} is not presented")
            elif initializer is not None:
                initializer(name, arr)

        for name, arr in sorted(self._arg_params.items()):
            _impl(name, arr, arg_params)
        for name, arr in sorted(self._aux_params.items()):
            _impl(name, arr, aux_params)

        self.params_initialized = True
        self._params_dirty = False
        self._exec_group.set_params(self._arg_params, self._aux_params)

    def get_params(self):
        if not (self.binded and self.params_initialized):
            raise MXNetError("module must be binded and initialized")
        if self._params_dirty:
            self._sync_params_from_devices()
        return self._arg_params, self._aux_params

    def _sync_params_from_devices(self):
        """Device -> CPU master copy (reference module.py:472)."""
        self._exec_group.get_params(self._arg_params, self._aux_params)
        self._params_dirty = False

    # -- optimizer ---------------------------------------------------------
    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        if not (self.binded and self.params_initialized):
            raise MXNetError("module must be binded and initialized")
        if self.optimizer_initialized and not force_init:
            self.logger.warning("optimizer already initialized, ignoring")
            return
        (kvstore, update_on_kvstore) = _create_kvstore(
            kvstore, len(self._context), self._arg_params)
        if isinstance(optimizer, str):
            batch_size = self._exec_group.batch_size
            if kvstore and kvstore.type == "dist_sync":
                batch_size *= kvstore.num_workers
            idx2name = dict(enumerate(self._param_names))
            optimizer_params = dict(optimizer_params)
            # default rescale to 1/global-batch; explicit user value wins
            optimizer_params.setdefault("rescale_grad", 1.0 / batch_size)
            optimizer = opt.create(
                optimizer, param_idx2name=idx2name, sym=self._symbol,
                **optimizer_params)
        self._optimizer = optimizer
        self._kvstore = kvstore
        self._update_on_kvstore = update_on_kvstore
        self._updater = None
        if kvstore:
            _initialize_kvstore(kvstore=kvstore,
                                param_arrays=self._exec_group.param_arrays,
                                arg_params=self._arg_params,
                                param_names=self._param_names,
                                update_on_kvstore=update_on_kvstore)
        if update_on_kvstore and kvstore:
            kvstore.set_optimizer(self._optimizer)
        else:
            self._updater = opt.get_updater(optimizer)
        self._fused = None  # optimizer changed: rebuild the fused program
        self.optimizer_initialized = True

    # -- fused train step --------------------------------------------------
    def _select_fused(self):
        """The single-dispatch :class:`FusedTrainStep` when this module
        can take it, else None (→ classic per-param loop).

        Eligibility mirrors what the one compiled program can express:
        a single context / single executor without ctx-group segments,
        local (non-kvstore) updates through the module's own updater, a
        ``step_param``-capable optimizer, plain ``write`` grads over the
        module's own parameters, and no monitor (monitoring needs the
        eager per-node path).  ``MXTPU_FUSED_STEP=0`` force-disables.
        """
        from . import fused_step as fused_step_mod

        def _no(reason):
            # every fallback verdict lands in the /statusz selection
            # log — "why is training unfused?" without a debugger
            fused_step_mod.note_selection(False, reason)
            return None

        if not env_flag("MXTPU_FUSED_STEP"):
            return _no("env_disabled")
        if self._fused is not None:
            # fast path for the per-batch call in custom train_step
            # loops: the full eligibility scan below is O(num_params)
            # host work; every mutation that could flip the verdict
            # (bind, init_optimizer, borrow_optimizer, install_monitor)
            # resets self._fused to None
            return self._fused
        if not (self.binded and self.params_initialized
                and self.optimizer_initialized and self.for_training):
            return _no("not_ready")
        if self._update_on_kvstore or self._kvstore is not None:
            return _no("kvstore")
        if self._updater is None or \
                getattr(self._updater, "optimizer", None) is not self._optimizer:
            return _no("custom_updater")  # unknown numerics
        if not getattr(self._optimizer, "supports_step_tree", False):
            return _no("optimizer_no_step_tree")
        if len(self._context) != 1 or len(self._exec_group.execs) != 1:
            return _no("multi_context")
        exe = self._exec_group.execs[0]
        if getattr(exe, "_multi_ctx", False) \
                or exe._monitor_callback is not None:
            return _no("monitor_or_ctx_groups")
        if not exe._grad_names:
            return _no("no_trainable_grads")
        if not set(exe._grad_names) <= set(self._param_names):
            return _no("inputs_need_grad")  # input grads need backward()
        if any(exe._grad_req[n] != "write" for n in exe._grad_names):
            return _no("grad_req_not_write")
        self._fused = FusedTrainStep(
            exe, self._optimizer, self._updater, self._param_names,
            self._exec_group.data_names, self._exec_group.label_names)
        fused_step_mod.note_selection(True, "eligible")
        return self._fused

    def train_step(self, data_batch):
        """One forward+backward+update.  Takes the fused single-dispatch
        program when eligible; otherwise the classic loop.  Returns True
        when the fused path ran."""
        fused = self._select_fused()
        if fused is None:
            return super().train_step(data_batch)
        fused.step(data_batch)
        self._params_dirty = True
        return True

    def _stage_batch(self, data_batch):
        """Move a batch's arrays to the (single) device ahead of the
        step that consumes it — ``jax.device_put`` is non-blocking, so
        staging batch t+1 overlaps the in-flight step t."""
        if data_batch is None or len(self._context) != 1:
            return data_batch
        import jax

        from ..io import DataBatch
        from ..optimizer import _dispatch_inc

        ctx = self._context[0]
        dev = ctx.jax_device()

        def put(arrs):
            out = []
            for a in arrs or []:
                raw = a._data if isinstance(a, nd.NDArray) else np.asarray(a)
                out.append(nd.NDArray(jax.device_put(raw, dev), ctx))
            return out

        _dispatch_inc(self, "stage")
        return DataBatch(put(data_batch.data), put(data_batch.label),
                         data_batch.pad, data_batch.index)

    # -- compute -----------------------------------------------------------
    def forward(self, data_batch, is_train=None):
        if not (self.binded and self.params_initialized):
            raise MXNetError("module must be binded and initialized")
        self._exec_group.forward(data_batch, is_train)

    def backward(self, out_grads=None):
        if not (self.binded and self.params_initialized):
            raise MXNetError("module must be binded and initialized")
        self._exec_group.backward(out_grads)

    def update(self):
        """Apply optimizer using kvstore-aggregated grads
        (reference module.py:403 / model.py:87-115)."""
        if not (self.binded and self.params_initialized
                and self.optimizer_initialized):
            raise MXNetError("module not fully initialized")
        self._params_dirty = True
        if self._update_on_kvstore:
            _update_params_on_kvstore(self._exec_group.param_arrays,
                                      self._exec_group.grad_arrays,
                                      self._kvstore)
        else:
            _update_params(self._exec_group.param_arrays,
                           self._exec_group.grad_arrays,
                           updater=self._updater,
                           num_device=len(self._context),
                           kvstore=self._kvstore)

    def get_outputs(self, merge_multi_context=True):
        return self._exec_group.get_outputs(merge_multi_context)

    def get_input_grads(self, merge_multi_context=True):
        return self._exec_group.get_input_grads(merge_multi_context)

    def update_metric(self, eval_metric, labels):
        self._exec_group.update_metric(eval_metric, labels)

    def install_monitor(self, mon):
        if not self.binded:
            raise MXNetError("call bind first")
        self._fused = None  # monitors need the eager per-node path
        self._exec_group.install_monitor(mon)

    # -- checkpoint --------------------------------------------------------
    def save_checkpoint(self, prefix, epoch, save_optimizer_states=False):
        self._symbol.save(f"{prefix}-symbol.json")
        arg_params, aux_params = self.get_params()
        save_checkpoint(prefix, epoch, self._symbol, arg_params, aux_params)
        if save_optimizer_states:
            if self._update_on_kvstore:
                self._kvstore.save_optimizer_states(f"{prefix}-{epoch:04d}.states")
            else:
                import pickle

                with open(f"{prefix}-{epoch:04d}.states", "wb") as f:
                    f.write(pickle.dumps(self._updater.states))

    @staticmethod
    def load(prefix, epoch, load_optimizer_states=False, **kwargs):
        sym, args, auxs = load_checkpoint(prefix, epoch)
        mod = Module(symbol=sym, **kwargs)
        mod._arg_params = args
        mod._aux_params = auxs
        mod.params_initialized = True
        if load_optimizer_states:
            mod._preload_opt_states = f"{prefix}-{epoch:04d}.states"
        return mod
