"""BucketingModule: variable-length training via per-bucket executors.

Rebuild of python/mxnet/module/bucketing_module.py.  ``sym_gen(bucket_key)``
returns (symbol, data_names, label_names); one Module per bucket key is
bound lazily and parameters are shared across buckets
(``switch_bucket``, reference bucketing_module.py:195-220).  Where the
reference shares a GraphStoragePool across bucket executors
(graph_executor.h:50-56), here XLA compiles one program per bucket shape
and JAX's compilation cache plays the shared-pool role; padded-shape
buckets bound the number of recompiles (SURVEY.md §5 long-context).
"""

from __future__ import annotations

import logging

from ..base import MXNetError
from ..initializer import Uniform
from .base_module import BaseModule
from .module import Module

__all__ = ["BucketingModule"]


class BucketingModule(BaseModule):
    def __init__(self, sym_gen, default_bucket_key=None, logger=logging,
                 context=None, work_load_list=None):
        super().__init__(logger=logger)
        if default_bucket_key is None:
            raise ValueError("default_bucket_key must be set")
        self._sym_gen = sym_gen
        self._default_bucket_key = default_bucket_key
        self._context = context
        self._work_load_list = work_load_list
        self._buckets = {}
        self._curr_module = None
        self._curr_bucket_key = None

    @property
    def default_bucket_key(self):
        return self._default_bucket_key

    @property
    def symbol(self):
        return self._curr_module.symbol

    @property
    def data_names(self):
        if self.binded:
            return self._curr_module.data_names
        sym, data_names, _ = self._call_sym_gen(self._default_bucket_key)
        return data_names

    @property
    def output_names(self):
        if self.binded:
            return self._curr_module.output_names
        sym, _, _ = self._call_sym_gen(self._default_bucket_key)
        return sym.list_outputs()

    @property
    def data_shapes(self):
        return self._curr_module.data_shapes

    @property
    def label_shapes(self):
        return self._curr_module.label_shapes

    @property
    def output_shapes(self):
        return self._curr_module.output_shapes

    def _call_sym_gen(self, bucket_key):
        return self._sym_gen(bucket_key)

    # -- bind / switch -----------------------------------------------------
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        if force_rebind:
            self._buckets = {}
            self.binded = False
        if self.binded:
            self.logger.warning("Already binded, ignoring bind()")
            return
        if shared_module is not None and not isinstance(shared_module,
                                                        BucketingModule):
            raise MXNetError(
                "shared_module for BucketingModule must itself be a "
                "BucketingModule")
        if shared_module is not None and not (shared_module.binded
                                              and shared_module.params_initialized):
            raise MXNetError(
                "shared_module must be binded and params-initialized")
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        sym, data_names, label_names = self._call_sym_gen(self._default_bucket_key)
        # external sharing (beyond the reference, which asserts
        # shared_module is None here — bucketing_module.py:176): a
        # train/eval BucketingModule pair shares one set of parameter
        # arrays and one optimizer through the default-bucket Module;
        # each bucket bound later inherits the sharing via switch_bucket
        shared_default = (
            shared_module._buckets[shared_module._default_bucket_key]
            if shared_module is not None else None)
        module = Module(sym, data_names, label_names, logger=self.logger,
                        context=self._context,
                        work_load_list=self._work_load_list)
        module.bind(data_shapes, label_shapes, for_training, inputs_need_grad,
                    force_rebind=False, shared_module=shared_default,
                    grad_req=grad_req)
        self.binded = True
        self._curr_module = module
        self._curr_bucket_key = self._default_bucket_key
        self._buckets[self._default_bucket_key] = module
        if shared_module is not None:
            self.params_initialized = True
        if module.optimizer_initialized:
            self._shared_optimizer_source = module
            self.optimizer_initialized = True

    def switch_bucket(self, bucket_key, data_shapes, label_shapes=None):
        """Switch to (lazily binding) the bucket's module, sharing params
        with the default bucket (reference bucketing_module.py:195).
        Like the reference, binding a NEW bucket requires init_params to
        have run (Module.bind's shared_module contract)."""
        if not self.binded:
            raise MXNetError("call bind before switch_bucket")
        if bucket_key not in self._buckets:
            sym, data_names, label_names = self._call_sym_gen(bucket_key)
            module = Module(sym, data_names, label_names, logger=self.logger,
                            context=self._context,
                            work_load_list=self._work_load_list)
            module.bind(data_shapes, label_shapes, self._curr_module.for_training,
                        self._curr_module.inputs_need_grad, force_rebind=False,
                        shared_module=self._buckets[self._default_bucket_key])
            self._buckets[bucket_key] = module
        self._curr_module = self._buckets[bucket_key]
        self._curr_bucket_key = bucket_key

    # -- params ------------------------------------------------------------
    def init_params(self, initializer=Uniform(0.01), arg_params=None,
                    aux_params=None, allow_missing=False, force_init=False):
        if self.params_initialized and not force_init:
            return
        if not self.binded:
            raise MXNetError("call bind before init_params")
        self._curr_module.init_params(initializer=initializer,
                                      arg_params=arg_params,
                                      aux_params=aux_params,
                                      allow_missing=allow_missing,
                                      force_init=force_init)
        self.params_initialized = True

    def get_params(self):
        return self._curr_module.get_params()

    # -- optimizer ---------------------------------------------------------
    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        if self.optimizer_initialized and not force_init:
            return
        self._curr_module.init_optimizer(kvstore, optimizer, optimizer_params,
                                         force_init=force_init)
        self._shared_optimizer_source = self._curr_module
        self.optimizer_initialized = True

    def _propagate_optimizer(self, module):
        """Reuse the one optimizer/updater/kvstore across bucket modules so
        update counts and state are shared."""
        module.borrow_optimizer(self._shared_optimizer_source)

    # -- compute -----------------------------------------------------------
    def forward(self, data_batch, is_train=None):
        """Switches bucket based on data_batch.bucket_key."""
        if data_batch.bucket_key is not None:
            self.switch_bucket(data_batch.bucket_key,
                               data_batch.provide_data,
                               data_batch.provide_label)
            if self.optimizer_initialized and not self._curr_module.optimizer_initialized:
                self._propagate_optimizer(self._curr_module)
        self._curr_module.forward(data_batch, is_train=is_train)

    def backward(self, out_grads=None):
        self._curr_module.backward(out_grads)

    def update(self):
        # bucket modules ALIAS one set of parameter arrays and one dirty
        # flag (shared_exec wiring in switch_bucket -> Module.bind ->
        # simple_bind), so the update is visible to every bucket without
        # a propagation copy — the same single-copy semantics as the
        # reference's shared executor memory (executor_group.py:439-533)
        self._curr_module.update()

    def get_outputs(self, merge_multi_context=True):
        return self._curr_module.get_outputs(merge_multi_context)

    def get_input_grads(self, merge_multi_context=True):
        return self._curr_module.get_input_grads(merge_multi_context)

    def update_metric(self, eval_metric, labels):
        self._curr_module.update_metric(eval_metric, labels)

    def install_monitor(self, mon):
        for module in self._buckets.values():
            module.install_monitor(mon)
