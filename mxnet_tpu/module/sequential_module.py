"""SequentialModule: chain of modules executed back to back.

Rebuild of python/mxnet/module/sequential_module.py — forward feeds each
module's outputs as the next module's data; backward chains input grads.
"""

from __future__ import annotations

import logging

from ..base import MXNetError
from ..initializer import Uniform
from ..io import DataBatch
from .base_module import BaseModule

__all__ = ["SequentialModule"]


class SequentialModule(BaseModule):
    META_TAKE_LABELS = "take_labels"
    META_AUTO_WIRING = "auto_wiring"

    def __init__(self, logger=logging):
        super().__init__(logger=logger)
        self._modules = []
        self._metas = []
        self._label_shapes = None

    def add(self, module, **kwargs):
        self._modules.append(module)
        self._metas.append(kwargs)
        self.binded = False
        self.params_initialized = False
        self.optimizer_initialized = False
        return self

    @property
    def data_names(self):
        if self._modules:
            return self._modules[0].data_names
        return []

    @property
    def output_names(self):
        if self._modules:
            return self._modules[-1].output_names
        return []

    @property
    def data_shapes(self):
        return self._modules[0].data_shapes

    @property
    def label_shapes(self):
        return self._label_shapes

    @property
    def output_shapes(self):
        return self._modules[-1].output_shapes

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        if self.binded and not force_rebind:
            self.logger.warning("Already binded, ignoring bind()")
            return
        if shared_module is not None:
            # beyond the reference (which asserts None here,
            # sequential_module.py:217): share layer-by-layer with a
            # structurally identical SequentialModule
            if not isinstance(shared_module, SequentialModule):
                raise MXNetError(
                    "shared_module for SequentialModule must itself be a "
                    "SequentialModule")
            if len(shared_module._modules) != len(self._modules):
                raise MXNetError(
                    "shared_module must contain the same number of "
                    f"sub-modules ({len(shared_module._modules)} vs "
                    f"{len(self._modules)})")
            if not (shared_module.binded and shared_module.params_initialized):
                raise MXNetError(
                    "shared_module must be binded and params-initialized")
        if not self._modules:
            raise MXNetError("add modules first")
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self._label_shapes = label_shapes

        my_data_shapes = data_shapes
        anybody_ever_needs_label = False
        for i_layer, (meta, module) in enumerate(zip(self._metas, self._modules)):
            meta_take_labels = meta.get(self.META_TAKE_LABELS, False)
            if meta_take_labels:
                my_label_shapes = label_shapes
                anybody_ever_needs_label = True
            else:
                my_label_shapes = None
            my_inputs_need_grad = for_training and (inputs_need_grad or i_layer > 0)
            if meta.get(self.META_AUTO_WIRING, False):
                data_names = module.data_names
                my_data_shapes = [(new_name, shape[1]) for new_name, shape in
                                  zip(data_names, my_data_shapes)]
            module.bind(data_shapes=my_data_shapes, label_shapes=my_label_shapes,
                        for_training=for_training,
                        inputs_need_grad=my_inputs_need_grad,
                        force_rebind=force_rebind,
                        shared_module=(shared_module._modules[i_layer]
                                       if shared_module is not None else None),
                        grad_req=grad_req)
            my_data_shapes = module.output_shapes
        if not anybody_ever_needs_label:
            self._label_shapes = None
        self.binded = True
        if shared_module is not None:
            self.params_initialized = True
            if shared_module.optimizer_initialized:
                self.optimizer_initialized = True

    def init_params(self, initializer=Uniform(0.01), arg_params=None,
                    aux_params=None, allow_missing=False, force_init=False):
        if self.params_initialized and not force_init:
            return
        for module in self._modules:
            module.init_params(initializer=initializer, arg_params=arg_params,
                               aux_params=aux_params,
                               allow_missing=allow_missing,
                               force_init=force_init)
        # check no duplicated names
        self.params_initialized = True

    def get_params(self):
        arg_params, aux_params = {}, {}
        for module in self._modules:
            arg, aux = module.get_params()
            arg_params.update(arg)
            aux_params.update(aux)
        return arg_params, aux_params

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        if self.optimizer_initialized and not force_init:
            return
        for module in self._modules:
            module.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                                  optimizer_params=optimizer_params,
                                  force_init=force_init)
        self.optimizer_initialized = True

    def forward(self, data_batch, is_train=None):
        batch = DataBatch(data_batch.data, data_batch.label, data_batch.pad,
                          data_batch.index)
        for i, module in enumerate(self._modules):
            module.forward(batch, is_train=is_train)
            if i < len(self._modules) - 1:
                batch = DataBatch(module.get_outputs(), data_batch.label,
                                  data_batch.pad, data_batch.index)

    def backward(self, out_grads=None):
        for i, module in reversed(list(enumerate(self._modules))):
            module.backward(out_grads=out_grads)
            if i == 0:
                break
            out_grads = module.get_input_grads()

    def update(self):
        for module in self._modules:
            module.update()

    def get_outputs(self, merge_multi_context=True):
        return self._modules[-1].get_outputs(merge_multi_context)

    def get_input_grads(self, merge_multi_context=True):
        if not self.inputs_need_grad:
            raise MXNetError("bind with inputs_need_grad=True first")
        return self._modules[0].get_input_grads(merge_multi_context)

    def update_metric(self, eval_metric, labels):
        for meta, module in zip(self._metas, self._modules):
            if meta.get(self.META_TAKE_LABELS, False):
                module.update_metric(eval_metric, labels)

    def install_monitor(self, mon):
        for module in self._modules:
            module.install_monitor(mon)
