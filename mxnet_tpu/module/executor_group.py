"""Data-parallel executor group.

Rebuild of python/mxnet/module/executor_group.py: slice each batch across
device contexts (``decide_slices``), keep one bound executor per device,
fan out forward/backward, and merge outputs (``merge_multi_context``).
On TPU hardware each context is a chip; per-chip executors are fused XLA
programs and batch slices transfer host->device asynchronously.
"""

from __future__ import annotations

import numpy as np

from .. import ndarray as nd
from ..base import MXNetError
from ..io import DataDesc

__all__ = ["DataParallelExecutorGroup"]


def _split_input_slice(batch_size, work_load_list):
    """Slice ranges per device, weighted by workload
    (reference executor_manager.py:15-50)."""
    total = sum(work_load_list)
    if batch_size < len(work_load_list):
        raise MXNetError("batch size smaller than device count")
    slices = []
    start = 0
    for i, load in enumerate(work_load_list):
        if i == len(work_load_list) - 1:
            end = batch_size
        else:
            end = start + int(round(batch_size * load / total))
        slices.append(slice(start, end))
        start = end
    return slices


def _merge_multi_context(outputs):
    """Concatenate per-device outputs along the batch axis
    (reference executor_group.py:52)."""
    return [nd.concatenate(parts, axis=0) if len(parts) > 1 else parts[0]
            for parts in outputs]


class DataParallelExecutorGroup:
    def __init__(self, symbol, contexts, workload, data_shapes, label_shapes,
                 param_names, for_training, inputs_need_grad, shared_group=None,
                 logger=None, fixed_param_names=None, grad_req="write"):
        self.symbol = symbol
        self.contexts = contexts
        self.workload = workload or [1] * len(contexts)
        self.param_names = list(param_names)
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self.fixed_param_names = set(fixed_param_names or [])
        self.arg_names = symbol.list_arguments()
        self.aux_names = symbol.list_auxiliary_states()
        self.execs = []
        self.shared_group = shared_group
        if shared_group is not None and list(shared_group.contexts) != list(
                contexts):
            # silent partial sharing (some executors aliased, others
            # fresh) would leave the extras training on stale weights;
            # the reference's _bind_ith_exec likewise requires matching
            # device lists
            raise MXNetError(
                f"shared_group contexts {shared_group.contexts} do not "
                f"match this group's contexts {contexts}")

        self.grad_req = {}
        for name in self.arg_names:
            if name in self.param_names:
                self.grad_req[name] = (
                    "null" if not for_training or name in self.fixed_param_names
                    else grad_req)
            elif inputs_need_grad and any(name == d[0] for d in data_shapes):
                self.grad_req[name] = grad_req
            else:
                self.grad_req[name] = "null"

        self.bind_exec(data_shapes, label_shapes)

    # -- binding -----------------------------------------------------------
    @staticmethod
    def _batch_axis(desc):
        """Batch ('N') axis of one input from its layout; -1 = no batch
        axis, the input is replicated whole to every device (reference
        executor_group.py:193 major_axis)."""
        return DataDesc.get_batch_axis(getattr(desc, "layout", None))

    def decide_slices(self, data_shapes):
        """Batch-axis slicing honoring per-input layouts
        (reference executor_group.py:193): every input with a batch axis
        must agree on the batch size; axis -1 inputs are not sliced."""
        self.batch_axes = {}
        batch_size = None
        for desc in data_shapes:
            axis = self._batch_axis(desc)
            self.batch_axes[desc.name] = axis
            if axis == -1:
                continue
            b = desc.shape[axis]
            if batch_size is None:
                batch_size = b
            elif b != batch_size:
                raise MXNetError(
                    f"all data must share one batch size: {desc.name} has "
                    f"shape {desc.shape} (axis {axis}) vs batch {batch_size}"
                    "; give no-batch-axis inputs a layout without 'N' "
                    "via mx.io.DataDesc")
        if batch_size is None:
            raise MXNetError("at least one input needs a batch axis")
        self.batch_size = batch_size
        self.slices = _split_input_slice(batch_size, self.workload)

    def _sliced_shape(self, shape, islice, axis=0):
        if axis == -1:
            return tuple(shape)
        shape = list(shape)
        shape[axis] = islice.stop - islice.start
        return tuple(shape)

    @staticmethod
    def _slice_along(arr, islice, axis):
        if axis == -1:
            return arr
        if axis == 0:
            return arr[islice]
        return arr[(slice(None),) * axis + (islice,)]

    def bind_exec(self, data_shapes, label_shapes):
        self.data_shapes = [DataDesc(*d) if not isinstance(d, DataDesc) else d
                            for d in data_shapes]
        self.label_shapes = ([DataDesc(*l) if not isinstance(l, DataDesc) else l
                              for l in label_shapes] if label_shapes else [])
        self.decide_slices(self.data_shapes + self.label_shapes)
        self.data_names = [d.name for d in self.data_shapes]
        self.label_names = [l.name for l in self.label_shapes]
        self.execs = []
        for i, ctx in enumerate(self.contexts):
            islice = self.slices[i]
            shapes = {d.name: self._sliced_shape(d.shape, islice,
                                                 self.batch_axes[d.name])
                      for d in self.data_shapes + self.label_shapes}
            # memory sharing across bound groups (reference
            # _bind_ith_exec shared_exec, executor_group.py:439-533):
            # the i-th executor of the shared group donates its
            # matching param/grad/aux arrays
            shared_exec = (self.shared_group.execs[i]
                           if self.shared_group is not None
                           and i < len(self.shared_group.execs) else None)
            exe = self.symbol.simple_bind(ctx, grad_req=self.grad_req,
                                          shared_exec=shared_exec, **shapes)
            self.execs.append(exe)

    # -- params ------------------------------------------------------------
    def set_params(self, arg_params, aux_params, allow_extra=False):
        for exe in self.execs:
            exe.copy_params_from(arg_params, aux_params,
                                 allow_extra_params=allow_extra)

    def get_params(self, arg_params, aux_params):
        """Copy params back to CPU dicts (reference: averages over devices
        to wash out any drift)."""
        for name in self.param_names:
            arrs = [exe.arg_dict[name] for exe in self.execs]
            weight = sum(a.asnumpy() for a in arrs) / len(arrs)
            arg_params[name][:] = weight
        for name in self.aux_names:
            arrs = [exe.aux_dict[name] for exe in self.execs]
            aux = sum(a.asnumpy() for a in arrs) / len(arrs)
            aux_params[name][:] = aux

    # -- compute -----------------------------------------------------------
    def load_data_batch(self, data_batch):
        """Stage a batch for a bare ``forward`` (reference
        executor_group load_data_batch).  Arrays are SNAPSHOTTED — the
        reference copies to device at load, so a data pipeline that
        recycles its batch buffers between load and forward must not
        leak the mutation into training (same contract as
        DataParallelExecutorManager.load_data_batch)."""
        from ..io import DataBatch as _DataBatch

        def _snap(arrs):
            return [a.copy() if hasattr(a, "copy") else np.array(a)
                    for a in (arrs or [])]

        self._staged_batch = _DataBatch(
            _snap(data_batch.data), _snap(data_batch.label),
            data_batch.pad, data_batch.index)

    def forward(self, data_batch=None, is_train=None):
        if data_batch is None:
            data_batch = getattr(self, "_staged_batch", None)
            if data_batch is None:
                raise MXNetError("no batch: pass one or load_data_batch first")
        else:
            # "bare forward re-runs the last batch" must mean the MOST
            # RECENT one, however it arrived
            self._staged_batch = data_batch
        if is_train is None:
            is_train = self.for_training
        data = data_batch.data
        labels = data_batch.label or []
        for i, exe in enumerate(self.execs):
            islice = self.slices[i]
            for name, arr in zip(self.data_names, data):
                exe.arg_dict[name][:] = self._slice_along(
                    arr, islice, self.batch_axes[name])
            for name, arr in zip(self.label_names, labels):
                if name in exe.arg_dict:
                    exe.arg_dict[name][:] = self._slice_along(
                        arr, islice, self.batch_axes[name])
            exe.forward(is_train=is_train)

    def get_outputs(self, merge_multi_context=True):
        outputs = [[exe.outputs[i] for exe in self.execs]
                   for i in range(len(self.execs[0].outputs))]
        if merge_multi_context:
            return _merge_multi_context(outputs)
        return outputs

    def get_input_grads(self, merge_multi_context=True):
        if not self.inputs_need_grad:
            raise MXNetError("bind with inputs_need_grad=True first")
        grads = [[exe.grad_dict[name] for exe in self.execs]
                 for name in self.data_names]
        if not merge_multi_context:
            return grads
        merged = []
        for name, parts in zip(self.data_names, grads):
            axis = self.batch_axes[name]
            if len(parts) == 1:
                merged.append(parts[0])
            elif axis == -1:
                # replicated input: every device saw the whole array, so
                # per-device gradients sum (not concatenate)
                total = parts[0]
                for p in parts[1:]:
                    total = total + p
                merged.append(total)
            else:
                merged.append(nd.concatenate(parts, axis=axis))
        return merged

    def backward(self, out_grads=None):
        if not self.for_training:
            raise MXNetError("re-bind with for_training=True to call backward")
        for i, exe in enumerate(self.execs):
            if out_grads is None:
                exe.backward()
            else:
                islice = self.slices[i]
                exe.backward([g[islice] for g in out_grads])

    def update_metric(self, eval_metric, labels):
        if (getattr(eval_metric, "device_active", False)
                and len(self.execs) == 1
                and len(labels) == len(self.execs[0].outputs)):
            # device-side accumulation: one async jitted contribution,
            # no asnumpy stall.  Pairing must be positional 1:1 (the
            # host kernels zip the same way); anything else — multiple
            # devices, label/output arity mismatch — keeps the host path
            eval_metric.update_device(labels, self.execs[0].outputs)
            return
        # labels pair positionally with the bound label names; extra
        # labels beyond the bound names (incl. the bound-without-labels
        # case) slice along axis 0
        axes = [self.batch_axes.get(n, 0)
                for n in self.label_names[:len(labels)]]
        axes += [0] * (len(labels) - len(axes))
        for i, exe in enumerate(self.execs):
            islice = self.slices[i]
            labels_slice = [self._slice_along(label, islice, axis)
                            for axis, label in zip(axes, labels)]
            eval_metric.update(labels_slice, exe.outputs)

    @property
    def grad_arrays(self):
        """Per-param list of per-device gradient NDArrays; fixed params
        (grad_req null) have no gradient buffer and yield None, which
        the updater paths skip (reference model.py:98-115 contract)."""
        return [[exe.grad_dict.get(name) for exe in self.execs]
                for name in self.param_names]

    @property
    def param_arrays(self):
        return [[exe.arg_dict[name] for exe in self.execs]
                for name in self.param_names]

    @property
    def aux_arrays(self):
        return [[exe.aux_dict[name] for exe in self.execs]
                for name in self.aux_names]

    def install_monitor(self, mon):
        for exe in self.execs:
            mon.install(exe)
