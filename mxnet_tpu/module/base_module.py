"""BaseModule: the high-level train/predict template.

Rebuild of python/mxnet/module/base_module.py — ``fit`` (base_module.py:288),
``score``, ``predict``, ``forward_backward``, parameter get/set — over the
abstract interface (bind / init_params / init_optimizer / forward /
backward / update / update_metric) that Module, BucketingModule,
SequentialModule and PythonModule implement.
"""

from __future__ import annotations

import logging
import time

import numpy as np

from .. import metric as metric_mod
from .. import ndarray as nd
from .. import telemetry
from ..lint.annotations import hot_path
from ..base import MXNetError, env_flag, env_int
from ..callback import BatchEndParam
from ..initializer import Uniform

__all__ = ["BaseModule"]


class BaseModule:
    def __init__(self, logger=logging):
        self.logger = logger
        self.binded = False
        self.for_training = False
        self.inputs_need_grad = False
        self.params_initialized = False
        self.optimizer_initialized = False
        self._symbol = None

    # ------------------------------------------------------------------ #
    # abstract interface                                                  #
    # ------------------------------------------------------------------ #
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        raise NotImplementedError

    def init_params(self, initializer=Uniform(0.01), arg_params=None,
                    aux_params=None, allow_missing=False, force_init=False):
        raise NotImplementedError

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        raise NotImplementedError

    def forward(self, data_batch, is_train=None):
        raise NotImplementedError

    def backward(self, out_grads=None):
        raise NotImplementedError

    def update(self):
        raise NotImplementedError

    def update_metric(self, eval_metric, labels):
        raise NotImplementedError

    def get_outputs(self, merge_multi_context=True):
        raise NotImplementedError

    def get_input_grads(self, merge_multi_context=True):
        raise NotImplementedError

    def get_params(self):
        raise NotImplementedError

    def set_params(self, arg_params, aux_params, allow_missing=False,
                   force_init=True):
        self.init_params(initializer=None, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=allow_missing,
                         force_init=force_init)

    @property
    def data_names(self):
        raise NotImplementedError

    @property
    def output_names(self):
        raise NotImplementedError

    @property
    def data_shapes(self):
        raise NotImplementedError

    @property
    def label_shapes(self):
        raise NotImplementedError

    @property
    def output_shapes(self):
        raise NotImplementedError

    @property
    def symbol(self):
        return self._symbol

    # ------------------------------------------------------------------ #
    # composite operations                                                #
    # ------------------------------------------------------------------ #
    def forward_backward(self, data_batch):
        self.forward(data_batch, is_train=True)
        self.backward()

    def train_step(self, data_batch):
        """One forward+backward+optimizer step.  Subclasses with a
        fused single-dispatch program (Module) override; the default is
        the classic two-phase loop.  Returns True when fused."""
        self.forward_backward(data_batch)
        self.update()
        return False

    def _select_fused(self):
        """Fused-train-step object when this module supports the
        single-dispatch path (Module overrides), else None."""
        return None

    def _stage_batch(self, data_batch):
        """Pre-stage a batch's arrays onto the device (non-blocking);
        default no-op for modules without a single device context."""
        return data_batch

    def score(self, eval_data, eval_metric, num_batch=None,
              batch_end_callback=None, score_end_callback=None, reset=True,
              epoch=0):
        """Evaluate on a data iterator (base_module.py score)."""
        if not (self.binded and self.params_initialized):
            raise MXNetError("module must be binded and initialized")
        if reset:
            eval_data.reset()
        if not isinstance(eval_metric, metric_mod.EvalMetric):
            eval_metric = metric_mod.create(eval_metric)
        eval_metric.reset()
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            self.update_metric(eval_metric, eval_batch.label)
            if batch_end_callback is not None:
                param = BatchEndParam(epoch=epoch, nbatch=nbatch,
                                      eval_metric=eval_metric)
                for cb in _as_list(batch_end_callback):
                    cb(param)
        if score_end_callback is not None:
            param = BatchEndParam(epoch=epoch, nbatch=nbatch,
                                  eval_metric=eval_metric)
            for cb in _as_list(score_end_callback):
                cb(param)
        return eval_metric.get_name_value()

    def predict(self, eval_data, num_batch=None, merge_batches=True,
                reset=True, always_output_list=False):
        """Forward over an iterator, collecting outputs (base_module.py
        predict; strips pad rows like the reference)."""
        if not (self.binded and self.params_initialized):
            raise MXNetError("module must be binded and initialized")
        if reset:
            eval_data.reset()
        output_list = []
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            pad = eval_batch.pad
            outputs = [out[0:out.shape[0] - pad] for out in self.get_outputs()]
            output_list.append(outputs)
        if not output_list:
            return output_list
        if merge_batches:
            num_outputs = len(output_list[0])
            for out in output_list:
                if len(out) != num_outputs:
                    raise MXNetError("inconsistent output count across batches")
            merged = [nd.concatenate([out[i] for out in output_list])
                      for i in range(num_outputs)]
            if num_outputs == 1 and not always_output_list:
                return merged[0]
            return merged
        return output_list

    def fit(self, train_data, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None, kvstore="local",
            optimizer="sgd", optimizer_params=(("learning_rate", 0.01),),
            eval_end_callback=None, eval_batch_end_callback=None,
            initializer=Uniform(0.01), arg_params=None, aux_params=None,
            allow_missing=False, force_rebind=False, force_init=False,
            begin_epoch=0, num_epoch=None, validation_metric=None,
            monitor=None):
        """Train (reference base_module.py:288 fit)."""
        if num_epoch is None:
            raise MXNetError("num_epoch must be specified")
        self.bind(data_shapes=train_data.provide_data,
                  label_shapes=train_data.provide_label,
                  for_training=True, force_rebind=force_rebind)
        if monitor is not None:
            self.install_monitor(monitor)
        self.init_params(initializer=initializer, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=allow_missing,
                         force_init=force_init)
        self.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                            optimizer_params=optimizer_params)
        if validation_metric is None:
            validation_metric = eval_metric
        if not isinstance(eval_metric, metric_mod.EvalMetric):
            eval_metric = metric_mod.create(eval_metric)

        # telemetry handles resolved once per fit() call (no-op objects
        # when MXTPU_TELEMETRY is unset — the disabled-path contract)
        tel_batches = telemetry.counter(
            "mxtpu_fit_batches_total", "batches processed by fit()")
        tel_epochs = telemetry.counter(
            "mxtpu_fit_epochs_total", "epochs completed by fit()")
        tel_epoch_secs = telemetry.histogram(
            "mxtpu_fit_epoch_seconds", "wall time per epoch",
            buckets=(1.0, 5.0, 15.0, 60.0, 300.0, 1800.0, 7200.0))
        tel_phase = telemetry.histogram(
            "mxtpu_fit_phase_seconds", "per-batch fit-loop phase time",
            ("phase",))
        ph_data = tel_phase.labels(phase="data_wait")
        ph_fwbw = tel_phase.labels(phase="forward_backward")
        ph_update = tel_phase.labels(phase="update")
        ph_metric = tel_phase.labels(phase="update_metric")

        # single-dispatch path: forward+backward+update compiled into
        # one donated XLA program, async batch staging, and (when the
        # metric supports it) device-side metric accumulation so no
        # per-batch host sync remains.  Monitors force the classic loop
        # (_select_fused rejects them — they need eager execution).
        fused = self._select_fused() if monitor is None else None
        # registered only when taken, so the classic loop's phase set
        # stays exactly {data_wait, forward_backward, update, update_metric}
        ph_fused = (tel_phase.labels(phase="fused_step")
                    if fused is not None else None)
        if fused is not None and env_flag("MXTPU_DEVICE_METRICS"):
            eval_metric.device_accumulate(
                env_int("MXTPU_METRIC_SYNC_FREQUENT", 50))
        else:
            # explicit: a metric instance reused from an earlier fused
            # fit must follow THIS run's (classic/host) path
            eval_metric.device_accumulate(0)

        for epoch in range(begin_epoch, num_epoch):
            # perf_counter, not time.time(): NTP slews/steps make the
            # wall clock non-monotonic, so "Time cost=" lines could jump
            tic = time.perf_counter()
            eval_metric.reset()
            data_iter = iter(train_data)
            if fused is not None:
                nbatch = self._fit_epoch_fused(
                    data_iter, eval_metric, batch_end_callback, epoch,
                    ph_data, ph_fused, ph_metric, tel_batches)
            else:
                nbatch = 0
                while True:
                    t0 = time.perf_counter()
                    with telemetry.span("fit.data_wait"):
                        data_batch = next(data_iter, None)
                    if data_batch is None:
                        break
                    ph_data.observe(time.perf_counter() - t0)
                    if monitor is not None:
                        monitor.tic()
                    t0 = time.perf_counter()
                    with telemetry.span("fit.forward_backward"):
                        self.forward_backward(data_batch)
                    ph_fwbw.observe(time.perf_counter() - t0)
                    t0 = time.perf_counter()
                    with telemetry.span("fit.update"):
                        self.update()
                    ph_update.observe(time.perf_counter() - t0)
                    t0 = time.perf_counter()
                    with telemetry.span("fit.update_metric"):
                        self.update_metric(eval_metric, data_batch.label)
                    ph_metric.observe(time.perf_counter() - t0)
                    tel_batches.inc()
                    if monitor is not None:
                        monitor.toc_print()
                    if batch_end_callback is not None:
                        param = BatchEndParam(epoch=epoch, nbatch=nbatch,
                                              eval_metric=eval_metric)
                        for cb in _as_list(batch_end_callback):
                            cb(param)
                    nbatch += 1
            for name, val in eval_metric.get_name_value():
                self.logger.info("Epoch[%d] Train-%s=%f", epoch, name, val)
            epoch_secs = time.perf_counter() - tic
            tel_epochs.inc()
            tel_epoch_secs.observe(epoch_secs)
            if telemetry.enabled():
                # enclosing epoch span (same perf_counter clock as the
                # per-phase spans, so it nests around them in the trace)
                telemetry.tracer().add_complete(
                    "fit.epoch", tic, time.perf_counter(),
                    {"epoch": epoch, "batches": nbatch})
            self.logger.info("Epoch[%d] Time cost=%.3f", epoch, epoch_secs)

            arg_params, aux_params = self.get_params()
            if epoch_end_callback is not None:
                for cb in _as_list(epoch_end_callback):
                    cb(epoch, self.symbol, arg_params, aux_params)

            if eval_data is not None:
                res = self.score(eval_data, validation_metric,
                                 score_end_callback=eval_end_callback,
                                 batch_end_callback=eval_batch_end_callback,
                                 epoch=epoch)
                for name, val in res:
                    self.logger.info("Epoch[%d] Validation-%s=%f", epoch, name, val)
            train_data.reset()

    @hot_path
    def _fit_epoch_fused(self, data_iter, eval_metric, batch_end_callback,
                         epoch, ph_data, ph_fused, ph_metric, tel_batches):
        """One epoch on the single-dispatch path: each batch is one
        donated compiled program (forward+backward+whole-pytree update),
        batch t+1 is pulled from the iterator and staged to the device
        while step t is still in flight (JAX async dispatch — nothing
        here blocks), and metric accumulation stays on device until its
        sync point.  Returns the batch count."""
        from ..optimizer import _dispatch_inc

        nbatch = 0
        warned_fallback = False
        t0 = time.perf_counter()
        with telemetry.span("fit.data_wait"):
            nxt = next(data_iter, None)
        wait = time.perf_counter() - t0
        staged = self._stage_batch(nxt)
        while staged is not None:
            ph_data.observe(wait)
            batch = staged
            t0 = time.perf_counter()
            with telemetry.span("fit.fused_step"):
                fused_ran = self.train_step(batch)
            if fused_ran:
                ph_fused.observe(time.perf_counter() - t0)
            elif not warned_fallback:
                # eligibility flipped mid-fit (env kill switch, monitor
                # installed from a callback): the batches still train on
                # the classic loop; say so once instead of silently
                # reporting fused-phase timings over per-param dispatches
                warned_fallback = True
                self.logger.warning(
                    "fused train step fell back to the classic loop "
                    "mid-fit; fused_step phase timings stop here")
            # overlap: host iterator + host->device copy of batch t+1
            # run while the device crunches batch t
            t0 = time.perf_counter()
            with telemetry.span("fit.data_wait"):
                nxt = next(data_iter, None)
            wait = time.perf_counter() - t0
            staged = self._stage_batch(nxt)
            t0 = time.perf_counter()
            with telemetry.span("fit.update_metric"):
                self.update_metric(eval_metric, batch.label)
            ph_metric.observe(time.perf_counter() - t0)
            if getattr(eval_metric, "device_active", False):
                # the device accumulator's one jitted add; counted here
                # (not in update_device) so validation-time device
                # metrics don't pollute the per-TRAIN-batch accounting
                _dispatch_inc(self, "metric")
            tel_batches.inc()
            if batch_end_callback is not None:
                param = BatchEndParam(epoch=epoch, nbatch=nbatch,
                                      eval_metric=eval_metric)
                for cb in _as_list(batch_end_callback):
                    cb(param)
            nbatch += 1
        return nbatch

    # -- checkpointing -----------------------------------------------------
    def save_params(self, fname):
        arg_params, aux_params = self.get_params()
        save_dict = {("arg:%s" % k): v for k, v in arg_params.items()}
        save_dict.update({("aux:%s" % k): v for k, v in aux_params.items()})
        nd.save(fname, save_dict)

    def load_params(self, fname):
        save_dict = nd.load(fname)
        arg_params, aux_params = {}, {}
        for k, value in save_dict.items():
            tp, name = k.split(":", 1)
            if tp == "arg":
                arg_params[name] = value
            elif tp == "aux":
                aux_params[name] = value
            else:
                raise MXNetError(f"invalid param file {fname}")
        self.set_params(arg_params, aux_params)

    def install_monitor(self, mon):
        raise NotImplementedError


def _as_list(obj):
    if isinstance(obj, (list, tuple)):
        return obj
    return [obj]
