"""Single-dispatch fused train step.

The reference MXNet hides per-op latency behind its C++ dependency
engine, which overlaps data loading, per-parameter SGD updates and
kvstore reduces (SURVEY §1; the engine-scheduled ``ccSGD`` fused update
in src/optimizer/sgd-inl.h).  The TPU-idiomatic equivalent is to compile
the ENTIRE train step — forward, ``jax.vjp`` backward, gradient
rescale/clip and the optimizer update over the whole parameter/state
pytree — into one donated XLA program, so a training batch costs one
host dispatch instead of ``1 + num_params``.

:class:`FusedTrainStep` wraps a bound single-context :class:`Executor`
plus an optimizer exposing the pure functional ``step_param`` /
``step_tree`` surface (mxnet_tpu/optimizer.py).  Numerics match the
per-param loop by construction: both paths trace the same
``step_param``, the same schedule/multiplier plumbing computes lr/wd per
parameter on the host, and the update-count bookkeeping increments
exactly like the per-param loop so checkpoint-resume across paths is
seamless.  Weights and optimizer state are donated on TPU (mirroring
the optimizer module's ``_donate`` guard); on CPU XLA ignores donation,
so the path is still correct, just without in-place buffer reuse.

Selection lives in :meth:`Module._select_fused`; anything the fused
program cannot express — multiple contexts, kvstore reduction, custom
updaters, monitors, ``grad_req`` other than ``write``, optimizers
without ``step_param`` (SGLD's RNG operand) — falls back to the classic
forward/backward/per-param loop.
"""

from __future__ import annotations

import collections
import hashlib
import time

import jax
import jax.numpy as jnp
import numpy as np

from .. import jax_compat
from ..aot import export_store as aot_store
from ..base import MXNetError, env_flag
from ..lint.annotations import hot_path
from ..ndarray import NDArray
from ..optimizer import (_dispatch_inc, _donate, _state_commit,
                         _state_leaves)
from ..telemetry import flight as flight_mod
from ..telemetry import statusz as statusz_mod

__all__ = ["FusedTrainStep", "note_selection", "selection_state"]

# -- fused-path selection log (the /statusz "why is training unfused?"
# answer): Module._select_fused records every verdict here ---------------------
_selections = collections.deque(maxlen=16)


def note_selection(selected, reason):
    """Record one fused-path eligibility verdict (Module._select_fused).
    Repeats of the same verdict fold into the last entry's ``count`` —
    a custom train loop re-scanning every batch logs one line, not
    sixteen."""
    if (_selections and _selections[-1]["selected"] == bool(selected)
            and _selections[-1]["reason"] == str(reason)):
        # mxtpu-lint: disable=wall-clock (statusz display timestamp)
        _selections[-1]["t"] = round(time.time(), 3)
        _selections[-1]["count"] = _selections[-1].get("count", 1) + 1
        return
    # mxtpu-lint: disable=wall-clock (statusz display timestamp)
    _selections.append({"t": round(time.time(), 3),
                        "selected": bool(selected), "reason": str(reason)})


def selection_state():
    """Recent verdicts, newest last — served under /statusz."""
    return {"recent": list(_selections),
            "fused_env_enabled": env_flag("MXTPU_FUSED_STEP"),
            "numeric_watch": env_flag("MXTPU_NUMERIC_WATCH", False)}


statusz_mod.register("train.fused_step", selection_state)


class FusedTrainStep:
    """One compiled XLA program per (executor, optimizer) doing
    forward + backward + whole-pytree optimizer update.

    ``step(data_batch)`` dispatches asynchronously (JAX async dispatch:
    the call returns before the device finishes), leaves the executor's
    outputs/aux/params rebound to the program's results, and keeps the
    updater's per-index optimizer state in sync with the per-param
    path's representation — so checkpointing and a later fallback to
    the classic loop see exactly the state they expect.
    """

    def __init__(self, executor, optimizer, updater, param_names,
                 data_names, label_names):
        self._exe = executor
        self._opt = optimizer
        self._updater = updater
        self._param_names = list(param_names)
        self._data_names = list(data_names)
        self._label_names = list(label_names)
        self._indices = {name: i for i, name in enumerate(param_names)}
        # trainable = params the executor holds gradients for, in
        # param order (the per-param loop's enumeration)
        self._trainable = [n for n in param_names
                           if n in executor._grad_names]
        if not self._trainable:
            raise MXNetError("fused step needs at least one trainable param")

        graph = executor._graph
        opt = optimizer
        # opt-in numeric watchdog (MXTPU_NUMERIC_WATCH): the program
        # additionally returns (outputs-finite, global grad norm) and
        # the host checks them — one forced sync per step, the price of
        # catching a NaN the step it appears instead of epochs later
        self._watch = env_flag("MXTPU_NUMERIC_WATCH", False)
        watch = self._watch

        def program(params, others, aux, states, key, lrs, wds, t):
            def f(p):
                outs, new_aux = graph({**p, **others}, aux, key, True)
                return outs, new_aux

            outs, vjp_fn, new_aux = jax.vjp(f, params, has_aux=True)
            # loss-layer head-grad contract: ones per output (the same
            # default the executor's fused fwd_bwd uses)
            head = tuple(jnp.ones(o.shape, o.dtype) for o in outs)
            grads = vjp_fn(head)[0]
            new_params, new_states = opt.step_tree(params, grads, states,
                                                   lrs, wds, t)
            if watch:
                outs_ok = jnp.asarray(True)
                for o in outs:
                    outs_ok = jnp.logical_and(outs_ok,
                                              jnp.isfinite(o).all())
                gsq = jnp.asarray(0.0, jnp.float32)
                for g in jax.tree_util.tree_leaves(grads):
                    gsq = gsq + jnp.sum(
                        jnp.square(g.astype(jnp.float32)))
                return (outs, new_params, new_states, new_aux,
                        outs_ok, jnp.sqrt(gsq))
            return outs, new_params, new_states, new_aux

        # donate weights (arg 0) and optimizer state (arg 3): on TPU the
        # update reuses their buffers in place, halving peak param memory
        self._program = jax.jit(program, donate_argnums=_donate(0, 3))
        # AOT restart path (mxnet_tpu/aot/): resolved lazily at the
        # first step, when the concrete arg shapes exist
        self._aot_resolved = self._aot_store() is None

    # -- AOT export/load (mxnet_tpu/aot/) ----------------------------------
    @staticmethod
    def _aot_store():
        return aot_store.default_store()

    def _aot_fingerprint(self, args):
        """What pins the traced fused program: the symbol graph, the
        optimizer's baked-in scalars (anything read at trace time —
        momentum, rescale_grad, clip — becomes a compiled constant),
        every leaf shape/dtype, and the donation policy.  lr/wd/t are
        runtime operands and deliberately absent."""
        opt = self._opt
        # num_update/begin_num_update are runtime operands (t), not
        # trace-time constants — keying on them would re-export on
        # every checkpoint resume.  np.generic covers numpy scalars
        # (rescale_grad=np.float32(...) is baked into the trace just
        # like a Python float and must key the artifact the same way).
        # mxtpu-lint: disable=host-sync (np.generic host scalars —
        # one-time AOT fingerprinting, no device values involved)
        baked = {k: (v.item() if isinstance(v, np.generic) else v)
                 for k, v in sorted(vars(opt).items())
                 if isinstance(v, (int, float, str, bool, type(None),
                                   np.generic))
                 and k not in ("num_update", "begin_num_update")}
        leaves = [(str(jax.tree_util.tree_structure(args)),)]
        for leaf in jax.tree_util.tree_leaves(args):
            leaves.append((tuple(getattr(leaf, "shape", ())),
                           str(getattr(leaf, "dtype", type(leaf)))))
        sym_hash = hashlib.sha256(
            self._exe._symbol.tojson().encode()).hexdigest()
        return aot_store.fingerprint(
            subsystem="fused_step", symbol=sym_hash,
            optimizer=type(opt).__name__, baked=baked, leaves=leaves,
            donate=list(_donate(0, 3)), numeric_watch=self._watch)

    def _resolve_aot(self, args):
        """Swap self._program for an AOT artifact (or write one): the
        restarted process deserializes instead of re-tracing forward+
        backward+update, and the XLA compile of the round-tripped
        module hits the persistent compile cache."""
        self._aot_resolved = True
        store = self._aot_store()
        if store is None:
            return
        specs = jax.tree_util.tree_map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), args)
        fp = self._aot_fingerprint(specs)
        exported = store.load(fp, label="fused-step")
        if exported is None:
            try:
                exported = jax_compat.export_fn(self._program, *specs)
            except Exception:
                return                 # unexportable: keep the plain jit
            store.save(fp, exported, label="fused-step")
        self._program = jax.jit(exported.call,
                                donate_argnums=_donate(0, 3))

    # -- staging -----------------------------------------------------------
    def _as_device_value(self, src, bound, name):
        """Batch input -> jax array matching the bound array's
        shape/dtype on the executor's device (the contract
        ``arg_dict[name][:] = arr`` enforces on the classic path)."""
        if isinstance(src, NDArray):
            val = src._data
        else:
            # mxtpu-lint: disable=host-sync (host batch input staging:
            # src is the caller's host array, not a device value)
            val = np.asarray(src)
        if val.dtype != np.dtype(bound.dtype):
            val = val.astype(bound.dtype)
        if tuple(val.shape) != tuple(bound.shape):
            raise MXNetError(
                f"fused step: input {name!r} has shape {tuple(val.shape)}, "
                f"bound shape is {tuple(bound.shape)}")
        return jax.device_put(val, self._exe._ctx.jax_device())

    # -- the step ----------------------------------------------------------
    @hot_path
    def step(self, data_batch):
        """Dispatch one fused train step for ``data_batch`` (async)."""
        exe = self._exe
        opt = self._opt
        states = self._updater.states

        # stage batch inputs (device-resident already when the fit loop
        # pre-staged them; host arrays transfer here)
        arrays = {}
        for name, arr in zip(self._data_names, data_batch.data):
            arrays[name] = self._as_device_value(arr, exe.arg_dict[name], name)
        for name, arr in zip(self._label_names, data_batch.label or []):
            if name in exe.arg_dict:
                arrays[name] = self._as_device_value(arr, exe.arg_dict[name],
                                                     name)

        # host-side schedule bookkeeping, identical to the per-param
        # loop: every trainable index counts one update, THEN lr/wd are
        # read (num_update is already advanced for all of them — the
        # same values the per-param loop computes)
        for name in self._trainable:
            if self._indices[name] not in states:
                states[self._indices[name]] = opt.create_state(
                    self._indices[name], exe.arg_dict[name])
            opt._update_count(self._indices[name])
        t = opt.num_update
        lrs = {n: jnp.float32(opt._get_lr(self._indices[n]))
               for n in self._trainable}
        wds = {n: jnp.float32(opt._get_wd(self._indices[n]))
               for n in self._trainable}

        params, others = {}, {}
        trainable = set(self._trainable)
        for name, arr in zip(exe.arg_names, exe.arg_arrays):
            if name in trainable:
                params[name] = arr._data
            elif name in arrays:
                others[name] = arrays[name]
                arr._set(arrays[name])  # keep arg_dict observable state
            else:
                others[name] = arr._data
        aux = {k: a._data for k, a in zip(exe.aux_names, exe.aux_arrays)}
        state_leaves = {n: _state_leaves(states[self._indices[n]])
                        for n in self._trainable}
        key = exe._next_key()

        t_op = jnp.int32(t)
        if not self._aot_resolved:
            self._resolve_aot((params, others, aux, state_leaves, key,
                               lrs, wds, t_op))
        _dispatch_inc(self, "fused_step")
        if self._watch:
            (outs, new_params, new_states, new_aux, outs_ok,
             gnorm) = self._program(params, others, aux, state_leaves,
                                    key, lrs, wds, t_op)
            # ONE batched read for both watchdog scalars — the
            # watchdog's contract is one forced sync per step, not one
            # per scalar (a separate float(gnorm) + bool(outs_ok)
            # would block the dispatch queue twice)
            # mxtpu-lint: disable=host-sync (the watchdog's designed
            # once-per-step sync point)
            ok_h, gn = map(float, jax.device_get((outs_ok, gnorm)))
            from .. import telemetry

            telemetry.gauge("mxtpu_train_grad_norm",
                            "global gradient norm (numeric watchdog)"
                            ).set(gn)
            if not ok_h:
                flight_mod.record_anomaly("fused_step_loss", step=int(t))
            if not np.isfinite(gn):
                flight_mod.record_anomaly("fused_step_grad_norm",
                                          step=int(t))
        else:
            outs, new_params, new_states, new_aux = self._program(
                params, others, aux, state_leaves, key, lrs, wds, t_op)

        # commit: rebind executor arrays to the program's results (no
        # device work — the references move, the buffers stay put)
        for name in self._trainable:
            exe.arg_dict[name]._set(new_params[name])
            _state_commit(states[self._indices[name]], new_states[name])
        for k, arr in zip(exe.aux_names, exe.aux_arrays):
            arr._set(new_aux[k])
        exe._outputs = [NDArray(o, exe._ctx) for o in outs]
        # gradients were consumed inside the program; stale pending
        # state from an earlier unfused run must not survive
        exe._pending_grads = None
        exe._partial = None
        exe._partial_key = None
        return exe._outputs
