"""Asynchronous dependency engine.

Rebuild of the reference's dataflow scheduler (include/mxnet/engine.h,
src/engine/threaded_engine.{h,cc}, threaded_engine_perdevice.cc) for the
TPU runtime.  Division of labor in this framework:

- **Device compute** is scheduled by XLA/PJRT: every jitted program is
  dispatched asynchronously by JAX onto the device stream, which already
  provides the per-device in-order async pipeline the reference built
  ThreadedEnginePerDevice for.  A compiled graph segment == one engine op
  (the reference's "bulk segment", graph_executor.cc:842-892, made the
  default unit).
- **Host-side work** (data pipeline stages, checkpoint writes, custom
  Python ops, cross-device staging) still needs genuine dependency
  scheduling — that is what this engine does.

Semantics mirror threaded_engine.h:87-189: each ``Var`` holds a queue of
pending reader/writer blocks; an op runs when all its const (read) vars
have granted read access and all mutable (write) vars have reached it at
the queue head.  ``NaiveEngine`` runs everything inline (the documented
debugging path, threaded_engine.cc:306-314); ``ThreadedEngine`` dispatches
ready ops to a worker pool.  Selection via ``MXNET_ENGINE_TYPE`` env var,
exactly like src/engine/engine.cc:13-39.
"""

from __future__ import annotations

import os
import threading
from collections import deque

__all__ = ["Engine", "Var", "get_engine", "set_engine_type", "FnProperty"]


class FnProperty:
    """Operator property hints (engine.h:58-69)."""

    NORMAL = 0
    COPY_FROM_DEVICE = 1
    COPY_TO_DEVICE = 2
    CPU_PRIORITIZED = 3
    ASYNC = 4


class Var:
    """A schedulable variable (engine.h Var / threaded_engine.h ThreadedVar).

    Holds a FIFO of pending accessors.  Readers at the head of the queue
    may proceed concurrently; a writer must be alone at the head.
    """

    __slots__ = ("_lock", "_queue", "_active_readers", "_active_writer",
                 "name", "native")

    def __init__(self, name=None):
        self._lock = threading.Lock()
        self._queue = deque()  # (op_block, is_write)
        self._active_readers = 0
        self._active_writer = False
        self.name = name
        self.native = None  # C++ var handle when used by NativeEngine

    def __repr__(self):
        return f"Var({self.name or hex(id(self))})"


class _OpBlock:
    __slots__ = ("fn", "const_vars", "mutable_vars", "wait", "lock", "prop",
                 "done", "exc", "priority")

    def __init__(self, fn, const_vars, mutable_vars, prop, priority=0):
        self.fn = fn
        self.const_vars = const_vars
        self.mutable_vars = mutable_vars
        self.prop = prop
        self.priority = priority
        self.wait = len(const_vars) + len(mutable_vars)
        self.lock = threading.Lock()
        self.done = threading.Event()
        self.exc = None

    def dec_wait(self):
        with self.lock:
            self.wait -= 1
            return self.wait == 0


class Engine:
    """Dependency engine base: push ops with read/write sets."""

    def __init__(self):
        self._pending = 0
        self._pending_lock = threading.Lock()
        self._all_done = threading.Condition(self._pending_lock)
        self._exceptions = []

    # -- public API (engine.h:74-223) --------------------------------------
    def new_variable(self, name=None) -> Var:
        return Var(name)

    def push(self, fn, const_vars=(), mutable_vars=(), prop=FnProperty.NORMAL,
             priority=0):
        """Schedule ``fn()`` to run once its dependencies are satisfied.

        ``const_vars`` are read, ``mutable_vars`` are written; no var may
        appear twice across the two sets (CheckDuplicate,
        threaded_engine.cc:205-237).
        """
        const_vars = tuple(const_vars)
        mutable_vars = tuple(mutable_vars)
        seen = set()
        for v in const_vars + mutable_vars:
            if id(v) in seen:
                raise ValueError(f"duplicate variable {v} in dependency sets")
            seen.add(id(v))
        block = _OpBlock(fn, const_vars, mutable_vars, prop, priority)
        with self._pending_lock:
            self._pending += 1
        if not const_vars and not mutable_vars:
            self._dispatch(block)
            return block
        # Enqueue on every var; a var grants access immediately if possible.
        ready = 0
        for v in const_vars:
            if self._append_read(v, block):
                ready += 1
        for v in mutable_vars:
            if self._append_write(v, block):
                ready += 1
        # Decrement wait for the grants that happened synchronously.
        fire = False
        for _ in range(ready):
            if block.dec_wait():
                fire = True
        if fire:
            self._dispatch(block)
        return block

    def wait_for_var(self, var: Var):
        """Block until all ops touching ``var`` pushed so far completed."""
        done = threading.Event()
        self.push(done.set, const_vars=(var,))
        done.wait()

    def check_exceptions(self):
        """Raise the first exception any completed op left behind
        (threaded_engine.h on_complete error propagation); callers that
        synchronize on single vars use this to surface async failures
        without a full wait_for_all."""
        with self._pending_lock:
            if not self._exceptions:
                return
            exc = self._exceptions[:]
            self._exceptions.clear()
        raise exc[0]

    def wait_for_all(self):
        with self._all_done:
            while self._pending:
                self._all_done.wait()
        self.check_exceptions()

    def delete_variable(self, var: Var, on_delete=None):
        """Schedule deletion after all pending ops on var complete."""
        if on_delete is not None:
            self.push(on_delete, mutable_vars=(var,))

    # -- var queue mechanics (threaded_engine.h:87-189) ---------------------
    def _append_read(self, var: Var, block) -> bool:
        """Returns True if read access is granted immediately."""
        with var._lock:
            if not var._active_writer and not var._queue:
                var._active_readers += 1
                return True
            var._queue.append((block, False))
            return False

    def _append_write(self, var: Var, block) -> bool:
        with var._lock:
            if not var._active_writer and var._active_readers == 0 and not var._queue:
                var._active_writer = True
                return True
            var._queue.append((block, True))
            return False

    def _complete(self, block):
        # publish the exception BEFORE releasing vars: a waiter woken by
        # the release must find it in check_exceptions (no race window)
        if block.exc is not None:
            with self._pending_lock:
                self._exceptions.append(block.exc)
        for v in block.const_vars:
            self._release(v, is_write=False)
        for v in block.mutable_vars:
            self._release(v, is_write=True)
        block.done.set()
        with self._pending_lock:
            self._pending -= 1
            if self._pending == 0:
                self._all_done.notify_all()

    def _release(self, var: Var, is_write: bool):
        to_fire = []
        with var._lock:
            if is_write:
                var._active_writer = False
            else:
                var._active_readers -= 1
            # Grant queued accessors now runnable.
            while var._queue and not var._active_writer:
                nxt, nxt_write = var._queue[0]
                if nxt_write:
                    if var._active_readers == 0:
                        var._queue.popleft()
                        var._active_writer = True
                        to_fire.append(nxt)
                    break
                var._queue.popleft()
                var._active_readers += 1
                to_fire.append(nxt)
        for blk in to_fire:
            if blk.dec_wait():
                self._dispatch(blk)

    # -- execution ----------------------------------------------------------
    def _dispatch(self, block):
        raise NotImplementedError

    def _run(self, block):
        try:
            block.fn()
        except BaseException as e:  # propagated at wait_for_all
            block.exc = e
        finally:
            self._complete(block)


class NaiveEngine(Engine):
    """Synchronous inline execution (src/engine/naive_engine.cc)."""

    def _dispatch(self, block):
        self._run(block)


class _PriorityPool:
    """Worker pool draining a priority heap: highest ``priority`` first,
    FIFO among equals (the reference's std::priority_queue dispatch,
    threaded_engine_pooled.cc) — this is what makes ``priority=-key``
    pushes order comm the way the next forward pass consumes weights."""

    def __init__(self, num_workers, name):
        import heapq

        self._heapq = heapq
        self._heap = []  # (-priority, seq, fn)
        self._cv = threading.Condition()
        self._seq = 0
        self._shutdown = False
        self._threads = [
            threading.Thread(target=self._loop, name=f"{name}-{i}",
                             daemon=True)
            for i in range(num_workers)]
        for t in self._threads:
            t.start()

    def submit(self, fn, priority=0):
        with self._cv:
            self._heapq.heappush(self._heap, (-priority, self._seq, fn))
            self._seq += 1
            self._cv.notify()

    def close(self):
        """Drain the heap then let every worker exit."""
        with self._cv:
            self._shutdown = True
            self._cv.notify_all()
        for t in self._threads:
            t.join(timeout=5)

    def _loop(self):
        while True:
            with self._cv:
                while not self._heap and not self._shutdown:
                    self._cv.wait()
                if self._shutdown and not self._heap:
                    return
                _, _, fn = self._heapq.heappop(self._heap)
            fn()


def _close_pools(*pools):
    for p in pools:
        p.close()


class ThreadedEngine(Engine):
    """Worker-pool execution (src/engine/threaded_engine_perdevice.cc).

    One shared priority pool for normal work plus a dedicated pool for
    prioritized / IO work, standing in for the reference's per-device +
    copy pools (device streams are owned by PJRT here).  Within each
    pool, ready ops dispatch highest-priority-first.
    """

    def __init__(self, num_workers=None):
        super().__init__()
        if num_workers is None:
            num_workers = int(os.environ.get("MXNET_CPU_WORKER_NTHREADS", "4"))
        self._pool = _PriorityPool(num_workers, "mxtpu-engine")
        self._io_pool = _PriorityPool(2, "mxtpu-engine-io")
        # non-singleton engines (tests, ad-hoc) must not park worker
        # threads forever once collected
        import weakref

        self._finalizer = weakref.finalize(self, _close_pools, self._pool,
                                           self._io_pool)

    def close(self):
        """Stop the worker pools (idempotent; runs at GC otherwise)."""
        self._finalizer()

    def _dispatch(self, block):
        pool = (
            self._io_pool
            if block.prop in (FnProperty.COPY_FROM_DEVICE, FnProperty.COPY_TO_DEVICE,
                              FnProperty.CPU_PRIORITIZED)
            else self._pool
        )
        pool.submit(lambda: self._run(block), priority=block.priority)


class NativeEngine(Engine):
    """ctypes binding to the C++ engine (src/engine.cc) — the native
    rebuild of ThreadedEnginePerDevice.  Dependency tracking, queues and
    worker threads live in C++; Python callables run as callbacks on the
    C++ workers (ctypes re-acquires the GIL per call)."""

    def __init__(self, num_workers=None, num_io_workers=2):
        import ctypes

        from .libinfo import find_lib

        super().__init__()
        self._lib = find_lib()
        if self._lib is None:
            raise RuntimeError("native library unavailable; build src/ first")
        if num_workers is None:
            num_workers = int(os.environ.get("MXNET_CPU_WORKER_NTHREADS", "4"))
        self._handle = self._lib.MXTPUEngineCreate(num_workers, num_io_workers)
        self._CB = ctypes.CFUNCTYPE(None, ctypes.c_void_p)
        self._live = {}  # keep callbacks alive until executed
        self._live_lock = threading.Lock()
        self._ct = ctypes

    def new_variable(self, name=None) -> Var:
        v = Var(name)
        v.native = self._lib.MXTPUEngineNewVar(self._handle)
        return v

    def push(self, fn, const_vars=(), mutable_vars=(), prop=FnProperty.NORMAL,
             priority=0):
        ct = self._ct
        const_vars = tuple(const_vars)
        mutable_vars = tuple(mutable_vars)
        seen = set()
        for v in const_vars + mutable_vars:
            if id(v) in seen:
                raise ValueError(f"duplicate variable {v} in dependency sets")
            seen.add(id(v))
        token = object()

        def trampoline(_payload, _fn=fn, _token=token):
            try:
                _fn()
            except BaseException as e:
                with self._pending_lock:
                    self._exceptions.append(e)
            finally:
                with self._live_lock:
                    self._live.pop(id(_token), None)

        cb = self._CB(trampoline)
        with self._live_lock:
            self._live[id(token)] = (cb, token)
        cvars = (ct.c_void_p * max(1, len(const_vars)))(
            *[v.native for v in const_vars])
        mvars = (ct.c_void_p * max(1, len(mutable_vars)))(
            *[v.native for v in mutable_vars])
        native_prop = 1 if prop in (FnProperty.COPY_FROM_DEVICE,
                                    FnProperty.COPY_TO_DEVICE,
                                    FnProperty.CPU_PRIORITIZED) else 0
        self._lib.MXTPUEnginePushPriority(
            self._handle, ct.cast(cb, ct.c_void_p), None, cvars,
            len(const_vars), mvars, len(mutable_vars), native_prop,
            int(priority))

    def wait_for_var(self, var: Var):
        self._lib.MXTPUEngineWaitForVar(self._handle, var.native)

    def wait_for_all(self):
        self._lib.MXTPUEngineWaitForAll(self._handle)
        self.check_exceptions()


_engine = None
_engine_lock = threading.Lock()

_ENGINE_KINDS = {}


def _make_engine(kind: str) -> Engine:
    if kind == "NaiveEngine":
        return NaiveEngine()
    if kind == "NativeEngine":
        try:
            return NativeEngine()
        except RuntimeError:
            return ThreadedEngine()
    return ThreadedEngine()


def get_engine() -> Engine:
    """Singleton engine, selected by MXNET_ENGINE_TYPE (engine.cc:13-39):
    NaiveEngine | ThreadedEngine | NativeEngine (C++)."""
    global _engine
    with _engine_lock:
        if _engine is None:
            _engine = _make_engine(os.environ.get("MXNET_ENGINE_TYPE",
                                                  "ThreadedEngine"))
        return _engine


def set_engine_type(kind: str):
    """Switch engine implementation ('NaiveEngine' | 'ThreadedEngine' |
    'NativeEngine')."""
    global _engine
    with _engine_lock:
        if _engine is not None:
            _engine.wait_for_all()
        _engine = _make_engine(kind)
