"""Imperative NDArray.

Rebuild of the reference NDArray stack (include/mxnet/ndarray.h,
src/ndarray/ndarray.cc, python/mxnet/ndarray.py) on a JAX/XLA backend.

Execution model: every NDArray wraps a **committed** ``jax.Array`` on the
device of its ``Context``.  Ops dispatch through per-(op, params) jitted
callables — JAX's async dispatch plays the role of the reference's
dependency engine for device work (ops return immediately; device-side
ordering is per-device program order, a superset of the reference's
read/write-dependency order), and ``wait_to_read`` maps to
``block_until_ready`` (reference ndarray.h:123-139).

The module-level op functions (``dot``, ``FullyConnected``, …) are
generated at import time by enumerating the op registry — the same
runtime-discovery pattern as the reference's
``_init_ndarray_module``/``_make_ndarray_function``
(python/mxnet/ndarray.py:1128-1305).
"""

from __future__ import annotations

import builtins
import functools
import sys

import jax
import jax.numpy as jnp
import numpy as np

from . import random as _random
from .base import MXNetError, np_dtype, numeric_types
from .context import Context, cpu, current_context
from .ops import OP_REGISTRY

__all__ = [
    "NDArray", "array", "empty", "zeros", "ones", "full", "arange",
    "concatenate", "save", "load", "imperative_invoke", "onehot_encode",
    "choose_element_0index", "fill_element_0index", "waitall",
    "add", "subtract", "multiply", "divide", "true_divide",
]

# Generated op functions (sum, max, slice, abs, ...) shadow builtins in this
# module's namespace; keep safe references for internal use.
_pyslice = slice
_pysum = sum


class NDArray:
    """Multi-dimensional array on a device context."""

    __slots__ = ("_data", "_ctx", "writable")

    def __init__(self, data, ctx=None, writable=True):
        if ctx is None:
            ctx = current_context()
        self._ctx = ctx
        self._data = data
        self.writable = writable

    # -- core properties ---------------------------------------------------
    @property
    def shape(self):
        return tuple(self._data.shape)

    @property
    def size(self):
        return int(np.prod(self.shape)) if self.shape else 1

    @property
    def ndim(self):
        return len(self.shape)

    @property
    def dtype(self):
        return np.dtype(self._data.dtype)

    @property
    def context(self) -> Context:
        return self._ctx

    @property
    def T(self):
        return transpose(self)

    # -- sync / host transfer (reference ndarray.h:123-139, ndarray.py:465)
    def wait_to_read(self):
        self._data.block_until_ready()

    def wait_to_write(self):
        self._data.block_until_ready()

    def asnumpy(self) -> np.ndarray:
        return np.asarray(jax.device_get(self._data))

    def __array__(self, dtype=None, copy=None):
        # numpy interop: np.asarray(nd_arr) / np_buf[:] = nd_arr
        if copy is False:
            # device_get always copies; NumPy 2 protocol: never-copy
            # requests must fail rather than silently detach
            raise ValueError("NDArray cannot be converted to numpy "
                             "without a copy; use np.asarray(arr) instead")
        a = self.asnumpy()
        return a.astype(dtype) if dtype is not None else a

    def asscalar(self):
        if self.size != 1:
            raise ValueError("the array is not scalar-sized")
        return self.asnumpy().reshape(())[()]

    def astype(self, dtype):
        return NDArray(self._data.astype(np_dtype(dtype)), self._ctx)

    # -- copies ------------------------------------------------------------
    def copyto(self, other):
        """Copy to another NDArray (in place) or a Context (new array).

        Reference ndarray.py:511 / CopyFromTo ndarray.cc:226-290.
        """
        if isinstance(other, NDArray):
            if other.shape != self.shape:
                raise ValueError(f"copyto shape mismatch {self.shape} vs {other.shape}")
            other._data = jax.device_put(
                self._data.astype(other.dtype), other._ctx.jax_device())
            return other
        if isinstance(other, Context):
            return NDArray(jax.device_put(self._data, other.jax_device()), other)
        raise TypeError(f"copyto does not support {type(other)}")

    def as_in_context(self, ctx: Context):
        if ctx == self._ctx:
            return self
        return self.copyto(ctx)

    def copy(self):
        return NDArray(self._data + 0, self._ctx)

    def reshape(self, shape):
        if isinstance(shape, (int, np.integer)):
            shape = (shape,)
        return NDArray(jnp.reshape(self._data, shape), self._ctx)

    def broadcast_to(self, shape):
        """Broadcast to ``shape``, allowing only size-1 dims to grow
        (reference ndarray.py broadcast_to)."""
        cur = self.shape
        if len(cur) != len(shape):
            cur = (1,) * (len(shape) - len(cur)) + tuple(cur)
        for c, t in zip(cur, shape):
            if c != t and c != 1:
                raise ValueError(
                    f"cannot broadcast {self.shape} to {tuple(shape)}: only "
                    "size-1 dimensions may be expanded")
        return NDArray(jnp.broadcast_to(self._data.reshape(cur), shape),
                       self._ctx)

    # -- mutation ----------------------------------------------------------
    def _check_writable(self):
        if not self.writable:
            raise MXNetError("trying to write to a read-only NDArray")

    def _set(self, data):
        self._check_writable()
        self._data = data
        return self

    def __setitem__(self, key, value):
        self._check_writable()
        if isinstance(value, NDArray):
            value = value._data
        elif isinstance(value, numeric_types):
            pass
        else:
            value = jnp.asarray(np.asarray(value), dtype=self.dtype)
        if isinstance(key, _pyslice) and key == _pyslice(None):
            if isinstance(value, numeric_types):
                self._data = jnp.full(self.shape, value, self.dtype)
            else:
                self._data = jnp.broadcast_to(value, self.shape).astype(self.dtype)
            self._data = jax.device_put(self._data, self._ctx.jax_device())
        else:
            self._data = self._data.at[key].set(value)

    def __getitem__(self, key):
        return NDArray(self._data[key], self._ctx)

    # -- python protocol ---------------------------------------------------
    def __len__(self):
        return self.shape[0]

    def __repr__(self):
        return f"<NDArray {'x'.join(map(str, self.shape))} @{self._ctx}>"

    def __bool__(self):
        if self.size == 1:
            return bool(self.asscalar())
        raise ValueError("ambiguous truth value of multi-element NDArray")

    def __float__(self):
        return float(self.asscalar())

    def __int__(self):
        return int(self.asscalar())

    # -- arithmetic (ndarray.py:105+) --------------------------------------
    def __add__(self, other):
        return _ufunc(self, other, "_plus", "_plus_scalar")

    __radd__ = __add__

    def __sub__(self, other):
        return _ufunc(self, other, "_minus", "_minus_scalar")

    def __rsub__(self, other):
        return _ufunc(self, other, None, "_rminus_scalar")

    def __mul__(self, other):
        return _ufunc(self, other, "_mul", "_mul_scalar")

    __rmul__ = __mul__

    def __truediv__(self, other):
        return _ufunc(self, other, "_div", "_div_scalar")

    def __rtruediv__(self, other):
        return _ufunc(self, other, None, "_rdiv_scalar")

    def __pow__(self, other):
        return _ufunc(self, other, "_power", "_power_scalar")

    def __rpow__(self, other):
        return _ufunc(self, other, None, "_rpower_scalar")

    def __neg__(self):
        return imperative_invoke("negative", [self], {})[0]

    def __iadd__(self, other):
        return self._set((self + other)._data)

    def __isub__(self, other):
        return self._set((self - other)._data)

    def __imul__(self, other):
        return self._set((self * other)._data)

    def __itruediv__(self, other):
        return self._set((self / other)._data)

    def __eq__(self, other):
        return _ufunc(self, other, "_equal", "_equal_scalar")

    def __ne__(self, other):
        return _ufunc(self, other, "_not_equal", "_not_equal_scalar")

    def __gt__(self, other):
        return _ufunc(self, other, "_greater", "_greater_scalar")

    def __ge__(self, other):
        return _ufunc(self, other, "_greater_equal", "_greater_equal_scalar")

    def __lt__(self, other):
        return _ufunc(self, other, "_lesser", "_lesser_scalar")

    def __le__(self, other):
        return _ufunc(self, other, "_lesser_equal", "_lesser_equal_scalar")

    __hash__ = object.__hash__


def _ufunc(lhs, rhs, op_name, scalar_op_name):
    if isinstance(rhs, NDArray):
        if op_name is None:
            raise TypeError("operation not supported between two NDArrays")
        return imperative_invoke(op_name, [lhs, rhs], {})[0]
    if isinstance(rhs, numeric_types):
        return imperative_invoke(scalar_op_name, [lhs], {"scalar": float(rhs)})[0]
    raise TypeError(f"unsupported operand type {type(rhs)}")


# -- imperative dispatch -----------------------------------------------------
@functools.lru_cache(maxsize=None)
def _cached_jit(op_name, params, train):
    """One jitted callable per (op, params, train); JAX retraces per
    shape/dtype — the rebuild of the reference's cached engine ops keyed
    by executable (SURVEY.md §7 hard part (b))."""
    op = OP_REGISTRY.get(op_name)

    def fn(*args):
        if op.need_rng:
            inputs, key = list(args[:-1]), args[-1]
        else:
            inputs, key = list(args), None
        outs, _ = op.forward(params, inputs, [], train, key)
        return tuple(outs)

    return jax.jit(fn)


def imperative_invoke(op_name, inputs, kwargs, out=None, ctx=None, train=True):
    """Invoke a registered op on NDArrays (reference MXFuncInvoke path,
    src/c_api/c_api.cc:410-436 → registered function → Engine::PushSync)."""
    op = OP_REGISTRY.get(op_name)
    # var-arg ops infer num_args from the input count, matching the
    # symbol frontend (reference key_var_num_args fills in BOTH
    # frontends, python/mxnet/ndarray.py:1128-1305)
    kv = op.key_var_num_args
    if kv and kv not in kwargs and inputs:
        kwargs = {**kwargs, kv: len(inputs)}
    params = op.make_params(kwargs)
    if inputs:
        ctx = _check_same_context(op_name, inputs)
    elif ctx is None:
        ctx = current_context()
    fn = _cached_jit(op_name, params, train)
    args = [arr._data for arr in inputs]
    if op.need_rng:
        args.append(_random.next_key())
    if not inputs:
        with jax.default_device(ctx.jax_device()):
            raw = fn(*args)
    else:
        raw = fn(*args)
    results = [NDArray(r, ctx) for r in raw]
    if out is not None:
        outs = out if isinstance(out, (list, tuple)) else [out]
        for dst, src in zip(outs, results):
            dst._set(jax.device_put(src._data.astype(dst.dtype), dst._ctx.jax_device()))
        return list(outs)
    return results


# -- creation ----------------------------------------------------------------
def _resolve_ctx(ctx):
    return ctx if ctx is not None else current_context()


def array(source, ctx=None, dtype=None) -> NDArray:
    """Create an NDArray from any array-like (reference ndarray.py array)."""
    ctx = _resolve_ctx(ctx)
    if isinstance(source, NDArray):
        source = source.asnumpy()
    if dtype is None:
        # reference default: float32 unless the source already carries a
        # non-float64 numpy dtype (python/mxnet/ndarray.py array)
        if isinstance(source, np.ndarray) and source.dtype != np.float64:
            dtype = source.dtype
        else:
            dtype = np.float32
    arr = np.asarray(source, dtype=np_dtype(dtype))
    return NDArray(jax.device_put(arr, ctx.jax_device()), ctx)


def empty(shape, ctx=None, dtype=None) -> NDArray:
    return zeros(shape, ctx, dtype)


def zeros(shape, ctx=None, dtype=None) -> NDArray:
    ctx = _resolve_ctx(ctx)
    if isinstance(shape, int):
        shape = (shape,)
    data = jax.device_put(jnp.zeros(shape, np_dtype(dtype)), ctx.jax_device())
    return NDArray(data, ctx)


def ones(shape, ctx=None, dtype=None) -> NDArray:
    ctx = _resolve_ctx(ctx)
    if isinstance(shape, int):
        shape = (shape,)
    data = jax.device_put(jnp.ones(shape, np_dtype(dtype)), ctx.jax_device())
    return NDArray(data, ctx)


def full(shape, val, ctx=None, dtype=None) -> NDArray:
    ctx = _resolve_ctx(ctx)
    if isinstance(shape, int):
        shape = (shape,)
    data = jax.device_put(jnp.full(shape, val, np_dtype(dtype)), ctx.jax_device())
    return NDArray(data, ctx)


def arange(start, stop=None, step=1.0, repeat=1, ctx=None, dtype=None) -> NDArray:
    ctx = _resolve_ctx(ctx)
    vals = np.arange(start, stop, step, dtype=np_dtype(dtype))
    if repeat > 1:
        vals = np.repeat(vals, repeat)
    return NDArray(jax.device_put(vals, ctx.jax_device()), ctx)


def concatenate(arrays, axis=0, always_copy=True) -> NDArray:
    if not arrays:
        raise ValueError("need at least one array")
    if len(arrays) == 1 and not always_copy:
        return arrays[0]
    ctx = arrays[0].context
    return NDArray(jnp.concatenate([a._data for a in arrays], axis=axis), ctx)


def onehot_encode(indices: NDArray, out: NDArray) -> NDArray:
    """Fill out with one-hot rows from indices (reference _onehot_encode)."""
    depth = out.shape[1]
    hot = jax.nn.one_hot(indices._data.astype(jnp.int32), depth, dtype=out.dtype)
    out._set(jax.device_put(hot, out._ctx.jax_device()))
    return out


def _check_same_context(op_name, arrays):
    ctx = arrays[0].context
    for arr in arrays[1:]:
        if arr.context != ctx:
            raise MXNetError(
                f"{op_name}: inputs on different contexts "
                f"({arr.context} vs {ctx}); use copyto/as_in_context")
    return ctx


def choose_element_0index(lhs: NDArray, rhs: NDArray, out=None) -> NDArray:
    """Pick ``lhs[i, rhs[i]]`` for each row i (0-based index).

    Reference: ``MXNET_REGISTER_NDARRAY_FUN(choose_element_0index)``
    src/ndarray/ndarray.cc:728 (MatChooseRowElem kernel).
    """
    ctx = _check_same_context("choose_element_0index", [lhs, rhs])
    idx = rhs._data.astype(jnp.int32)
    picked = jnp.take_along_axis(lhs._data, idx[:, None], axis=1)[:, 0]
    if out is not None:
        out._set(jax.device_put(picked.astype(out.dtype),
                                out._ctx.jax_device()))
        return out
    return NDArray(picked, ctx)


def fill_element_0index(lhs: NDArray, mhs: NDArray, rhs: NDArray,
                        out=None) -> NDArray:
    """Return a copy of ``lhs`` with ``[i, rhs[i]] = mhs[i]`` per row i
    (0-based); writes into ``out`` instead when given (pass ``out=lhs``
    for the in-place form).

    Reference: ``MXNET_REGISTER_NDARRAY_FUN(fill_element_0index)``
    src/ndarray/ndarray.cc:734 (MatFillRowElem ternary kernel).
    """
    ctx = _check_same_context("fill_element_0index", [lhs, mhs, rhs])
    idx = rhs._data.astype(jnp.int32)
    rows = jnp.arange(lhs.shape[0])
    filled = lhs._data.at[rows, idx].set(mhs._data.astype(lhs.dtype))
    if out is not None:
        out._set(jax.device_put(filled.astype(out.dtype),
                                out._ctx.jax_device()))
        return out
    return NDArray(filled, ctx)


def _mixed_nd_binary(left, right, op, scalar_op, rscalar_op, py_op, fname):
    """NDArray/Number dispatch of the reference module helpers
    (python/mxnet/ndarray.py:773-850 power/maximum/minimum)."""
    if isinstance(left, NDArray) and isinstance(right, NDArray):
        return imperative_invoke(op, [left, right], {})[0]
    if isinstance(left, NDArray) and isinstance(right, numeric_types):
        return imperative_invoke(scalar_op, [left],
                                 {"scalar": float(right)})[0]
    if isinstance(left, numeric_types) and isinstance(right, NDArray):
        return imperative_invoke(rscalar_op, [right],
                                 {"scalar": float(left)})[0]
    if isinstance(left, numeric_types) and isinstance(right, numeric_types):
        return py_op(left, right)
    raise TypeError(
        f"{fname}: types ({type(left)}, {type(right)}) not supported")


def power(lhs, rhs):
    """lhs ** rhs with NDArray/Number operands (ndarray.py:773)."""
    return _mixed_nd_binary(lhs, rhs, "_power", "_power_scalar",
                            "_rpower_scalar", lambda a, b: a ** b, "power")


def maximum(lhs, rhs):
    """Elementwise max with NDArray/Number operands (ndarray.py:799)."""
    # builtins.max: generated op functions shadow builtins here (the
    # module already keeps _pyslice/_pysum aliases for the same reason)
    return _mixed_nd_binary(lhs, rhs, "_maximum", "_maximum_scalar",
                            "_maximum_scalar", builtins.max, "maximum")


def minimum(lhs, rhs):
    """Elementwise min with NDArray/Number operands (ndarray.py:825)."""
    return _mixed_nd_binary(lhs, rhs, "_minimum", "_minimum_scalar",
                            "_minimum_scalar", builtins.min, "minimum")


def add(lhs, rhs):
    """Elementwise sum, either operand an NDArray or scalar (reference
    ndarray.py:669)."""
    if isinstance(lhs, NDArray):
        return lhs + rhs
    return rhs + lhs


def subtract(lhs, rhs):
    """Elementwise difference (reference ndarray.py:695)."""
    if isinstance(lhs, NDArray):
        return lhs - rhs
    return rhs.__rsub__(lhs)


def multiply(lhs, rhs):
    """Elementwise product (reference ndarray.py:721)."""
    if isinstance(lhs, NDArray):
        return lhs * rhs
    return rhs * lhs


def divide(lhs, rhs):
    """Elementwise quotient (reference ndarray.py:747)."""
    if isinstance(lhs, NDArray):
        return lhs / rhs
    return rhs.__rtruediv__(lhs)


true_divide = divide


def waitall():
    """Block until all dispatched work completes (Engine::WaitForAll)."""
    from .engine import get_engine

    get_engine().wait_for_all()
    jax.effects_barrier()


# -- serialization (reference mx.nd.save/load, ndarray.py:1001-1086) ---------
def save(fname: str, data):
    """Save a list or str->NDArray dict (two-artifact checkpoint contract)."""
    if isinstance(data, NDArray):
        data = [data]
    def _np(v):
        # numpy values serialize directly — no device round-trip
        return v.asnumpy() if hasattr(v, "asnumpy") else np.asarray(v)

    if isinstance(data, (list, tuple)):
        payload = {f"__list__:{i}": _np(a) for i, a in enumerate(data)}
    elif isinstance(data, dict):
        payload = {k: _np(v) for k, v in data.items()}
    else:
        raise TypeError("save expects NDArray, list or dict")
    with open(fname, "wb") as f:
        np.savez(f, **_encode_bf16(payload))


def load(fname: str):
    with np.load(fname, allow_pickle=False) as zf:
        payload = _decode_bf16({k: zf[k] for k in zf.files})
    if payload and all(k.startswith("__list__:") for k in payload):
        items = sorted(payload.items(), key=lambda kv: int(kv[0].split(":")[1]))
        return [array(v) for _, v in items]
    return {k: array(v) for k, v in payload.items()}


def _encode_bf16(payload):
    """npz can't store bfloat16: stash as uint16 with a name tag."""
    out = {}
    for k, v in payload.items():
        if v.dtype == np_dtype("bfloat16"):
            out["__bf16__:" + k] = v.view(np.uint16)
        else:
            out[k] = v
    return out


def _decode_bf16(payload):
    out = {}
    for k, v in payload.items():
        if k.startswith("__bf16__:"):
            out[k[len("__bf16__:"):]] = v.view(np_dtype("bfloat16"))
        else:
            out[k] = v
    return out


# -- runtime-generated op functions ------------------------------------------
def _make_ndarray_function(op_name):
    op = OP_REGISTRY.get(op_name)

    def generic_fn(*args, **kwargs):
        out = kwargs.pop("out", None)
        ctx = kwargs.pop("ctx", None)
        if isinstance(ctx, str):
            ctx = Context(*ctx.split("(")) if False else ctx  # pragma: no cover
        inputs = []
        for a in args:
            if isinstance(a, NDArray):
                inputs.append(a)
            elif isinstance(a, (np.ndarray, list, tuple)) and not kwargs.get("_no_coerce"):
                inputs.append(array(a, ctx=ctx))
            else:
                raise TypeError(f"{op_name}: positional args must be NDArray, got {type(a)}")
        results = imperative_invoke(op_name, inputs, kwargs, out=out, ctx=ctx)
        return results[0] if len(results) == 1 else results

    generic_fn.__name__ = op_name
    generic_fn.__qualname__ = op_name
    generic_fn.__doc__ = (
        f"Imperative op ``{op_name}``"
        + (f"\n{op.param_cls.__doc__}" if op.param_cls else "")
    )
    return generic_fn


def Custom(*args, op_type=None, **kwargs):
    """Generic custom-op invoker (``mx.nd.Custom(..., op_type=name)``,
    src/operator/custom.cc): dispatches to the registered CustomOpProp."""
    if op_type is None:
        raise TypeError("Custom requires op_type=<registered custom op name>")
    if op_type not in OP_REGISTRY:
        raise MXNetError(f"Custom op {op_type!r} is not registered")
    return _make_ndarray_function(op_type)(*args, **kwargs)


def _init_ndarray_module():
    mod = sys.modules[__name__]
    # NDArray/Number dispatch helpers (reference ndarray.py:773-850)
    # take precedence over raw registry creators of the same name
    keep = {"power": power, "maximum": maximum, "minimum": minimum}
    for name in OP_REGISTRY.list():
        fn = _make_ndarray_function(name)
        setattr(mod, name, fn)
        canonical = OP_REGISTRY.get(name)
        if canonical.name.lower() == name:
            setattr(mod, canonical.name, fn)  # preserve CamelCase spelling
    for name, fn in keep.items():
        setattr(mod, name, fn)


_init_ndarray_module()
