"""Version-portability shims for the narrow set of jax APIs whose
import path moved between the versions this framework runs against.

The repo targets current jax (``jax.shard_map``, replication checking
under ``check_vma=``); accelerator hosts frequently pin an older
release where the same function lives at
``jax.experimental.shard_map.shard_map`` and the kwarg is spelled
``check_rep=``.  Everything else the framework uses is stable across
that range, so this module stays deliberately tiny — one import site
per moved symbol, no feature flags.
"""

from __future__ import annotations

try:                                    # jax >= 0.6: public API
    from jax import shard_map as _shard_map
    _CHECK_KW = "check_vma"
except ImportError:                     # older jax: experimental path
    from jax.experimental.shard_map import shard_map as _shard_map
    _CHECK_KW = "check_rep"

__all__ = ["shard_map", "jax_export", "export_fn", "serialize_exported",
           "deserialize_exported"]


def shard_map(f, mesh=None, in_specs=None, out_specs=None,
              check_vma=None, **kw):
    """``jax.shard_map`` with the replication-check kwarg translated
    to whatever the installed jax spells it (check_vma/check_rep)."""
    if check_vma is not None:
        kw[_CHECK_KW] = check_vma
    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, **kw)


def jax_export():
    """The ``jax.export`` module, or None when this jax has no
    serializable-executable support.

    On the 0.4.x line the submodule must be imported explicitly before
    ``jax.export`` attribute access resolves; before 0.4.30 the same
    functions lived at ``jax.experimental.export``.  Callers treat None
    as "no AOT artifacts on this install" and fall back to fresh
    tracing — never as an error.
    """
    try:
        import jax.export as ex
        return ex
    except ImportError:
        pass
    try:
        from jax.experimental import export as ex
        return ex
    except ImportError:
        return None


def export_fn(jitted, *arg_specs, **kw):
    """``jax.export.export(jitted)(*arg_specs)``: trace+lower a jitted
    callable at the given ``jax.ShapeDtypeStruct`` specs into an
    ``Exported`` (serializable StableHLO).  Raises RuntimeError when the
    installed jax cannot export."""
    ex = jax_export()
    if ex is None:
        raise RuntimeError("this jax installation has no jax.export — "
                           "AOT executable artifacts are unavailable")
    return ex.export(jitted, **kw)(*arg_specs)


def serialize_exported(exported):
    """Exported -> bytes (StableHLO + calling convention)."""
    return exported.serialize()


def deserialize_exported(blob):
    """bytes -> Exported; raises on a corrupt or incompatible blob
    (callers catch and fall back to fresh compilation)."""
    ex = jax_export()
    if ex is None:
        raise RuntimeError("this jax installation has no jax.export")
    return ex.deserialize(blob)
