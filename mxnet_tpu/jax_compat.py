"""Version-portability shims for the narrow set of jax APIs whose
import path moved between the versions this framework runs against.

The repo targets current jax (``jax.shard_map``, replication checking
under ``check_vma=``); accelerator hosts frequently pin an older
release where the same function lives at
``jax.experimental.shard_map.shard_map`` and the kwarg is spelled
``check_rep=``.  Everything else the framework uses is stable across
that range, so this module stays deliberately tiny — one import site
per moved symbol, no feature flags.
"""

from __future__ import annotations

try:                                    # jax >= 0.6: public API
    from jax import shard_map as _shard_map
    _CHECK_KW = "check_vma"
except ImportError:                     # older jax: experimental path
    from jax.experimental.shard_map import shard_map as _shard_map
    _CHECK_KW = "check_rep"

__all__ = ["shard_map"]


def shard_map(f, mesh=None, in_specs=None, out_specs=None,
              check_vma=None, **kw):
    """``jax.shard_map`` with the replication-check kwarg translated
    to whatever the installed jax spells it (check_vma/check_rep)."""
    if check_vma is not None:
        kw[_CHECK_KW] = check_vma
    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, **kw)
