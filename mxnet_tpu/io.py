"""Data iterators.

Rebuild of the reference IO stack (include/mxnet/io.h, src/io/*, python
frontend python/mxnet/io.py): the ``DataIter`` protocol
(BeforeFirst/Next ≙ reset/next), ``DataBatch`` with pad/index,
``NDArrayIter`` (numpy feeding), ``ResizeIter``, ``PrefetchingIter``
(background-thread double-buffering, the PrefetcherIter equivalent —
iter_prefetcher.h:47-152), ``CSVIter`` and ``MNISTIter`` (idx format,
with distributed ``part_index``/``num_parts`` sharding like
iter_mnist.cc).  The ImageRecordIter pipeline lives in image_io.py.
"""

from __future__ import annotations

import queue
import re
import struct
import threading
import time

import numpy as np

from . import ndarray as nd
from . import telemetry
from .base import MXNetError, env_int
from .ndarray import NDArray

__all__ = ["DataIter", "DataBatch", "NDArrayIter", "ResizeIter",
           "PrefetchingIter", "CSVIter", "MNISTIter", "DataDesc",
           "LayoutMapper", "DefaultLayoutMapper", "MXDataIter",
           "iter_registry"]


class DataDesc:
    """Name+shape(+dtype+layout) of one data stream (io.py DataDesc)."""

    def __init__(self, name, shape, dtype=np.float32, layout="NCHW"):
        self.name = name
        self.shape = tuple(shape)
        self.dtype = dtype
        self.layout = layout

    def __iter__(self):  # unpack like a (name, shape) tuple
        yield self.name
        yield self.shape

    def __getitem__(self, i):
        return (self.name, self.shape)[i]

    def __repr__(self):
        return f"DataDesc[{self.name},{self.shape},{self.dtype},{self.layout}]"

    @staticmethod
    def get_batch_axis(layout):
        """Batch ('N') axis of a layout string; 0 for None (whole-array
        default), -1 when the layout has no batch axis (reference io.py
        DataDesc.get_batch_axis — the one implementation; the executor
        group's slicing delegates here)."""
        if layout is None:
            return 0
        return layout.find("N")

    @staticmethod
    def get_list(shapes, types):
        """DataDesc list from (name, shape) pairs and optional
        (name, type) pairs (reference io.py:629-643)."""
        if types is not None:
            type_dict = dict(types)
            return [DataDesc(x[0], x[1], type_dict[x[0]]) for x in shapes]
        return [DataDesc(x[0], x[1]) for x in shapes]


class DataBatch:
    """One mini-batch (reference io.py:86)."""

    def __init__(self, data, label=None, pad=0, index=None,
                 bucket_key=None, provide_data=None, provide_label=None):
        self.data = data
        self.label = label if label is not None else []
        self.pad = pad
        self.index = index
        self.bucket_key = bucket_key
        self.provide_data = provide_data
        self.provide_label = provide_label


def _tel_batch_counter(it):
    """Per-instance cached ``mxtpu_io_batches_total{iterator=...}``
    child (the shared NOOP when telemetry is disabled, so the counting
    costs one attribute call per batch)."""
    child = getattr(it, "_tel_batches", None)
    if child is None:
        child = telemetry.counter(
            "mxtpu_io_batches_total", "batches produced by data iterators",
            ("iterator",)).labels(iterator=type(it).__name__)
        it._tel_batches = child
    return child


class DataIter:
    """Iterator protocol (reference io.py:100): reset / next / iter, with
    provide_data/provide_label shape advertisement."""

    def __init__(self):
        self.batch_size = 0

    def reset(self):
        pass

    def __iter__(self):
        return self

    def __next__(self):
        return self.next()

    def next(self) -> DataBatch:
        if self.iter_next():
            _tel_batch_counter(self).inc()
            return DataBatch(self.getdata(), self.getlabel(), self.getpad(),
                             self.getindex())
        raise StopIteration

    def iter_next(self):
        raise NotImplementedError

    def getdata(self):
        return None

    def getlabel(self):
        # base returns None (reference io.py:152-160 `pass`): label-free
        # iterators (e.g. a GAN noise source) only override getdata
        return None

    def getindex(self):
        return None

    def getpad(self):
        return 0

    # NOTE: not properties — the reference idiom lets subclasses simply
    # assign self.provide_data/provide_label in __init__ (e.g. the
    # reference DCGAN's RandIter, example/gan/dcgan.py:75-80); read-only
    # properties here would break such user iterators.
    provide_data = None
    provide_label = None


def _init_data(data, allow_empty, default_name):
    """Normalize input data to list of (name, np.ndarray) (io.py:330-365)."""
    if data is None:
        if not allow_empty:
            raise ValueError("data cannot be None")
        return []
    if isinstance(data, (np.ndarray, NDArray)):
        data = [data]
    if isinstance(data, (list, tuple)):
        if not allow_empty and len(data) == 0:
            raise ValueError("empty data")
        if len(data) == 1:
            data = {default_name: data[0]}
        else:
            data = {f"_{i}_{default_name}": d for i, d in enumerate(data)}
    if not isinstance(data, dict):
        raise TypeError("data must be array, list or dict")
    out = []
    for k, v in data.items():
        if isinstance(v, NDArray):
            v = v.asnumpy()
        out.append((k, np.asarray(v)))
    return out


class NDArrayIter(DataIter):
    """Iterate over in-memory numpy/NDArray data (reference io.py:402).

    Supports shuffle, discard/pad/roll_over last-batch handling.
    """

    def __init__(self, data, label=None, batch_size=1, shuffle=False,
                 last_batch_handle="pad", data_name="data", label_name="softmax_label"):
        super().__init__()
        self.data = _init_data(data, allow_empty=False, default_name=data_name)
        self.label = _init_data(label, allow_empty=True, default_name=label_name)
        self.num_data = self.data[0][1].shape[0]
        if shuffle:
            perm = np.random.permutation(self.num_data)
            self.data = [(k, v[perm]) for k, v in self.data]
            self.label = [(k, v[perm]) for k, v in self.label]
        if last_batch_handle == "discard":
            self.num_data = (self.num_data // batch_size) * batch_size
        if self.num_data < batch_size:
            raise MXNetError("batch_size larger than dataset size")
        self.batch_size = batch_size
        self.last_batch_handle = last_batch_handle
        self.cursor = -batch_size

    @property
    def provide_data(self):
        return [DataDesc(k, (self.batch_size,) + v.shape[1:], v.dtype)
                for k, v in self.data]

    @property
    def provide_label(self):
        return [DataDesc(k, (self.batch_size,) + v.shape[1:], v.dtype)
                for k, v in self.label]

    def hard_reset(self):
        """Ignore roll_over: rewind to the exact start (reference
        io.py:477)."""
        self.cursor = -self.batch_size

    def reset(self):
        if self.last_batch_handle == "roll_over" and self.cursor > self.num_data:
            self.cursor = -self.batch_size + (self.cursor % self.num_data) % self.batch_size
        else:
            self.cursor = -self.batch_size

    def iter_next(self):
        self.cursor += self.batch_size
        return self.cursor < self.num_data

    def _getdata(self, data_source):
        if self.cursor + self.batch_size <= self.num_data:
            return [nd.array(v[self.cursor:self.cursor + self.batch_size])
                    for _, v in data_source]
        # pad: wrap around
        pad = self.batch_size - (self.num_data - self.cursor)
        return [nd.array(np.concatenate([v[self.cursor:], v[:pad]], axis=0))
                for _, v in data_source]

    def getdata(self):
        return self._getdata(self.data)

    def getlabel(self):
        return self._getdata(self.label)

    def getpad(self):
        if (self.last_batch_handle == "pad"
                and self.cursor + self.batch_size > self.num_data):
            return self.cursor + self.batch_size - self.num_data
        return 0


class ResizeIter(DataIter):
    """Resize an iterator to ``size`` batches per epoch (io.py ResizeIter)."""

    def __init__(self, data_iter, size, reset_internal=True):
        super().__init__()
        self.data_iter = data_iter
        self.size = size
        self.reset_internal = reset_internal
        self.cur = 0
        self.current_batch = None
        self.batch_size = data_iter.batch_size

    @property
    def provide_data(self):
        return self.data_iter.provide_data

    @property
    def provide_label(self):
        return self.data_iter.provide_label

    def reset(self):
        self.cur = 0
        if self.reset_internal:
            self.data_iter.reset()

    def iter_next(self):
        if self.cur == self.size:
            return False
        try:
            self.current_batch = self.data_iter.next()
        except StopIteration:
            self.data_iter.reset()
            self.current_batch = self.data_iter.next()
        self.cur += 1
        return True

    def getdata(self):
        return self.current_batch.data

    def getlabel(self):
        return self.current_batch.label

    def getindex(self):
        return self.current_batch.index

    def getpad(self):
        return self.current_batch.pad


class PrefetchingIter(DataIter):
    """Background-thread prefetch over one or more iterators
    (reference io.py:236 + dmlc ThreadedIter double-buffering)."""

    def __init__(self, iters, rename_data=None, rename_label=None,
                 capacity=None):
        super().__init__()
        if not isinstance(iters, list):
            iters = [iters]
        self.iters = iters
        self.rename_data = rename_data
        self.rename_label = rename_label
        self.batch_size = self.provide_data[0][1][0]
        if capacity is None:
            # deployment-wide default; the constructor argument wins
            capacity = env_int("MXTPU_PREFETCH_CAPACITY", 2)
        self.capacity = max(1, int(capacity))
        self._queue = queue.Queue(maxsize=self.capacity)
        self._epoch = 0
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._producer, daemon=True)
        self._thread.start()
        self.current_batch = None

    @property
    def provide_data(self):
        if self.rename_data is None:
            return sum([i.provide_data for i in self.iters], [])
        return sum([[DataDesc(r[n], s) if isinstance(s, tuple) else (r[n], s)
                     for n, s in i.provide_data]
                    for r, i in zip(self.rename_data, self.iters)], [])

    @property
    def provide_label(self):
        if self.rename_label is None:
            return sum([i.provide_label for i in self.iters], [])
        return sum([[DataDesc(r[n], s) if isinstance(s, tuple) else (r[n], s)
                     for n, s in i.provide_label]
                    for r, i in zip(self.rename_label, self.iters)], [])

    def _producer(self):
        while not self._stop.is_set():
            try:
                batches = [i.next() for i in self.iters]
                self._queue.put(("batch", batches))
            except StopIteration:
                self._queue.put(("end", None))
                for i in self.iters:
                    i.reset()

    def reset(self):
        # drain until epoch-end marker so next epoch starts fresh
        while True:
            kind, _ = self._queue.get()
            if kind == "end":
                break

    def _tel_wait_hist(self):
        # cached per instance, re-resolved when telemetry enablement
        # flips — an iterator built before enable() must not stay a
        # permanent no-op
        cached = getattr(self, "_tel_wait", None)
        enabled = telemetry.enabled()
        if cached is None or cached[0] is not enabled:
            hist = telemetry.histogram(
                "mxtpu_io_wait_seconds",
                "time the consumer blocked on the prefetch queue",
                ("iterator",)).labels(iterator=type(self).__name__)
            self._tel_wait = cached = (enabled, hist)
        return cached[1]

    def _tel_depth_gauge(self):
        cached = getattr(self, "_tel_depth", None)
        enabled = telemetry.enabled()
        if cached is None or cached[0] is not enabled:
            g = telemetry.gauge(
                "mxtpu_io_prefetch_depth",
                "batches currently buffered in the prefetch queue",
                ("iterator",)).labels(iterator=type(self).__name__)
            self._tel_depth = cached = (enabled, g)
        return cached[1]

    def iter_next(self):
        # queue wait == how far the producer thread is behind the
        # consumer (0 means the pipeline keeps up; the per-batch analog
        # of the fit loop's data_wait phase)
        t0 = time.perf_counter()
        kind, batches = self._queue.get()
        self._tel_wait_hist().observe(time.perf_counter() - t0)
        # live depth AFTER the pop: capacity means the producer is fully
        # ahead, 0 means the consumer is about to block
        self._tel_depth_gauge().set(self._queue.qsize())
        if kind == "end":
            return False
        data = sum([b.data for b in batches], [])
        label = sum([b.label for b in batches], [])
        self.current_batch = DataBatch(data, label, batches[0].pad,
                                       batches[0].index)
        return True

    def next(self):
        if self.iter_next():
            _tel_batch_counter(self).inc()
            return self.current_batch
        raise StopIteration

    def getdata(self):
        return self.current_batch.data

    def getlabel(self):
        return self.current_batch.label

    def getindex(self):
        return self.current_batch.index

    def getpad(self):
        return self.current_batch.pad

    def __del__(self):
        self._stop.set()


class CSVIter(NDArrayIter):
    """CSV-backed iterator (src/io/iter_csv.cc equivalent)."""

    def __init__(self, data_csv, data_shape, label_csv=None, label_shape=(1,),
                 batch_size=1, round_batch=True, **kwargs):
        data = np.loadtxt(data_csv, delimiter=",", dtype=np.float32)
        data = data.reshape((-1,) + tuple(data_shape))
        label = None
        if label_csv is not None:
            label = np.loadtxt(label_csv, delimiter=",", dtype=np.float32)
            label = label.reshape((-1,) + tuple(label_shape))
            if label_shape == (1,):
                label = label.reshape(-1)
        kwargs.setdefault("label_name", "label")
        super().__init__(data, label, batch_size,
                         last_batch_handle="pad" if round_batch else "discard",
                         **kwargs)


def _read_idx_file(path):
    """Read an MNIST idx file (iter_mnist.cc format)."""
    import gzip

    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        magic = struct.unpack(">i", f.read(4))[0]
        ndim = magic % 256
        dims = [struct.unpack(">i", f.read(4))[0] for _ in range(ndim)]
        dtype = np.dtype({8: np.uint8, 9: np.int8, 11: np.int16, 12: np.int32,
                          13: np.float32, 14: np.float64}[(magic >> 8) % 256])
        data = np.frombuffer(f.read(), dtype=dtype.newbyteorder(">"))
        return data.reshape(dims).astype(dtype)


class MNISTIter(NDArrayIter):
    """MNIST idx-format iterator (src/io/iter_mnist.cc:250) with
    flat/shuffle/partition options including distributed sharding via
    part_index / num_parts."""

    def __init__(self, image, label, batch_size=128, shuffle=True, flat=False,
                 silent=False, seed=0, part_index=0, num_parts=1,
                 input_shape=None, **kwargs):
        images = _read_idx_file(image).astype(np.float32) / 255.0
        labels = _read_idx_file(label).astype(np.float32)
        if flat:
            images = images.reshape(images.shape[0], -1)
        else:
            images = images.reshape(images.shape[0], 1,
                                    images.shape[1], images.shape[2])
        if input_shape is not None:
            images = images.reshape((images.shape[0],) + tuple(input_shape))
        if num_parts > 1:
            images = images[part_index::num_parts]
            labels = labels[part_index::num_parts]
        if shuffle:
            rng = np.random.RandomState(seed)
            perm = rng.permutation(images.shape[0])
            images, labels = images[perm], labels[perm]
        super().__init__(images, labels, batch_size, shuffle=False,
                         last_batch_handle="discard", **kwargs)


# -- layout mappers (reference io.py:24-85) ---------------------------------

class LayoutMapper:
    """Decide the layout (hence batch axis) of a stream from its NAME
    alone — the reference protocol (io.py:24-57) used when shapes come
    without :class:`DataDesc` metadata.  The TPU build carries layouts
    on ``DataDesc`` directly; this mapper exists for reference-style
    code that encodes layout in names instead."""

    def get_layout_string(self, name):
        raise NotImplementedError

    def get_batch_axis(self, name):
        """Index of the 'N' axis; -1 when the stream has no batch axis."""
        layout = self.get_layout_string(name)
        return -1 if layout is None else layout.find("N")


class DefaultLayoutMapper(LayoutMapper):
    """Name-tag layout mapper (reference io.py:59-85): a name carrying a
    ``:__layout_NTC__`` tag yields that layout; anything else yields the
    constructor default.  (The tag regex accepts a full layout string —
    multi-character — rather than the single character the reference's
    pattern matched.)"""

    LAYOUT_PATTERN = re.compile(r":__layout_([A-Za-z]+)__")

    def __init__(self, default_layout="NCHW"):
        self._default = default_layout

    def get_layout_string(self, name):
        m = self.LAYOUT_PATTERN.search(name)
        return m.group(1) if m else self._default


# -- by-name iterator factory (reference io.py:521 MXDataIter) --------------

def iter_registry():
    """Name → iterator class for every registered iterator; the same
    registry backs the C ABI's MXTPUListDataIters/MXTPUDataIterCreate
    (reference: runtime-discovered C++ iterators, MXNET_REGISTER_IO_ITER
    include/mxnet/io.h:24-98)."""
    from . import image_io
    return {"MNISTIter": MNISTIter, "CSVIter": CSVIter,
            "NDArrayIter": NDArrayIter,
            "ImageRecordIter": image_io.ImageRecordIter}


def MXDataIter(name, **kwargs):
    """Create a registered iterator by name — the reference's handle-based
    ``MXDataIter`` (io.py:521, backed by MXDataIterCreateIter) as a
    factory.  In the TPU build every iterator is a Python class with a
    native fast path, so the 'handle' is simply the instance."""
    cls = iter_registry().get(name)
    if cls is None:
        raise MXNetError(
            f"no data iterator {name!r}; available: {sorted(iter_registry())}")
    return cls(**kwargs)
