"""Time-series ring: periodic metric snapshots -> windowed rates.

The metrics registry (metrics.py) holds *monotonic* counters — tokens
generated, rejections, handoff bytes — which answer "how much, ever".
Operations questions are windowed: "tokens/sec over the last minute",
"p90 queue depth over the last five".  Prometheus answers those
server-side with ``rate()``; this module is the in-process analog, so
the serve monitor, the ``/statusz`` page and the fleet collector can
read windowed rates *locally* with no external scraper deployed.

A :class:`TimeSeriesRing` is a bounded ring of ``(t, {series: value})``
samples.  Values come from anywhere flat — :func:`flatten_registry`
folds the process registry into one dict (histograms contribute
``_count``/``_sum``), :func:`parse_prometheus_text` does the same for
a scraped ``/metrics`` body (the fleet collector feeds per-replica
rings from replicas' scraped statusz + metrics) — and the read side is

  ``rate(name, window_s)``          per-second increase of a counter
                                    (reset-aware: a restarted process
                                    restarts the series, not the math)
  ``delta(name, window_s)``         absolute increase over the window
  ``quantile_over(name, window_s)`` nearest-rank quantile of sampled
                                    values (gauges: queue depth, KV
                                    utilization)
  ``latest(name)`` / ``series(name, window_s)``

The process-global ring is **off by default and fully inert**: no ring
object, no statusz section, and — by design — no thread ever.  Set
``MXTPU_TIMESERIES`` to a ring capacity (samples kept) to enable it;
sampling then piggybacks on call sites that already run periodically
(``ServeMonitor.tic``'s logging cadence), rate-limited to one sample
per ``MXTPU_TIMESERIES_INTERVAL`` seconds.  When enabled, the ring
registers a ``timeseries`` section on ``/statusz`` with windowed rates
of the headline serve counters.
"""

from __future__ import annotations

import re
import threading
import time
from collections import deque

__all__ = ["TimeSeriesRing", "flatten_registry", "parse_prometheus_text",
           "nearest_rank", "ring", "sample", "configure",
           "ENV_CAPACITY", "ENV_INTERVAL"]


def nearest_rank(sorted_vals, q):
    """Nearest-rank quantile of an ascending list (None when empty) —
    THE quantile convention for the whole observability stack: the
    serve stats reservoirs, the ring's ``quantile_over`` and the fleet
    collector all call this one helper, so their percentiles can never
    disagree on the same data.  (``tools/trace_report.py`` carries an
    intentionally separate copy: it must stay stdlib-only.)"""
    if not sorted_vals:
        return None
    idx = min(len(sorted_vals) - 1, max(0, round(q * (len(sorted_vals) - 1))))
    return sorted_vals[int(idx)]

ENV_CAPACITY = "MXTPU_TIMESERIES"
ENV_INTERVAL = "MXTPU_TIMESERIES_INTERVAL"

# the /statusz "rates" teaser: headline serve counters rendered as
# 60-second windowed rates when present in the ring
_HEADLINE = (
    ("mxtpu_serve_tokens_generated_total", "tokens_per_sec"),
    ("mxtpu_serve_completed_total", "completed_per_sec"),
    ("mxtpu_serve_backpressure_rejects_total", "rejects_per_sec"),
    ("mxtpu_fleet_handoff_bytes_total{direction=received}",
     "handoff_recv_bytes_per_sec"),
)


def _series_key(name, label_names, label_values):
    if not label_names:
        return name
    labels = ",".join(f"{n}={v}"
                      for n, v in zip(label_names, label_values))
    return f"{name}{{{labels}}}"


def flatten_registry(registry):
    """One flat ``{series_key: float}`` view of a metrics Registry:
    counters/gauges contribute their value under
    ``name{label=value,...}`` (bare ``name`` when label-free);
    histograms contribute ``name_count`` and ``name_sum`` (both
    monotonic, so ``rate()`` works on them — count/sec and the mean
    over a window as ``delta(sum)/delta(count)``)."""
    out = {}
    for fam in registry.collect():
        for key, child in fam.children():
            if fam.kind == "histogram":
                out[_series_key(fam.name + "_count", fam.label_names,
                                key)] = float(child.count)
                out[_series_key(fam.name + "_sum", fam.label_names,
                                key)] = float(child.sum)
            else:
                out[_series_key(fam.name, fam.label_names,
                                key)] = float(child.value)
    return out


# one exposition line: name{labels} value  (labels optional; the
# histogram _bucket series are skipped — quantiles over raw samples
# are the ring's own job).  The value is matched loosely and parsed
# by float(): a character-class would silently drop legal spellings
# (repr(6.5e-05) carries a '-' INSIDE the exponent, "+Inf"/"NaN" vary
# by producer), and a dropped sample holes the series with no failure
# counted anywhere.
_PROM_LINE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^}]*\})?\s+(\S+)$")
_PROM_LABEL = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="([^"]*)"')


def parse_prometheus_text(text):
    """Parse a Prometheus 0.0.4 text exposition into the same flat
    ``{series_key: float}`` shape :func:`flatten_registry` produces
    (label quoting stripped; ``_bucket`` series dropped)."""
    out = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _PROM_LINE.match(line)
        if not m:
            continue
        name, labels, value = m.groups()
        if name.endswith("_bucket"):
            continue
        try:
            v = float(value)
        except ValueError:
            continue
        if labels:
            pairs = _PROM_LABEL.findall(labels)
            key = (name + "{"
                   + ",".join(f"{k}={val}" for k, val in pairs) + "}")
        else:
            key = name
        out[key] = v
    return out


class TimeSeriesRing:
    """Bounded ring of ``(t, values)`` samples with windowed readers.

    Thread-safe: the write side may be a monitor/scrape thread while
    `/statusz` or the fleet view reads.  ``clock`` is injectable
    (fake-clock tests); it must be monotonic — every window computation
    is an elapsed-time question.
    """

    def __init__(self, capacity=512, clock=time.monotonic):
        self.capacity = max(2, int(capacity))
        self.clock = clock
        self._lock = threading.Lock()
        self._samples = deque(maxlen=self.capacity)  # guarded-by: _lock
        self._taken = 0                              # guarded-by: _lock
        self._last_sample_t = None                   # guarded-by: _lock

    # -- write side ----------------------------------------------------------
    def append(self, values, now=None):
        """Record one sample (a flat ``{series: number}`` dict;
        non-numeric values are dropped)."""
        t = self.clock() if now is None else now
        vals = {}
        for k, v in values.items():
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                continue
            vals[str(k)] = float(v)
        with self._lock:
            self._samples.append((t, vals))
            self._taken += 1
        return t

    def sample_registry(self, registry, now=None, min_interval_s=0.0):
        """Append a registry snapshot, rate-limited to one sample per
        ``min_interval_s``.  Returns True when a sample was taken."""
        t = self.clock() if now is None else now
        with self._lock:
            if (self._last_sample_t is not None and min_interval_s > 0
                    and t - self._last_sample_t < min_interval_s):
                return False
            self._last_sample_t = t
        self.append(flatten_registry(registry), now=t)
        return True

    # -- read side -----------------------------------------------------------
    def _points(self, name, window_s, now):
        cutoff = None if window_s is None else now - window_s
        with self._lock:
            return [(t, vals[name]) for t, vals in self._samples
                    if name in vals
                    and (cutoff is None or t >= cutoff)]

    def series(self, name, window_s=None, now=None):
        """``[(t, value)]`` of one series, oldest first, optionally
        restricted to the trailing window."""
        now = self.clock() if now is None else now
        return self._points(name, window_s, now)

    def latest(self, name):
        """Most recent value of a series, or None."""
        with self._lock:
            for t, vals in reversed(self._samples):
                if name in vals:
                    return vals[name]
        return None

    def delta(self, name, window_s, now=None):
        """Absolute increase of a monotonic counter over the window —
        reset-aware: a value drop (process restart) contributes the
        fresh life's absolute level, never a negative step."""
        pts = self.series(name, window_s, now)
        if len(pts) < 2:
            return None
        total = 0.0
        for (_, a), (_, b) in zip(pts, pts[1:]):
            total += (b - a) if b >= a else b
        return total

    def rate(self, name, window_s, now=None):
        """Per-second increase of a monotonic counter over the trailing
        window (None with < 2 points or zero elapsed time)."""
        pts = self.series(name, window_s, now)
        if len(pts) < 2:
            return None
        dt = pts[-1][0] - pts[0][0]
        if dt <= 0:
            return None
        return self.delta(name, window_s, now) / dt

    def quantile_over(self, name, window_s, q, now=None):
        """Nearest-rank quantile of a series' sampled values over the
        window (the gauge analog of ``rate``: p90 queue depth)."""
        return nearest_rank(
            sorted(v for _, v in self.series(name, window_s, now)), q)

    def names(self):
        """Every series name currently present in the ring."""
        out = set()
        with self._lock:
            for _, vals in self._samples:
                out.update(vals)
        return sorted(out)

    def __len__(self):
        with self._lock:
            return len(self._samples)

    @property
    def taken(self):
        with self._lock:
            return self._taken

    def span_s(self):
        """Elapsed time covered by the ring's samples."""
        with self._lock:
            if len(self._samples) < 2:
                return 0.0
            return self._samples[-1][0] - self._samples[0][0]

    def statusz(self):
        """The ``/statusz`` ``timeseries`` section: ring shape plus
        60-second windowed rates of the headline serve counters."""
        rates = {}
        for name, label in _HEADLINE:
            r = self.rate(name, 60.0)
            if r is not None:
                rates[label] = round(r, 3)
        return {"samples": len(self), "capacity": self.capacity,
                "taken": self.taken, "span_s": round(self.span_s(), 3),
                "series": len(self.names()), "rates_60s": rates}


# -- the process-global ring (env-gated; inert when unconfigured) -----------
_global_lock = threading.Lock()
_global_ring = None        # guarded-by: _global_lock
_global_checked = False    # guarded-by: _global_lock


def configure(capacity, interval_s=1.0):
    """Programmatic enable (tests / embedders): create the global ring
    with ``capacity`` samples and register its statusz section.
    ``capacity`` <= 0 tears it down (back to inert)."""
    global _global_ring, _global_checked
    from . import statusz as statusz_mod

    with _global_lock:
        _global_checked = True
        if capacity and capacity > 0:
            _global_ring = TimeSeriesRing(capacity)
            _global_ring.sample_interval_s = float(interval_s)
            statusz_mod.register("timeseries", _global_ring.statusz)
        else:
            _global_ring = None
            statusz_mod.unregister("timeseries")
    return _global_ring


def ring():
    """The process-global ring, or None when unconfigured.  Created on
    first call from ``MXTPU_TIMESERIES`` (ring capacity; 0/unset =
    off — no object, no statusz section, and never a thread)."""
    with _global_lock:
        if _global_checked:
            return _global_ring
    from ..base import env_float, env_int

    cap = env_int(ENV_CAPACITY, 0)
    return configure(cap, env_float(ENV_INTERVAL, 1.0))


def sample(now=None):
    """Sample the process registry into the global ring (no-op when
    unconfigured) — call from any periodic site; the per-ring interval
    keeps high-frequency callers cheap.  Returns True on a sample."""
    r = ring()
    if r is None:
        return False
    from mxnet_tpu import telemetry

    return r.sample_registry(telemetry.registry(), now=now,
                             min_interval_s=getattr(
                                 r, "sample_interval_s", 1.0))
