"""Metrics registry: Counter / Gauge / Histogram families with label
sets, and the shared no-op objects the disabled path hands out.

Model follows Prometheus client conventions (a *family* is the named
metric; ``labels(...)`` resolves one *child* per label-value tuple) so
the text exposition in exporters.py is a straight serialization.  All
mutation goes through per-family locks — instrumented call sites may
live on the PrefetchingIter producer thread, the ShardedTrainer
prefetch thread, or an HTTP scrape thread simultaneously.

The disabled path never reaches any of this: ``telemetry.counter()``
returns the module-level ``NOOP`` singleton whose methods are empty —
one attribute call per event, no locks, no allocation (the contract
tests/test_telemetry.py pins for every instrumented site).
"""

from __future__ import annotations

import bisect
import threading

__all__ = ["Registry", "Counter", "Gauge", "Histogram", "NOOP",
           "DEFAULT_BUCKETS"]

# latency-oriented default buckets (seconds), Prometheus client defaults
DEFAULT_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                   0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0)


class _Noop:
    """Shared do-nothing stand-in for every metric object when
    telemetry is disabled.  ``labels()`` returns itself, so cached
    children at instrumented sites are this same singleton."""

    __slots__ = ()

    def labels(self, *args, **kwargs):
        return self

    def inc(self, amount=1):
        pass

    def dec(self, amount=1):
        pass

    def set(self, value):
        pass

    def observe(self, value):
        pass


NOOP = _Noop()


class _Child:
    """One (family, label-values) time series."""

    __slots__ = ("_lock", "value")

    def __init__(self, lock):
        self._lock = lock
        self.value = 0.0


class _CounterChild(_Child):
    __slots__ = ()

    def inc(self, amount=1):
        if amount < 0:
            raise ValueError("counters can only increase")
        with self._lock:
            self.value += amount


class _GaugeChild(_Child):
    __slots__ = ()

    def set(self, value):
        with self._lock:
            self.value = float(value)

    def inc(self, amount=1):
        with self._lock:
            self.value += amount

    def dec(self, amount=1):
        with self._lock:
            self.value -= amount


class _HistogramChild:
    __slots__ = ("_lock", "_uppers", "bucket_counts", "sum", "count")

    def __init__(self, lock, uppers):
        self._lock = lock
        self._uppers = uppers              # finite upper bounds, sorted
        self.bucket_counts = [0] * (len(uppers) + 1)   # + the +Inf bucket
        self.sum = 0.0
        self.count = 0

    def observe(self, value):
        value = float(value)
        i = bisect.bisect_left(self._uppers, value)
        with self._lock:
            self.bucket_counts[i] += 1
            self.sum += value
            self.count += 1

    def cumulative(self):
        """[(upper_bound, cumulative_count)] with the trailing +Inf
        (``float('inf')``) bucket — the Prometheus ``le`` view."""
        with self._lock:
            counts = list(self.bucket_counts)
        out, acc = [], 0
        for ub, c in zip(list(self._uppers) + [float("inf")], counts):
            acc += c
            out.append((ub, acc))
        return out


class _Family:
    kind = None

    def __init__(self, name, help, label_names):
        self.name = name
        self.help = help
        self.label_names = tuple(label_names)
        self._lock = threading.Lock()
        self._children = {}

    def _new_child(self):
        raise NotImplementedError

    def labels(self, *values, **kv):
        if values and kv:
            raise ValueError("pass label values positionally or by "
                             "keyword, not both")
        if kv:
            if set(kv) != set(self.label_names):
                raise ValueError(
                    f"{self.name}: expected labels {self.label_names}, "
                    f"got {tuple(sorted(kv))}")
            key = tuple(str(kv[n]) for n in self.label_names)
        else:
            if len(values) != len(self.label_names):
                raise ValueError(
                    f"{self.name}: expected {len(self.label_names)} label "
                    f"values, got {len(values)}")
            key = tuple(str(v) for v in values)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._new_child()
                self._children[key] = child
            return child

    def _default(self):
        if self.label_names:
            raise ValueError(
                f"{self.name} has labels {self.label_names}; resolve a "
                "child with .labels(...) first")
        return self.labels()

    # label-free convenience: family acts as its own single child
    def inc(self, amount=1):
        self._default().inc(amount)

    def dec(self, amount=1):
        self._default().dec(amount)

    def set(self, value):
        self._default().set(value)

    def observe(self, value):
        self._default().observe(value)

    def children(self):
        """Sorted [(label_values_tuple, child)] snapshot."""
        with self._lock:
            return sorted(self._children.items())


class Counter(_Family):
    kind = "counter"

    def _new_child(self):
        return _CounterChild(self._lock)


class Gauge(_Family):
    kind = "gauge"

    def _new_child(self):
        return _GaugeChild(self._lock)


class Histogram(_Family):
    kind = "histogram"

    def __init__(self, name, help, label_names, buckets=DEFAULT_BUCKETS):
        super().__init__(name, help, label_names)
        uppers = sorted(float(b) for b in buckets if b != float("inf"))
        if not uppers:
            raise ValueError("histogram needs at least one finite bucket")
        self.buckets = tuple(uppers)

    def _new_child(self):
        return _HistogramChild(self._lock, self.buckets)


class Registry:
    """Process-wide metric store: get-or-create families by name, with
    kind/label-schema consistency enforced (two call sites registering
    the same name must agree, or one of them is silently measuring the
    wrong thing)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._families = {}

    def _get_or_create(self, cls, name, help, label_names, **kw):
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                if not isinstance(fam, cls):
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{fam.kind}, not {cls.kind}")
                if fam.label_names != tuple(label_names):
                    raise ValueError(
                        f"metric {name!r} already registered with labels "
                        f"{fam.label_names}, not {tuple(label_names)}")
                if "buckets" in kw:
                    want = tuple(sorted(float(b) for b in kw["buckets"]
                                        if b != float("inf")))
                    if fam.buckets != want:
                        raise ValueError(
                            f"histogram {name!r} already registered with "
                            f"buckets {fam.buckets}, not {want} — two "
                            "sites observing into different bounds would "
                            "silently misbucket one of them")
                return fam
            fam = cls(name, help, label_names, **kw)
            self._families[name] = fam
            return fam

    def counter(self, name, help="", label_names=()):
        return self._get_or_create(Counter, name, help, label_names)

    def gauge(self, name, help="", label_names=()):
        return self._get_or_create(Gauge, name, help, label_names)

    def histogram(self, name, help="", label_names=(),
                  buckets=DEFAULT_BUCKETS):
        return self._get_or_create(Histogram, name, help, label_names,
                                   buckets=buckets)

    def collect(self):
        """Sorted family list (stable exposition/snapshot order)."""
        with self._lock:
            return [self._families[n] for n in sorted(self._families)]

    def snapshot(self):
        """JSON-serializable view: {name: {kind, help, label_names,
        samples}}; histogram samples carry cumulative buckets with
        ``+Inf`` spelled as a string (JSON has no Infinity)."""
        out = {}
        for fam in self.collect():
            samples = []
            for key, child in fam.children():
                labels = dict(zip(fam.label_names, key))
                if fam.kind == "histogram":
                    samples.append({
                        "labels": labels,
                        "count": child.count,
                        "sum": child.sum,
                        "buckets": [["+Inf" if ub == float("inf")
                                     else ub, c]
                                    for ub, c in child.cumulative()],
                    })
                else:
                    samples.append({"labels": labels, "value": child.value})
            out[fam.name] = {"kind": fam.kind, "help": fam.help,
                             "label_names": list(fam.label_names),
                             "samples": samples}
        return out

    def clear(self):
        """Drop every family (tests).  Handles cached by instrumented
        sites keep working but detach from future snapshots."""
        with self._lock:
            self._families.clear()
