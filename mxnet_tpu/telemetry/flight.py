"""Flight recorder: an always-on bounded ring of recent events, dumped
atomically to disk when something goes wrong.

Request tracing answers "what happened to this request" — but only if
it was enabled and sampled.  The flight recorder answers "what was the
engine doing in the 30 seconds before it fell over" WITHOUT requiring
any foresight: recording is always on (a bounded ``deque`` append per
event — request lifecycle events, scheduler decisions, per-step
summaries, error/anomaly markers), and the ring is written out as one
atomic JSON file when

  * an engine ``step()`` raises an unhandled exception,
  * an SLO breach fires (a deadline-miss rejection, or the rejection
    rate over the recent-submit window crossing
    ``MXTPU_FLIGHT_REJECT_RATE``),
  * a numeric anomaly trips the watchdog (``MXTPU_NUMERIC_WATCH``), or
  * a caller asks (:func:`dump_now`, the post-mortem "give me
    everything right now" hook).

Automatic dumps are opt-in via ``MXTPU_FLIGHT_DIR`` (no directory, no
files — the ring still records so an explicit ``dump_now(dir=...)``
works).  ``MXTPU_FLIGHT_EVENTS`` sizes the ring (default 4096).  Dumps
are rate-limited per reason (:attr:`FlightRecorder.min_dump_interval_s`)
so a storm of identical breaches cannot fill the disk; engine-exception
dumps bypass the limit (``force=True``).

Each dump also embeds the telemetry registry snapshot and the
``/statusz`` provider snapshot, so the post-mortem file is
self-contained even when no exporter was running.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque

__all__ = ["FlightRecorder", "recorder", "dump_now", "record_anomaly",
           "ENV_DIR", "ENV_EVENTS", "ENV_REJECT_RATE"]

ENV_DIR = "MXTPU_FLIGHT_DIR"
ENV_EVENTS = "MXTPU_FLIGHT_EVENTS"
ENV_REJECT_RATE = "MXTPU_FLIGHT_REJECT_RATE"

DEFAULT_EVENTS = 4096


class FlightRecorder:
    """Bounded ring of ``(ts, kind, fields)`` records + atomic dumps."""

    def __init__(self, max_events=None, min_dump_interval_s=30.0):
        if max_events is None:
            from ..base import env_int

            max_events = env_int(ENV_EVENTS, DEFAULT_EVENTS)
        self.max_events = max(1, int(max_events))
        self.min_dump_interval_s = float(min_dump_interval_s)
        self._lock = threading.Lock()
        self._events = deque(maxlen=self.max_events)  # guarded-by: _lock
        self._seen = 0                                # guarded-by: _lock
        # reason -> monotonic time of last dump (rate limiting must not
        # ride the wall clock: an NTP step backwards would re-arm — or
        # suppress — every reason at once)
        self._last_dump = {}                          # guarded-by: _lock
        self.dumps = 0                                # guarded-by: _lock

    # -- recording (the always-on hot path) --------------------------------
    def record(self, kind, **fields):
        """Append one event to the ring (cheap: one locked deque
        append; the ``maxlen`` deque evicts the oldest on overflow)."""
        # caller fields first, then the reserved keys — "t"/"kind" are
        # the ring's own schema and must never be clobbered by a
        # caller's same-named payload field
        ev = dict(fields) if fields else {}
        # post-mortem events correlate with external logs by timestamp
        # mxtpu-lint: disable=wall-clock (wall timestamp is the point)
        ev["t"] = time.time()
        ev["kind"] = kind
        with self._lock:
            self._events.append(ev)
            self._seen += 1

    def events(self):
        """Snapshot of the ring, oldest first."""
        with self._lock:
            return list(self._events)

    @property
    def seen(self):
        """Total events ever recorded (``seen - len(events())`` have
        scrolled out of the ring)."""
        with self._lock:
            return self._seen

    def clear(self):
        with self._lock:
            self._events.clear()
            self._seen = 0
            self._last_dump = {}

    # -- dumping -----------------------------------------------------------
    def _dir(self, dir=None):
        return dir or os.environ.get(ENV_DIR)

    def dump(self, reason, dir=None, extra=None, force=False):
        """Write the ring (plus registry + statusz snapshots) to
        ``<dir>/flight-<ms>-<reason>.json`` atomically.  Returns the
        path, or None when no directory is configured (automatic dumps
        are opt-in via ``MXTPU_FLIGHT_DIR``) or the per-reason rate
        limit suppressed this one.  Never raises — a failing post-mortem
        writer must not add a second failure to the first."""
        d = self._dir(dir)
        if not d:
            return None
        # wall for the payload/filename (operators correlate dumps with
        # logs), monotonic for the rate limit (immune to NTP steps)
        # mxtpu-lint: disable=wall-clock (post-mortem file timestamp)
        now = time.time()
        mono = time.monotonic()
        with self._lock:
            last = self._last_dump.get(reason)
            if not force and last is not None \
                    and mono - last < self.min_dump_interval_s:
                return None
            self._last_dump[reason] = mono
            events = list(self._events)
            seen = self._seen
        payload = {"ts": round(now, 3), "reason": str(reason),
                   "pid": os.getpid(),
                   "events": events,
                   "events_seen": seen,
                   "ring_capacity": self.max_events}
        if extra:
            payload["extra"] = extra
        # self-contained post-mortem: fold in what the live endpoints
        # would have shown (guarded — the dump must survive a broken
        # provider)
        try:
            from mxnet_tpu import telemetry

            payload["registry"] = telemetry.registry().snapshot()
        except Exception as e:
            # a broken snapshot must not kill the dump — but the dump
            # itself records that its registry section is missing
            payload.setdefault("snapshot_errors", []).append(
                f"registry: {e!r}")
        try:
            from . import statusz

            payload["statusz"] = statusz.snapshot()
        except Exception as e:
            payload.setdefault("snapshot_errors", []).append(
                f"statusz: {e!r}")
        safe = "".join(c if c.isalnum() or c in "-_" else "_"
                       for c in str(reason))[:64] or "dump"
        path = os.path.join(d, f"flight-{int(now * 1000)}-{safe}.json")
        try:
            os.makedirs(d, exist_ok=True)
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(payload, f, default=str)
            os.replace(tmp, path)
        except OSError:
            return None
        with self._lock:
            self.dumps += 1
        return path


_recorder = None
_recorder_lock = threading.Lock()


def recorder():
    """The process-wide flight recorder (created on first use)."""
    global _recorder
    if _recorder is None:
        with _recorder_lock:
            if _recorder is None:
                _recorder = FlightRecorder()
    return _recorder


def dump_now(reason="on_demand", dir=None):
    """On-demand post-mortem dump of the process-wide ring (bypasses
    the rate limit).  Returns the path or None."""
    return recorder().dump(reason, dir=dir, force=True)


def record_anomaly(site, dump_reason="numeric_anomaly", **info):
    """The numeric-watchdog sink: count
    ``mxtpu_numeric_anomalies_total{site}``, mark the ring, and fire a
    (rate-limited) flight dump — instead of silently corrupting a run.
    Returns the dump path or None."""
    from mxnet_tpu import telemetry

    # straight into the registry (not the enabled-gated accessor): the
    # watchdog is its own opt-in, and an anomaly count must survive even
    # when MXTPU_TELEMETRY is unset — it rides the flight dump
    telemetry.registry().counter(
        "mxtpu_numeric_anomalies_total",
        "NaN/Inf detections by the numeric watchdog",
        ("site",)).labels(site=site).inc()
    rec = recorder()
    rec.record("anomaly", site=site, **info)
    return rec.dump(dump_reason, extra={"site": site, **info})
