"""Per-program performance attribution for the serve engine.

Answers "where did the device time go?" per compiled serve program —
(kind, bucket) = prefill/chunk/decode/draft/draft_chunk/verify/restore
x batch bucket — with two independently-gated halves:

**Cost table (default ON, ``MXTPU_PERF_ATTRIB=0`` to disable).**  At
program-resolve time (fresh trace, warm AOT artifact load, or a
process-local step-cache hit) the engine hands each compiled program
to :meth:`PerfAttrib.note_cost`, which records XLA's
``cost_analysis()`` — flops, bytes accessed, output bytes — keyed by
(kind, bucket).  Pure host-side bookkeeping at compile cadence: no
dispatch-path cost, no extra syncs.  When a backend reports no usable
cost analysis the engine's analytic fallback (``flops.gpt_token_flops``
/ ``gpt_prefill_flops``) fills the flops column instead.

**Sampled device timing (default OFF, ``MXTPU_PERF_ATTRIB_SAMPLE=N``
samples every Nth step).**  On sampled steps only, each dispatch is
bracketed ``t0()`` .. ``done()``: ``done`` calls ``block_until_ready``
on the program's outputs and records the elapsed wall-time into a
``mxtpu_serve_program_seconds{kind,bucket}`` histogram plus derived
achieved-TFLOP/s, MFU (vs ``flops.peak_flops_per_chip``), MBU (vs
``flops.peak_hbm_bytes_per_chip``) and cost-per-1k-tokens gauges.  The
sync is rate-gated and rides the engine's existing step cadence, so
with sampling off (the default) the hot path gains ZERO host syncs —
``done(None, ...)`` is a dict lookup and an integer add.  The engine's
step loop immediately consumes the outputs anyway (the designed
``_unpack_outs`` sync point), so sampled timing re-orders the wait, it
does not add device work.

Inertness contract (the PR 10/11 rule): attribution never touches
tokens, program cache keys, or AOT fingerprints — both knobs in any
combination leave greedy output byte-identical and ``_spec_digest``
unchanged (pinned in tests/test_perf_attrib.py).
"""

from __future__ import annotations

import math
import time

from ..base import env_flag, env_int

__all__ = ["PerfAttrib", "ENV_ENABLE", "ENV_SAMPLE",
           "PROGRAM_SECONDS_BUCKETS"]

ENV_ENABLE = "MXTPU_PERF_ATTRIB"          # cost table (default on)
ENV_SAMPLE = "MXTPU_PERF_ATTRIB_SAMPLE"   # sample every Nth step (0=off)

# finer-grained than metrics.DEFAULT_BUCKETS: bucketed serve programs
# live in the 10us .. 1s band on real chips
PROGRAM_SECONDS_BUCKETS = (1e-5, 2.5e-5, 5e-5, 1e-4, 2.5e-4, 5e-4,
                           1e-3, 2.5e-3, 5e-3, 1e-2, 2.5e-2, 5e-2,
                           0.1, 0.25, 0.5, 1.0, 2.5)

_RECENT = 512    # per-program recent-sample window for p99


class _Prog:
    """Per-(kind,bucket) dispatch/timing accumulator."""

    __slots__ = ("dispatches", "sampled", "total_s", "recent")

    def __init__(self):
        self.dispatches = 0
        self.sampled = 0
        self.total_s = 0.0
        self.recent = []          # bounded ring of sampled seconds

    def record(self, dt):
        if self.sampled < _RECENT:
            self.recent.append(dt)
        else:
            self.recent[self.sampled % _RECENT] = dt
        self.sampled += 1
        self.total_s += dt

    def p99(self):
        if not self.recent:
            return None
        s = sorted(self.recent)
        return s[min(len(s) - 1, int(0.99 * len(s)))]

    def mean(self):
        return self.total_s / self.sampled if self.sampled else None


class PerfAttrib:
    """One per engine, constructed AFTER ``telemetry.enable()`` (the
    handle-caching asymmetry: metric handles are cached here at
    construction).  The engine is never referenced — like the program
    builders, this object must not retain a retired engine."""

    def __init__(self, clock=time.perf_counter):
        self.enabled = env_flag(ENV_ENABLE, True)
        self.sample_every = max(0, env_int(ENV_SAMPLE, 0))
        self._clock = clock
        self._cost = {}           # (kind, bucket) -> cost-table entry
        self._prog = {}           # (kind, bucket) -> _Prog
        self._armed = False
        self._step_s = 0.0        # timed seconds within the armed step
        self._sampled_steps = 0
        self._tokens = 0          # all emitted tokens (cheap int add)
        self._sampled_tokens = 0  # emitted during sampled steps
        self._device_s = 0.0      # timed seconds across sampled steps
        self.cost_errors = 0      # cost_analysis() refusals (statusz)
        try:
            from .. import flops as _flops

            self.peak_flops = _flops.peak_flops_per_chip()
            self.peak_bytes = _flops.peak_hbm_bytes_per_chip()
        except Exception:
            # off-accelerator / uninitialized backend: utilization
            # columns degrade to None, attribution still works
            self.peak_flops = None
            self.peak_bytes = None
            self.cost_errors += 1
        from .. import telemetry as tel

        self._hist = tel.histogram(
            "mxtpu_serve_program_seconds",
            "sampled device wall-time per serve program dispatch",
            ("kind", "bucket"), buckets=PROGRAM_SECONDS_BUCKETS)
        self._g_tflops = tel.gauge(
            "mxtpu_serve_achieved_tflops",
            "achieved TFLOP/s over sampled dispatches", ("kind",))
        self._g_mfu = tel.gauge(
            "mxtpu_serve_mfu",
            "achieved FLOP/s over peak_flops_per_chip", ("kind",))
        self._g_mbu = tel.gauge(
            "mxtpu_serve_mbu",
            "achieved bytes/s over peak HBM bandwidth", ("kind",))
        self._g_cost = tel.gauge(
            "mxtpu_serve_cost_per_1k_tokens_seconds",
            "sampled device-seconds per 1000 emitted tokens")

    # -- cost table (compile cadence) -----------------------------------
    def note_cost(self, kind, bucket, fn, fallback_flops=None,
                  fallback_bytes=None):
        """Record ``fn``'s ``cost_analysis()`` under (kind, bucket);
        idempotent per key, tolerant of backends/fallback callables
        without one.  ``fallback_flops`` (the analytic estimate) fills
        the flops column when XLA reports none."""
        if not self.enabled:
            return
        key = (kind, int(bucket))
        if key in self._cost:
            return
        ent = {"flops": None, "bytes_accessed": None,
               "output_bytes": None, "source": None}
        try:
            ca = fn.cost_analysis()
            if isinstance(ca, (list, tuple)):   # older jax: list of dicts
                ca = ca[0] if ca else {}
            f = float(ca.get("flops", 0.0) or 0.0)
            if f > 0.0 and math.isfinite(f):
                ent["flops"] = f
                ent["source"] = "cost_analysis"
            b = float(ca.get("bytes accessed", 0.0) or 0.0)
            if b > 0.0 and math.isfinite(b):
                ent["bytes_accessed"] = b
            ob = float(ca.get("bytes accessed output", 0.0) or 0.0)
            if ob > 0.0 and math.isfinite(ob):
                ent["output_bytes"] = ob
        except Exception:
            # lazy-jit fallbacks have no .cost_analysis(); some
            # backends raise — the analytic column covers for them
            self.cost_errors += 1
        if ent["flops"] is None and fallback_flops:
            ent["flops"] = float(fallback_flops)
            ent["source"] = "analytic"
        if ent["bytes_accessed"] is None and fallback_bytes:
            ent["bytes_accessed"] = float(fallback_bytes)
        self._cost[key] = ent

    def cost(self, kind, bucket):
        """The cost-table entry for (kind, bucket), or None."""
        return self._cost.get((kind, int(bucket)))

    # -- sampled timing (step cadence) ----------------------------------
    def arm(self, step_id):
        """Called once at the top of every engine step: decides whether
        THIS step's dispatches are timed (every ``sample_every``-th
        step).  Never armed when sampling is off (the default)."""
        if self.sample_every > 0 and step_id % self.sample_every == 0:
            self._armed = True
            self._step_s = 0.0
        else:
            self._armed = False

    def t0(self):
        """Dispatch-start stamp: a clock read when this step is armed,
        None otherwise (the default — no syscalls, no syncs)."""
        return self._clock() if self._armed else None

    def done(self, t0, kind, bucket, outs=None):
        """Dispatch-end bracket.  Always counts the dispatch (dict
        lookup + int add); on armed steps additionally blocks on
        ``outs`` and records the elapsed device wall-time."""
        key = (kind, int(bucket))
        p = self._prog.get(key)
        if p is None:
            p = self._prog[key] = _Prog()
        p.dispatches += 1
        if t0 is None:
            return
        if outs is not None:
            import jax

            # rate-gated sampled sync (armed steps only; the default
            # path passes t0=None and never reaches here)
            jax.block_until_ready(outs)
        dt = self._clock() - t0
        p.record(dt)
        self._step_s += dt
        self._hist.labels(kind=kind, bucket=str(int(bucket))).observe(dt)

    def on_step(self, emitted):
        """Called once per engine step with the tokens emitted; closes
        out an armed step (token accounting + gauge refresh)."""
        self._tokens += int(emitted)
        if not self._armed:
            return
        self._armed = False
        self._sampled_steps += 1
        self._sampled_tokens += int(emitted)
        self._device_s += self._step_s
        self._update_gauges()

    # -- derived utilization --------------------------------------------
    def _kind_rates(self):
        """{kind: (seconds, achieved_flops, achieved_bytes)} over the
        sampled dispatches (flops/bytes from the cost table, so a
        missing entry contributes time but no utilization)."""
        agg = {}
        for (kind, bucket), p in self._prog.items():
            if not p.sampled:
                continue
            ent = self._cost.get((kind, bucket)) or {}
            s, f, b = agg.get(kind, (0.0, 0.0, 0.0))
            s += p.total_s
            f += (ent.get("flops") or 0.0) * p.sampled
            b += (ent.get("bytes_accessed") or 0.0) * p.sampled
            agg[kind] = (s, f, b)
        return agg

    def _totals(self):
        """(seconds, flops, bytes) across all sampled dispatches."""
        s = f = b = 0.0
        for ks, kf, kb in self._kind_rates().values():
            s += ks
            f += kf
            b += kb
        return s, f, b

    def _update_gauges(self):
        for kind, (s, f, b) in self._kind_rates().items():
            if s <= 0.0:
                continue
            self._g_tflops.labels(kind=kind).set(f / s / 1e12)
            if self.peak_flops:
                self._g_mfu.labels(kind=kind).set(f / s / self.peak_flops)
            if self.peak_bytes:
                self._g_mbu.labels(kind=kind).set(b / s / self.peak_bytes)
        if self._sampled_tokens:
            self._g_cost.set(
                1000.0 * self._device_s / self._sampled_tokens)

    def mfu(self):
        """Overall sampled MFU, or None (no samples / unknown peak)."""
        s, f, _ = self._totals()
        if s <= 0.0 or not self.peak_flops:
            return None
        return f / s / self.peak_flops

    def tok_flops(self):
        """Achieved FLOPs per emitted token over sampled steps."""
        _, f, _ = self._totals()
        if not self._sampled_tokens or f <= 0.0:
            return None
        return f / self._sampled_tokens

    # -- surfaces --------------------------------------------------------
    def summary(self):
        """Compact dict for ServeMonitor tails and fleet scrape rows;
        None when attribution is disabled."""
        if not self.enabled:
            return None
        s, f, b = self._totals()
        sampled = sum(p.sampled for p in self._prog.values())
        out = {
            "sampled": sampled,
            "achieved_tflops": (f / s / 1e12) if s > 0.0 else None,
            "mfu": self.mfu(),
            "mbu": (b / s / self.peak_bytes
                    if s > 0.0 and self.peak_bytes else None),
            "tok_flops": self.tok_flops(),
            "cost_per_1k_tokens_s": (
                1000.0 * self._device_s / self._sampled_tokens
                if self._sampled_tokens else None),
        }
        return out

    def statusz(self):
        """The engine statusz ``perf`` section: knob state, overall
        goodput, and the per-program table; None when disabled."""
        if not self.enabled:
            return None
        total_s, total_f, _ = self._totals()
        progs = []
        for key in sorted(set(self._cost) | set(self._prog)):
            kind, bucket = key
            ent = self._cost.get(key) or {}
            p = self._prog.get(key)
            mean = p.mean() if p else None
            flops = ent.get("flops")
            row = {
                "kind": kind,
                "bucket": bucket,
                "dispatches": p.dispatches if p else 0,
                "sampled": p.sampled if p else 0,
                "mean_s": mean,
                "p99_s": p.p99() if p else None,
                "flops": flops,
                "bytes_accessed": ent.get("bytes_accessed"),
                "output_bytes": ent.get("output_bytes"),
                "source": ent.get("source"),
                "achieved_tflops": (flops / mean / 1e12
                                    if flops and mean else None),
                "mfu": (flops / mean / self.peak_flops
                        if flops and mean and self.peak_flops else None),
                "share": (p.total_s / total_s
                          if p and total_s > 0.0 else None),
            }
            progs.append(row)
        out = {
            "enabled": True,
            "sample_every": self.sample_every,
            "sampled_steps": self._sampled_steps,
            "sampled_tokens": self._sampled_tokens,
            "tokens": self._tokens,
            "device_seconds": self._device_s,
            "cost_errors": self.cost_errors,
            "peak_flops_per_chip": self.peak_flops,
            "peak_hbm_bytes_per_chip": self.peak_bytes,
            "achieved_tflops": (total_f / total_s / 1e12
                                if total_s > 0.0 else None),
            "mfu": self.mfu(),
            "tok_flops": self.tok_flops(),
            "cost_per_1k_tokens_s": (
                1000.0 * self._device_s / self._sampled_tokens
                if self._sampled_tokens else None),
            "programs": progs,
        }
        return out
