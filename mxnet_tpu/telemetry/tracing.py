"""Host-side span tracer emitting Chrome-trace-format JSON.

``profiler.py`` captures what the *device* does (XLA traces via
jax.profiler); this tracer captures what the *host* does around it —
data wait, dispatch, compile, optimizer update, serve step — as
complete ("ph": "X") events that Perfetto / chrome://tracing load
directly.  Open the host trace next to the XLA device trace and the
host phases line up against device time (docs/how_to/observability.md
shows the workflow).

Every span additionally enters a ``jax.profiler.TraceAnnotation`` when
one can be constructed, so if an XLA trace IS active
(``profiler.start()``), the same host phases appear *inside* the
device trace too — zero-cost when no capture is running.

Events are buffered in a bounded in-memory RING (``max_events``): on
overflow the OLDEST event is evicted and counted in ``dropped``, so a
long-running serve always keeps the most recent tail — exactly the
window a post-mortem needs (dropping the newest would discard the
moments before the failure).  The buffer is written by
:meth:`SpanTracer.write` or the telemetry atexit dump.

Besides the implicit per-OS-thread tracks, callers may emit events on
*virtual* tracks (explicit ``tid`` + :meth:`SpanTracer.set_track_name`)
— the request tracer renders one track per in-flight serve request this
way, next to the host-thread spans.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time

__all__ = ["SpanTracer", "NOOP_SPAN"]


class _NoopSpan:
    """Reentrant do-nothing context manager for the disabled path."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


NOOP_SPAN = _NoopSpan()


class _Span:
    __slots__ = ("_tracer", "name", "args", "_t0", "_xla")

    def __init__(self, tracer, name, args):
        self._tracer = tracer
        self.name = name
        self.args = args
        self._xla = None

    def __enter__(self):
        ann = self._tracer._annotation_cls()
        if ann is not None:
            try:
                self._xla = ann(self.name)
                self._xla.__enter__()
            except Exception:
                self._xla = None
                self._tracer.xla_ann_errors += 1
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        end = time.perf_counter()
        if self._xla is not None:
            try:
                self._xla.__exit__(*exc)
            except Exception:
                # the host span must still land; the failure is
                # visible as a counter on the tracer (xla_ann_errors)
                self._tracer.xla_ann_errors += 1
        self._tracer.add_complete(self.name, self._t0, end, self.args)
        return False


class SpanTracer:
    def __init__(self, max_events=200_000):
        self.max_events = int(max_events)
        self.dropped = 0
        # jax.profiler.TraceAnnotation enter/exit failures (counted,
        # never raised — spans still record host-side)
        self.xla_ann_errors = 0
        self._events = collections.deque()
        self._lock = threading.Lock()
        self._pid = os.getpid()
        self._track_names = {}         # explicit tid -> display name
        # perf_counter epoch all span timestamps are relative to
        self._t0 = time.perf_counter()
        self._ann_cls = False          # False = not resolved yet

    def _annotation_cls(self):
        if self._ann_cls is False:
            try:
                import jax

                self._ann_cls = jax.profiler.TraceAnnotation
            except Exception:
                self._ann_cls = None
        return self._ann_cls

    def span(self, name, **args):
        """Context manager recording one complete event around a block."""
        return _Span(self, name, args)

    def _push(self, ev):
        # ring semantics: evict the OLDEST event on overflow so the
        # buffer always holds the newest tail; evictions count in
        # ``dropped``
        with self._lock:
            while len(self._events) >= self.max_events:
                self._events.popleft()
                self.dropped += 1
            self._events.append(ev)

    def add_complete(self, name, start, end, args=None, tid=None,
                     cat="host"):
        ev = {"name": name, "ph": "X", "cat": cat,
              "pid": self._pid,
              "tid": threading.get_ident() if tid is None else int(tid),
              "ts": (start - self._t0) * 1e6,
              "dur": max(0.0, (end - start) * 1e6)}
        if args:
            ev["args"] = dict(args)
        self._push(ev)

    def instant(self, name, _tid=None, **args):
        """Zero-duration marker ("ph": "i")."""
        ev = {"name": name, "ph": "i", "s": "t", "cat": "host",
              "pid": self._pid,
              "tid": threading.get_ident() if _tid is None else int(_tid),
              "ts": (time.perf_counter() - self._t0) * 1e6}
        if args:
            ev["args"] = dict(args)
        self._push(ev)

    def now(self):
        """Current timestamp on this tracer's clock (perf_counter —
        pass to :meth:`add_complete` start/end)."""
        return time.perf_counter()

    def set_track_name(self, tid, name):
        """Name a virtual track (explicit-tid events, e.g. one per
        in-flight serve request)."""
        with self._lock:
            self._track_names[int(tid)] = str(name)

    def trace_events(self):
        """Buffered events plus the process/thread metadata records
        Perfetto uses for track names."""
        with self._lock:
            events = list(self._events)
            track_names = dict(self._track_names)
        meta = [{"name": "process_name", "ph": "M", "pid": self._pid,
                 "args": {"name": "mxtpu host"}}]
        for tid in sorted({e["tid"] for e in events}):
            meta.append({"name": "thread_name", "ph": "M",
                         "pid": self._pid, "tid": tid,
                         "args": {"name": track_names.get(
                             tid, f"host-thread-{tid}")}})
        return meta + events

    def write(self, path):
        """Write the Chrome-trace JSON object form (Perfetto /
        chrome://tracing / ``profiler.summarize``-style consumers)."""
        # event "ts" fields are relative to self._t0 (a perf_counter
        # stamp with no cross-process meaning); the anchor maps ts=0
        # to the wall clock so tools/timeline_report.py can align this
        # file with other replicas' traces and device captures
        # mxtpu-lint: disable=wall-clock (cross-process trace-stitch anchor)
        t0_epoch = time.time() - (time.perf_counter() - self._t0)
        payload = {"traceEvents": self.trace_events(),
                   "displayTimeUnit": "ms",
                   "otherData": {"producer": "mxnet_tpu.telemetry",
                                 "dropped_events": self.dropped,
                                 "t0_epoch": t0_epoch}}
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(payload, f)
        os.replace(tmp, path)
        return path

    def clear(self):
        with self._lock:
            self._events = collections.deque()
            self._track_names = {}
            self.dropped = 0
