"""Bridge jax.monitoring events into the metrics registry.

JAX stamps named events through ``jax.monitoring`` — most usefully the
compile-path durations (``/jax/core/compile/backend_compile_duration``
and friends, names vary by version).  Installing the listeners turns
recompile storms (the classic bucketing bug: a new XLA program per
batch shape) into visible counters:

  mxtpu_jax_events_total{event=...}        every monitored jax event
  mxtpu_jax_compile_total{event=...}       compile-path events only
  mxtpu_jax_compile_seconds{event=...}     compile-path durations

Listeners are registered once per process and gate on the telemetry
enabled flag at *call* time, so a later ``telemetry.disable()`` stops
the recording without needing jax's ``clear_event_listeners`` (which
would drop other libraries' listeners too).
"""

from __future__ import annotations

__all__ = ["install"]

# compile durations stretch far beyond the request-latency defaults
COMPILE_BUCKETS = (0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
                   30.0, 60.0, 120.0, 300.0)

_installed = False


def install(registry, enabled_fn):
    """Register jax.monitoring listeners feeding ``registry``;
    ``enabled_fn() -> bool`` is consulted on every event."""
    global _installed
    if _installed:
        return True
    try:
        from jax import monitoring
    except Exception:
        return False

# families are re-resolved per event (get-or-create is one locked dict
    # lookup, and jax events are rare — compiles, not steps) so a test's
    # registry.clear() can't leave the listeners feeding detached series

    def _on_event(event, **kwargs):
        if not enabled_fn():
            return
        registry.counter("mxtpu_jax_events_total",
                         "jax.monitoring events seen",
                         ("event",)).labels(event=event).inc()
        if "compile" in event:
            registry.counter("mxtpu_jax_compile_total",
                             "jax compile-path events",
                             ("event",)).labels(event=event).inc()
        # persistent-compile-cache traffic (mxnet_tpu/aot/cache.py): jax
        # stamps a hit per executable read back from disk and a miss per
        # executable it is about to write — so on this event stream a
        # counted miss IS a put (misses that fail the cache's size/time
        # thresholds stamp neither and are invisible here by design)
        if event.endswith("/compilation_cache/cache_hits"):
            registry.counter("mxtpu_compile_cache_hits",
                             "persistent compile-cache hits").inc()
        elif event.endswith("/compilation_cache/cache_misses"):
            registry.counter("mxtpu_compile_cache_misses",
                             "persistent compile-cache misses").inc()
            registry.counter("mxtpu_compile_cache_puts",
                             "persistent compile-cache writes").inc()

    def _on_duration(event, duration, **kwargs):
        _on_event(event, **kwargs)
        if enabled_fn() and "compile" in event:
            registry.histogram(
                "mxtpu_jax_compile_seconds", "jax compile-path durations",
                ("event",), buckets=COMPILE_BUCKETS
            ).labels(event=event).observe(duration)

    try:
        monitoring.register_event_listener(_on_event)
        monitoring.register_event_duration_secs_listener(_on_duration)
    except Exception:
        return False
    _installed = True
    return True
