"""Per-step host-overhead decomposition for the serve engine.

Answers "where did the *host* wall-clock go?" inside every
``Engine._step_inner`` iteration — the runtime complement to the lint
``host-sync`` checker's static map, and the measurement baseline for
ROADMAP 1(c)'s multi-step host loop (any future N-steps-per-turn
dispatch has to beat these numbers, phase by phase).

**Lap/cursor model.**  ``begin(step_id)`` stamps the step start and
resets the cursor; every ``lap(phase)`` attributes the time elapsed
since the cursor to ``phase`` and advances the cursor; ``commit(...)``
sweeps whatever remains into ``callbacks`` and seals the entry.  Every
nanosecond between begin and commit lands in exactly one phase, so the
per-step phase seconds sum to the step wall time by construction
(pinned in tests/test_profiling.py).  Phases:

  schedule          admission fanout + scheduler.schedule() +
                    host-KV restore dispatch + utilization sampling
  prefill_dispatch  host operand build + async prefill/chunk dispatch
  decode_dispatch   host operand build + async decode/draft/verify
                    dispatch (spec ingest rides here too)
  device_wait       time blocked on device results (the designed
                    ``_unpack_outs`` sync, plus the greedy-spec
                    drafted/verified syncs)
  host_sync         post-sync host bookkeeping: token append, radix/
                    scheduler updates, request-trace events
  callbacks         step tail: flight record, stats/perf callbacks,
                    spec-window prune, telemetry gauges

**Cost.**  A lap is one ``perf_counter`` read and a dict add — the
recorder is default ON (``MXTPU_STEP_PROFILE=0`` to disable) and gated
≤1.02x tokens/s by the serve_bench ``step-profile`` A/B contract
(PROFILE_BENCH.json).  Disabled, the engine holds the NOOP recorder
whose methods are empty — zero clock reads on the hot path.

Surfaces: a bounded ring of per-step entries (``MXTPU_STEP_PROFILE_RING``,
default 256), cumulative per-phase totals, the ``step_profile`` engine
statusz section (which flight dumps embed via the statusz snapshot),
and ``mxtpu_step_phase_seconds{phase}`` histograms.  The statusz
section carries a perf_counter↔epoch clock anchor so
tools/timeline_report.py can place the rings on the fleet timeline.

Inertness contract (the PR 10/11 rule): the recorder never touches
tokens, program cache keys, or AOT fingerprints — on or off, greedy
output is byte-identical and ``_spec_digest`` unchanged.
"""

from __future__ import annotations

import collections
import time

from ..base import env_flag, env_int

__all__ = ["StepProfiler", "NOOP_STEP_PROFILER", "make_step_profiler",
           "PHASES", "ENV_ENABLE", "ENV_RING", "PHASE_SECONDS_BUCKETS"]

ENV_ENABLE = "MXTPU_STEP_PROFILE"        # step decomposition (default on)
ENV_RING = "MXTPU_STEP_PROFILE_RING"     # per-step entry ring size

PHASES = ("schedule", "prefill_dispatch", "decode_dispatch",
          "device_wait", "host_sync", "callbacks")

# host phases live well below program dispatches: 1us .. 100ms band
PHASE_SECONDS_BUCKETS = (1e-6, 2.5e-6, 5e-6, 1e-5, 2.5e-5, 5e-5,
                         1e-4, 2.5e-4, 5e-4, 1e-3, 2.5e-3, 5e-3,
                         1e-2, 2.5e-2, 5e-2, 0.1, 0.25)

_STATUSZ_RECENT = 50     # ring tail carried on statusz / flight dumps


class _NoopStepProfiler:
    """Shared disabled recorder: every hot-path call is a no-op pass.

    The engine holds this singleton when ``MXTPU_STEP_PROFILE=0`` so
    the step loop pays one attribute load + empty call per lap and
    zero clock reads."""

    enabled = False

    def begin(self, step_id):
        pass

    def lap(self, phase):
        pass

    def commit(self, emitted=0, prefills=0, decodes=0):
        pass

    def recent(self, n=_STATUSZ_RECENT):
        return []

    def summary(self):
        return None

    def statusz(self):
        return {"enabled": False}


NOOP_STEP_PROFILER = _NoopStepProfiler()


class StepProfiler:
    """One per engine, constructed AFTER ``telemetry.enable()`` (the
    handle-caching asymmetry: the phase histogram handle is cached here
    at construction).  Single-writer: only the engine step loop calls
    begin/lap/commit; readers (statusz handlers on HTTP threads) see a
    consistent tail because entries are appended whole."""

    enabled = True

    def __init__(self, clock=time.perf_counter, ring=None):
        self._clock = clock
        n = ring if ring is not None else env_int(ENV_RING, 256)
        self._ring = collections.deque(maxlen=max(1, int(n)))
        self._totals = {p: 0.0 for p in PHASES}
        self._steps = 0
        self._wall_s = 0.0
        self._emitted = 0
        self._cur = {}            # in-flight step: phase -> seconds
        self._step_id = 0
        self._t_begin = 0.0
        self._t_cursor = 0.0
        # perf_counter<->epoch anchor: lets timeline_report place ring
        # entries (perf-domain t0s) on the fleet's wall-clock axis.
        # mxtpu-lint: disable=wall-clock (one-shot epoch anchor for trace stitching)
        self._anchor = {"perf": clock(), "epoch": time.time()}
        from .. import telemetry as tel

        self._hist = tel.histogram(
            "mxtpu_step_phase_seconds",
            "host wall-time per serve-step phase", ("phase",),
            buckets=PHASE_SECONDS_BUCKETS)

    # -- hot path (engine step loop only) --------------------------------
    def begin(self, step_id):
        """Stamp the step start; resets the lap cursor."""
        self._step_id = step_id
        self._t_begin = self._t_cursor = self._clock()
        self._cur = {}

    def lap(self, phase):
        """Attribute elapsed-since-cursor to ``phase``; advance cursor."""
        now = self._clock()
        self._cur[phase] = self._cur.get(phase, 0.0) + (now - self._t_cursor)
        self._t_cursor = now

    def commit(self, emitted=0, prefills=0, decodes=0):
        """Seal the in-flight step: the residual since the last lap goes
        to ``callbacks``, the entry enters the ring, totals/histograms
        update."""
        now = self._clock()
        cur = self._cur
        cur["callbacks"] = cur.get("callbacks", 0.0) + (now - self._t_cursor)
        self._t_cursor = now
        wall = now - self._t_begin
        entry = {
            "step": self._step_id,
            "t0": self._t_begin,
            "wall_s": wall,
            "emitted": int(emitted),
            "prefills": int(prefills),
            "decodes": int(decodes),
            "phases": cur,
        }
        self._ring.append(entry)
        self._cur = {}
        self._steps += 1
        self._wall_s += wall
        self._emitted += int(emitted)
        totals = self._totals
        hist = self._hist
        for phase, dt in cur.items():
            totals[phase] = totals.get(phase, 0.0) + dt
            hist.labels(phase=phase).observe(dt)

    # -- surfaces --------------------------------------------------------
    def recent(self, n=_STATUSZ_RECENT):
        """The last ``n`` ring entries, oldest first."""
        if n <= 0:
            return []
        ring = list(self._ring)
        return ring[-n:]

    def fractions(self):
        """{phase: fraction of recorded wall time}, or None pre-step."""
        if self._wall_s <= 0.0:
            return None
        return {p: self._totals[p] / self._wall_s for p in PHASES}

    def summary(self):
        """Compact dict for monitor tails / fleet scrape rows."""
        return {
            "steps": self._steps,
            "wall_s": self._wall_s,
            "emitted": self._emitted,
            "fractions": self.fractions(),
        }

    def statusz(self):
        """The engine statusz ``step_profile`` section.  Unlike perf
        attribution this knob is default-on, so the section always
        reports its enabled state rather than collapsing to None."""
        # mxtpu-lint: disable=wall-clock (refreshed epoch anchor for trace stitching)
        anchor = {"perf": self._clock(), "epoch": time.time()}
        return {
            "enabled": True,
            "ring": self._ring.maxlen,
            "steps": self._steps,
            "wall_s": self._wall_s,
            "emitted": self._emitted,
            "totals_s": dict(self._totals),
            "fractions": self.fractions(),
            "clock_anchor": anchor,
            "recent": self.recent(),
        }


def make_step_profiler(clock=time.perf_counter):
    """The engine's constructor hook: a live recorder when
    ``MXTPU_STEP_PROFILE`` is on (the default), the shared NOOP
    otherwise."""
    if env_flag(ENV_ENABLE, True):
        return StepProfiler(clock=clock)
    return NOOP_STEP_PROFILER
