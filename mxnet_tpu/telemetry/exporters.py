"""Exporters: Prometheus text exposition (file + stdlib HTTP endpoint)
and JSONL snapshot logs.

The text format is the Prometheus 0.0.4 exposition format, so the file
written by :func:`write_prometheus` can be scraped by a node-exporter
textfile collector, and :func:`serve_http` is a real ``/metrics``
endpoint (stdlib ``http.server`` only — no new dependencies).
:func:`append_jsonl` appends one timestamped registry snapshot per
call; ``tools/metrics_report.py`` renders either artifact as a
terminal table.
"""

from __future__ import annotations

import json
import os
import threading
import time

__all__ = ["to_prometheus_text", "write_prometheus", "append_jsonl",
           "serve_http"]


def _escape_label(value):
    return (str(value).replace("\\", r"\\").replace('"', r'\"')
            .replace("\n", r"\n"))


def _fmt_value(v):
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _fmt_le(ub):
    return "+Inf" if ub == float("inf") else _fmt_value(ub)


def _labels_text(label_names, values, extra=()):
    pairs = [f'{n}="{_escape_label(v)}"'
             for n, v in list(zip(label_names, values)) + list(extra)]
    return "{" + ",".join(pairs) + "}" if pairs else ""


def to_prometheus_text(registry):
    """Serialize a Registry in Prometheus text exposition format."""
    lines = []
    for fam in registry.collect():
        if fam.help:
            lines.append(f"# HELP {fam.name} {fam.help}")
        lines.append(f"# TYPE {fam.name} {fam.kind}")
        for key, child in fam.children():
            if fam.kind == "histogram":
                for ub, c in child.cumulative():
                    lines.append(
                        f"{fam.name}_bucket"
                        f"{_labels_text(fam.label_names, key, [('le', _fmt_le(ub))])}"
                        f" {c}")
                base = _labels_text(fam.label_names, key)
                lines.append(f"{fam.name}_sum{base} {_fmt_value(child.sum)}")
                lines.append(f"{fam.name}_count{base} {child.count}")
            else:
                lines.append(
                    f"{fam.name}{_labels_text(fam.label_names, key)} "
                    f"{_fmt_value(child.value)}")
    return "\n".join(lines) + "\n"


def write_prometheus(registry, path):
    """Atomic write of the text exposition to ``path``."""
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write(to_prometheus_text(registry))
    os.replace(tmp, path)
    return path


def append_jsonl(registry, path, extra=None):
    """Append one ``{"ts": ..., "metrics": {...}}`` snapshot line."""
    # mxtpu-lint: disable=wall-clock (JSONL record timestamp)
    rec = {"ts": round(time.time(), 3), "metrics": registry.snapshot()}
    if extra:
        rec.update(extra)
    with open(path, "a") as f:
        f.write(json.dumps(rec) + "\n")
    return path


def serve_http(registry, port, host="127.0.0.1"):
    """Start a daemon-thread HTTP endpoint; returns the server
    (``server.server_address[1]`` is the bound port — pass ``port=0``
    for an ephemeral one; ``server.shutdown()`` stops it).

    Routes: ``/metrics`` (Prometheus text), ``/metrics.json`` (registry
    snapshot), ``/statusz`` (live introspection HTML),
    ``/statusz.json`` (same as JSON — statusz.py providers) and
    ``/healthz`` (cheap liveness/readiness JSON from the statusz
    health providers — no registry render, no statusz assembly, so
    supervisors/routers can probe at high frequency)."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):
            if self.path in ("/", "/metrics"):
                body = to_prometheus_text(registry).encode()
                ctype = "text/plain; version=0.0.4; charset=utf-8"
            elif self.path == "/healthz":
                from . import statusz

                body = json.dumps(statusz.health()).encode()
                ctype = "application/json"
            elif self.path == "/metrics.json":
                body = json.dumps(registry.snapshot()).encode()
                ctype = "application/json"
            elif self.path in ("/statusz", "/statusz.json"):
                from . import statusz

                snap = statusz.snapshot()
                if self.path.endswith(".json"):
                    body = json.dumps(snap, default=str).encode()
                    ctype = "application/json"
                else:
                    body = statusz.render_html(snap).encode()
                    ctype = "text/html; charset=utf-8"
            else:
                self.send_error(404)
                return
            self.send_response(200)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *args):      # no stderr chatter per scrape
            pass

    server = ThreadingHTTPServer((host, port), Handler)
    thread = threading.Thread(target=server.serve_forever, daemon=True,
                              name="mxtpu-telemetry-http")
    thread.start()
    return server
