"""/statusz: live process introspection behind the telemetry HTTP server.

Metrics answer "how fast"; `/statusz` answers "what is the process
doing RIGHT NOW": in-flight serve requests with ages and phases, queue
depth, KV block-manager occupancy, AOT compile-cache and export-store
state, fused-train-step selection decisions, the jax backend/device
inventory, and uptime — one JSON (``/statusz.json``) or HTML
(``/statusz``) snapshot assembled from registered *providers*.

A provider is a zero-arg callable returning a JSON-serializable dict.
Subsystems register at construction time (``serve.Engine``,
``CompileCacheManager``, the fused-step selector); long-lived objects
register through a weakref (:func:`register_weak`) so a retired engine
drops out of the page instead of pinning multi-GB parameter dicts.  A
provider that raises contributes ``{"error": ...}`` — one broken
subsystem never takes down the page.

The snapshot is also embedded in every flight-recorder dump, so
post-mortems carry the same live-state view the endpoint would have
served.
"""

from __future__ import annotations

import itertools
import os
import threading
import time

__all__ = ["register", "register_weak", "unregister", "snapshot",
           "render_html", "bytes_by_device", "register_health",
           "unregister_health", "health"]

_lock = threading.Lock()
_providers = {}                  # name -> zero-arg callable
_health_providers = {}           # name -> zero-arg callable
# uptime is an ELAPSED quantity: monotonic, so an NTP step can't make
# a 2-minute-old process report hours (or negative seconds) of uptime
_start_m = time.monotonic()
_uid = itertools.count()


def register(name, fn):
    """Register provider ``fn`` under ``name`` (replacing any previous
    one).  Returns ``name`` for a later :func:`unregister`."""
    with _lock:
        _providers[str(name)] = fn
    return str(name)


def register_weak(obj, name, method="statusz"):
    """Register ``obj.<method>()`` without keeping ``obj`` alive; the
    entry auto-unregisters once ``obj`` is collected."""
    import weakref

    name = f"{name}#{next(_uid)}"
    ref = weakref.ref(obj)

    def provider():
        target = ref()
        if target is None:
            unregister(name)
            return None
        return getattr(target, method)()

    return register(name, provider)


def unregister(name):
    with _lock:
        _providers.pop(name, None)


# -- /healthz: liveness/readiness, deliberately CHEAP -------------------------
# A supervisor or router probing every replica every few hundred ms
# must not pay the /statusz.json assembly cost (every provider runs,
# jax inventory, JSON of the whole engine state).  Health providers are
# a separate, tiny registry: each returns a small dict with a
# ``status`` field ("ok" / "draining" / anything else = unhealthy) and
# the endpoint renders only those.
def register_health(name, fn):
    """Register health provider ``fn`` (zero-arg -> small dict with a
    ``status`` key) under ``name``; returns ``name``."""
    with _lock:
        _health_providers[str(name)] = fn
    return str(name)


def unregister_health(name):
    with _lock:
        _health_providers.pop(name, None)


def health():
    """One cheap liveness/readiness snapshot: ``status`` is "ok" when
    every provider reports ok, else the first non-ok status (providers
    that raise report status "error" without taking the page down).
    Never touches the metrics registry or the statusz providers."""
    with _lock:
        providers = dict(_health_providers)
    out = {"status": "ok", "pid": os.getpid(),
           "uptime_s": round(time.monotonic() - _start_m, 3)}
    checks = {}
    for name, fn in sorted(providers.items()):
        try:
            c = fn()
        except Exception as e:
            c = {"status": "error", "error": repr(e)}
        if c is None:               # dead weakref-style provider
            continue
        checks[name] = c
        st = c.get("status") if isinstance(c, dict) else None
        if st is not None and st != "ok" and out["status"] == "ok":
            out["status"] = str(st)
    if checks:
        out["checks"] = checks
    return out


def bytes_by_device(arrays):
    """Per-device HBM-resident bytes for a collection of jax arrays:
    ``{device_id: bytes}`` summed over each array's addressable
    shards.  Sharded arrays count each shard where it lives; a
    replicated array counts once per device — exactly its real
    footprint.  Non-jax leaves (numpy, None) are skipped, so callers
    can pass a mixed parameter dict's values directly."""
    out = {}
    for arr in arrays:
        shards = getattr(arr, "addressable_shards", None)
        if shards is None:
            continue
        try:
            for shard in shards:
                dev = getattr(shard.device, "id", None)
                if dev is None:
                    continue
                data = shard.data
                out[int(dev)] = (out.get(int(dev), 0)
                                 + int(getattr(data, "nbytes", 0)))
        except (RuntimeError, ValueError):
            continue                 # deleted/donated-away array
    return out


def _jax_inventory():
    try:
        import jax

        return {"version": jax.__version__,
                "backend": jax.default_backend(),
                "device_count": jax.device_count(),
                "devices": [{"id": d.id, "platform": d.platform,
                             "kind": getattr(d, "device_kind", "")}
                            for d in jax.devices()]}
    except Exception as e:                       # jax not initialized yet
        return {"error": repr(e)}


def snapshot():
    """One JSON-serializable snapshot of every registered provider plus
    the process section (pid, uptime, jax inventory)."""
    with _lock:
        providers = dict(_providers)
    out = {"process": {"pid": os.getpid(),
                       "uptime_s": round(time.monotonic() - _start_m, 3),
                       # mxtpu-lint: disable=wall-clock (the "time"
                       # field IS the wall timestamp readers correlate
                       # with their logs)
                       "time": round(time.time(), 3)},
           "jax": _jax_inventory()}
    for name, fn in sorted(providers.items()):
        try:
            section = fn()
        except Exception as e:
            section = {"error": repr(e)}
        if section is not None:                  # None = dead weakref
            out[name] = section
    return out


def _html_value(value):
    import html as _html
    import json as _json

    return ("<pre>"
            + _html.escape(_json.dumps(value, indent=2, default=str))
            + "</pre>")


def render_html(snap=None):
    """Minimal dependency-free HTML view of :func:`snapshot` — one
    <section> per provider with the JSON pretty-printed."""
    import html as _html

    snap = snapshot() if snap is None else snap
    parts = ["<!doctype html><html><head><title>mxtpu /statusz</title>",
             "<style>body{font-family:monospace;margin:1em}",
             "h2{border-bottom:1px solid #999;margin:1em 0 .2em}",
             "pre{margin:.2em 0 .8em;white-space:pre-wrap}</style>",
             "</head><body><h1>mxtpu /statusz</h1>"]
    for name in snap:
        parts.append(f"<h2>{_html.escape(str(name))}</h2>")
        parts.append(_html_value(snap[name]))
    parts.append("</body></html>")
    return "".join(parts)
