"""Unified telemetry: one process-wide metrics registry + host span
tracer feeding shared exporters (Prometheus text / HTTP, JSONL, Chrome
trace), replacing the fragmented point tools the reference grew
(Monitor stat hooks, Speedometer prints, engine traces — SURVEY.md §5).

Everything is **off by default** and env-gated:

  MXTPU_TELEMETRY=1            enable (or call ``telemetry.enable()``)
  MXTPU_TELEMETRY_DIR          artifact dir for the atexit dump
                               (default ./mxtpu_telemetry)
  MXTPU_TELEMETRY_HTTP_PORT    also serve live /metrics + /statusz
                               endpoints

Request-scoped observability rides the same package (each with its own
opt-in; docs/how_to/observability.md):

  MXTPU_REQUEST_TRACE[=path]   per-request serve timelines, JSONL
                               (request_trace.py; sample-rate knob
                               MXTPU_REQUEST_TRACE_SAMPLE)
  MXTPU_FLIGHT_DIR             flight-recorder auto-dump directory
                               (flight.py; the in-memory ring is
                               always on)
  MXTPU_NUMERIC_WATCH=1        NaN/Inf watchdog on fused-train-step
                               loss/grad-norm and serve logits

Disabled, every accessor returns a shared no-op object — instrumented
hot paths (Module.fit, io iterators, serve.Engine, ShardedTrainer) pay
one attribute call per event and allocate nothing (pinned by
tests/test_telemetry.py's overhead-guard contract).  Enabled, a run
leaves ``metrics.prom`` (Prometheus text exposition), ``metrics.jsonl``
(appended snapshot log) and ``host_trace.json`` (Chrome trace, opens in
Perfetto next to profiler.py's XLA device traces) under the telemetry
dir; ``tools/metrics_report.py`` renders any of them as a table.

Typical use:

    from mxnet_tpu import telemetry
    telemetry.enable()                       # or MXTPU_TELEMETRY=1
    reqs = telemetry.counter("myapp_requests_total", "requests served")
    reqs.inc()
    with telemetry.span("load_shard", shard=3):
        ...
    telemetry.dump()                         # write the artifact set
"""

from __future__ import annotations

import atexit
import functools
import os

from . import (exporters, flight, jaxmon, metrics, profiling,
               request_trace, statusz, timeseries, tracing)
from .exporters import (append_jsonl, serve_http, to_prometheus_text,
                        write_prometheus)
from .flight import FlightRecorder
from .metrics import DEFAULT_BUCKETS, NOOP, Registry
from .request_trace import RequestTracer
from .tracing import NOOP_SPAN, SpanTracer

__all__ = ["enabled", "enable", "disable", "reset", "counter", "gauge",
           "histogram", "span", "traced", "registry", "tracer",
           "snapshot", "dump", "out_dir", "NOOP", "NOOP_SPAN",
           "DEFAULT_BUCKETS", "to_prometheus_text", "write_prometheus",
           "append_jsonl", "serve_http", "Registry", "SpanTracer",
           "flight", "statusz", "profiling", "request_trace",
           "timeseries", "FlightRecorder", "RequestTracer"]

_enabled = False
_registry = Registry()
_tracer = SpanTracer()
_out_dir = None
_http_server = None
_atexit_registered = False


def enabled():
    """Whether telemetry is recording in this process."""
    return _enabled


def registry():
    """The process-wide Registry (real object even when disabled —
    instrumented sites just never reach it then)."""
    return _registry


def tracer():
    return _tracer


def out_dir():
    """The artifact directory dump() writes into."""
    return _out_dir or os.environ.get("MXTPU_TELEMETRY_DIR") \
        or "mxtpu_telemetry"


def enable(dir=None, http_port=None, atexit_dump=False):
    """Turn recording on (idempotent).  ``dir`` overrides the artifact
    directory; ``http_port`` starts a live /metrics endpoint;
    ``atexit_dump`` registers the end-of-process artifact write (the
    env-var path sets it — programmatic callers dump() explicitly)."""
    global _enabled, _out_dir, _http_server, _atexit_registered
    _enabled = True
    if dir is not None:
        _out_dir = dir
    jaxmon.install(_registry, enabled)
    if http_port is not None and _http_server is None:
        try:
            _http_server = serve_http(_registry, int(http_port))
        except OSError as e:
            # e.g. two workers inheriting one MXTPU_TELEMETRY_HTTP_PORT:
            # losing the endpoint must not turn `import mxnet_tpu` into
            # a crash — telemetry degrades, the program runs
            import warnings

            warnings.warn(f"telemetry: /metrics endpoint on port "
                          f"{http_port} unavailable ({e}); metrics are "
                          "still collected and dumped to files",
                          stacklevel=2)
    if atexit_dump and not _atexit_registered:
        _atexit_registered = True
        atexit.register(_atexit_dump)
    return _registry


def disable():
    """Stop recording spans and jax events.  Already-collected data is
    kept (dump() still works).  NOTE the handle-caching asymmetry:
    sites that re-fetch handles per call (Module.fit) go back to the
    no-op objects, but objects built while enabled (serve.Engine,
    StatsRecorder, ShardedTrainer, iterators) cached real metric
    handles at construction and keep recording into the registry —
    symmetrically, objects built while DISABLED cached the no-ops and
    stay silent after a later enable().  Construct instrumented
    objects after enable(), and treat disable() as "stop new spans",
    not a per-site mute."""
    global _enabled
    _enabled = False


def reset():
    """Drop all collected metrics and spans (tests)."""
    _registry.clear()
    _tracer.clear()


# -- accessors: real objects when enabled, shared no-ops when not --------
def counter(name, help="", label_names=()):
    if not _enabled:
        return NOOP
    return _registry.counter(name, help, label_names)


def gauge(name, help="", label_names=()):
    if not _enabled:
        return NOOP
    return _registry.gauge(name, help, label_names)


def histogram(name, help="", label_names=(), buckets=DEFAULT_BUCKETS):
    if not _enabled:
        return NOOP
    return _registry.histogram(name, help, label_names, buckets)


def span(name, **args):
    """Context manager recording one host span (Chrome-trace X event;
    also annotates any active XLA trace)."""
    if not _enabled:
        return NOOP_SPAN
    return _tracer.span(name, **args)


def traced(name=None):
    """Decorator form of :func:`span` (enablement checked per call, so
    decorating at import time is safe)."""

    def deco(fn):
        label = name or fn.__qualname__

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            if not _enabled:
                return fn(*args, **kwargs)
            with _tracer.span(label):
                return fn(*args, **kwargs)

        return wrapper

    return deco


def snapshot():
    """JSON-serializable snapshot for bench records and dashboards:
    ``{"enabled": bool, "metrics": {...}}``."""
    return {"enabled": _enabled, "metrics": _registry.snapshot()}


def dump(dir=None):
    """Write the artifact set; returns {kind: path}.

    metrics.prom       Prometheus text exposition (overwritten)
    metrics.jsonl      appended timestamped snapshot line
    host_trace.json    Chrome-trace JSON of the host spans
    """
    d = dir or out_dir()
    os.makedirs(d, exist_ok=True)
    return {
        "prometheus": write_prometheus(
            _registry, os.path.join(d, "metrics.prom")),
        "jsonl": append_jsonl(_registry, os.path.join(d, "metrics.jsonl")),
        "trace": _tracer.write(os.path.join(d, "host_trace.json")),
    }


def _note_internal_error(site):
    """Count a telemetry-internal failure on
    ``mxtpu_telemetry_errors_total{site}`` — observability failures
    must at least move a counter (mxtpu-lint swallowed-exception),
    even though they may never raise into the caller."""
    try:
        _registry.counter("mxtpu_telemetry_errors_total",
                          "telemetry-internal failures",
                          ("site",)).labels(site=site).inc()
    # mxtpu-lint: disable=swallowed-exception (last-resort guard: the
    # error accountant itself must never raise into serving code)
    except Exception:
        pass


def _atexit_dump():
    try:
        dump()
    except Exception:
        # never let telemetry turn a clean exit into a traceback — but
        # leave a trace for anyone still scraping /metrics at teardown
        _note_internal_error("atexit_dump")


# one parser for every MXTPU_* boolean knob (base.env_flag), so the
# accepted spellings can't fork between telemetry and the rest of the
# stack (mxtpu-lint env-discipline)
from ..base import env_flag  # noqa: E402

if env_flag("MXTPU_TELEMETRY", False):
    _port = os.environ.get("MXTPU_TELEMETRY_HTTP_PORT")
    enable(dir=os.environ.get("MXTPU_TELEMETRY_DIR"),
           http_port=int(_port) if _port else None,
           atexit_dump=True)
