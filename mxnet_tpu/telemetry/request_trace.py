"""Request-scoped tracing for the serving engine.

Aggregate counters (metrics.py) answer "how fast is the system";
this module answers "what happened to THIS request" (Dapper-style
causality).  Every ``serve.Request`` gets a trace id and an event
timeline —

  submitted → admitted/resumed → prefill_start/prefill_end →
  decode (one per iteration: batch id, batch size, tokens so far,
  tokens emitted this iteration — up to k+1 under speculative
  decoding, where the event also carries the accepted draft count) →
  preempted (reason) → … → finished | rejected (reason) | cancelled

— recorded by the scheduler and the engine through the hooks below.
Three consumers, by cost:

* **flight ring** (always on): every event also lands in the flight
  recorder's bounded ring, so post-mortems see recent request history
  even with tracing off.
* **JSONL export** (``MXTPU_REQUEST_TRACE=1`` or ``=<path>``): one line
  per request, written atomically-appended when the request reaches a
  terminal state — a line is a COMPLETE timeline by construction (no
  orphan events).  ``MXTPU_REQUEST_TRACE_SAMPLE`` (0..1, default 1.0)
  samples per request (deterministic hash of the rid) so production can
  keep the knob on cheaply; ``tools/trace_report.py`` reconstructs
  per-phase latency percentiles from the file.
* **Chrome-trace request tracks** (when telemetry is enabled): each
  traced request's phases (queued / prefill / decode / preempted) are
  emitted as complete events on a virtual track — one ``tid`` per
  in-flight request, reused after completion — so Perfetto shows
  request lifetimes side by side with the host spans.

Counters fed here (re-fetched per call, so enable() ordering never
matters): ``mxtpu_serve_rejections_total{reason}`` and
``mxtpu_serve_preemptions_total{reason}`` — the same reason codes the
``ServeStats.reject_reasons`` snapshot and the timeline carry, so all
three views agree by construction (pinned by
tests/test_observability.py).
"""

from __future__ import annotations

import json
import os
import threading
import time

from . import flight

__all__ = ["RequestTracer", "NOOP_TRACER", "ENV_ENABLE", "ENV_FILE",
           "ENV_SAMPLE", "ENV_PUSH", "TERMINAL_EVENTS"]

ENV_ENABLE = "MXTPU_REQUEST_TRACE"
ENV_FILE = "MXTPU_REQUEST_TRACE_FILE"
ENV_SAMPLE = "MXTPU_REQUEST_TRACE_SAMPLE"
# live trace shipping: terminal request-trace lines are ALSO POSTed to
# this URL (the fleet collector's /trace endpoint), so cross-replica
# stitched timelines exist while the fleet runs instead of only after
# collecting every replica's JSONL file
ENV_PUSH = "MXTPU_TRACE_PUSH_URL"

TERMINAL_EVENTS = ("finished", "rejected", "cancelled")

# virtual Chrome-trace tids for request tracks start here — far above
# plausible small ints, far below real pthread idents, and stable so
# repeated runs diff cleanly.  The pool is PROCESS-global (all tracers
# emit into the one process-wide SpanTracer): two engines in one
# process must never hand out the same tid to concurrent requests
_TRACK_BASE = 10_000
_track_lock = threading.Lock()
_free_tracks = []
_next_track = [_TRACK_BASE]


def _acquire_track():
    global _free_tracks
    with _track_lock:
        if _free_tracks:
            return _free_tracks.pop()
        tid = _next_track[0]
        _next_track[0] += 1
        return tid


def _release_track(tid):
    with _track_lock:
        _free_tracks.append(tid)


class _NoopTracer:
    """Do-nothing stand-in (scheduler default, so a bare Scheduler in a
    test needs no wiring)."""

    __slots__ = ()
    enabled = False

    def submitted(self, req):
        pass

    def event(self, req, name, **args):
        pass

    def terminal(self, req, name, **args):
        pass

    def close(self):
        pass


NOOP_TRACER = _NoopTracer()


class _TracePusher:
    """Background shipper of terminal trace lines to one URL.

    One daemon worker per distinct URL (shared across tracers via
    :func:`_pusher_for`), fed through a bounded queue — serving threads
    only ever enqueue; a slow or dead collector costs a queue slot and
    a dropped-line count, never a stalled request handler."""

    def __init__(self, url, maxsize=256, timeout_s=2.0):
        import queue

        self.url = url
        self.timeout_s = float(timeout_s)
        self._q = queue.Queue(maxsize=int(maxsize))
        self.pushed = 0            # guarded-by: _lock
        self.dropped = 0           # guarded-by: _lock
        self.errors = 0            # guarded-by: _lock
        self._lock = threading.Lock()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="mxtpu-trace-push")
        self._thread.start()

    def push(self, record):
        try:
            self._q.put_nowait(record)
        except Exception:
            # full queue: drop — shipping is best-effort by design, the
            # local JSONL file (when configured) still has the line
            with self._lock:
                self.dropped += 1
            self._count("dropped")

    @staticmethod
    def _count(outcome):
        from mxnet_tpu import telemetry

        telemetry.counter("mxtpu_trace_push_total",
                          "terminal trace lines shipped to "
                          "MXTPU_TRACE_PUSH_URL", ("outcome",)
                          ).labels(outcome=outcome).inc()

    def _run(self):
        import urllib.request

        while True:
            record = self._q.get()
            try:
                req = urllib.request.Request(
                    self.url, data=json.dumps(record).encode(),
                    method="POST",
                    headers={"Content-Type": "application/json"})
                with urllib.request.urlopen(req, timeout=self.timeout_s):
                    pass
                with self._lock:
                    self.pushed += 1
                self._count("ok")
            except Exception:
                # collector down/unreachable: count and move on — the
                # pusher must survive the collector's whole lifecycle
                with self._lock:
                    self.errors += 1
                self._count("error")


_pushers = {}                      # guarded-by: _pushers_lock
_pushers_lock = threading.Lock()


def _pusher_for(url):
    """One shared pusher (thread + queue) per distinct URL — many
    engines in one process must not each grow a shipping thread."""
    with _pushers_lock:
        p = _pushers.get(url)
        if p is None:
            p = _pushers[url] = _TracePusher(url)
        return p


def _sampled(rid, rate):
    if rate >= 1.0:
        return True
    if rate <= 0.0:
        return False
    # deterministic per-rid hash (Knuth multiplicative) — reproducible
    # across runs, no RNG state on the hot path
    return ((rid * 2654435761) & 0xFFFFFFFF) / 2 ** 32 < rate


class RequestTracer:
    """Per-request event timelines; see module docstring.

    Constructed per engine (`serve.Engine` wires itself and its
    scheduler to one).  ``path``/``sample`` override the env knobs.
    """

    def __init__(self, path=None, sample=None, source="serve",
                 push_url=None):
        env = os.environ.get(ENV_ENABLE, "")
        if path is None and env and env not in ("0", "false", "False",
                                                "off", "no"):
            # MXTPU_REQUEST_TRACE=<path> names the file directly;
            # any other truthy value enables with the default path
            if os.sep in env or env.endswith(".jsonl"):
                path = env
            else:
                path = os.environ.get(ENV_FILE) or self._default_path()
        self.path = path
        # live shipping (MXTPU_TRACE_PUSH_URL -> the fleet collector's
        # /trace endpoint): enables timeline collection even without a
        # local JSONL file; the shared per-URL pusher thread only
        # exists once a URL is configured (inert otherwise)
        if push_url is None:
            push_url = os.environ.get(ENV_PUSH) or None
        self._pusher = _pusher_for(push_url) if push_url else None
        # replica identity stamped onto shipped/written lines (the
        # fleet front sets it so the collector can attribute a line —
        # e.g. an SLO-offending request — to the replica that served
        # it); None keeps the line schema byte-identical to older runs
        self.identity = None
        # catalog model id stamped like identity (the fleet front sets
        # it): lets ``trace_report --stitch`` show which checkpoint
        # served each hop.  None keeps the historical schema
        self.model = None
        self.enabled = path is not None or self._pusher is not None
        if sample is None:
            try:
                sample = float(os.environ.get(ENV_SAMPLE, "") or 1.0)
            except ValueError:
                sample = 1.0
        self.sample = min(1.0, max(0.0, float(sample)))
        self.source = source
        self._pid = os.getpid()
        self._lock = threading.Lock()
        self._file = None
        self._flight = flight.recorder()
        self.traced = 0                # requests whose timeline was kept
        self.written = 0               # JSONL lines written
        # optional hook fired on EVERY terminal event (sampled or not)
        # — the engine hangs its SLO-breach detection here
        self.on_terminal = None

    @staticmethod
    def _default_path():
        from mxnet_tpu import telemetry

        return os.path.join(telemetry.out_dir(), "request_trace.jsonl")

    # -- counters (re-fetched per call; no-ops unless MXTPU_TELEMETRY) ----
    @staticmethod
    def _count_rejection(reason):
        from mxnet_tpu import telemetry

        telemetry.counter("mxtpu_serve_rejections_total",
                          "rejected requests by reason",
                          ("reason",)).labels(reason=reason).inc()

    @staticmethod
    def _count_preemption(reason):
        from mxnet_tpu import telemetry

        telemetry.counter("mxtpu_serve_preemptions_total",
                          "scheduler preemptions by reason",
                          ("reason",)).labels(reason=reason).inc()

    # -- recording hooks (scheduler + engine call these) -------------------
    def submitted(self, req):
        """First event of a request's life; stamps trace identity on
        the Request — unless the caller pre-stamped one (a fleet
        router propagates its trace id across replica hops, so every
        hop's JSONL line shares the id and ``tools/trace_report.py``
        can stitch the request back together)."""
        if req.trace_id is None:
            req.trace_id = f"{self._pid:x}-{req.rid}"
        req._trace_sampled = self.enabled and _sampled(req.rid, self.sample)
        req._trace_events = [] if req._trace_sampled else None
        if req._trace_sampled:
            self.traced += 1
            # hold a virtual Chrome track for the request's whole life:
            # concurrent in-flight requests (across ALL engines in the
            # process) land on distinct tids
            req._trace_tid = _acquire_track()
        self._record(req, "submitted", {"prompt_tokens": int(req.prompt.size),
                                        "max_new_tokens": req.max_new_tokens})

    def event(self, req, name, **args):
        if name == "preempted":
            self._count_preemption(args.get("reason", "unknown"))
        self._record(req, name, args)

    def terminal(self, req, name, **args):
        """Final event (finished/rejected/cancelled): records, counts,
        and — for sampled requests — writes the JSONL line and the
        Chrome-trace request track."""
        if name == "rejected":
            self._count_rejection(args.get("reason", "unknown"))
        self._record(req, name, args)
        if self.on_terminal is not None:
            try:
                self.on_terminal(req, name, args)
            except Exception:
                # observability never kills serving — but a broken SLO
                # hook is a real bug and must move a counter
                from mxnet_tpu import telemetry

                telemetry._note_internal_error("on_terminal_hook")
        events = getattr(req, "_trace_events", None)
        if events is None:
            return
        req._trace_events = None       # finalize exactly once
        self._write_line(req, name, events)
        self._emit_track(req, events)

    def _record(self, req, name, args):
        t = time.perf_counter()
        self._flight.record("request", rid=req.rid, ev=name, **args)
        events = getattr(req, "_trace_events", None)
        if events is not None:
            ev = {"ev": name, "t": t}
            if args:
                ev.update(args)
            events.append(ev)

    # -- JSONL export ------------------------------------------------------
    def _write_line(self, req, status, events):
        record = {"trace_id": req.trace_id, "rid": req.rid,
                  "tenant": getattr(req, "tenant", None),
                  "status": status,
                  "prompt_tokens": int(req.prompt.size),
                  "max_new_tokens": req.max_new_tokens,
                  "generated": len(req.tokens),
                  "n_preemptions": req.n_preemptions,
                  "events": events}
        if self.identity is not None:      # only-when-set: schema pin
            record["replica"] = self.identity
            # perf_counter↔epoch anchor: event "t" fields are
            # perf-domain and processes don't share a perf epoch, so
            # fleet lines (identity set ⇒ multi-process timeline
            # exists) carry the pair timeline_report solves for the
            # offset with.  Bare-engine lines keep the historic schema
            epoch = time.time()  # mxtpu-lint: disable=wall-clock (cross-process trace-stitch anchor)
            record["clock"] = {"perf": time.perf_counter(),
                               "epoch": epoch}
        if self.model is not None:         # only-when-set: schema pin
            record["model"] = self.model
        adapter = getattr(req, "adapter_id", None)
        if adapter is not None:            # only-when-set: schema pin
            record["adapter"] = adapter
        if self.source != "serve":
            # mark non-engine lines (the router's) so the collector's
            # SLO layer can tell client-truth lines from replica-local
            # ones; engine lines keep their historical schema
            record["source"] = self.source
        if self._pusher is not None:
            self._pusher.push(record)
        if self.path is None:
            return
        line = json.dumps(record)
        try:
            with self._lock:
                if self._file is None:
                    d = os.path.dirname(self.path)
                    if d:
                        os.makedirs(d, exist_ok=True)
                    self._file = open(self.path, "a")
                self._file.write(line + "\n")
                self._file.flush()     # a crash loses no finished request
            self.written += 1
        except OSError:
            pass                       # tracing must never kill serving

    def close(self):
        with self._lock:
            if self._file is not None:
                try:
                    self._file.close()
                except OSError:
                    pass
                self._file = None

    # -- Chrome-trace request tracks ---------------------------------------
    def _emit_track(self, req, events):
        tid = getattr(req, "_trace_tid", None)
        if tid is None:
            return
        req._trace_tid = None
        try:
            from mxnet_tpu import telemetry

            if telemetry.enabled():
                tracer = telemetry.tracer()
                tracer.set_track_name(
                    tid, f"serve-req-slot-{tid - _TRACK_BASE}")
                base = {"rid": req.rid, "trace_id": req.trace_id}
                for name, start, end, extra in _phases(events):
                    tracer.add_complete(name, start, end,
                                        args=dict(base, **extra), tid=tid,
                                        cat="request")
        finally:
            _release_track(tid)


def _phases(events):
    """Reduce an event timeline to (phase, start_t, end_t, args)
    intervals: queued / prefill / decode / preempted.

    ``tools/trace_report.py`` applies the SAME boundary rules in its
    own stdlib-only ``phase_breakdown`` (it must run without importing
    this package); tests/test_observability.py pins the two
    implementations to agree on a shared timeline — change the
    attribution here and there together."""
    if not events:
        return []
    out = []
    end_t = events[-1]["t"]
    # boundary state machine over the ordered timeline
    mark_t = events[0]["t"]            # start of the open interval
    state = "queued"
    for ev in events:
        name, t = ev["ev"], ev["t"]
        if name == "prefill_start":
            out.append((state, mark_t, t, {}))
            state, mark_t = "prefill", t
        elif name == "prefill_end":
            out.append((state, mark_t, t,
                        {"resume": bool(ev.get("resume"))}))
            state, mark_t = "decode", t
        elif name == "preempted":
            out.append((state, mark_t, t, {}))
            state, mark_t = "preempted", t
        elif name in TERMINAL_EVENTS:
            extra = {"status": name}
            if "reason" in ev:
                extra["reason"] = ev["reason"]
            out.append((state, mark_t, t, extra))
            state, mark_t = None, t
    if state is not None and end_t > mark_t:   # no terminal event seen
        out.append((state, mark_t, end_t, {"status": "open"}))
    return [(n, s, e, a) for n, s, e, a in out if e >= s]
