"""Custom operators defined in Python.

Rebuild of python/mxnet/operator.py (CustomOp/CustomOpProp + register,
plus the legacy NumpyOp/NDArrayOp callback classes) and their C++ bridges
(src/operator/custom-inl.h, ndarray_op-inl.h, native_op-inl.h).

TPU-native mechanics: a custom op's ``forward``/``backward`` run as
host callbacks via ``jax.pure_callback`` inside the compiled graph — the
analog of the reference's async-safe frontend-callback operator.  The op
declares shapes/dtypes through a ``CustomOpProp`` exactly as in the
reference, so graph inference composes.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from .base import MXNetError
from .ndarray import NDArray
from .ops.op import OpDef, OP_REGISTRY
from .registry import Registry

__all__ = ["CustomOp", "CustomOpProp", "register", "PythonOp", "NumpyOp",
           "NDArrayOp", "get_all_registered_operators"]

_CUSTOM_REGISTRY = Registry("custom-op")


class _HostArray(np.ndarray):
    """Buffer handed to custom-op callbacks.

    A plain numpy view (callbacks run inside ``jax.pure_callback``,
    where dispatching jax ops would deadlock the runtime) extended with
    the NDArray reading surface reference custom ops use
    (``asnumpy``/``wait_to_read`` — python/mxnet/operator.py passes
    NDArrays to CustomOp callbacks)."""

    def asnumpy(self):
        return np.asarray(self)

    def wait_to_read(self):
        return self

    def wait_to_write(self):
        return self

    @property
    def context(self):
        from .context import cpu

        return cpu()


def _host_array(a):
    return np.ascontiguousarray(a).view(_HostArray)


class CustomOp:
    """Base class for custom op execution (operator.py CustomOp)."""

    def forward(self, is_train, req, in_data, out_data, aux):
        raise NotImplementedError

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        raise NotImplementedError

    def assign(self, dst, req, src):
        if req in ("write", "inplace"):
            dst[:] = src
        elif req == "add":
            dst[:] = dst + src if isinstance(dst, np.ndarray) else dst + src
        elif req == "null":
            pass
        else:
            raise MXNetError(f"invalid req {req!r}")


class CustomOpProp:
    """Metadata for a custom op (operator.py CustomOpProp)."""

    def __init__(self, need_top_grad=True):
        self.need_top_grad_ = need_top_grad

    def list_arguments(self):
        return ["data"]

    def list_outputs(self):
        return ["output"]

    def list_auxiliary_states(self):
        return []

    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0]] * len(self.list_outputs()), []

    def infer_type(self, in_type):
        return (in_type, [in_type[0]] * len(self.list_outputs()),
                [in_type[0]] * len(self.list_auxiliary_states()))

    def need_top_grad(self):
        return self.need_top_grad_

    def declare_backward_dependency(self, out_grad, in_data, out_data):
        """Ids of blobs backward needs (reference operator.py custom-op
        default).  Informational here: jax.vjp tracks true dependencies
        and XLA prunes the rest — kept for API parity."""
        deps = []
        if self.need_top_grad_:
            deps.extend(out_grad)
        deps.extend(in_data)
        deps.extend(out_data)
        return deps

    def create_operator(self, ctx, shapes, dtypes):
        raise NotImplementedError


class _CustomOpDef(OpDef):
    """Adapter lowering a CustomOpProp into the framework op registry via
    host callbacks."""

    def __init__(self, name, prop_cls):
        self.name = name
        self.prop_cls = prop_cls
        self.param_cls = None
        self.has_backward = True
        self.is_loss = False

    def make_params(self, kwargs):
        return self.prop_cls(**kwargs)

    def list_arguments(self, prop):
        return list(prop.list_arguments())

    def list_outputs(self, prop):
        return list(prop.list_outputs())

    def list_auxiliary_states(self, prop):
        return list(prop.list_auxiliary_states())

    def infer_shape(self, prop, in_shapes):
        ins, outs, auxs = prop.infer_shape(list(in_shapes))
        return list(ins), [tuple(o) for o in outs], [tuple(a) for a in auxs]

    def infer_dtype(self, prop, in_dtypes):
        # custom ops default to float32 when nothing is known (reference
        # custom-op behavior: frontends assume float32 absent hints)
        filled = [d if d is not None else np.dtype(np.float32)
                  for d in in_dtypes]
        ins, outs, auxs = prop.infer_type(filled)
        return list(ins), list(outs), list(auxs)

    def _get_op(self, prop, shapes, dtypes):
        return prop.create_operator(None, shapes, dtypes)

    def forward(self, prop, inputs, aux, train, key):
        shapes = [tuple(x.shape) for x in inputs]
        dtypes = [np.dtype(x.dtype) for x in inputs]
        _, out_shapes, _ = self.infer_shape(prop, shapes)
        _, out_dtypes, _ = self.infer_dtype(prop, dtypes)
        op = self._get_op(prop, shapes, dtypes)
        n_out = len(out_shapes)

        def host_fwd(*arrs):
            in_data = [_host_array(a) for a in arrs]
            out_data = [_host_array(np.zeros(s, d))
                        for s, d in zip(out_shapes, out_dtypes)]
            op.forward(is_train=train, req=["write"] * n_out,
                       in_data=in_data, out_data=out_data, aux=[])
            return tuple(np.asarray(o) for o in out_data)

        result_shapes = tuple(jax.ShapeDtypeStruct(s, d)
                              for s, d in zip(out_shapes, out_dtypes))
        outs = jax.pure_callback(host_fwd, result_shapes, *inputs)
        return list(outs), list(aux)

    def backward(self, prop, out_grads, inputs, outputs):
        shapes = [tuple(x.shape) for x in inputs]
        dtypes = [np.dtype(x.dtype) for x in inputs]
        op = self._get_op(prop, shapes, dtypes)

        def host_bwd(*arrs):
            n_in = len(inputs)
            n_out = len(outputs)
            in_data = [_host_array(a) for a in arrs[:n_in]]
            out_data = [_host_array(a) for a in arrs[n_in:n_in + n_out]]
            ograds = [_host_array(a) for a in arrs[n_in + n_out:]]
            in_grad = [_host_array(np.zeros(d.shape, d.dtype))
                       for d in in_data]
            op.backward(req=["write"] * n_in, out_grad=ograds, in_data=in_data,
                        out_data=out_data, in_grad=in_grad, aux=[])
            return tuple(np.asarray(g) for g in in_grad)

        result_shapes = tuple(jax.ShapeDtypeStruct(x.shape, x.dtype)
                              for x in inputs)
        grads = jax.pure_callback(host_bwd, result_shapes,
                                  *inputs, *outputs, *out_grads)
        return list(grads)


def register(reg_name):
    """Register a CustomOpProp subclass under a name usable from
    nd./sym. (reference operator.py register)."""

    def do_register(prop_cls):
        opdef = _CustomOpDef(reg_name, prop_cls)
        OP_REGISTRY.register(reg_name, opdef)
        _CUSTOM_REGISTRY.register(reg_name, prop_cls)
        # refresh generated frontends
        from . import ndarray as nd_mod
        from . import symbol as sym_mod

        setattr(nd_mod, reg_name, nd_mod._make_ndarray_function(reg_name))
        setattr(sym_mod, reg_name, sym_mod._make_symbol_function(reg_name))
        # keep the native C-ABI registry in sync for in-process frontends
        try:
            from . import c_api as _c_api

            if _c_api._PUBLISHED:
                _c_api.publish_registry()
        # mxtpu-lint: disable=swallowed-exception (C-ABI re-publish is
        # best-effort sync for in-process frontends; Python registry
        # already holds the op)
        except Exception:
            pass
        return prop_cls

    return do_register


def get_all_registered_operators():
    return OP_REGISTRY.list()


class PythonOp:
    """Base class of legacy python operators (reference operator.py:19
    PythonOp): callable symbol factory with need_top_grad metadata.
    Subclasses: NumpyOp (raw-buffer callbacks), NDArrayOp (NDArray
    callbacks)."""

    def __init__(self, need_top_grad=True):
        self.need_top_grad_ = need_top_grad
        self._registered = None

    def need_top_grad(self):
        """Whether backward needs the head gradient (reference
        operator.py:110)."""
        return self.need_top_grad_

    def list_arguments(self):
        return ["data"]

    def list_outputs(self):
        return ["output"]

    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0]]

    def forward(self, in_data, out_data):
        raise NotImplementedError

    def backward(self, out_grad, in_data, out_data, in_grad):
        raise NotImplementedError

    def _ensure_registered(self):
        if self._registered:
            return self._registered
        legacy = self
        name = f"_numpy_op_{type(self).__name__}_{id(self):x}"

        class _Prop(CustomOpProp):
            def __init__(self):
                super().__init__(need_top_grad=legacy.need_top_grad_)

            def list_arguments(self):
                return legacy.list_arguments()

            def list_outputs(self):
                return legacy.list_outputs()

            def infer_shape(self, in_shape):
                ins, outs = legacy.infer_shape(in_shape)
                return ins, outs, []

            def create_operator(self, ctx, shapes, dtypes):
                # NumpyOp callbacks work on RAW numpy buffers mutated in
                # place (reference _Native, operator.py NumpyOp), unlike
                # CustomOp which receives NDArrays.
                def _buf(x):
                    # asnumpy() views can be read-only; legacy callbacks
                    # mutate their buffers in place
                    return np.array(x.asnumpy())

                class _Op(CustomOp):
                    def forward(self, is_train, req, in_data, out_data, aux):
                        ins = [_buf(d) for d in in_data]
                        outs = [_buf(o) for o in out_data]
                        legacy.forward(in_data=ins, out_data=outs)
                        for dst, src in zip(out_data, outs):
                            dst[:] = src

                    def backward(self, req, out_grad, in_data, out_data,
                                 in_grad, aux):
                        ogs = [_buf(g) for g in out_grad]
                        ins = [_buf(d) for d in in_data]
                        outs = [_buf(o) for o in out_data]
                        igs = [_buf(g) for g in in_grad]
                        legacy.backward(out_grad=ogs, in_data=ins,
                                        out_data=outs, in_grad=igs)
                        for dst, src in zip(in_grad, igs):
                            dst[:] = src

                return _Op()

        register(name)(_Prop)
        self._registered = name
        return name

    def __call__(self, *args, **kwargs):
        # reference operator.py:33 — instances are callable symbol factories
        return self.get_symbol(*args, **kwargs)

    def get_symbol(self, *args, **kwargs):
        from . import symbol as sym_mod

        name = self._ensure_registered()
        return getattr(sym_mod, name)(*args, **kwargs)


class NumpyOp(PythonOp):
    """Legacy callback op over numpy buffers (reference operator.py
    NumpyOp / _Native).  Subclass and call ``get_symbol``."""


class NDArrayOp(PythonOp):
    """Legacy callback op over NDArrays (reference operator.py NDArrayOp).
    Same bridge as NumpyOp here: callbacks receive numpy views."""

    def declare_backward_dependency(self, out_grad, in_data, out_data):
        """Ids of blobs backward needs (reference operator.py:372-393).
        Informational here: jax.vjp tracks true data dependencies and
        XLA dead-code-eliminates the rest, so the declaration cannot
        cause stale-buffer bugs — kept for API parity."""
        deps = []
        if self.need_top_grad():
            deps.extend(out_grad)
        deps.extend(in_data)
        deps.extend(out_data)
        return deps
