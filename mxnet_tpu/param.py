"""Typed, declarative parameter structs.

Rebuild of dmlc::Parameter (``DMLC_DECLARE_PARAMETER`` — see reference
usage e.g. src/io/iter_prefetcher.h:26-44, src/optimizer/sgd-inl.h:21-40).
Every operator / iterator / optimizer declares a ``Params`` subclass whose
fields carry type, default, range and docs.  This is the load-bearing
piece of the config system (SURVEY.md §5 "Config / flag system"): it
gives kwargs validation, auto-generated docstrings, and a serializable
``to_dict`` used for graph JSON round-trips.

Usage::

    class ConvParams(Params):
        kernel = field(tuple_of(int), required=True, doc="conv kernel size")
        num_filter = field(int, required=True, lower=1)
        stride = field(tuple_of(int), default=None, doc="defaults to 1s")
        layout = field(str, default="NCHW", enum=("NCHW", "NHWC"))

    p = ConvParams(kernel=(3, 3), num_filter=64)
"""

from __future__ import annotations

import ast

__all__ = ["Params", "field", "tuple_of", "ParamError"]

_REQUIRED = object()


class ParamError(ValueError):
    pass


class _Field:
    __slots__ = ("name", "type", "default", "enum", "lower", "upper", "doc", "required")

    def __init__(self, type_, default=_REQUIRED, enum=None, lower=None, upper=None,
                 doc="", required=False):
        self.name = None
        self.type = type_
        self.default = _REQUIRED if required else default
        self.enum = enum
        self.lower = lower
        self.upper = upper
        self.doc = doc
        self.required = required or default is _REQUIRED

    def coerce(self, value):
        if value is None:
            return None
        try:
            value = self.type(value) if not isinstance(value, _TupleOf) else self.type(value)
        except (TypeError, ValueError) as e:
            raise ParamError(f"field {self.name}: cannot convert {value!r}: {e}") from None
        if self.enum is not None and value not in self.enum:
            raise ParamError(f"field {self.name}: {value!r} not in {self.enum}")
        if self.lower is not None and value < self.lower:
            raise ParamError(f"field {self.name}: {value!r} < lower bound {self.lower}")
        if self.upper is not None and value > self.upper:
            raise ParamError(f"field {self.name}: {value!r} > upper bound {self.upper}")
        return value


def field(type_, default=_REQUIRED, enum=None, lower=None, upper=None, doc="",
          required=False):
    """Declare a typed field inside a Params subclass."""
    return _Field(type_, default, enum, lower, upper, doc, required)


class _TupleOf:
    """Coercer for tuple-valued fields; accepts tuples, lists, scalars and
    the reference's string syntax ``"(2, 2)"`` (kwargs arrive as strings
    through its C API registry; we accept the same for compat)."""

    def __init__(self, elem_type):
        self.elem_type = elem_type

    def __call__(self, value):
        if isinstance(value, str):
            value = ast.literal_eval(value)
        if not isinstance(value, (tuple, list)):
            value = (value,)
        return tuple(self.elem_type(v) for v in value)

    @property
    def __name__(self):
        return f"tuple_of({self.elem_type.__name__})"


def tuple_of(elem_type):
    return _TupleOf(elem_type)


def _coerce_bool(v):
    if isinstance(v, str):
        return v.lower() in ("1", "true", "yes")
    return bool(v)


class _ParamsMeta(type):
    def __new__(mcls, name, bases, ns):
        fields = {}
        for base in bases:
            fields.update(getattr(base, "_fields", {}))
        for key, val in list(ns.items()):
            if isinstance(val, _Field):
                val.name = key
                if val.type is bool:
                    val.type = _coerce_bool
                fields[key] = val
                del ns[key]
        ns["_fields"] = fields
        cls = super().__new__(mcls, name, bases, ns)
        if fields:
            cls.__doc__ = (cls.__doc__ or "") + "\n\nParameters\n----------\n" + "\n".join(
                f"{f.name} : {getattr(f.type, '__name__', f.type)}"
                + ("" if f.required else f", optional (default={f.default!r})")
                + (f"\n    {f.doc}" if f.doc else "")
                for f in fields.values()
            )
        return cls


class Params(metaclass=_ParamsMeta):
    """Base class for declarative parameter structs."""

    _fields: dict = {}

    def __init__(self, **kwargs):
        cls = type(self)
        for key, value in kwargs.items():
            if key not in cls._fields:
                raise ParamError(
                    f"{cls.__name__}: unknown argument {key!r}; "
                    f"valid arguments: {sorted(cls._fields)}"
                )
            object.__setattr__(self, key, cls._fields[key].coerce(value))
        for key, f in cls._fields.items():
            if key not in kwargs:
                if f.default is _REQUIRED:
                    raise ParamError(f"{cls.__name__}: missing required argument {key!r}")
                object.__setattr__(self, key, f.default)

    def to_dict(self) -> dict:
        """Non-default fields as a str->str dict (graph JSON serialization)."""
        out = {}
        for key, f in type(self)._fields.items():
            val = getattr(self, key)
            if f.default is _REQUIRED or val != f.default:
                out[key] = str(val)
        return out

    def full_dict(self) -> dict:
        return {key: getattr(self, key) for key in type(self)._fields}

    def __repr__(self):
        inner = ", ".join(f"{k}={getattr(self, k)!r}" for k in type(self)._fields)
        return f"{type(self).__name__}({inner})"

    def __eq__(self, other):
        return type(self) is type(other) and self.full_dict() == other.full_dict()

    def __hash__(self):
        return hash(tuple(sorted((k, repr(v)) for k, v in self.full_dict().items())))

    @classmethod
    def argument_names(cls):
        return list(cls._fields)
