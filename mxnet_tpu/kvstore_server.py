"""Server-process entry wrapper (rebuild of
python/mxnet/kvstore_server.py).

The reference auto-enters a server event loop when ``DMLC_ROLE=server``
(`_init_kvstore_server_module`, kvstore_server.py:58) and wraps it in a
``KVStoreServer`` class whose ``run()`` blocks until a stop command.
Here the server is :class:`mxnet_tpu.ps.PSServer` (started standalone by
``tools/launch.py -s N`` as ``python -m mxnet_tpu.ps``); this module
keeps the reference's class/entry shape for code that imports it
directly.
"""

from __future__ import annotations

import os

from .ps import PSServer

__all__ = ["KVStoreServer", "server_role"]


def server_role():
    """True when this process was launched as a parameter-server shard
    (reference: ``DMLC_ROLE == 'server'``)."""
    return os.environ.get("DMLC_ROLE", os.environ.get("MXTPU_ROLE", "")) \
        == "server"


class KVStoreServer:
    """Blocking server wrapper (reference kvstore_server.py:11-57).

    The reference wraps a worker-side KVStore handle; here the server is
    self-contained — construct with the worker count (and optional
    host/port) and ``run()`` serves until a stop command arrives from
    rank 0 (the reference's ``kStopServer`` command analog).
    """

    def __init__(self, num_workers, host="127.0.0.1", port=0):
        self.num_workers = int(num_workers)
        self.host = host
        self.port = port
        self._server = None

    @property
    def address(self):
        if self._server is None:
            raise RuntimeError("server not started; call run()")
        return self._server.addr

    def run(self):
        """Serve until stopped (reference KVStoreServer.run)."""
        self._server = PSServer(self.num_workers, port=self.port,
                                host=self.host).start()
        self._server.join()


def _init_kvstore_server_module(num_workers=None):
    """Enter the server loop when launched in the server role
    (reference kvstore_server.py:58-67)."""
    if num_workers is None:
        from .base import env_int

        # DMLC_NUM_WORKER (reference launcher contract) wins; the
        # MXTPU_* fallback rides the shared parser
        dmlc = os.environ.get("DMLC_NUM_WORKER")
        num_workers = (int(dmlc) if dmlc
                       else env_int("MXTPU_NUM_PROCS", 1))
    KVStoreServer(num_workers).run()
