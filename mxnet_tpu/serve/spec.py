"""Draft-model speculative decoding for the serving engine.

Plain continuous-batching decode emits exactly ONE token per running
request per iteration — decode throughput is bound by one bucketed
dispatch per token.  Speculative decoding (Leviathan et al. 2023;
Chen et al. 2023) breaks that bound: a small *draft* model proposes
``k`` tokens per request, and the *target* model scores all ``k+1``
positions in ONE bucketed verify dispatch.  With greedy (temperature
0) acceptance — keep the longest prefix of drafted tokens whose target
argmax agrees, plus the target's own token at the first disagreement —
the emitted stream is **provably token-identical to plain decode**: the
target's argmax decides every emitted token, the draft only decides how
many arrive per dispatch.

Per engine iteration with ``spec_k = k`` the decode batch costs

  1 draft dispatch   (``k+1`` single-token steps of the small draft
                      model, unrolled inside one XLA program)
  1 verify dispatch  (the target model over ``k+1`` rows per request,
                      write-then-attend through the paged block table)

and emits between 1 and ``k+1`` tokens per request — vs one target
dispatch per token.  The win is largest where per-dispatch overhead or
memory-bound decode dominates, exactly the serving decode hot loop.

At temperature > 0 (a sampling-mode engine) acceptance switches to
SPECULATIVE SAMPLING (the same papers' stochastic rule): the draft
samples each proposal from its warped distribution q, the target
accepts proposal ``x`` with probability ``min(1, p(x)/q(x))`` and the
first rejection resamples from the normalized residual
``max(p - q, 0)`` — the emitted stream is distribution-identical to
plain sampling from p, so the spec speedup extends to stochastic
traffic.  The whole acceptance chain runs inside the verify program
(``_build_verify`` with ``cfg.sampling``); the draft's q vectors ship
device-to-device from the draft dispatch and the host only ever syncs
the emitted rows.  Greedy rows (one-hot p and q) degenerate to the
argmax rule exactly, so a mixed batch needs no special casing.

The :class:`DraftWorker` here owns the draft side: the draft
checkpoint's parameters, its OWN (much smaller) paged K/V cache pair,
and the per-request ingest bookkeeping.  The draft cache shares the
target's block geometry and per-request block *tables* verbatim — the
target's ``BlockManager`` already guarantees table disjointness, so the
draft needs no block accounting of its own.  Draft-cache contents
affect ONLY the acceptance rate, never the output: correctness rides
entirely on the target's verify pass, which is why the draft side may
lazily re-ingest context (admission, preemption-resume, prefix-cache
hits) without any bitwise-reproducibility obligations.

Rollback: the verify pass writes target K/V for all ``k+1`` candidate
positions; after acceptance the engine truncates the request's block
table back to the accepted length (``BlockManager.truncate``) so
rejected drafts never hold cache blocks across iterations.  Stale K/V
*within* kept blocks is overwritten write-then-attend before any later
position can read it, the same argument that makes null-block garbage
safe.
"""

from __future__ import annotations

import collections
import threading

import numpy as np

import jax
import jax.numpy as jnp

from ..base import env_float
from ..models.generate import (detect_gpt_variant, normalize_gpt_params,
                               reconcile_decode_config)
from ..telemetry import flight as flight_mod

__all__ = ["DraftWorker", "ENV_SPEC", "ENV_MIN_ACCEPT"]

ENV_SPEC = "MXTPU_SERVE_SPEC"
ENV_MIN_ACCEPT = "MXTPU_SPEC_MIN_ACCEPT"

# rolling acceptance-rate window length (verify events); the low-
# acceptance flight dump waits for MIN_WINDOW events before judging
WINDOW = 256
MIN_WINDOW = 32


class DraftWorker:
    """The draft-model half of speculative decoding.

    Owns the draft checkpoint's device-resident parameters and its own
    K/V cache pair shaped ``(draft_layers, num_blocks, block_size,
    draft_kv_heads, draft_head_dim)`` — the same block geometry as the
    target so the per-request block tables are shared verbatim.  All
    compiled draft programs resolve through the owning engine's program
    machinery (``_STEP_CACHE`` / AOT export store / warmup manifests),
    keyed ``kind="draft"`` (the k-step proposal loop, bucketed over the
    decode batch) and ``kind="draft_chunk"`` (context ingest, the chunk
    program built over the draft config).

    Mutable state is the per-request ingest ledger and the rolling
    acceptance window; both are read by ``/statusz`` scrapes from other
    threads, so mutations lock.
    """

    def __init__(self, engine, params, num_heads=None, window=None,
                 symbol=None, name="gpt"):
        if symbol is not None:
            num_heads, window = reconcile_decode_config(symbol, num_heads,
                                                        window)
        if num_heads is None:
            raise ValueError(
                "draft num_heads is required (pass draft_num_heads=, or "
                "draft_symbol= to read it from the draft's trained graph)")
        window = 0 if window is None else int(window)
        if window < 0:
            raise ValueError(f"draft window must be >= 0 (got {window})")
        params = normalize_gpt_params(params, name)
        spec = detect_gpt_variant(params, num_heads, name)
        if spec["vocab"] != engine.spec["vocab"]:
            raise ValueError(
                f"draft vocab ({spec['vocab']}) must match the target's "
                f"({engine.spec['vocab']}) — drafted token ids feed the "
                "target verify program directly")
        if (spec["pos_table"] is not None
                and spec["pos_table"] < engine.max_model_len):
            raise ValueError(
                f"draft positional table ({spec['pos_table']}) is shorter "
                f"than max_model_len ({engine.max_model_len}) — the draft "
                "must be able to read every position the target serves")
        from .engine import _ModelCfg

        self.name = name
        self.cfg = _ModelCfg(
            name=name, n_layers=spec["n_layers"],
            num_heads=int(num_heads), head_dim=spec["head_dim"],
            kv_heads=spec["kv_heads"], pos_table=spec["pos_table"],
            swiglu=spec["swiglu"], tied=spec["tied"],
            rmsnorm=spec["rmsnorm"], window=window,
            block_size=engine.block_size,
            # the draft cfg itself stays sampling=False: on a
            # sampling-mode engine the draft program's warp/operand
            # layout rides the TARGET cfg (``_build_draft(sample_cfg=)``
            # — keyed by the engine cfg in _spec_key either way), and
            # the draft_chunk ingest program never samples at all.
            # The draft cache stays fp even under MXTPU_SERVE_KV_DTYPE=
            # int8: it is small by design, and draft-cache contents
            # only ever move the acceptance rate, never a token
            sampling=False, sample_cap=0, numeric_watch=False,
            kv_quant=False)
        # place the draft weights; under tensor parallelism they
        # replicate (the draft is small by design — sharding it would
        # buy latency nothing and complicate the program cache keys)
        rep = (engine._shardings.rep if engine._shardings is not None
               else None)
        self._owned = []
        placed = {}
        for k, v in params.items():
            arr = (jax.device_put(v, rep) if rep is not None
                   else jnp.asarray(v))
            if arr is not v:
                self._owned.append(arr)
            placed[k] = arr
        self.params = placed
        dt = self.params[f"{name}_tok_embed_weight"].dtype
        shape = (spec["n_layers"], engine.num_blocks, engine.block_size,
                 spec["kv_heads"], spec["head_dim"])
        self.cache_k = jnp.zeros(shape, dt)
        self.cache_v = jnp.zeros(shape, dt)
        self.min_accept = env_float(ENV_MIN_ACCEPT, 0.0)
        self._lock = threading.Lock()
        # rid -> (preemption epoch, draft-valid positions): which
        # prefix of the request's context the draft cache holds.  A
        # resume-by-recomputation bumps the epoch, forcing a full
        # re-ingest into the request's NEW block table.
        self._valid = {}                          # guarded-by: _lock
        # rolling (k, accepted) per verify — the statusz acceptance
        # window and the low-acceptance anomaly trigger
        self._window = collections.deque(maxlen=WINDOW)  # guarded-by: _lock

    # -- context ingest ------------------------------------------------------
    def context_gap(self, req):
        """Positions ``[0, req.cache_len)`` the draft cache does NOT
        yet hold for ``req`` (0 when drafting can start right away)."""
        with self._lock:
            state = self._valid.get(req.rid)
        if state is not None and state[0] == req.n_preemptions \
                and state[1] >= req.cache_len:
            return 0
        return int(req.cache_len)

    def note_ingested(self, req, n_positions):
        with self._lock:
            self._valid[req.rid] = (req.n_preemptions, int(n_positions))

    def note_drafted(self, req, n_positions):
        """The draft program just wrote K/V through ``n_positions``
        (the k-step loop writes every candidate position, so the next
        iteration never has an ingest gap whatever was accepted)."""
        self.note_ingested(req, n_positions)

    def forget(self, rid):
        """Request left the engine (finished/cancelled): drop its
        ingest ledger entry so the table stays bounded by the number of
        in-flight requests."""
        with self._lock:
            self._valid.pop(rid, None)

    def prune(self, live_rids):
        """Drop ledger entries for rids no longer running — requests
        that left the engine between decode iterations (preempted then
        rejected/cancelled) never pass the per-batch ``forget`` path,
        and the table must stay bounded by the live running set."""
        with self._lock:
            for rid in [r for r in self._valid if r not in live_rids]:
                del self._valid[rid]

    # -- acceptance accounting ----------------------------------------------
    def on_verify(self, k, accepted):
        """One verify pass proposed ``k`` tokens and the target
        accepted ``accepted``.  Feeds the rolling window; when the
        windowed rate sits below ``MXTPU_SPEC_MIN_ACCEPT`` the flight
        recorder dumps (rate-limited per reason) — a silently diverging
        draft is a perf regression nobody sees in correctness tests."""
        with self._lock:
            self._window.append((int(k), int(accepted)))
            rate = self._window_rate_locked()
            n = len(self._window)
        if (self.min_accept > 0.0 and n >= MIN_WINDOW
                and rate is not None and rate < self.min_accept):
            flight_mod.recorder().dump(
                "spec_low_acceptance",
                extra={"accept_rate": round(rate, 4),
                       "threshold": self.min_accept, "window": n})

    def _window_rate_locked(self):
        drafted = sum(k for k, _ in self._window)
        if not drafted:
            return None
        return sum(a for _, a in self._window) / drafted

    def accept_rate_window(self):
        """Acceptance rate over the rolling window (None before any
        verify)."""
        with self._lock:
            rate = self._window_rate_locked()
        return None if rate is None else round(rate, 4)

    # -- introspection -------------------------------------------------------
    def statusz(self, engine):
        """The engine's ``/statusz`` ``spec`` section."""
        cfg = self.cfg
        with self._lock:
            window_n = len(self._window)
            rate = self._window_rate_locked()
            tracked = len(self._valid)
        rate_greedy, rate_stochastic = engine._stats.spec_mode_rates()
        return {
            "k": engine.spec_k,
            # the greedy-vs-stochastic acceptance split (rejection-
            # sampled verifies vs exact argmax ones) — the SAME
            # formula ServeStats.snapshot reads, so the views cannot
            # drift
            "accept_rate_greedy": rate_greedy,
            "accept_rate_stochastic": rate_stochastic,
            "draft": {
                "name": self.name,
                "n_layers": cfg.n_layers,
                "d_model": cfg.num_heads * cfg.head_dim,
                "kv_heads": cfg.kv_heads,
                "params_bytes": sum(int(v.nbytes)
                                    for v in self.params.values()),
                "kv_cache_bytes": 2 * int(self.cache_k.nbytes),
            },
            "accept_rate_window": (None if rate is None
                                   else round(rate, 4)),
            "window_verifies": window_n,
            "min_accept": self.min_accept,
            "tracked_requests": tracked,
            "verify_buckets": engine.verify_buckets(),
        }

    def shutdown(self):
        """Release the draft-side device buffers (mirrors
        ``Engine.shutdown``'s exactly-what-we-placed policy)."""
        for arr in self._owned + [self.cache_k, self.cache_v]:
            try:
                arr.delete()
            except (RuntimeError, ValueError):
                pass              # already donated-away or deleted
        self._owned = []
        self.cache_k = self.cache_v = None
        self.params = None
        with self._lock:
            self._valid.clear()


# -- acceptance rule (host-side, pure) ---------------------------------------
def accept_greedy(drafted_row, target_row, k):
    """Greedy acceptance for one request: ``drafted_row`` holds the k
    drafted tokens, ``target_row`` the target's k+1 argmax tokens (row
    j scored after consuming row j's input).  Returns ``(accepted,
    emit)``: the agreeing-prefix length and the tokens to emit — the
    accepted drafts plus the target's own token at the first
    disagreement (or its bonus token when everything agreed).  The
    emitted stream is exactly what plain greedy decode would produce.
    """
    a = 0
    while a < k and int(drafted_row[a]) == int(target_row[a]):
        a += 1
    return a, [int(x) for x in drafted_row[:a]] + [int(target_row[a])]


# -- compiled-program bodies -------------------------------------------------
def _rope_rows(u, pos):
    """RoPE over arbitrary leading dims: flatten rows, reuse the
    engine's rotation, restore the shape."""
    from .engine import _rope

    lead = u.shape[:-2]
    flat = u.reshape((-1,) + u.shape[-2:])
    return _rope(flat, pos.reshape(-1)).reshape(
        lead + u.shape[-2:])


def _build_draft(cfg, k, donate, shardings=None, sample_cfg=None):
    """The k-step draft-proposal program (kind="draft", bucketed over
    the decode batch).  Unrolls ``k+1`` single-token steps of the draft
    model inside ONE jit: step ``j`` writes the fed token's K/V at
    ``pos+j`` through the (target-shared) block table, attends via
    ``paged_attention``, and its proposal feeds step ``j+1``.  Steps
    ``0..k-1`` produce the k drafted tokens; step ``k`` is write-only —
    it parks the last draft's K/V so the next iteration never has an
    ingest gap even when every draft is accepted (its logits head is
    dead code XLA eliminates).

    With ``sample_cfg`` (the TARGET engine's sampling-mode cfg) each
    step SAMPLES its proposal from the draft's warped distribution q
    — per-request (B,)-shaped temperature/top-p/top-k operands, the
    same warp the target applies — and the program additionally
    returns q in CANDIDATE space: the sampled token's own probability
    ``q_at (B, k)`` plus the per-step candidate probabilities and
    vocab ids ``(B, k, cap)`` pairs.  That is everything the verify
    program's rejection-sampling acceptance ever evaluates q at (the
    drafted tokens and the target's own candidate ids), shipped
    device-to-device at ``cap``-width instead of a dense ``(B, k,
    vocab)`` tensor — on a 50k vocab that is ~400x less inter-dispatch
    HBM traffic on the decode hot path.  Without it (greedy engines)
    the proposal is the historical argmax, byte-for-byte.
    """
    from .engine import _filter_logits, _forward_token_batch

    def draft(params, ck, cv, toks, pos, tables, rng):
        S = tables.shape[1] * cfg.block_size
        cur = toks
        outs = []
        for j in range(k + 1):
            # a step past the table's last slot writes to the null
            # block (zeroed table row) instead of clamp-aliasing onto
            # the request's last real block; the row's own output is
            # garbage, but it can only ever be a beyond-quota draft
            # the verify-side emit cap drops
            tbl = jnp.where((pos + j < S)[:, None], tables, 0)
            logits, ck, cv, _, _ = _forward_token_batch(
                cfg, params, ck, cv, None, None, cur, pos + j, tbl)
            if j < k:
                cur = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                outs.append(cur)
        return jnp.stack(outs, axis=1), ck, cv

    def draft_rs(params, ck, cv, toks, pos, tables, temp, topp, topk,
                 rng):
        S = tables.shape[1] * cfg.block_size
        keys = jax.random.split(rng, k)
        cur = toks
        outs, q_at, q_vals, q_idx = [], [], [], []
        for j in range(k + 1):
            tbl = jnp.where((pos + j < S)[:, None], tables, 0)
            logits, ck, cv, _, _ = _forward_token_batch(
                cfg, params, ck, cv, None, None, cur, pos + j, tbl)
            if j < k:
                # sample the proposal from the warped draft
                # distribution and keep that EXACT distribution —
                # q(x) of min(1, p/q) acceptance — as the candidate
                # (probability, vocab-id) pairs plus the sampled
                # token's own q
                masked, idx = _filter_logits(sample_cfg, logits, temp,
                                             topp, topk)
                probs = jax.nn.softmax(masked, axis=-1)
                choice = jax.random.categorical(keys[j], masked,
                                                axis=-1)
                cur = jnp.take_along_axis(
                    idx, choice[..., None],
                    axis=-1)[..., 0].astype(jnp.int32)
                outs.append(cur)
                q_at.append(jnp.take_along_axis(
                    probs, choice[..., None], axis=-1)[..., 0])
                q_vals.append(probs)
                q_idx.append(idx)
        return (jnp.stack(outs, axis=1), jnp.stack(q_at, axis=1),
                jnp.stack(q_vals, axis=1), jnp.stack(q_idx, axis=1),
                ck, cv)

    sampling = sample_cfg is not None
    kw = {"donate_argnums": (1, 2) if donate else ()}
    if shardings is not None:
        rep = shardings.rep
        kw["in_shardings"] = (rep,) * (10 if sampling else 7)
        kw["out_shardings"] = (rep,) * (6 if sampling else 3)
    return jax.jit(draft_rs if sampling else draft, **kw)


def _build_verify(cfg, k, donate, shardings=None):
    """The target-model verify program (kind="verify", bucketed over
    the decode batch; ``k`` is static config).  Scores ``k+1`` rows per
    request — the last emitted token plus the k drafts — through the
    paged block table in one dispatch: all rows' K/V is written FIRST,
    then each row attends to every cache position <= its own (the
    write-then-attend trick of the decode and chunk programs, which
    makes in-window causality exact without a dense score matrix).  The
    attention math mirrors ``ops.attention.paged_attention`` (same
    gather, same scale-by-multiply, same f32 softmax) so a verify row's
    logits track what the single-token decode program would compute for
    the same context.

    On a sampling-mode engine (``cfg.sampling``) the program ALSO owns
    acceptance: rejection sampling (Leviathan et al. 2023; Chen et al.
    2023) entirely on device.  With p the target's warped distribution
    at each position and q the draft's (shipped in as ``(B, k, V)``
    operands straight off the draft dispatch), draft j is accepted
    with probability ``min(1, p(x_j)/q(x_j))``; the first rejection
    resamples from the normalized residual ``max(p - q, 0)`` and a
    fully-accepted run samples a bonus token from the last row's p.
    The emitted prefix is distribution-identical to sampling from p
    token by token — whatever the draft proposed — and greedy rows
    (one-hot p and q) degenerate to exact argmax-prefix acceptance.
    Outputs: the emit rows ``(B, k+1)``, accepted counts ``(B,)`` and
    the emitted tokens' logprob views, so the host's only sync is the
    result.
    """
    from .engine import (_awfc, _cache_outs, _filter_logits, _kv_dequant,
                         _kv_quant_vals, _ln, _logits, _logprob_outs,
                         _mlp, _safe_log, _sample, _split_cache_args)

    name = cfg.name
    Hq, Hkv, Dh = cfg.num_heads, cfg.kv_heads, cfg.head_dim
    group = Hq // Hkv
    d_model = Hq * Dh
    window = cfg.window
    K1 = k + 1
    scale = 1.0 / np.sqrt(Dh)

    def verify(params, *rest):
        """``rows`` (B, K1) int32 token ids; ``pos0`` (B,) the cache
        position of each request's row 0; ``tables`` (B, W).  Returns
        the target's (B, K1) greedy tokens (row j's token decided after
        consuming rows 0..j) — or, in sampling mode, the
        rejection-sampled emit rows + accepted counts + logprobs."""
        adp = slots = None
        if cfg.adapters:
            adp, rest = rest[0], rest[1:]
        ck, cv, ksc, vsc, tail = _split_cache_args(cfg, rest)
        if cfg.sampling:
            toks0, drafted, q_at, q_vals, q_idx, pos0, tables = tail[:7]
            tail = tail[7:]
            rows = jnp.concatenate([toks0[:, None], drafted], axis=1)
        else:
            rows, pos0, tables = tail[:3]
            tail = tail[3:]
        if cfg.adapters:
            slots, tail = tail[0], tail[1:]
        if cfg.sampling:
            temp, topp, topk, rng = tail
        else:
            rng, = tail
        B = rows.shape[0]
        pos = pos0[:, None] + jnp.arange(K1)[None, :]      # (B, K1)
        x = params[f"{name}_tok_embed_weight"][rows]       # (B, K1, D)
        if cfg.pos_table is not None:
            # clamp padded rows: their position may exceed the table
            pidx = jnp.minimum(pos, cfg.pos_table - 1)
            x = x + params[f"{name}_pos_embed_weight"][0, pidx]
        S = tables.shape[1] * cfg.block_size
        # candidate rows past the request's final position (a quota-
        # capped last iteration) write to the NULL block: a clamped
        # gather would alias them onto the LAST table slot and clobber
        # real K/V.  Null-block garbage is never read back — the
        # causal mask only admits logical positions backed by real
        # blocks — and the emit cap drops those rows' tokens anyway.
        bidx = jnp.minimum(pos // cfg.block_size, tables.shape[1] - 1)
        blk = jnp.where(pos < S,
                        jnp.take_along_axis(tables, bidx, axis=1), 0)
        off = pos % cfg.block_size
        spos = jnp.arange(S)[None, None, :]
        keep = spos <= pos[:, :, None]                     # (B, K1, S)
        if window:
            keep = jnp.logical_and(keep, spos > pos[:, :, None] - window)
        for i in range(cfg.n_layers):
            p = f"{name}_l{i}"
            h = _ln(x, params[f"{p}_ln1_gamma"],
                    None if cfg.rmsnorm else params[f"{p}_ln1_beta"])
            q = _awfc(cfg, params, adp, f"{p}_q", h, slots)
            kk = _awfc(cfg, params, adp, f"{p}_k", h, slots)
            v = _awfc(cfg, params, adp, f"{p}_v", h, slots)
            qh = q.reshape(B, K1, Hq, Dh)
            kh = kk.reshape(B, K1, Hkv, Dh)
            vh = v.reshape(B, K1, Hkv, Dh)
            if cfg.pos_table is None:
                qh, kh = _rope_rows(qh, pos), _rope_rows(kh, pos)
            if cfg.kv_quant:
                kq, ks = _kv_quant_vals(kh)
                vq, vs = _kv_quant_vals(vh)
                ck = ck.at[i, blk, off].set(kq)
                ksc = ksc.at[i, blk, off].set(ks)
                cv = cv.at[i, blk, off].set(vq)
                vsc = vsc.at[i, blk, off].set(vs)
            else:
                ck = ck.at[i, blk, off].set(kh)
                cv = cv.at[i, blk, off].set(vh)
            # every row of a request shares its table: gather the
            # request's logical cache view once per layer, mask per
            # row by position (paged_attention's formulation with a
            # row axis added)
            kb = ck[i][tables].reshape(B, S, Hkv, Dh)
            vb = cv[i][tables].reshape(B, S, Hkv, Dh)
            if cfg.kv_quant:
                kb = _kv_dequant(kb, ksc[i][tables].reshape(B, S, Hkv),
                                 x.dtype)
                vb = _kv_dequant(vb, vsc[i][tables].reshape(B, S, Hkv),
                                 x.dtype)
            qg = qh.reshape(B, K1, Hkv, group, Dh)
            sc = jnp.einsum("bckgd,bskd->bkgcs", qg, kb) * scale
            sc = jnp.where(keep[:, None, None], sc,
                           jnp.asarray(-jnp.inf, sc.dtype))
            pr = jax.nn.softmax(sc.astype(jnp.float32),
                                axis=-1).astype(x.dtype)
            at = jnp.einsum("bkgcs,bskd->bckgd", pr, vb)
            x = x + _awfc(cfg, params, adp, f"{p}_proj",
                          at.reshape(B, K1, d_model), slots)
            x = x + _mlp(cfg, params, p, x, adp=adp, slots=slots)
        logits = _logits(cfg, params, x)                   # (B, K1, V)
        caches = _cache_outs(cfg, ck, cv, ksc, vsc)
        if cfg.sampling:
            # -- rejection-sampling acceptance, on device --------------
            # everything runs in CANDIDATE space (sample_cap wide,
            # never vocab-wide): the residual max(p - q, 0) is
            # supported only where p > 0, i.e. inside the target's
            # candidate set, so neither distribution materializes a
            # full-vocab vector — q arrives as the draft's candidate
            # (probability, id) pairs and is re-evaluated at the
            # target's candidate ids by id matching
            kacc, kres, kbonus = jax.random.split(rng, 3)
            # p: the target's warped sampling distribution per row
            # (operands broadcast over the K1 axis); greedy rows are
            # exactly one-hot, so accept degenerates to argmax match
            masked_p, idx_p = _filter_logits(
                cfg, logits, temp[:, None], topp[:, None],
                topk[:, None])                           # (B, K1, cap)
            p_cand = jax.nn.softmax(masked_p, axis=-1)
            idx_k = idx_p[:, :K1 - 1]                    # (B, k, cap)
            # p(x_j): x_j's probability under the target's filtered
            # distribution (0 when the draft proposed outside the
            # target's candidate set); q(x_j) shipped from the draft
            p_at = jnp.sum(
                jnp.where(idx_k == drafted[..., None],
                          p_cand[:, :K1 - 1], 0.0), axis=-1)
            u = jax.random.uniform(kacc, drafted.shape)
            # u < min(1, p/q)  <=>  u*q < p (q(x_j) > 0: x_j was
            # sampled from q)
            accept = u * q_at < p_at
            acc = jnp.sum(jnp.cumprod(accept.astype(jnp.int32),
                                      axis=1), axis=1)     # (B,)
            # the first rejection resamples from the normalized
            # residual max(p - q, 0) — together with the acceptance
            # rule this reproduces p exactly (Leviathan 2023, Thm 1).
            # An identically-zero residual means p == q: acceptance
            # was certain there, the row is never read — substitute p
            # to keep the categorical well-defined
            # q at the TARGET's candidate ids, by id matching the
            # draft's candidate pairs (candidate ids are unique per
            # row, so at most one match contributes)
            q_cand = jnp.sum(
                jnp.where(idx_k[..., :, None] == q_idx[..., None, :],
                          q_vals[..., None, :], 0.0), axis=-1)
            res = jnp.maximum(p_cand[:, :K1 - 1] - q_cand, 0.0)
            rsum = jnp.sum(res, axis=-1, keepdims=True)
            res = jnp.where(rsum > 0, res / rsum, p_cand[:, :K1 - 1])
            corr_c = jax.random.categorical(kres, _safe_log(res),
                                            axis=-1)       # (B, k)
            corr = jnp.take_along_axis(
                idx_k, corr_c[..., None], axis=-1)[..., 0]
            # the bonus token samples from the last row's p directly
            # (categorical over the masked logits IS sampling from p;
            # greedy rows pick candidate 0 — the argmax — exactly)
            bonus_c = jax.random.categorical(kbonus, masked_p[:, K1 - 1],
                                             axis=-1)
            bonus = jnp.take_along_axis(
                idx_p[:, K1 - 1], bonus_c[..., None], axis=-1)[..., 0]
            first_rej = jnp.minimum(acc, K1 - 2)
            corr_at = jnp.take_along_axis(
                corr, first_rej[:, None], axis=1)[:, 0]
            fixed = jnp.where(acc < K1 - 1, corr_at,
                              bonus).astype(jnp.int32)
            jj = jnp.arange(K1)[None, :]
            pad = jnp.concatenate(
                [drafted, jnp.zeros((B, 1), jnp.int32)], axis=1)
            emit = jnp.where(jj < acc[:, None], pad,
                             fixed[:, None]).astype(jnp.int32)
            outs = (emit, acc.astype(jnp.int32)) \
                + _logprob_outs(logits, emit)
        else:
            outs = (_sample(cfg, logits, rng),)
        if cfg.numeric_watch:
            outs = outs + (jnp.isfinite(logits).all(),)
        return outs + caches

    from .engine import _jit_kwargs

    return jax.jit(verify, **_jit_kwargs(
        cfg, donate, shardings, 7 if cfg.sampling else 3,
        n_lead=5 if cfg.sampling else None))
