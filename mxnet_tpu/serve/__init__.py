"""Continuous-batching inference serving (beyond-parity subsystem).

The reference framework serves predictions one static batch at a time
(``predict.Predictor``); this package adds the modern multi-tenant
serving stack on top of the same checkpoints:

- ``kv_block_manager`` — paged KV-cache block accounting (vLLM-style):
  one fixed device cache carved into blocks, per-request block tables,
  LRU eviction of finished/preempted requests' blocks.
- ``scheduler`` — iteration-level continuous batching (Orca-style):
  bounded FIFO admission, prefill/decode interleaving, preemption by
  recomputation under cache pressure, per-request deadlines with
  graceful rejection instead of OOM.
- ``engine`` — the public ``serve.Engine``: ``submit() -> Request``,
  ``stream()``, ``step()``, ``shutdown()``, bucketed jit programs.
- ``stats`` — ``ServeStats`` snapshots (queue depth, TTFT, tokens/sec,
  block utilization, preemption/eviction counters); pair with
  ``mxnet_tpu.monitor.ServeMonitor`` for periodic logging.

Benchmark: ``tools/serve_bench.py`` (SERVE_BENCH.json artifact).
"""

from .engine import Engine
from .kv_block_manager import BlockManager, NoFreeBlocks
from .scheduler import (CANCELLED, FINISHED, REJECTED, RUNNING, WAITING,
                        QueueFull, Request, Scheduler)
from .stats import ServeStats, StatsRecorder

__all__ = ["Engine", "BlockManager", "NoFreeBlocks", "QueueFull",
           "Request", "Scheduler", "ServeStats", "StatsRecorder",
           "WAITING", "RUNNING", "FINISHED", "REJECTED", "CANCELLED"]
