"""Continuous-batching inference serving (beyond-parity subsystem).

The reference framework serves predictions one static batch at a time
(``predict.Predictor``); this package adds the modern multi-tenant
serving stack on top of the same checkpoints:

- ``kv_block_manager`` — paged KV-cache block accounting (vLLM-style):
  one fixed device cache carved into blocks, per-request block tables,
  LRU eviction of finished/preempted requests' blocks; with
  ``MXTPU_SERVE_HOST_KV_BYTES`` set, evicted prefix-cache blocks park
  in a bounded host-DRAM pool (``HostKVPool``) and restore on radix
  hit instead of recomputing (docs/how_to/serve.md "Host-RAM KV
  offload tier").
- ``scheduler`` — iteration-level continuous batching (Orca-style):
  bounded FIFO admission, prefill/decode interleaving, preemption by
  recomputation under cache pressure, per-request deadlines with
  graceful rejection instead of OOM.
- ``engine`` — the public ``serve.Engine``: ``submit() -> Request``,
  ``stream()``, ``step()``, ``shutdown()``, bucketed jit programs;
  per-request ``temperature``/``top_p``/``top_k``/``n``/``logprobs``
  ride the batch as traced OPERANDS in sampling mode
  (env ``MXTPU_SERVE_SAMPLING`` — one program per bucket serves any
  mix of sampling configs; docs/how_to/serve.md "Per-request
  sampling");
  ``tp=N`` (env ``MXTPU_SERVE_TP``) runs the same programs
  tensor-parallel over a ``{'tp': N}`` mesh with regex-rule parameter
  sharding (``parallel.partition``) and a head-sharded KV-cache
  (docs/how_to/serve.md "Tensor-parallel sharded serving").
- ``adapters`` — paged multi-tenant LoRA (S-LoRA/Punica-style):
  ``AdapterStore`` pages per-projection A/B delta stacks in
  engine-owned device arrays (content-addressed, refcounted,
  LRU-evicted to a host-RAM tier), and ``Engine.submit(adapter_id=)``
  threads each row's adapter slot through the bucket programs as a
  traced OPERAND — one program per bucket serves any adapter mix with
  zero fresh traces, slot 0 a true zero delta (env
  ``MXTPU_SERVE_ADAPTERS``; docs/how_to/serve.md "Multi-tenant
  adapters").
- ``stats`` — ``ServeStats`` snapshots (queue depth, TTFT, tokens/sec,
  block utilization, preemption/eviction counters, rejection reasons);
  pair with ``mxnet_tpu.monitor.ServeMonitor`` for periodic logging.

Request-scoped observability (docs/how_to/observability.md): every
request carries a trace id and event timeline (``MXTPU_REQUEST_TRACE``
exports JSONL; ``tools/trace_report.py`` folds it into per-phase
latency percentiles), lifecycle events always feed the telemetry
flight-recorder ring (``MXTPU_FLIGHT_DIR`` dumps it on engine
exceptions / SLO breaches), and live engines appear on the telemetry
server's ``/statusz`` page.

Benchmark: ``tools/serve_bench.py`` (SERVE_BENCH.json artifact).
"""

from .adapters import AdapterStore, NoAdapterSlots
from .engine import Engine
from .kv_block_manager import BlockManager, HostKVPool, NoFreeBlocks
from .scheduler import (CANCELLED, FINISHED, REJECTED, RUNNING, WAITING,
                        QueueFull, Request, Scheduler)
from .spec import DraftWorker
from .stats import ServeStats, StatsRecorder

__all__ = ["AdapterStore", "Engine", "BlockManager", "DraftWorker",
           "HostKVPool", "NoAdapterSlots", "NoFreeBlocks", "QueueFull",
           "Request", "Scheduler", "ServeStats", "StatsRecorder",
           "WAITING", "RUNNING", "FINISHED", "REJECTED", "CANCELLED"]
