"""Paged multi-tenant LoRA adapter store (S-LoRA / Punica style).

Hundreds of tenants' fine-tuned models served at shared base-model
cost: each adapter is a set of low-rank ``(A, B)`` delta pairs per
projection matmul, and every projection adds ``scale * x @ A.T @ B.T``
on top of the frozen base weight.  The deltas live in engine-owned
**paged device stacks** — one ``(S, r_max, d_in)`` A-stack and one
``(S, d_out, r_max)`` B-stack per projection stem plus an ``(S,)``
f32 scale vector — indexed by a per-request *slot* operand inside the
bucketed programs, so ONE traced program per bucket serves any mix of
adapters (the PR 15 traced-operand rule: slots are operands, never
trace keys).

Slot discipline (BlockManager-style accounting):

* **slot 0 is the base model** — its A/B rows and scale are true
  zeros, so a base-row's logits are ``base + 0.0``: token-identical
  to an adapters-off engine.
* slots are **content-addressed** by the sha1 digest of the adapter's
  arrays (ids are aliases onto digests — two tenants uploading the
  same weights share one slot),
* **refcounted** while any queued/running request pins them (a
  preempted request keeps its pin — preemption never fires the
  terminal hook),
* **LRU-evicted** to the host-RAM tier when cold (refcount 0); the
  host tier has its own byte budget and evicts registrations that are
  not device-resident,
* loadable at runtime from **disk** (``save_file``/``load_file``,
  ``np.savez`` container) or **over the wire**
  (``export_records``/``import_records`` — the handoff codec's
  base64 + per-array sha1 framing, corrupt payloads rejected).

Capacity pressure is a *transient* condition: ``acquire`` raises
:class:`NoAdapterSlots` when every slot is pinned, which the engine
maps to a retriable ``adapter_slots`` rejection (fleet replicas
return 503, never a breaker-opening 500).
"""

import base64
import collections
import hashlib
import threading

import numpy as np


class NoAdapterSlots(RuntimeError):
    """Every adapter slot is pinned by a running request (transient —
    retry once some request finishes and drops its refcount)."""


def gpt_stems(name, n_layers, swiglu, tied, params):
    """Projection-stem map ``stem -> (d_out, d_in)`` for a GPT tower,
    read from the checkpoint's ``*_weight`` shapes — the exact stem
    enumeration the quantizer uses, minus the head/embedding (adapters
    never touch the tied embedding or the logits head)."""
    props = ["q", "k", "v", "proj", "ff_up", "ff_down"]
    if swiglu:
        props.append("ff_gate")
    stems = collections.OrderedDict()
    for i in range(n_layers):
        for p in props:
            stem = f"{name}_l{i}_{p}"
            w = params.get(f"{stem}_weight")
            if w is None:
                raise ValueError(f"missing projection weight: {stem}")
            stems[stem] = (int(w.shape[0]), int(w.shape[1]))
    return stems


def _digest(arrays, alpha):
    """Content address: sha1 over the sorted (stem, shape, bytes)
    stream plus the scaling alpha — byte-identical uploads under
    different ids collapse onto one digest (and one device slot)."""
    h = hashlib.sha1()
    h.update(f"alpha={float(alpha)}".encode())
    for stem in sorted(arrays):
        a, b = arrays[stem]
        for tag, arr in (("A", a), ("B", b)):
            arr = np.ascontiguousarray(arr)
            h.update(f"{stem}.{tag}:{arr.dtype}:{arr.shape}".encode())
            h.update(arr.tobytes())
    return h.hexdigest()[:16]


class AdapterStore:
    """Paged device-resident LoRA adapter slots + a host-RAM tier.

    ``stems`` maps projection stem -> ``(d_out, d_in)``; ``rank`` is
    the padded per-slot rank ceiling (adapters with a smaller rank are
    zero-padded — padding rows contribute exactly 0 to the delta);
    ``slots`` counts device slots INCLUDING the reserved all-zero
    slot 0; ``shardings`` optionally maps each device-array key to a
    ``NamedSharding`` so the stacks shard with their parent
    projections under tp.
    """

    def __init__(self, stems, rank, slots, dtype=np.float32,
                 host_bytes=None, shardings=None):
        if slots < 2:
            raise ValueError("adapters needs >= 2 slots "
                             "(slot 0 is the reserved base-model row)")
        if rank < 1:
            raise ValueError("adapter rank must be >= 1")
        self.stems = dict(stems)
        self.rank = int(rank)
        self.slots = int(slots)
        self.dtype = np.dtype(dtype)
        self.host_bytes = host_bytes
        self.sharding = dict(shardings) if shardings else None
        self._lock = threading.RLock()
        self._alias = {}                 # guarded-by: _lock (id -> digest)
        self._host = collections.OrderedDict()  # guarded-by: _lock
        self._host_used = 0              # guarded-by: _lock
        self._loaded = {}                # guarded-by: _lock (digest -> slot)
        self._slot_digest = [None] * self.slots  # guarded-by: _lock
        self._slot_ref = [0] * self.slots        # guarded-by: _lock
        self._free = list(range(1, self.slots))  # guarded-by: _lock
        self._cold = collections.OrderedDict()   # guarded-by: _lock
        self.loads = 0                   # guarded-by: _lock
        self.device_evictions = 0        # guarded-by: _lock
        self.host_evictions = 0          # guarded-by: _lock
        import jax.numpy as jnp

        device = {}
        for stem, (dout, din) in self.stems.items():
            device[f"{stem}_A"] = jnp.zeros(
                (self.slots, self.rank, din), self.dtype)
            device[f"{stem}_B"] = jnp.zeros(
                (self.slots, dout, self.rank), self.dtype)
        device["scale"] = jnp.zeros((self.slots,), jnp.float32)
        if self.sharding:
            import jax

            device = {k: jax.device_put(v, self.sharding[k])
                      for k, v in device.items()}
        self._device = device            # guarded-by: _lock (rebinds)

    # -- registration (host tier) -------------------------------------

    def register(self, adapter_id, arrays, alpha=None):
        """Register ``{stem: (A, B)}`` numpy pairs under ``adapter_id``
        in the host tier (device load is lazy, at first ``acquire``).
        ``A`` is ``(r, d_in)``, ``B`` is ``(d_out, r)`` with
        ``r <= rank``; stems absent from ``arrays`` stay zero.
        Returns the content digest."""
        if not isinstance(adapter_id, str) or not adapter_id:
            raise ValueError("adapter id must be a non-empty string")
        clean, nbytes = {}, 0
        for stem, pair in arrays.items():
            if stem not in self.stems:
                raise ValueError(f"unknown projection stem: {stem}")
            a, b = (np.asarray(x) for x in pair)
            dout, din = self.stems[stem]
            r = a.shape[0] if a.ndim == 2 else -1
            if a.ndim != 2 or b.ndim != 2 or r > self.rank or r < 1 \
                    or a.shape[1] != din or b.shape != (dout, r):
                raise ValueError(
                    f"{stem}: want A (r<={self.rank}, {din}) / "
                    f"B ({dout}, r), got A {a.shape} / B {b.shape}")
            clean[stem] = (a, b)
            nbytes += a.nbytes + b.nbytes
        if not clean:
            raise ValueError("adapter has no projection deltas")
        ranks = {p[0].shape[0] for p in clean.values()}
        if len(ranks) != 1:
            raise ValueError(f"mixed per-stem ranks: {sorted(ranks)}")
        r = ranks.pop()
        alpha = float(alpha) if alpha is not None else float(r)
        digest = _digest(clean, alpha)
        with self._lock:
            if digest not in self._host:
                self._host_make_room(nbytes)
                self._host[digest] = {
                    "arrays": clean, "alpha": alpha, "rank": r,
                    "bytes": nbytes, "ids": set(),
                }
                self._host_used += nbytes
            self._host[digest]["ids"].add(adapter_id)
            self._host.move_to_end(digest)
            self._alias[adapter_id] = digest
        return digest

    def _host_make_room(self, nbytes):
        # called with _lock held (reentrant — re-entering is free and
        # keeps the lock discipline checkable)
        with self._lock:
            if self.host_bytes is None:
                return
            if nbytes > self.host_bytes:
                raise ValueError(
                    f"adapter ({nbytes}B) exceeds the host tier budget "
                    f"({self.host_bytes}B, MXTPU_SERVE_ADAPTER_HOST_BYTES)")
            for digest in list(self._host):
                if self._host_used + nbytes <= self.host_bytes:
                    break
                if digest in self._loaded:
                    continue        # device-resident copies stay pinned
                rec = self._host.pop(digest)
                self._host_used -= rec["bytes"]
                self.host_evictions += 1
                for aid in rec["ids"]:
                    self._alias.pop(aid, None)
            if self._host_used + nbytes > self.host_bytes:
                raise ValueError("host adapter tier full (every entry "
                                 "is device-resident)")

    def known(self, adapter_id):
        with self._lock:
            return adapter_id in self._alias

    def ids(self):
        with self._lock:
            return sorted(self._alias)

    def loaded(self):
        """Adapter ids currently device-resident (hot or cold)."""
        with self._lock:
            out = set()
            for digest in self._loaded:
                rec = self._host.get(digest)
                out |= rec["ids"] if rec else set()
            return sorted(out)

    # -- slot accounting ----------------------------------------------

    def acquire(self, adapter_id):
        """Pin ``adapter_id`` for one request and return its device
        slot, loading it from the host tier (evicting the coldest
        resident adapter if no slot is free).  Raises ``KeyError`` for
        an unknown id, :class:`NoAdapterSlots` when every slot is
        pinned by running requests."""
        with self._lock:
            digest = self._alias[adapter_id]
            slot = self._loaded.get(digest)
            if slot is not None:
                if self._slot_ref[slot] == 0:
                    self._cold.pop(slot, None)
                self._slot_ref[slot] += 1
                self._host.move_to_end(digest)
                return slot
            if self._free:
                slot = self._free.pop()
            elif self._cold:
                slot, old = self._cold.popitem(last=False)
                del self._loaded[old]
                self._slot_digest[slot] = None
                self.device_evictions += 1
            else:
                raise NoAdapterSlots(
                    f"all {self.slots - 1} adapter slots are pinned")
            self._load_slot(slot, digest)
            self._loaded[digest] = slot
            self._slot_digest[slot] = digest
            self._slot_ref[slot] = 1
            self._host.move_to_end(digest)
            return slot

    def release(self, slot):
        """Drop one pin (idempotent per request — the engine zeroes
        the request's slot after calling).  A slot at refcount 0 stays
        loaded and joins the cold-LRU tail."""
        with self._lock:
            if not 0 < slot < self.slots or self._slot_ref[slot] == 0:
                return
            self._slot_ref[slot] -= 1
            if self._slot_ref[slot] == 0:
                self._cold[slot] = self._slot_digest[slot]
                self._cold.move_to_end(slot)

    def unload(self, adapter_id):
        """Force an adapter off the device (catalog rebalance).  Only
        cold adapters unload; a pinned one raises ``RuntimeError``.
        The host-tier registration stays."""
        with self._lock:
            digest = self._alias[adapter_id]
            slot = self._loaded.get(digest)
            if slot is None:
                return False
            if self._slot_ref[slot]:
                raise RuntimeError(
                    f"adapter {adapter_id!r} is pinned by "
                    f"{self._slot_ref[slot]} running request(s)")
            self._cold.pop(slot, None)
            del self._loaded[digest]
            self._slot_digest[slot] = None
            self._free.append(slot)
            return True

    def forget(self, adapter_id):
        """De-catalog an adapter (the rebalancer's unload half):
        device-unload it AND drop its host-tier registration, so the
        replica stops advertising it.  Cold only — a pinned adapter
        raises ``RuntimeError`` (drain first).  Other ids aliasing the
        same content keep theirs; returns False for an unknown id."""
        with self._lock:
            digest = self._alias.get(adapter_id)
            if digest is None:
                return False
            rec = self._host[digest]
            if len(rec["ids"]) == 1:
                self.unload(adapter_id)        # RuntimeError if pinned
                self._host.pop(digest)
                self._host_used -= rec["bytes"]
            rec["ids"].discard(adapter_id)
            self._alias.pop(adapter_id, None)
            return True

    def _load_slot(self, slot, digest):
        # called with _lock held (reentrant — re-entering is free and
        # keeps the lock discipline checkable)
        with self._lock:
            rec = self._host[digest]
            import jax
            import jax.numpy as jnp

            device = dict(self._device)
            for stem, (dout, din) in self.stems.items():
                a = np.zeros((self.rank, din), self.dtype)
                b = np.zeros((dout, self.rank), self.dtype)
                pair = rec["arrays"].get(stem)
                if pair is not None:
                    r = pair[0].shape[0]
                    a[:r] = pair[0]
                    b[:, :r] = pair[1]
                for tag, row in (("A", a), ("B", b)):
                    key = f"{stem}_{tag}"
                    new = device[key].at[slot].set(jnp.asarray(row))
                    if self.sharding:
                        new = jax.device_put(new, self.sharding[key])
                    device[key] = new
            scale = np.float32(rec["alpha"] / rec["rank"])
            device["scale"] = device["scale"].at[slot].set(scale)
            if self.sharding:
                device["scale"] = jax.device_put(device["scale"],
                                                 self.sharding["scale"])
            self._device = device
            self.loads += 1

    @property
    def device(self):
        """The program operand: the current device-stack pytree."""
        with self._lock:
            return self._device

    # -- disk + wire codecs -------------------------------------------

    def save_file(self, adapter_id, path):
        with self._lock:
            rec = self._host[self._alias[adapter_id]]
            arrays = {f"{s}.A": p[0] for s, p in rec["arrays"].items()}
            arrays.update(
                {f"{s}.B": p[1] for s, p in rec["arrays"].items()})
            alpha = rec["alpha"]
        np.savez(path, __alpha__=np.float64(alpha), **arrays)

    def load_file(self, adapter_id, path):
        """Register an adapter from a ``save_file`` container."""
        with np.load(path) as z:
            alpha = float(z["__alpha__"])
            arrays = {}
            for name in z.files:
                if name == "__alpha__":
                    continue
                stem, tag = name.rsplit(".", 1)
                arrays.setdefault(stem, [None, None])
                arrays[stem][0 if tag == "A" else 1] = z[name]
        return self.register(adapter_id, {s: tuple(p)
                                          for s, p in arrays.items()},
                             alpha=alpha)

    def export_records(self, adapter_id):
        """Wire payload (the handoff codec's base64 + sha1 framing):
        JSON-safe, integrity-checked per array on import."""
        with self._lock:
            digest = self._alias[adapter_id]
            rec = self._host[digest]
            records = []
            for stem, (a, b) in sorted(rec["arrays"].items()):
                for tag, arr in (("A", a), ("B", b)):
                    raw = np.ascontiguousarray(arr).tobytes()
                    records.append({
                        "name": f"{stem}.{tag}",
                        "dtype": str(arr.dtype),
                        "shape": list(arr.shape),
                        "sha1": hashlib.sha1(raw).hexdigest()[:16],
                        "data": base64.b64encode(raw).decode("ascii"),
                    })
            return {"adapter": adapter_id, "digest": digest,
                    "alpha": rec["alpha"], "rank": rec["rank"],
                    "records": records}

    def import_records(self, adapter_id, payload):
        """Register from an ``export_records`` payload; any array whose
        sha1 disagrees with its bytes rejects the whole adapter."""
        arrays = {}
        for r in payload.get("records") or []:
            raw = base64.b64decode(r["data"])
            if hashlib.sha1(raw).hexdigest()[:16] != r["sha1"]:
                raise ValueError(
                    f"adapter array {r['name']!r} failed its sha1 "
                    "integrity check")
            arr = np.frombuffer(raw, dtype=np.dtype(r["dtype"]))
            arr = arr.reshape(r["shape"]).copy()
            stem, tag = r["name"].rsplit(".", 1)
            arrays.setdefault(stem, [None, None])
            arrays[stem][0 if tag == "A" else 1] = arr
        if any(a is None or b is None for a, b in arrays.values()):
            raise ValueError("adapter payload missing an A/B half")
        return self.register(
            adapter_id, {s: tuple(p) for s, p in arrays.items()},
            alpha=payload.get("alpha"))

    # -- introspection ------------------------------------------------

    def stats(self):
        with self._lock:
            used = sum(1 for d in self._slot_digest[1:] if d)
            return {
                "slots": self.slots,
                "rank": self.rank,
                "slots_used": used,
                "slots_pinned": sum(1 for r in self._slot_ref[1:] if r),
                "slots_free": self.slots - 1 - used,
                "ids": sorted(self._alias),
                "loaded": self.loaded(),
                "registered": len(self._host),
                "host_bytes_used": self._host_used,
                "host_bytes_budget": self.host_bytes,
                "loads": self.loads,
                "device_evictions": self.device_evictions,
                "host_evictions": self.host_evictions,
            }
