"""Paged KV-cache block accounting for the serving engine.

One fixed device-resident cache (allocated once by ``serve.Engine``)
is carved into ``num_blocks`` blocks of ``block_size`` token slots
each.  This module owns the HOST-side bookkeeping only: which physical
blocks belong to which request (the per-request *block table*), the
free list, refcounts, the content-addressed prefix index and the LRU
eviction tier — the device arrays never move.
``ops.attention.paged_attention`` consumes the tables to gather K/V.

Block id 0 is the permanent *null block*: it is never allocated, block
tables pad with it past a request's last real block, and padded scatter
positions write into it.  Its contents are garbage by design — every
consumer masks by context length before the softmax.

Prefix caching (RadixAttention/PagedAttention-style sharing)
-----------------------------------------------------------

With ``prefix_cache`` on (env ``MXTPU_SERVE_PREFIX_CACHE``, default
on), every FULL block whose token content is known is *published*
under a content-addressed key ``H(parent_key, block_token_ids)``.
Chaining the parent key into each block's hash makes the key table an
implicit radix tree over token prefixes: walking a new request's
prompt block-by-block down the chain yields the longest cached prefix,
as a chain of refcounted physical blocks.  ``allocate(rid, n,
token_ids=...)`` returns ``(table, cached_tokens)`` — the table starts
with the shared chain (each hit block's refcount incremented) and the
engine prefills only the suffix.

Sharing changes the lifecycle:

  allocate()  -> every table entry holds a reference (fresh blocks at
                 refcount 1, prefix hits incremented)
  free()      -> DECREF, never a blind release: blocks still referenced
                 by another request's table are untouched.  A block
                 reaching refcount 0 parks — published blocks in the
                 prefix LRU (K/V intact, a future ``allocate`` can hit
                 them again), unpublished blocks in the legacy
                 per-request retained tier
  evict       -> only refcount-0 blocks are ever reclaimed, and
                 published blocks only as radix LEAVES (no cached
                 children), oldest-first — an interior block is never
                 pulled out from under a cached descendant chain

Copy-on-write: a shared block is never partially overwritten.  The one
place that could happen — a prompt fully covered by cached blocks still
needs its last position's logits, so the final span must be recomputed
— is handled at lookup time by capping the hit at ``n_tokens - 1``: the
last matched block is dropped from the hit and the engine recomputes
its tokens into a FRESH private block (recomputation is the copy).

Host-RAM offload tier (``HostKVPool``)
--------------------------------------

With a pool attached (env ``MXTPU_SERVE_HOST_KV_BYTES`` > 0), a
refcount-0 published LEAF reclaimed by the prefix LRU no longer
discards its K/V: the block's device contents are copied device→host
(the engine's ``set_offload_source`` callback) and parked in a bounded
host-DRAM numpy pool under the block's existing content key — the
HBM-as-L1 / DRAM-as-L2 hierarchy vLLM-style engines use for swapped
blocks.  ``_walk`` extends the radix chain walk into the host tier: a
host hit claims a FRESH device block, queues an async host→device
restore (``take_pending_restores`` — the engine dispatches the copies
before the first program that reads the blocks) and counts the span as
cached.  Restored blocks are token-identical to recompute by
construction (content-addressed keys + per-slot KV quantization), so
the tier is a pure capacity extension: DRAM is 10-100x HBM, and the
pool has its own LRU with the same leaf-only discipline.  Without a
pool every prefix eviction throws K/V away; ``discarded_tokens``
counts exactly those tokens — the number this tier exists to drive
down.

Prefill/decode handoff (``export_blocks`` / ``import_blocks``)
--------------------------------------------------------------

The same content-keyed host copies double as the WIRE FORMAT for
disaggregated serving (DistServe-style role-split fleets): a
prefill-role replica serializes a finished prompt's cached chain with
``export_blocks`` (device blocks gathered D2H through the offload
fetch path, already-parked blocks peeked from the pool) and a
decode-role replica ingests the records with ``import_blocks`` into
ITS host pool under the same keys — the existing radix walk + async
restore program then pull them HBM-ward ahead of the first decode
read, so a transferred span counts as ``cached_tokens`` and no decode
program changes.  Every record is verified against the chain hash
``H(parent_key, token_ids)`` at import: a truncated or corrupted
payload fails verification, the chain stops there, and the receiver
simply recomputes the rest from the prompt (degradation, never
corruption).  Equal keys mean equal prefixes, so the radix key IS the
transfer dedup — a receiver that already holds a block (either tier)
skips its bytes.
"""

from __future__ import annotations

import base64
import hashlib
import threading
import time
from collections import OrderedDict, deque

import numpy as np

from .. import telemetry
from ..base import env_flag, env_float, env_int

__all__ = ["BlockManager", "HostKVPool", "NoFreeBlocks", "RadixSummary",
           "chain_keys"]

# chaos-harness fault: simulated seconds per host-tier restore claim (a
# slow DRAM copy); with a restore budget set, a delay past the budget
# DEGRADES the hit to recompute instead of stalling the step loop
ENV_HOST_RESTORE_DELAY = "MXTPU_FAULT_HOST_RESTORE_DELAY"
ENV_HOST_RESTORE_BUDGET = "MXTPU_SERVE_HOST_KV_RESTORE_BUDGET"

# chain anchor for the first block of every sequence (the radix root)
_ROOT = b"mxtpu-radix-root"


class NoFreeBlocks(Exception):
    """Raised when an allocation cannot be satisfied even after
    evicting every refcount-0 retained/cached block.  The scheduler
    catches this and preempts a running request instead of letting the
    cache OOM."""


def blocks_for(n_tokens, block_size):
    """Physical blocks needed to hold ``n_tokens`` cache slots."""
    return -(-n_tokens // block_size)


def _block_key(parent, token_ids):
    """Content-addressed key of one full block: chain-hash of the
    parent block's key and this block's token ids.  Chaining makes
    equal keys mean equal whole PREFIXES, not just equal blocks."""
    h = hashlib.sha1(parent)
    h.update(np.asarray(token_ids, np.int32).tobytes())
    return h.digest()


def salted_root(salt):
    """Radix root for a KV-affecting request condition — e.g. a LoRA
    ``adapter_id``, whose K/V projections differ from the base
    model's.  Equal salt means equal chain keys (same-adapter requests
    share prefixes exactly like before); a different salt yields a
    fully disjoint key space, so adapter K/V can never be reused for
    base rows or across adapters — not by the local radix walk, not by
    a handoff import, not by the fleet KV fabric.  ``None``/empty is
    the historical unsalted root: every pre-adapter chain key is
    byte-identical to what it always was."""
    if not salt:
        return _ROOT
    h = hashlib.sha1(_ROOT)
    h.update(str(salt).encode())
    return h.digest()


def chain_keys(token_ids, block_size, max_blocks=None, salt=None):
    """Chain keys of ``token_ids``'s full blocks, in prefix order.

    The tokenizer-side half of cache-aware routing: the fleet router
    hashes an incoming prompt with THIS function (same
    ``H(parent_key, block_tokens)`` chain as the radix index, no model
    loaded) and probes each replica's advertised ``RadixSummary`` for
    the longest cached ancestor.  Copy-on-write capped exactly like
    ``_walk``: the final token's block always recomputes, so it is
    never part of the routable prefix."""
    bs = int(block_size)
    if bs < 1 or token_ids is None:
        return []
    n_full = len(token_ids) // bs
    if n_full and n_full * bs > len(token_ids) - 1:
        n_full -= 1                    # COW: last span recomputes
    if max_blocks is not None:
        n_full = min(n_full, int(max_blocks))
    out = []
    parent = salted_root(salt)
    for b in range(n_full):
        key = _block_key(parent, token_ids[b * bs:(b + 1) * bs])
        out.append(key)
        parent = key
    return out


class RadixSummary:
    """Compact advertisement of the radix cache's contents — the
    ``kv_summary`` payload a replica publishes on ``/healthz`` /
    ``/statusz`` so the fleet router can score prefix affinity without
    ever walking the tree.

    Two complementary structures, both maintained O(1) per
    publish/evict event (incremental — never a full-tree walk, and
    ``snapshot()`` on the scrape path only packs bits):

    - a COUNTING Bloom filter over every published block key in either
      tier: the ``k`` probe positions are carved straight out of the
      key's sha1 bytes (the key already IS a uniform hash — no second
      hash family), ``add`` increments / ``remove`` decrements a
      uint16 count, and the snapshot packs ``count > 0`` into a base64
      bitmap (``m`` bits -> ``m/8`` bytes on the wire: ~512 B + ~1/3
      base64 overhead at the default m=4096).  The false-positive rate
      is bounded by ``(1 - e^(-k*n/m))^k`` (~2.4% at n=512 keys) and a
      false positive is HARMLESS by contract: the router sends a
      request to a replica that turns out cache-cold, which recomputes
      — never an error, never a wrong token.  False negatives cannot
      happen while counts stay below the uint16 ceiling (add saturates
      rather than wraps, so a saturated position just stays set).
    - ``top``: the most recently published chain keys (truncated hex,
      the handoff codec's 16-char idiom), bounded at ``top_k`` — an
      exact-membership fast path for the hottest chains.

    Mutations arrive under the BlockManager/HostKVPool locks; the
    summary keeps its own leaf lock anyway so the two tiers can never
    race an unguarded numpy increment."""

    def __init__(self, block_size, bloom_bits=None, top_k=None):
        self.block_size = int(block_size)
        m = (env_int("MXTPU_ROUTE_SUMMARY_BLOOM_BITS", 4096)
             if bloom_bits is None else int(bloom_bits))
        self.m = max(64, int(m))
        self.k = 4
        self.top_k = max(0, env_int("MXTPU_ROUTE_SUMMARY_TOPK", 32)
                         if top_k is None else int(top_k))
        self._lock = threading.Lock()
        self._counts = np.zeros(self.m, np.uint16)  # guarded-by: _lock
        self._top = OrderedDict()                   # guarded-by: _lock
        self.keys = 0                               # guarded-by: _lock
        self.version = 0                            # guarded-by: _lock

    def _positions(self, key):
        return [int.from_bytes(key[4 * i:4 * i + 4], "little") % self.m
                for i in range(self.k)]

    def add(self, key):
        """One block published (either tier) under ``key``."""
        with self._lock:
            for p in self._positions(key):
                if self._counts[p] < np.iinfo(np.uint16).max:
                    self._counts[p] += 1
            self.keys += 1
            self.version += 1
            if self.top_k:
                hexk = key.hex()[:16]
                self._top[hexk] = True
                self._top.move_to_end(hexk)
                while len(self._top) > self.top_k:
                    self._top.popitem(last=False)

    def remove(self, key):
        """One block unpublished/evicted (either tier)."""
        with self._lock:
            for p in self._positions(key):
                if self._counts[p] > 0:
                    self._counts[p] -= 1
            self.keys = max(0, self.keys - 1)
            self.version += 1
            self._top.pop(key.hex()[:16], None)

    def clear(self):
        with self._lock:
            self._counts[:] = 0
            self._top.clear()
            self.keys = 0
            self.version += 1

    def snapshot(self):
        """JSON-ready advertisement (the wire form ``match`` probes).
        Size-bounded by construction: m/8 bloom bytes + top_k hex
        keys, independent of how many blocks are cached."""
        with self._lock:
            bits = np.packbits(self._counts > 0).tobytes()
            return {"block_size": self.block_size,
                    "keys": self.keys,
                    "version": self.version,
                    "bloom": {"m": self.m, "k": self.k,
                              "bits": base64.b64encode(bits)
                              .decode("ascii")},
                    "top": list(self._top)}

    @staticmethod
    def match(snapshot, keys):
        """How many leading ``keys`` (full digests, prefix order) the
        ``snapshot`` advertises — the router-side probe.  Chaining
        makes the first miss final: a block cannot be cached without
        its ancestor, so a deeper bloom hit past a miss would be a
        guaranteed false positive.  Pure stdlib (bytes + int ops) so
        the per-request router path never touches numpy, and any
        malformed snapshot scores zero instead of raising."""
        if not snapshot or not keys:
            return 0
        bloom = snapshot.get("bloom") or {}
        try:
            m = int(bloom.get("m") or 0)
            k = int(bloom.get("k") or 0)
            raw = base64.b64decode(bloom.get("bits") or "")
        except (TypeError, ValueError):
            return 0
        bloom_ok = m > 0 and k > 0 and len(raw) * 8 >= m
        top = set(snapshot.get("top") or ())
        depth = 0
        for key in keys:
            if key.hex()[:16] in top:
                depth += 1
                continue
            if not bloom_ok:
                break
            pos = [int.from_bytes(key[4 * i:4 * i + 4], "little") % m
                   for i in range(k)]
            if all((raw[p >> 3] >> (7 - (p & 7))) & 1 for p in pos):
                depth += 1
            else:
                break
        return depth


class HostKVPool:
    """Bounded host-DRAM pool of evicted prefix-cache blocks.

    Entries are keyed by the block's content-addressed radix key and
    hold the block's K/V as host numpy arrays (plus the int8 scale
    slots under quantized KV) — the same content the device block held,
    so a restore is byte-identical to recompute by construction.  The
    pool runs its own LRU under ``max_bytes`` with the same leaf-only
    discipline as the device tier (an entry whose CHILD is hosted is
    never evicted first: without the interior, the deeper entries are
    unreachable by the chain walk and would be dead bytes — the child
    link is registered before any room-making eviction, so an insert
    can never reclaim its own chain's interior).  An entry whose
    parent has already left BOTH tiers (a niche partial-unpublish
    path) is unreachable until its parent re-parks; the LRU simply
    ages it out.

    Chaos hook: ``MXTPU_FAULT_HOST_RESTORE_DELAY`` simulates a slow
    DRAM copy per claim; with ``MXTPU_SERVE_HOST_KV_RESTORE_BUDGET``
    set, a delay past the budget degrades the claim to a miss (the
    entry stays hosted, the engine recomputes) instead of stalling the
    serving step loop on the copy.
    """

    def __init__(self, max_bytes, block_tokens=0):
        self.max_bytes = int(max_bytes)
        if self.max_bytes <= 0:
            raise ValueError(
                f"max_bytes must be > 0 (got {max_bytes}); an absent "
                "pool is host_pool=None, not a zero-byte pool")
        self.block_tokens = int(block_tokens)
        self._lock = threading.RLock()
        # key -> (parent_key, arrays tuple, nbytes), LRU order
        self._entries = OrderedDict()   # guarded-by: _lock
        # parent key -> number of hosted entries chained under it
        # (leaf == absent); survives the parent's own restore so a
        # re-offloaded interior keeps protecting its hosted children
        self._by_parent = {}            # guarded-by: _lock
        # (on_add, on_remove) key callbacks the owning BlockManager
        # registers so its RadixSummary tracks host-tier membership
        # incrementally (None = nobody advertising)
        self._listener = None           # guarded-by: _lock
        self.bytes_used = 0             # guarded-by: _lock
        self.bytes_peak = 0             # guarded-by: _lock
        self.offloads = 0               # guarded-by: _lock
        self.restores = 0               # guarded-by: _lock
        self.evictions = 0              # guarded-by: _lock
        self.rejects = 0                # guarded-by: _lock
        self.degraded = 0               # guarded-by: _lock
        self.discarded_tokens = 0       # guarded-by: _lock
        self.fault_delay_s = env_float(ENV_HOST_RESTORE_DELAY, 0.0)
        self.restore_budget_s = env_float(ENV_HOST_RESTORE_BUDGET, 0.0)
        self._m_offloads = telemetry.counter(
            "mxtpu_serve_host_kv_offloads_total",
            "prefix-cache blocks parked in the host-DRAM tier")
        self._m_discarded = telemetry.counter(
            "mxtpu_serve_prefix_discarded_tokens_total",
            "tokens whose cached K/V an eviction threw away for good")
        # a fleet silently degrading restores to recompute must be
        # visible in Prometheus, not only in the pool's local counter
        self._m_degraded = telemetry.counter(
            "mxtpu_serve_host_kv_degraded_total",
            "host-tier restore claims degraded to recompute "
            "(restore budget exceeded)")

    def __len__(self):
        with self._lock:
            return len(self._entries)

    def has(self, key):
        with self._lock:
            return key in self._entries

    def keys(self):
        """Every hosted content key (LRU order) — the summary rebuild
        after a ``BlockManager.reset()``, never the scrape path."""
        with self._lock:
            return list(self._entries)

    def set_listener(self, on_add, on_remove):
        """Register per-key add/remove callbacks (the BlockManager's
        RadixSummary maintenance).  Callbacks run under ``_lock`` and
        must be leaf operations — they get the key only."""
        with self._lock:
            self._listener = (on_add, on_remove)

    def _remove(self, key):
        """Drop one entry (called under ``_lock``); returns its
        ``(parent, arrays, nbytes)``."""
        with self._lock:
            parent, arrays, nbytes = self._entries.pop(key)
            self.bytes_used -= nbytes
            if parent is not None and parent in self._by_parent:
                self._by_parent[parent] -= 1
                if not self._by_parent[parent]:
                    del self._by_parent[parent]
            if self._listener is not None:
                self._listener[1](key)
            return parent, arrays, nbytes

    def _evict_leaf(self):
        """Reclaim the oldest hosted entry with no hosted children —
        the host tier's final discard (called under ``_lock``)."""
        with self._lock:
            for key in self._entries:          # oldest first
                if self._by_parent.get(key, 0) == 0:
                    self._remove(key)
                    self.evictions += 1
                    self.discarded_tokens += self.block_tokens
                    self._m_discarded.inc(self.block_tokens)
                    return True
            return False

    def _insert(self, key, parent, arrays):
        """Budget-checked insert (called under ``_lock``); returns
        whether the entry was parked."""
        with self._lock:
            nbytes = sum(int(a.nbytes) for a in arrays)
            if nbytes > self.max_bytes:
                self.rejects += 1
                return False
            if key in self._entries:
                # re-offload of a restored block: content-addressed
                # keys mean the bytes are identical — refresh recency
                self._remove(key)
            # register the parent link BEFORE making room: the budget
            # eviction below must never reclaim the incoming entry's
            # own hosted parent to fit the child — that would park
            # bytes the chain walk can no longer reach
            if parent is not None:
                self._by_parent[parent] = self._by_parent.get(parent, 0) + 1
            while self.bytes_used + nbytes > self.max_bytes:
                if not self._evict_leaf():
                    if parent is not None and parent in self._by_parent:
                        self._by_parent[parent] -= 1
                        if not self._by_parent[parent]:
                            del self._by_parent[parent]
                    self.rejects += 1
                    return False
            self._entries[key] = (parent, tuple(arrays), nbytes)
            self.bytes_used += nbytes
            self.bytes_peak = max(self.bytes_peak, self.bytes_used)
            if self._listener is not None:
                self._listener[0](key)
            return True

    def put(self, key, parent, arrays):
        """Park one evicted block's host copies under ``key``.  Returns
        False (the caller counts a discard) when the entry cannot fit
        even after evicting every hosted leaf."""
        with self._lock:
            if not self._insert(key, parent, arrays):
                return False
            self.offloads += 1
            self._m_offloads.inc()
            return True

    def claim(self, key):
        """Pop ``key``'s host copies for a device restore; None on
        miss — including the chaos-degraded case, where the simulated
        DRAM copy would exceed the restore budget and the entry STAYS
        hosted while the caller falls back to recompute."""
        with self._lock:
            if key not in self._entries:
                return None
            if self.fault_delay_s:
                if (self.restore_budget_s
                        and self.fault_delay_s > self.restore_budget_s):
                    self.degraded += 1
                    self._m_degraded.inc()
                    return None
                time.sleep(self.fault_delay_s)   # the simulated copy
            _, arrays, _ = self._remove(key)
            self.restores += 1
            return arrays

    def peek(self, key):
        """``key``'s host arrays WITHOUT claiming (the entry stays
        parked, recency untouched); None on miss.  The handoff export
        path reads parked blocks through this — an export must never
        chaos-delay, degrade, or pop the local tier."""
        with self._lock:
            entry = self._entries.get(key)
            return None if entry is None else entry[1]

    def unclaim(self, key, parent, arrays):
        """Return a claimed entry after a failed allocation (no new
        offload is counted — the bytes never left the pool's custody
        semantically)."""
        with self._lock:
            self._insert(key, parent, arrays)

    def clear(self):
        """Deterministic release of every hosted array (engine
        shutdown rides this alongside its device-buffer deletes)."""
        with self._lock:
            if self._listener is not None:
                for key in self._entries:
                    self._listener[1](key)
            self._entries.clear()
            self._by_parent.clear()
            self.bytes_used = 0

    def stats(self):
        """JSON-ready snapshot — the ``/statusz`` ``host_kv`` section
        and the replica load signal's host-tier occupancy."""
        with self._lock:
            return {"max_bytes": self.max_bytes,
                    "bytes_used": self.bytes_used,
                    "bytes_peak": self.bytes_peak,
                    "utilization": round(
                        self.bytes_used / self.max_bytes, 4),
                    "entries": len(self._entries),
                    "offloads": self.offloads,
                    "restores": self.restores,
                    "evictions": self.evictions,
                    "rejects": self.rejects,
                    "degraded": self.degraded,
                    "discarded_tokens": self.discarded_tokens}


class BlockManager:
    """Host-side block accounting.  Mutations are serialized by the
    RLock below: the scheduler drives allocation from the engine's step
    thread while /statusz snapshots and admission checks may read from
    others (reads of the annotated structures are point-in-time
    snapshots; every write path is lock-wrapped and enforced by
    mxtpu-lint's unlocked-shared-state checker).  Reentrant because
    ``allocate``/``ensure_capacity`` call ``_take`` under the lock."""

    def __init__(self, num_blocks, block_size, prefix_cache=None,
                 host_pool=None):
        if num_blocks < 2:
            raise ValueError("need >= 2 blocks (block 0 is the null block)")
        if block_size < 1:
            raise ValueError("block_size must be >= 1")
        self.num_blocks = num_blocks
        self.block_size = block_size
        if prefix_cache is None:
            prefix_cache = env_flag("MXTPU_SERVE_PREFIX_CACHE", True)
        self.prefix_cache = bool(prefix_cache)
        # host-DRAM offload tier (None = off: every prefix eviction
        # discards, exactly the pre-offload lifecycle)
        self.host = host_pool
        self._lock = threading.RLock()
        # block 0 reserved as the null/padding block
        self._free = deque(range(1, num_blocks))  # guarded-by: _lock
        self._tables = {}                         # guarded-by: _lock
        self._lens = {}                           # guarded-by: _lock
        self._retained = OrderedDict()            # guarded-by: _lock
        # block id -> live table references (entries removed at 0)
        self._refs = {}                           # guarded-by: _lock
        # content-addressed radix index: key -> published block id
        self._index = {}                          # guarded-by: _lock
        self._key_of = {}                         # guarded-by: _lock
        self._parent = {}                         # guarded-by: _lock
        # key -> number of cached (published) children; leaf == absent
        self._children = {}                       # guarded-by: _lock
        # refcount-0 published blocks, reusable AND evictable (LRU)
        self._lru = OrderedDict()                 # guarded-by: _lock
        # per-request published chain of block keys (prefix order)
        self._chain = {}                          # guarded-by: _lock
        # reclaim EVENTS, not blocks: one legacy retained SET (however
        # many blocks it held) or one published leaf block each count
        # 1 — trend block-granular cache pressure via prefix_evictions
        self.evictions = 0                        # guarded-by: _lock
        self.prefix_hits = 0                      # guarded-by: _lock
        self.prefix_misses = 0                    # guarded-by: _lock
        # the subset of prefix_hits that resurrected >= 1 refcount-0
        # block parked in the prefix LRU (vs hits that only shared
        # blocks another live table already pinned) — what separates
        # "the park saved us" from "concurrency saved us" in the
        # cache-route bench
        self.prefix_resurrections = 0             # guarded-by: _lock
        self.prefix_tokens_saved = 0              # guarded-by: _lock
        self.prefix_evictions = 0                 # guarded-by: _lock
        # tokens whose cached K/V a prefix eviction threw away FOR GOOD
        # (not parked in the host tier) — the recompute debt the
        # offload tier exists to drive down; the host pool adds its own
        # final-discard count on top in prefix_stats()
        self.prefix_discarded_tokens = 0          # guarded-by: _lock
        self.host_hits = 0                        # guarded-by: _lock
        self.host_restored_tokens = 0             # guarded-by: _lock
        # device→host extraction for offload, registered by the cache
        # owner (the engine) via set_offload_source; None = every
        # eviction discards even with a pool attached
        self._offload_fetch = None                # guarded-by: _lock
        # (block, host arrays) pairs awaiting the engine's host→device
        # restore dispatch — drained via take_pending_restores() before
        # the first program that reads the blocks
        self._pending_restores = []               # guarded-by: _lock
        # rid -> tokens of its table restored from the host tier (the
        # admission trace / statusz in-flight split of cached_tokens)
        self._host_tokens = {}                    # guarded-by: _lock
        self._m_hits = telemetry.counter(
            "mxtpu_serve_prefix_hits_total",
            "prefix-cache lookups that reused >= 1 cached block")
        self._m_misses = telemetry.counter(
            "mxtpu_serve_prefix_misses_total",
            "prefix-cache lookups that reused nothing")
        self._m_saved = telemetry.counter(
            "mxtpu_serve_prefix_tokens_saved_total",
            "prompt tokens whose prefill was skipped via the prefix cache")
        self._m_discarded = telemetry.counter(
            "mxtpu_serve_prefix_discarded_tokens_total",
            "tokens whose cached K/V an eviction threw away for good")
        self._m_restored = telemetry.counter(
            "mxtpu_serve_host_kv_restored_tokens_total",
            "prompt tokens restored host->device instead of recomputed")
        self._m_resurrections = telemetry.counter(
            "mxtpu_serve_prefix_resurrections_total",
            "prefix hits that revived >= 1 block parked refcount-0 "
            "in the prefix LRU")
        # the routable-cache advertisement (None with the prefix cache
        # off — nothing content-addressed to advertise); maintained
        # incrementally at every publish/unpublish site in BOTH tiers
        self._summary = (RadixSummary(block_size)
                         if self.prefix_cache else None)
        if self._summary is not None and host_pool is not None:
            host_pool.set_listener(self._summary.add,
                                   self._summary.remove)
            for key in host_pool.keys():
                self._summary.add(key)

    def set_offload_source(self, fetch):
        """Register the device→host block extractor the eviction path
        calls to park a reclaimed block in the host tier (``fetch(blk)
        -> tuple of host arrays``, or None to skip offload)."""
        with self._lock:
            self._offload_fetch = fetch

    # -- capacity ------------------------------------------------------------
    @property
    def total_blocks(self):
        """Allocatable blocks (the null block excluded)."""
        return self.num_blocks - 1

    @property
    def blocks_in_use(self):
        """Distinct physical blocks referenced by at least one table
        (a block shared by N requests counts ONCE — it occupies one
        physical block, whatever its refcount)."""
        with self._lock:
            return len(self._refs)

    @property
    def free_blocks(self):
        """Immediately or lazily reclaimable blocks."""
        with self._lock:
            return (len(self._free) + len(self._lru)
                    + sum(len(b) for b in self._retained.values()))

    @property
    def retained_blocks(self):
        """Blocks parked refcount-0 (reclaimable; published ones hold
        reusable K/V in the prefix LRU, unpublished ones are the legacy
        per-request retained tier)."""
        with self._lock:
            return (len(self._lru)
                    + sum(len(b) for b in self._retained.values()))

    def utilization(self):
        return self.blocks_in_use / max(1, self.total_blocks)

    def occupancy(self):
        """One JSON-ready snapshot of the block accounting — the
        /statusz and flight-dump occupancy section.  Counts are BLOCK
        counts and identical at every tensor-parallel degree; byte
        translation per chip lives with the cache owner
        (``Engine.kv_cache_stats``), which knows the sharding.  Taken
        under the lock: a /statusz scrape must see one consistent
        snapshot, not a dict resizing under its iteration."""
        with self._lock:
            return {"in_use": self.blocks_in_use,
                    "retained": self.retained_blocks,
                    "free": len(self._free),
                    "total": self.total_blocks,
                    "utilization": round(self.utilization(), 4),
                    "evictions": self.evictions,
                    "prefix_cache": self.prefix_stats()}

    def prefix_stats(self):
        """The prefix-cache section of ``occupancy()``/``/statusz``:
        how much of the radix index is populated, shared and reusable,
        and the hit/miss/evict counters that explain a cache-cold
        replica."""
        with self._lock:
            looked = self.prefix_hits + self.prefix_misses
            shared = sum(1 for r in self._refs.values() if r > 1)
            discarded = self.prefix_discarded_tokens
            if self.host is not None:
                # the host tier's own LRU evictions are the FINAL
                # discard — the two sites together are every token
                # whose cached K/V is gone for good
                discarded += self.host.discarded_tokens
            return {"enabled": self.prefix_cache,
                    "cached_blocks": len(self._index),
                    "reusable_blocks": len(self._lru),
                    "shared_blocks": shared,
                    "max_refcount": max(self._refs.values(), default=0),
                    "hits": self.prefix_hits,
                    "misses": self.prefix_misses,
                    "resurrections": self.prefix_resurrections,
                    "hit_rate": (round(self.prefix_hits / looked, 4)
                                 if looked else None),
                    "tokens_saved": self.prefix_tokens_saved,
                    "evictions": self.prefix_evictions,
                    "discarded_tokens": discarded,
                    "host_hits": self.host_hits,
                    "host_restored_tokens": self.host_restored_tokens}

    def host_stats(self):
        """The host-tier occupancy snapshot (None without a pool)."""
        with self._lock:
            return None if self.host is None else self.host.stats()

    def summary(self):
        """The JSON-ready ``RadixSummary`` advertisement the replica
        publishes on ``/healthz``/``/statusz`` (None with the prefix
        cache off).  O(m/8) bit-packing, never a tree walk — safe on
        the scrape path at any cache size."""
        if self._summary is None:
            return None
        return self._summary.snapshot()

    def host_tokens(self, rid):
        """Tokens of ``rid``'s current table that were restored from
        the host tier rather than recomputed (0 for everyone else)."""
        with self._lock:
            return self._host_tokens.get(rid, 0)

    def take_pending_restores(self):
        """Atomically drain the queued (block, host arrays) restores —
        the engine dispatches the host→device copies before the first
        program that reads the blocks, so the step loop never blocks on
        a copy and the restored spans are in place by construction."""
        with self._lock:
            out, self._pending_restores = self._pending_restores, []
            return out

    def can_allocate(self, n_tokens, token_ids=None):
        """Whether ``allocate(n_tokens, token_ids=...)`` would succeed
        right now: blocks a prefix walk would reuse don't need to come
        off the free list.  Host-tier hits are counted on the TOKEN
        side only — a restored span still claims a fresh device block
        (the capacity math must never mistake DRAM bytes for HBM
        blocks), which ``prefix_probe``'s split encodes."""
        need = blocks_for(n_tokens, self.block_size)
        if token_ids is not None:
            cached_blocks, _ = self.prefix_probe(token_ids)
            need -= cached_blocks
        return need <= self.free_blocks

    def fits_at_all(self, n_tokens):
        """Whether a request of ``n_tokens`` could EVER hold the cache
        alone — the admission-time rejection test (back-pressure
        instead of a guaranteed later OOM)."""
        return blocks_for(n_tokens, self.block_size) <= self.total_blocks

    # -- prefix lookup -------------------------------------------------------
    def _walk(self, token_ids, salt=None):
        """Longest cached prefix of ``token_ids`` at block granularity
        (called under ``_lock``): returns the matched device
        ``[(key, block)]`` chain plus the ``[key]`` continuation the
        HOST tier holds past the device break (empty without a pool).
        Copy-on-write capped so at least ONE token is left for the
        engine to recompute (a fully-cached prompt still needs its last
        position's logits, and the recompute must never scribble into
        the shared final block) — host hits shed first: they are the
        deeper end of the chain.  ``salt`` scopes the chain (see
        :func:`salted_root`): an adapter request can only ever hit
        same-adapter K/V."""
        n = len(token_ids)
        bs = self.block_size
        hits = []
        parent = salted_root(salt)
        while (len(hits) + 1) * bs <= n:
            b = len(hits)
            key = _block_key(parent, token_ids[b * bs:(b + 1) * bs])
            blk = self._index.get(key)
            if blk is None:
                break
            hits.append((key, blk))
            parent = key
        host = []
        if self.host is not None:
            while (len(hits) + len(host) + 1) * bs <= n:
                b = len(hits) + len(host)
                key = _block_key(parent, token_ids[b * bs:(b + 1) * bs])
                if not self.host.has(key):
                    break
                host.append(key)
                parent = key
        while (len(hits) + len(host)) * bs > n - 1:
            (host or hits).pop()       # COW: recompute the final span
        return hits, host

    def prefix_probe(self, token_ids, salt=None):
        """(cached_blocks, cached_tokens) an ``allocate`` with these
        ``token_ids`` would reuse — admission-time capacity math, no
        state mutated.  ``cached_blocks`` counts only DEVICE hits (the
        blocks that need not come off the free list: a host-tier hit
        restores into a fresh device block); ``cached_tokens`` is the
        full prefill span skipped, device and host together."""
        with self._lock:
            if not self.prefix_cache or token_ids is None:
                return 0, 0
            hits, host = self._walk(token_ids, salt=salt)
            return len(hits), (len(hits) + len(host)) * self.block_size

    # -- prefill/decode handoff ----------------------------------------------
    def export_blocks(self, rid, token_ids, salt=None):
        """Serialize ``rid``'s cached prefix chain for ``token_ids``
        (its prompt) as wire records — the prefill side of a
        disaggregated prefill→decode handoff.

        Returns ``[(key, parent_key, block_token_ids, arrays), ...]``
        in prefix order: ``key``/``parent_key`` are the content-
        addressed radix keys (``parent_key`` None for the root block),
        ``arrays`` the block's host copies in the offload-tier layout
        (K, V[, int8 scale pairs]).  Derivation is purely content-
        addressed — the chain is re-walked from the token ids, so the
        export works both while ``rid`` is live and right after it
        finished (its published blocks park refcount-0 with K/V
        intact).  Device-resident blocks gather D2H through the
        registered offload fetch; already-parked blocks are peeked
        from the host pool without claiming.  A block missing from
        both tiers (evicted under pressure) ends the chain — the
        importer recomputes the rest, never a gap."""
        with self._lock:
            if not self.prefix_cache or self._offload_fetch is None:
                return []
            bs = self.block_size
            n = len(token_ids)
            out = []
            parent = salted_root(salt)
            parent_key = None
            while (len(out) + 1) * bs <= n:
                b = len(out)
                tok = [int(t) for t in token_ids[b * bs:(b + 1) * bs]]
                key = _block_key(parent, tok)
                blk = self._index.get(key)
                arrays = None
                if blk is not None:
                    arrays = self._offload_fetch(blk)
                elif self.host is not None:
                    arrays = self.host.peek(key)
                if arrays is None:
                    break
                out.append((key, parent_key, tok, tuple(arrays)))
                parent_key = key
                parent = key
            return out

    def import_blocks(self, records, salt=None):
        """Ingest handoff records into the host tier under their
        content keys — the decode side of a prefill→decode handoff.

        ``records`` is ``export_blocks``'s shape, in prefix order;
        ``arrays`` may be None for a block the sender's dedup probe
        found already hosted here (bytes skipped on the wire).  Every
        record is VERIFIED against the chain hash before it parks: a
        key that doesn't equal ``H(parent, token_ids)``, a record out
        of chain order, or a missing/undersized payload breaks the
        chain right there (content addressing is the integrity check —
        a truncated or corrupted handoff degrades to recompute, it can
        never poison the radix index).  Returns ``(imported, deduped,
        rejected)`` block counts; imported blocks are radix-walk hits
        from the very next ``allocate``, restored HBM-ward by the
        existing async restore path."""
        imported = deduped = 0
        with self._lock:
            expect_parent = None
            parent = salted_root(salt)
            for key, parent_key, token_ids, arrays in records:
                if (parent_key != expect_parent
                        or len(token_ids) != self.block_size
                        or _block_key(parent, token_ids) != key):
                    break
                if key in self._index or (self.host is not None
                                          and self.host.has(key)):
                    deduped += 1
                elif (arrays is None or self.host is None
                        or not self.host.put(key, parent_key,
                                             tuple(arrays))):
                    break
                else:
                    imported += 1
                expect_parent = key
                parent = key
        return imported, deduped, len(records) - imported - deduped

    def has_blocks(self, keys):
        """The subset of ``keys`` cached in EITHER tier right now —
        the handoff dedup probe (a sender skips the bytes of blocks
        the receiver already holds; a probe-then-evict race just means
        the chain breaks at import and the tail recomputes)."""
        with self._lock:
            return [k for k in keys
                    if k in self._index
                    or (self.host is not None and self.host.has(k))]

    # -- allocation ----------------------------------------------------------
    def _take(self, n):
        """Pop n free blocks, evicting refcount-0 parked blocks as
        needed: legacy retained sets first (their K/V is stale by
        construction), then prefix-LRU radix LEAVES oldest-first (an
        interior block never leaves before its cached children)."""
        with self._lock:
            while len(self._free) < n:
                if self._retained:
                    _, blocks = self._retained.popitem(last=False)  # oldest
                    self._free.extend(blocks)
                    self.evictions += 1
                    continue
                if not self._evict_prefix_leaf():
                    raise NoFreeBlocks(
                        f"need {n} blocks, {len(self._free)} free and "
                        "nothing refcount-0 left to evict")
            taken = [self._free.popleft() for _ in range(n)]
            for blk in taken:
                self._refs[blk] = 1
            return taken

    def _evict_prefix_leaf(self):
        """Reclaim the oldest refcount-0 published block that is a
        radix leaf (no cached children).  With a host pool attached the
        block's K/V parks device→host under its existing content key
        before the device block is reused; otherwise (or when the pool
        rejects it) the K/V is gone for good and ``discarded_tokens``
        counts the loss.  Reentrant-locked: every caller already holds
        ``_lock``."""
        with self._lock:
            for key in self._lru:       # oldest first
                if self._children.get(key, 0) == 0:
                    blk = self._index[key]
                    parked = False
                    if (self.host is not None
                            and self._offload_fetch is not None):
                        arrays = self._offload_fetch(blk)
                        if arrays is not None:
                            parked = self.host.put(
                                key, self._parent.get(key), arrays)
                    if not parked:
                        self.prefix_discarded_tokens += self.block_size
                        self._m_discarded.inc(self.block_size)
                    self._unpublish(key)
                    self._free.append(blk)
                    self.evictions += 1
                    self.prefix_evictions += 1
                    return True
            return False

    def _unpublish(self, key):
        """Drop ``key`` from the radix index; returns its physical
        block.  Reentrant-locked: every caller already holds ``_lock``."""
        with self._lock:
            blk = self._index.pop(key)
            self._key_of.pop(blk, None)
            parent = self._parent.pop(key, None)
            if parent is not None and parent in self._children:
                self._children[parent] -= 1
                if not self._children[parent]:
                    del self._children[parent]
            self._children.pop(key, None)
            self._lru.pop(key, None)
            if self._summary is not None:
                self._summary.remove(key)
            return blk

    def _ref_hit(self, blk):
        """Take one reference on a cached block: a refcount-0 LRU
        resident leaves the evictable tier the moment a table starts
        reading it.  Returns whether the block was actually parked in
        the LRU (a RESURRECTION, as opposed to sharing a block another
        live table already pins).  Reentrant-locked: callers already
        hold ``_lock``."""
        with self._lock:
            self._refs[blk] = self._refs.get(blk, 0) + 1
            if self._refs[blk] == 1:
                return self._lru.pop(self._key_of[blk], None) is not None
            return False

    def allocate(self, rid, n_tokens, token_ids=None, salt=None):
        """Create ``rid``'s block table covering ``n_tokens`` slots.

        Without ``token_ids`` (legacy callers): fresh blocks only,
        returns the table list.  With ``token_ids`` (the sequence the
        engine is about to prefill): the longest cached prefix is
        reused — hit blocks head the table with their refcounts
        incremented, only the remainder comes off the free list — and
        the return is ``(table, cached_tokens)`` so the caller prefills
        just the suffix."""
        with self._lock:
            if rid in self._tables:
                raise ValueError(
                    f"request {rid!r} already has a block table")
            if rid in self._retained:
                # a preempted request resuming: its parked UNPUBLISHED
                # blocks hold stale K/V (resume recomputes), so reclaim
                # them up front rather than leaking the entry when this
                # rid is freed again later (its published blocks live
                # in the prefix index and may be hit again right here)
                self._free.extend(self._retained.pop(rid))
            hits, host_keys = [], []
            if self.prefix_cache and token_ids is not None:
                hits, host_keys = self._walk(token_ids, salt=salt)
            # clear-miss precheck BEFORE any mutation or eviction (the
            # same optimistic math as can_allocate, one walk instead of
            # two): a request that cannot fit even by reclaiming every
            # parked block must not evict anything, count a hit, or
            # take references on the way to failing.  Host hits never
            # discount the block need — a restored span still claims a
            # fresh device block
            if blocks_for(n_tokens, self.block_size) - len(hits) \
                    > self.free_blocks:
                raise NoFreeBlocks(
                    f"request {rid!r} needs "
                    f"{blocks_for(n_tokens, self.block_size)} blocks "
                    f"({len(hits)} cached), {self.free_blocks} "
                    "free/reclaimable")
            # claim host entries BEFORE _take: eviction inside _take
            # offloads more blocks, and the pool's own LRU churn could
            # otherwise evict the very entries this walk matched.  A
            # claim that degrades (chaos restore-delay past the budget)
            # truncates the restored span — the rest recomputes
            claimed = []
            parent_key = hits[-1][0] if hits else None
            for key in host_keys:
                arrays = self.host.claim(key)
                if arrays is None:
                    break
                claimed.append((key, parent_key, arrays))
                parent_key = key
            if self.prefix_cache and token_ids is not None:
                if hits or claimed:
                    saved = (len(hits) + len(claimed)) * self.block_size
                    self.prefix_hits += 1
                    self.prefix_tokens_saved += saved
                    self._m_hits.inc()
                    self._m_saved.inc(saved)
                else:
                    self.prefix_misses += 1
                    self._m_misses.inc()
                if claimed:
                    self.host_hits += 1
                    self.host_restored_tokens += \
                        len(claimed) * self.block_size
                    self._m_restored.inc(len(claimed) * self.block_size)
                resurrected = 0
                for _, blk in hits:
                    if self._ref_hit(blk):
                        resurrected += 1
                if resurrected:
                    self.prefix_resurrections += 1
                    self._m_resurrections.inc()
            n = blocks_for(n_tokens, self.block_size)
            try:
                fresh = self._take(n - len(hits))
            except NoFreeBlocks:
                # undo the hit references and re-park the claimed host
                # entries: a failed allocation must not leave cached
                # blocks pinned un-evictable or hosted K/V dropped
                for key, blk in hits:
                    self._deref(blk, retain=True)
                for key, parent, arrays in claimed:
                    self.host.unclaim(key, parent, arrays)
                raise
            # restored blocks publish immediately under their existing
            # content keys (they ARE the cached chain, back on device)
            # and queue their host→device copies for the engine to
            # dispatch before anything reads them
            for (key, parent, arrays), blk in zip(claimed, fresh):
                self._index[key] = blk
                self._key_of[blk] = key
                self._parent[key] = parent
                if parent is not None:
                    self._children[parent] = \
                        self._children.get(parent, 0) + 1
                if self._summary is not None:
                    self._summary.add(key)
                self._pending_restores.append((blk, arrays))
            self._tables[rid] = [blk for _, blk in hits] + fresh
            self._lens[rid] = n * self.block_size
            self._chain[rid] = ([key for key, _ in hits]
                                + [key for key, _, _ in claimed])
            if token_ids is not None:
                self._host_tokens[rid] = len(claimed) * self.block_size
                return (list(self._tables[rid]),
                        (len(hits) + len(claimed)) * self.block_size)
            return list(self._tables[rid])

    def ensure_capacity(self, rid, n_tokens):
        """Grow ``rid``'s table to cover ``n_tokens`` slots (decode
        appends).  Raises NoFreeBlocks when the cache is exhausted —
        the scheduler's preemption trigger."""
        with self._lock:
            table = self._tables[rid]
            need = blocks_for(n_tokens, self.block_size) - len(table)
            if need > 0:
                table.extend(self._take(need))
                self._lens[rid] = len(table) * self.block_size
            return list(table)

    def table(self, rid):
        with self._lock:
            return list(self._tables[rid])

    def capacity(self, rid):
        """Token slots currently reserved for ``rid``."""
        with self._lock:
            return self._lens[rid]

    def reclaimable_blocks(self, rid):
        """Blocks ``free(rid)`` would actually park/release right now —
        the refcount-1 subset of its table.  A request whose blocks are
        all shared with other live tables reclaims nothing, which is
        what makes preempting it pointless (``Scheduler._pick_victim``
        consults this)."""
        with self._lock:
            return sum(1 for b in self._tables.get(rid, ())
                       if self._refs.get(b, 0) == 1)

    def truncate(self, rid, n_tokens):
        """Shrink ``rid``'s table to cover just ``n_tokens`` slots,
        releasing the tail blocks — the speculative-decoding rollback
        (rejected draft tokens' K/V lives in over-reserved tail blocks
        that the accepted sequence no longer needs).

        Bounded and share-safe by construction: only blocks BEYOND
        ``blocks_for(n_tokens)`` are candidates, and a candidate whose
        refcount exceeds 1 (shared through the prefix cache with
        another live table) stops the walk — truncation can never free,
        or even decref, a block another request still reads.  A
        released tail block that was published (cannot happen for a
        purely speculative tail — only accepted tokens are ever noted —
        but guarded anyway) is unpublished before returning to the
        free list.  Returns the number of blocks released."""
        with self._lock:
            table = self._tables.get(rid)
            if table is None:
                return 0
            keep = max(1, blocks_for(max(1, int(n_tokens)),
                                     self.block_size))
            freed = 0
            while len(table) > keep:
                blk = table[-1]
                if self._refs.get(blk, 0) > 1:
                    break          # shared prefix block — never touch
                table.pop()
                released = self._deref(blk, retain=False)
                if released is not None:
                    self._free.append(released)
                freed += 1
            self._lens[rid] = len(table) * self.block_size
            chain = self._chain.get(rid)
            if chain is not None and len(chain) > len(table):
                # the published chain can never extend past the table
                del chain[len(table):]
            return freed

    # -- publishing ----------------------------------------------------------
    def note_tokens(self, rid, token_ids, salt=None):
        """Publish ``rid``'s newly-FULL blocks under their chain keys.

        ``token_ids`` is the sequence whose K/V has been written so far
        (prompt prefix during prefill, prompt+generated during decode);
        every full block not yet in ``rid``'s chain is keyed and
        indexed.  A key already mapping to a DIFFERENT physical block
        (two identical prompts prefilled concurrently) keeps the
        existing mapping — this request's duplicate block simply stays
        private.  No-op with the prefix cache off."""
        if not self.prefix_cache:
            return
        with self._lock:
            table = self._tables.get(rid)
            if table is None:
                return
            chain = self._chain.setdefault(rid, [])
            n_full = min(len(token_ids) // self.block_size, len(table))
            while len(chain) < n_full:
                b = len(chain)
                parent = chain[-1] if chain else salted_root(salt)
                key = _block_key(
                    parent,
                    token_ids[b * self.block_size:(b + 1) * self.block_size])
                blk = table[b]
                if key not in self._index and blk not in self._key_of:
                    self._index[key] = blk
                    self._key_of[blk] = key
                    self._parent[key] = (parent if chain else None)
                    if chain:
                        self._children[parent] = \
                            self._children.get(parent, 0) + 1
                    if self._summary is not None:
                        self._summary.add(key)
                chain.append(key)

    # -- release -------------------------------------------------------------
    def _drop_pending(self, blk):
        """``blk`` left every table before its queued host→device
        restore was dispatched (cannot happen through the engine — it
        drains restores in the same step as the allocate — but the
        public API allows it): the device block never received the
        K/V, so it must NOT stay published as resurrectable.  Re-park
        the host copies and unpublish.  Called under ``_lock``."""
        with self._lock:
            kept, dropped = [], []
            for b, a in self._pending_restores:
                (dropped if b == blk else kept).append((b, a))
            if not dropped:
                return
            self._pending_restores[:] = kept
            key = self._key_of.get(blk)
            if key is not None:
                parent = self._parent.get(key)
                self._unpublish(key)
                if self.host is not None:
                    self.host.unclaim(key, parent, dropped[0][1])

    def _deref(self, blk, retain):
        """Drop one reference; returns the block if it reached
        refcount 0 UNPUBLISHED (the caller decides the retained-vs-free
        fate), else None.  Reentrant-locked: callers hold ``_lock``."""
        with self._lock:
            self._refs[blk] -= 1
            if self._refs[blk] > 0:
                return None            # another table still reads it
            del self._refs[blk]
            if self._pending_restores:
                self._drop_pending(blk)
            key = self._key_of.get(blk)
            if key is not None:
                if retain:
                    self._lru[key] = blk   # reusable AND evictable
                    self._lru.move_to_end(key)
                else:
                    self._unpublish(key)
                    self._free.append(blk)
                return None
            return blk

    def free(self, rid, retain=True):
        """Release ``rid``'s references.  DECREF semantics: blocks
        shared with another live table are untouched (preempting a
        sharer can never free blocks a running request still reads).
        Refcount-0 published blocks park in the prefix LRU (K/V intact,
        future prefix hits resurrect them); refcount-0 unpublished
        blocks park in the legacy retained tier with ``retain=True`` or
        return to the free list with ``retain=False``."""
        with self._lock:
            blocks = self._tables.pop(rid)
            self._lens.pop(rid)
            self._chain.pop(rid, None)
            self._host_tokens.pop(rid, None)
            loose = []
            for blk in blocks:
                released = self._deref(blk, retain)
                if released is not None:
                    loose.append(released)
            if loose:
                if retain:
                    self._retained[rid] = loose
                else:
                    self._free.extend(loose)

    def reset(self):
        with self._lock:
            self._free = deque(range(1, self.num_blocks))
            self._tables.clear()
            self._lens.clear()
            self._retained.clear()
            self._refs.clear()
            self._index.clear()
            self._key_of.clear()
            self._parent.clear()
            self._children.clear()
            self._lru.clear()
            self._chain.clear()
            self._host_tokens.clear()
            # hosted entries stay: they are content-addressed, so their
            # K/V remains valid for the tokens they hash — but restores
            # queued against now-recycled device blocks must not land
            del self._pending_restores[:]
            # the advertisement rebuilds from the surviving host tier
            # (reset is rare and operator-driven — never the scrape
            # path, so the one-off pool walk is fine here)
            if self._summary is not None:
                self._summary.clear()
                if self.host is not None:
                    for key in self.host.keys():
                        self._summary.add(key)
