"""Paged KV-cache block accounting for the serving engine.

One fixed device-resident cache (allocated once by ``serve.Engine``)
is carved into ``num_blocks`` blocks of ``block_size`` token slots
each.  This module owns the HOST-side bookkeeping only: which physical
blocks belong to which request (the per-request *block table*), the
free list, and the LRU eviction tier — the device arrays never move.
``ops.attention.paged_attention`` consumes the tables to gather K/V.

Block id 0 is the permanent *null block*: it is never allocated, block
tables pad with it past a request's last real block, and padded scatter
positions write into it.  Its contents are garbage by design — every
consumer masks by context length before the softmax.

Lifecycle of a block set:

  allocate()  -> owned by a live request (counted in ``blocks_in_use``)
  free()      -> retained: the ids park in an LRU of finished/preempted
                 requests and still hold their K/V (a future
                 prefix-cache hit could resurrect them); they are
                 reclaimed lazily, oldest request first, only when the
                 free list runs dry
  evict       -> back on the free list, contents forgotten
"""

from __future__ import annotations

import threading
from collections import OrderedDict, deque

__all__ = ["BlockManager", "NoFreeBlocks"]


class NoFreeBlocks(Exception):
    """Raised when an allocation cannot be satisfied even after
    evicting every retained (finished/preempted) block set.  The
    scheduler catches this and preempts a running request instead of
    letting the cache OOM."""


def blocks_for(n_tokens, block_size):
    """Physical blocks needed to hold ``n_tokens`` cache slots."""
    return -(-n_tokens // block_size)


class BlockManager:
    """Host-side block accounting.  Mutations are serialized by the
    RLock below: the scheduler drives allocation from the engine's step
    thread while /statusz snapshots and admission checks may read from
    others (reads of the annotated structures are point-in-time
    snapshots; every write path is lock-wrapped and enforced by
    mxtpu-lint's unlocked-shared-state checker).  Reentrant because
    ``allocate``/``ensure_capacity`` call ``_take`` under the lock."""

    def __init__(self, num_blocks, block_size):
        if num_blocks < 2:
            raise ValueError("need >= 2 blocks (block 0 is the null block)")
        if block_size < 1:
            raise ValueError("block_size must be >= 1")
        self.num_blocks = num_blocks
        self.block_size = block_size
        self._lock = threading.RLock()
        # block 0 reserved as the null/padding block
        self._free = deque(range(1, num_blocks))  # guarded-by: _lock
        self._tables = {}                         # guarded-by: _lock
        self._lens = {}                           # guarded-by: _lock
        self._retained = OrderedDict()            # guarded-by: _lock
        self.evictions = 0                        # guarded-by: _lock

    # -- capacity ------------------------------------------------------------
    @property
    def total_blocks(self):
        """Allocatable blocks (the null block excluded)."""
        return self.num_blocks - 1

    @property
    def blocks_in_use(self):
        with self._lock:
            return sum(len(t) for t in self._tables.values())

    @property
    def free_blocks(self):
        """Immediately or lazily reclaimable blocks."""
        with self._lock:
            return (len(self._free)
                    + sum(len(b) for b in self._retained.values()))

    @property
    def retained_blocks(self):
        """Blocks parked in the LRU tier (reclaimable, K/V intact)."""
        with self._lock:
            return sum(len(b) for b in self._retained.values())

    def utilization(self):
        return self.blocks_in_use / max(1, self.total_blocks)

    def occupancy(self):
        """One JSON-ready snapshot of the block accounting — the
        /statusz and flight-dump occupancy section.  Counts are BLOCK
        counts and identical at every tensor-parallel degree; byte
        translation per chip lives with the cache owner
        (``Engine.kv_cache_stats``), which knows the sharding.  Taken
        under the lock: a /statusz scrape must see one consistent
        snapshot, not a dict resizing under its iteration."""
        with self._lock:
            return {"in_use": self.blocks_in_use,
                    "retained": self.retained_blocks,
                    "free": len(self._free),
                    "total": self.total_blocks,
                    "utilization": round(self.utilization(), 4),
                    "evictions": self.evictions}

    def can_allocate(self, n_tokens):
        return blocks_for(n_tokens, self.block_size) <= self.free_blocks

    def fits_at_all(self, n_tokens):
        """Whether a request of ``n_tokens`` could EVER hold the cache
        alone — the admission-time rejection test (back-pressure
        instead of a guaranteed later OOM)."""
        return blocks_for(n_tokens, self.block_size) <= self.total_blocks

    # -- allocation ----------------------------------------------------------
    def _take(self, n):
        """Pop n free blocks, evicting LRU retained sets as needed."""
        with self._lock:
            while len(self._free) < n:
                if not self._retained:
                    raise NoFreeBlocks(
                        f"need {n} blocks, {len(self._free)} free and "
                        "nothing retained to evict")
                _, blocks = self._retained.popitem(last=False)  # oldest
                self._free.extend(blocks)
                self.evictions += 1
            return [self._free.popleft() for _ in range(n)]

    def allocate(self, rid, n_tokens):
        """Create ``rid``'s block table covering ``n_tokens`` slots."""
        with self._lock:
            if rid in self._tables:
                raise ValueError(
                    f"request {rid!r} already has a block table")
            if rid in self._retained:
                # a preempted request resuming: its parked blocks hold
                # stale K/V (resume recomputes), so reclaim them up
                # front rather than leaking the entry when this rid is
                # freed again later
                self._free.extend(self._retained.pop(rid))
            n = blocks_for(n_tokens, self.block_size)
            self._tables[rid] = self._take(n)
            self._lens[rid] = n * self.block_size
            return list(self._tables[rid])

    def ensure_capacity(self, rid, n_tokens):
        """Grow ``rid``'s table to cover ``n_tokens`` slots (decode
        appends).  Raises NoFreeBlocks when the cache is exhausted —
        the scheduler's preemption trigger."""
        with self._lock:
            table = self._tables[rid]
            need = blocks_for(n_tokens, self.block_size) - len(table)
            if need > 0:
                table.extend(self._take(need))
                self._lens[rid] = len(table) * self.block_size
            return list(table)

    def table(self, rid):
        with self._lock:
            return list(self._tables[rid])

    def capacity(self, rid):
        """Token slots currently reserved for ``rid``."""
        with self._lock:
            return self._lens[rid]

    def free(self, rid, retain=True):
        """Release ``rid``'s blocks.  ``retain=True`` (finished or
        preempted requests) parks them in the LRU tier; ``retain=False``
        returns them to the free list immediately."""
        with self._lock:
            blocks = self._tables.pop(rid)
            self._lens.pop(rid)
            if retain:
                self._retained[rid] = blocks
            else:
                self._free.extend(blocks)

    def reset(self):
        with self._lock:
            self._free = deque(range(1, self.num_blocks))
            self._tables.clear()
            self._lens.clear()
            self._retained.clear()
