"""Serving observability: counters + the ``ServeStats`` snapshot.

The engine owns one ``StatsRecorder`` and stamps it from the serving
loop; ``snapshot()`` freezes the current view into an immutable
``ServeStats`` for dashboards, ``tools/serve_bench.py``'s JSON record,
and the periodic ``mxnet_tpu.monitor.ServeMonitor`` log line (the
serving-side analog of ``Speedometer``'s samples/sec callback).

Tokens/sec is reported two ways: ``decode_tok_per_sec`` over a sliding
window of recent steps (the live rate a dashboard wants) and
``total_tok_per_sec`` over the engine's whole life (the benchmark
aggregate).
"""

from __future__ import annotations

import random
import time
from collections import deque
from dataclasses import asdict, dataclass, field

from .. import telemetry

__all__ = ["ServeStats", "StatsRecorder", "Reservoir"]


class Reservoir:
    """Bounded uniform sample of a stream (Vitter's algorithm R) with
    EXACT running count/sum/max — so means and maxima never degrade
    while the percentile view stays O(capacity) memory however long
    the engine serves.  Seeded RNG: two engines fed identical streams
    report identical percentiles (deterministic tests).

    Not locked: every writer is the engine step thread (the same
    single-writer discipline as the rest of StatsRecorder); snapshot
    readers copy under the GIL."""

    __slots__ = ("capacity", "_sample", "_rng", "count", "sum", "max")

    def __init__(self, capacity=2048, seed=0):
        self.capacity = max(1, int(capacity))
        self._sample = []
        self._rng = random.Random(seed)
        self.count = 0
        self.sum = 0.0
        self.max = None

    def add(self, value):
        value = float(value)
        self.count += 1
        self.sum += value
        if self.max is None or value > self.max:
            self.max = value
        if len(self._sample) < self.capacity:
            self._sample.append(value)
        else:
            j = self._rng.randrange(self.count)
            if j < self.capacity:
                self._sample[j] = value

    @property
    def mean(self):
        return self.sum / self.count if self.count else None

    def percentile(self, q):
        """Nearest-rank percentile of the retained sample (exact until
        ``count`` exceeds ``capacity``, a uniform estimate after)."""
        from ..telemetry.timeseries import nearest_rank

        return nearest_rank(sorted(self._sample), q)


@dataclass(frozen=True)
class ServeStats:
    """One immutable snapshot of the serving engine."""
    steps: int
    queue_depth: int
    running: int
    completed: int
    rejected: int
    preemptions: int
    evictions: int
    tokens_generated: int
    prompt_tokens: int
    blocks_in_use: int
    blocks_total: int
    block_utilization: float           # right now
    peak_block_utilization: float      # high-water mark across steps
    ttft_ms_mean: float | None
    ttft_ms_max: float | None
    decode_tok_per_sec: float | None   # sliding window over recent steps
    total_tok_per_sec: float | None    # engine lifetime aggregate
    # prefix-cache view (BlockManager.prefix_stats): prompt tokens the
    # engine actually ran prefill compute over vs tokens whose K/V was
    # reused from the content-addressed radix cache — the shared-prefix
    # workload's headline ratio (tools/serve_bench.py --workload
    # shared-prefix)
    prefill_tokens_computed: int = 0
    prefix_hits: int = 0
    prefix_misses: int = 0
    # hits whose first reused block was sitting on the evictable LRU
    # (refcount 0) — reuse that only exists because eviction had not
    # reached it yet; split out from plain hits so cache-route benches
    # can tell "still referenced" from "brought back from the brink"
    prefix_resurrections: int = 0
    prefix_hit_rate: float | None = None
    prefix_tokens_saved: int = 0
    prefix_evictions: int = 0
    # tokens whose cached K/V eviction threw away for good (device
    # discards plus the host tier's own final evictions) — the
    # recompute debt the DRAM offload tier exists to drive down
    prefix_discarded_tokens: int = 0
    # host-DRAM offload tier (BlockManager.host / HostKVPool): lookups
    # that restored at least one parked block, the restored token
    # total, and the pool's live occupancy.  All zero with the tier
    # off (MXTPU_SERVE_HOST_KV_BYTES=0).
    host_kv_hits: int = 0
    host_kv_restored_tokens: int = 0
    host_kv_offloads: int = 0
    host_kv_evictions: int = 0
    host_kv_degraded: int = 0
    # pool inserts rejected for size (offloads AND handoff imports —
    # a decode-role replica whose pool rejects ingests re-pays the
    # prefill compute the handoff was meant to ship)
    host_kv_rejects: int = 0
    host_kv_bytes_used: int = 0
    host_kv_entries: int = 0
    # speculative decoding (serve/spec.py): draft-proposed tokens and
    # the target's accept/reject split, plus the per-verify mean run
    # length and lifetime acceptance rate.  Zero/None with spec off.
    # tokens_generated and the tok/s rates above are fed from ACTUAL
    # emitted-token counts per iteration, so they stay correct when a
    # verify step emits up to k+1 tokens per request.
    spec_drafted_tokens: int = 0
    spec_accepted_tokens: int = 0
    spec_rejected_tokens: int = 0
    spec_verifies: int = 0
    accepted_per_verify: float | None = None
    spec_accept_rate: float | None = None
    # the greedy-vs-stochastic acceptance split: rejection-sampled
    # (temperature>0) verifies accept by min(1, p/q) while greedy ones
    # accept by exact argmax match, and a draft can diverge on one
    # class of traffic while looking healthy on the other.  Stochastic
    # raw counts ride along (greedy = total - stochastic).
    spec_drafted_tokens_stochastic: int = 0
    spec_accepted_tokens_stochastic: int = 0
    spec_accept_rate_greedy: float | None = None
    spec_accept_rate_stochastic: float | None = None
    # tail latency (bounded-reservoir percentiles — the SLO inputs):
    # TTFT is submit -> first token; TPOT (time-per-output-token /
    # inter-token latency) is the gap between consecutive token
    # emissions for one request, divided by the tokens the step
    # emitted (so a speculative verify's k+1-token step contributes
    # k+1 honest per-token observations, not one giant gap)
    ttft_ms_p50: float | None = None
    ttft_ms_p90: float | None = None
    ttft_ms_p99: float | None = None
    tpot_ms_mean: float | None = None
    tpot_ms_p50: float | None = None
    tpot_ms_p90: float | None = None
    tpot_ms_p99: float | None = None
    # mean decode-batch occupancy over the recent-step window (decode
    # slots scheduled / max_batch) — slot-based, so it stays honest
    # whatever the per-slot token yield is
    decode_occupancy: float | None = None
    # cumulative rejections by reason code (queue_full / deadline /
    # deadline_at_submit / tenant_share / exceeds_cache /
    # exceeds_max_len) — the same codes the request trace and
    # mxtpu_serve_rejections_total{reason} carry
    reject_reasons: dict = field(default_factory=dict)
    # per-tenant admission/outcome/latency table
    # (Scheduler.tenant_stats) — empty until requests carry tenants
    tenants: dict = field(default_factory=dict)
    # per-adapter goodput ({adapter_id: {completed, tokens}}) — empty
    # until requests carry adapter ids (the fleet catalog's per-model
    # traffic ground truth)
    adapters: dict = field(default_factory=dict)

    def as_dict(self):
        return asdict(self)


def _pct_ms(res, q):
    v = res.percentile(q)
    return None if v is None else round(v * 1e3, 3)


class StatsRecorder:
    def __init__(self, clock=time.monotonic, window_steps=64):
        self.clock = clock
        self.steps = 0
        self.completed = 0
        self.rejected = 0
        self.tokens_generated = 0
        self.prompt_tokens = 0
        self.prefill_tokens_computed = 0
        # bounded tail-latency reservoirs (mean/max stay exact): the
        # unbounded per-request TTFT list a long-lived replica would
        # otherwise grow is exactly what these replace
        self._ttft_res = Reservoir()
        self._tpot_res = Reservoir(seed=1)
        self._start_t = None
        self.peak_block_utilization = 0.0
        # (t, tokens_emitted) per step for the sliding-window rate
        self._window = deque(maxlen=window_steps)
        # telemetry bridge: every recorder event ALSO feeds the
        # process-wide registry, so ServeStats and the Prometheus
        # exposition agree by construction (no-op objects when
        # MXTPU_TELEMETRY is unset)
        self._m_steps = telemetry.counter(
            "mxtpu_serve_steps_total", "engine scheduler iterations")
        self._m_tokens = telemetry.counter(
            "mxtpu_serve_tokens_generated_total", "decode tokens emitted")
        self._m_completed = telemetry.counter(
            "mxtpu_serve_completed_total", "requests finished")
        self._m_prompt_tokens = telemetry.counter(
            "mxtpu_serve_prompt_tokens_total",
            "prompt tokens of completed requests")
        self._m_rejected = telemetry.counter(
            "mxtpu_serve_backpressure_rejects_total",
            "submits rejected by admission-queue back-pressure")
        self._m_ttft = telemetry.histogram(
            "mxtpu_serve_ttft_seconds", "time to first token")
        self._m_tpot = telemetry.histogram(
            "mxtpu_serve_tpot_seconds",
            "inter-token latency (per emitted token)")
        self._m_prefill_tokens = telemetry.counter(
            "mxtpu_serve_prefill_tokens_computed_total",
            "prompt tokens actually run through a prefill program "
            "(prefix-cache hits never reach here)")
        # speculative decoding: the draft/accept/reject token split —
        # agrees with ServeStats.spec_* by construction (one feed)
        self.spec_drafted_tokens = 0
        self.spec_accepted_tokens = 0
        self.spec_rejected_tokens = 0
        self.spec_verifies = 0
        # the greedy-vs-stochastic split (rejection-sampled verifies
        # vs exact argmax ones) — same single feed as the totals
        self.spec_drafted_tokens_stochastic = 0
        self.spec_accepted_tokens_stochastic = 0
        self._m_spec_mode_drafted = telemetry.counter(
            "mxtpu_serve_spec_mode_drafted_tokens_total",
            "draft-model tokens proposed, split by sampling mode",
            ("mode",))
        self._m_spec_mode_accepted = telemetry.counter(
            "mxtpu_serve_spec_mode_accepted_tokens_total",
            "accepted drafted tokens, split by sampling mode",
            ("mode",))
        self._m_spec_drafted = telemetry.counter(
            "mxtpu_serve_spec_drafted_tokens_total",
            "draft-model tokens proposed to the verify program")
        self._m_spec_accepted = telemetry.counter(
            "mxtpu_serve_spec_accepted_tokens_total",
            "drafted tokens the target model accepted")
        self._m_spec_rejected = telemetry.counter(
            "mxtpu_serve_spec_rejected_tokens_total",
            "drafted tokens the target model rejected")
        # per-adapter goodput: rows appear only for requests that
        # carried an adapter id, so adapter-less serving keeps the
        # historical snapshot/registry shape
        self.adapters = {}
        self._m_adapter_completed = telemetry.counter(
            "mxtpu_serve_adapter_completed_total",
            "completed requests by LoRA adapter", ("adapter",))
        self._m_adapter_tokens = telemetry.counter(
            "mxtpu_serve_adapter_tokens_total",
            "decode tokens emitted by LoRA adapter", ("adapter",))

    def on_verify(self, drafted, accepted, stochastic=False):
        """One speculative verify pass: ``drafted`` tokens proposed,
        ``accepted`` of them kept (the +1 corrected/bonus token is
        counted by ``on_step``'s emitted total, not here).
        ``stochastic`` marks a rejection-sampled (temperature>0)
        verify — the per-mode split rides the same single feed."""
        drafted, accepted = int(drafted), int(accepted)
        self.spec_verifies += 1
        self.spec_drafted_tokens += drafted
        self.spec_accepted_tokens += accepted
        self.spec_rejected_tokens += drafted - accepted
        if stochastic:
            self.spec_drafted_tokens_stochastic += drafted
            self.spec_accepted_tokens_stochastic += accepted
        mode = "stochastic" if stochastic else "greedy"
        if drafted:
            self._m_spec_mode_drafted.labels(mode=mode).inc(drafted)
        if accepted:
            self._m_spec_mode_accepted.labels(mode=mode).inc(accepted)
        self._m_spec_drafted.inc(drafted)
        if accepted:
            self._m_spec_accepted.inc(accepted)
        if drafted - accepted:
            self._m_spec_rejected.inc(drafted - accepted)

    def spec_mode_rates(self):
        """(greedy, stochastic) acceptance rates — the ONE formula
        both ``snapshot()`` and the statusz ``spec`` section read, so
        the two views cannot drift (None with no drafted tokens in
        that mode)."""
        drafted_g = (self.spec_drafted_tokens
                     - self.spec_drafted_tokens_stochastic)
        accepted_g = (self.spec_accepted_tokens
                      - self.spec_accepted_tokens_stochastic)
        greedy = round(accepted_g / drafted_g, 4) if drafted_g else None
        stochastic = (
            round(self.spec_accepted_tokens_stochastic
                  / self.spec_drafted_tokens_stochastic, 4)
            if self.spec_drafted_tokens_stochastic else None)
        return greedy, stochastic

    def on_prefill(self, tokens_computed):
        """One prefill pass (whole prompt, suffix, or one chunk) ran
        compute over ``tokens_computed`` prompt tokens."""
        self.prefill_tokens_computed += int(tokens_computed)
        self._m_prefill_tokens.inc(int(tokens_computed))

    def on_step(self, new_tokens, decode_batch=0):
        """One engine iteration emitted ``new_tokens`` tokens (the
        ACTUAL count — a speculative verify step contributes up to
        ``k+1`` per request) with ``decode_batch`` decode slots
        scheduled."""
        now = self.clock()
        if self._start_t is None:
            self._start_t = now
        self.steps += 1
        self.tokens_generated += new_tokens
        self._window.append((now, new_tokens, int(decode_batch)))
        self._m_steps.inc()
        if new_tokens:
            self._m_tokens.inc(new_tokens)

    def on_utilization(self, frac):
        """Stamp the cache high-water mark (the engine samples right
        after scheduling, when this step's blocks are all held —
        sampling after a drain would always read ~0)."""
        if frac > self.peak_block_utilization:
            self.peak_block_utilization = frac

    def on_first_token(self, ttft_s):
        self._ttft_res.add(ttft_s)
        self._m_ttft.observe(ttft_s)

    def on_tokens(self, req, n, now=None):
        """``n`` decode tokens just landed on ``req``: record their
        per-token gap (TPOT) since the request's previous emission.
        The first token has no gap — it is the TTFT observation — so
        callers invoke this only from the second emission on (the
        engine stamps ``_last_token_t`` at the first)."""
        if n < 1:
            return
        now = self.clock() if now is None else now
        last = getattr(req, "_last_token_t", None)
        if last is None:
            last = req.first_token_t
        req._last_token_t = now
        if last is None:
            return
        gap = max(0.0, (now - last) / n)
        # the histogram is per EMITTED token, like the reservoir: a
        # k+1-token speculative verify contributes k+1 observations to
        # BOTH, or the registry-derived TPOT would diverge from the
        # ServeStats percentiles exactly when spec decoding is on
        for _ in range(n):
            self._tpot_res.add(gap)
            self._m_tpot.observe(gap)

    def on_complete(self, req):
        self.completed += 1
        self.prompt_tokens += int(req.prompt.size)
        self._m_completed.inc()
        self._m_prompt_tokens.inc(int(req.prompt.size))
        adapter = getattr(req, "adapter_id", None)
        if adapter is not None:
            row = self.adapters.setdefault(
                adapter, {"completed": 0, "tokens": 0})
            row["completed"] += 1
            row["tokens"] += len(req.tokens)
            self._m_adapter_completed.labels(adapter=adapter).inc()
            self._m_adapter_tokens.labels(adapter=adapter).inc(
                len(req.tokens))

    def on_reject(self):
        """Counts the Prometheus back-pressure series only.  The
        rejected TOTAL is owned by ``Scheduler.rejections`` (which
        counts queue-full at submit too), so ServeStats never
        double-counts and a bare Scheduler stays self-consistent."""
        self.rejected += 1
        self._m_rejected.inc()

    def _window_rate(self):
        if len(self._window) < 2:
            return None
        dt = self._window[-1][0] - self._window[0][0]
        if dt <= 0:
            return None
        # the first entry's tokens predate the window's time span
        toks = sum(n for _, n, _ in list(self._window)[1:])
        return toks / dt

    def _window_occupancy(self, max_batch):
        """Mean decode-slot occupancy over the recent-step window."""
        if not self._window or not max_batch:
            return None
        slots = sum(b for _, _, b in self._window)
        return slots / (len(self._window) * max_batch)

    def snapshot(self, scheduler, blocks):
        now = self.clock()
        rate_greedy, rate_stochastic = self.spec_mode_rates()
        pfx = blocks.prefix_stats()
        host = blocks.host_stats() or {}
        total_rate = None
        if self._start_t is not None and now > self._start_t:
            total_rate = self.tokens_generated / (now - self._start_t)
        ttft_mean = self._ttft_res.mean
        occupancy = self._window_occupancy(scheduler.max_batch)
        if occupancy is not None:
            occupancy = round(occupancy, 4)
        return ServeStats(
            steps=self.steps,
            queue_depth=scheduler.queue_depth,
            running=len(scheduler.running),
            completed=self.completed,
            rejected=scheduler.rejections,
            preemptions=scheduler.preemptions,
            evictions=blocks.evictions,
            tokens_generated=self.tokens_generated,
            prompt_tokens=self.prompt_tokens,
            blocks_in_use=blocks.blocks_in_use,
            blocks_total=blocks.total_blocks,
            block_utilization=round(blocks.utilization(), 4),
            peak_block_utilization=round(self.peak_block_utilization, 4),
            ttft_ms_mean=(round(ttft_mean * 1e3, 3)
                          if ttft_mean is not None else None),
            ttft_ms_max=(round(self._ttft_res.max * 1e3, 3)
                         if self._ttft_res.max is not None else None),
            ttft_ms_p50=_pct_ms(self._ttft_res, 0.50),
            ttft_ms_p90=_pct_ms(self._ttft_res, 0.90),
            ttft_ms_p99=_pct_ms(self._ttft_res, 0.99),
            tpot_ms_mean=(round(self._tpot_res.mean * 1e3, 3)
                          if self._tpot_res.mean is not None else None),
            tpot_ms_p50=_pct_ms(self._tpot_res, 0.50),
            tpot_ms_p90=_pct_ms(self._tpot_res, 0.90),
            tpot_ms_p99=_pct_ms(self._tpot_res, 0.99),
            decode_tok_per_sec=(round(self._window_rate(), 1)
                                if self._window_rate() else None),
            total_tok_per_sec=(round(total_rate, 1)
                               if total_rate else None),
            spec_drafted_tokens=self.spec_drafted_tokens,
            spec_accepted_tokens=self.spec_accepted_tokens,
            spec_rejected_tokens=self.spec_rejected_tokens,
            spec_verifies=self.spec_verifies,
            accepted_per_verify=(
                round(self.spec_accepted_tokens / self.spec_verifies, 4)
                if self.spec_verifies else None),
            spec_accept_rate=(
                round(self.spec_accepted_tokens
                      / self.spec_drafted_tokens, 4)
                if self.spec_drafted_tokens else None),
            spec_drafted_tokens_stochastic=(
                self.spec_drafted_tokens_stochastic),
            spec_accepted_tokens_stochastic=(
                self.spec_accepted_tokens_stochastic),
            spec_accept_rate_greedy=rate_greedy,
            spec_accept_rate_stochastic=rate_stochastic,
            decode_occupancy=occupancy,
            reject_reasons=dict(scheduler.reject_reasons),
            tenants=scheduler.tenant_stats(),
            adapters={a: dict(row) for a, row in self.adapters.items()},
            prefill_tokens_computed=self.prefill_tokens_computed,
            prefix_hits=pfx["hits"],
            prefix_misses=pfx["misses"],
            prefix_resurrections=pfx.get("resurrections", 0),
            prefix_hit_rate=pfx["hit_rate"],
            prefix_tokens_saved=pfx["tokens_saved"],
            prefix_evictions=pfx["evictions"],
            prefix_discarded_tokens=pfx["discarded_tokens"],
            host_kv_hits=pfx["host_hits"],
            host_kv_restored_tokens=pfx["host_restored_tokens"],
            host_kv_offloads=host.get("offloads", 0),
            host_kv_evictions=host.get("evictions", 0),
            host_kv_degraded=host.get("degraded", 0),
            host_kv_rejects=host.get("rejects", 0),
            host_kv_bytes_used=host.get("bytes_used", 0),
            host_kv_entries=host.get("entries", 0),
        )
