"""The public serving engine: continuous batching over a paged KV-cache.

``serve.Engine`` drives a gpt() checkpoint (the same parameter dicts
``models/generate.py`` decodes) as a multi-tenant service:

  eng = mx.serve.Engine(params, symbol=net, num_blocks=512)
  req = eng.submit(prompt_ids, max_new_tokens=64)   # may raise QueueFull
  for tok in eng.stream(req):
      ...
  eng.shutdown()

Each ``step()`` is one scheduler iteration: at most
``max_prefills_per_step`` prefills (one jit-compiled program per
prompt-length bucket) followed by ONE batched single-token decode over
every running request (one program per batch bucket).  A prefill skips
whatever block-aligned prefix the content-addressed KV cache already
holds (``MXTPU_SERVE_PREFIX_CACHE``) and runs only the suffix through
a third program family — the *chunk* program, which attends through
the block table to the cached positions; the same program prefills
long prompts one ``MXTPU_SERVE_PREFILL_CHUNK``-token chunk per
iteration, interleaved with decodes.  All shapes are padded to
power-of-two buckets and the block-table width is fixed at
``max_model_len / block_size``, so the number of distinct XLA programs
is bounded by O(log max_batch + log max_model_len) — no per-request
recompiles, the serving analog of ``BucketingModule``'s bucket trick.

The KV-cache is ONE device-resident array pair per engine,
(layers, num_blocks, block_size, kv_heads, head_dim), carved into
blocks by ``kv_block_manager.BlockManager``; decode attends through
``ops.attention.paged_attention``.  Cache-pressure policy lives in
``scheduler.Scheduler`` (preemption + back-pressure), never here —
the engine only executes the schedule it is handed.

With ``tp=N`` (env ``MXTPU_SERVE_TP``) the same programs run GSPMD-
partitioned over a ``{'tp': N}`` mesh: parameters shard per the
regex partition rules (``parallel.partition``, Megatron/TP layout —
two all-reduces per layer), the KV-cache shards on its head axis so
every chip holds ``kv_heads/N`` of every block, and the exported AOT
artifacts key on the sharding (tp degree + rule digest enter the
fingerprint).  Block accounting, scheduling and the public API are
identical at every tp.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
import traceback

import numpy as np

import jax
import jax.numpy as jnp

from .. import jax_compat, telemetry
from ..aot import export_store as aot_store
from ..aot import warmup as aot_warmup
from ..base import env_flag, env_int
from ..lint.annotations import hot_path
from ..models.generate import (_fc, _gelu, _ln, detect_gpt_variant,
                               normalize_gpt_params,
                               reconcile_decode_config)
from ..parallel import partition as partition_mod
from ..parallel.mesh import NamedSharding, PartitionSpec, make_mesh
from ..ops.attention import paged_attention
from ..telemetry import flight as flight_mod
from ..telemetry import profiling
from ..telemetry import statusz as statusz_mod
from ..telemetry.perf_attrib import PerfAttrib
from ..telemetry.request_trace import RequestTracer
from . import adapters as adapters_mod
from .kv_block_manager import BlockManager, HostKVPool
from .scheduler import (CANCELLED, FINISHED, REJECTED, WAITING, QueueFull,
                        Request, Scheduler)
from . import spec as spec_mod
from .stats import StatsRecorder

__all__ = ["Engine"]

# Compiled prefill/decode programs shared across Engine instances with
# identical static configs (the serve_bench serial-baseline engine
# reuses every program its batched twin compiled).  The cached
# closures capture ONLY the immutable _ModelCfg — never an Engine —
# so a retired engine (and its multi-GB parameter dict) stays
# collectable while its programs outlive it.
_STEP_CACHE = {}

# the static model config the compiled programs close over
# (numeric_watch is part of it: the watchdog variant returns an extra
# logits-finite flag, so it is a DIFFERENT compiled program and a
# different AOT artifact; kv_quant likewise — the int8-KV variant
# threads two scale arrays through every program.  kv_quant=False is
# REMOVED from the AOT fingerprint dict so a quant-off engine keeps
# its pre-quant digests — see _aot_base_fp).
# ``sampling``/``sample_cap`` replace the old per-engine
# temperature/top_k TRACE KEYS: sampling params are per-request
# (B,)-shaped OPERANDS of the sampling-mode programs, so one program
# per bucket serves any mix of temperature/top-p/top-k with zero
# retraces.  sampling=False is the historical greedy program,
# byte-for-byte (and _aot_base_fp re-emits the historical
# temperature=0.0/top_k=None fingerprint fields for it).
_ModelCfg = collections.namedtuple("_ModelCfg", [
    "name", "n_layers", "num_heads", "head_dim", "kv_heads",
    "pos_table", "swiglu", "tied", "rmsnorm", "window", "block_size",
    "sampling", "sample_cap", "numeric_watch", "kv_quant",
    # paged LoRA multiplexing (serve/adapters.py): slot count and the
    # padded rank ceiling.  adapters=0 (off, the default) follows the
    # sampling precedent — both fields leave the AOT fingerprint so an
    # adapters-off engine keeps its historical digests
    "adapters", "adapter_rank"],
    defaults=(0, 0))

# top-logprob candidates every sampling-mode program returns per
# sampled position (static — the per-request ``logprobs`` count only
# selects how many of them the host surfaces)
TOP_LOGPROBS = 5

# per-engine GSPMD placement bundle for tensor-parallel serving (None
# on the single-device path): the tp mesh, the per-parameter
# NamedShardings resolved from the partition rules, the head-sharded
# KV-cache sharding, and the replicated sharding for tokens/positions/
# tables/rng.  Passed to the program builders — like _ModelCfg it holds
# no Engine reference, so _STEP_CACHE still cannot retain a retired
# engine's parameter dict.
# ``scale`` is the int8-KV scale arrays' sharding (head axis, like the
# cache); None outside kv_quant engines
#  ``adapters`` is the LoRA device-stack pytree's shardings (A/B stacks
# shard on the same axes as their parent projections); None outside
# adapter engines
_Shardings = collections.namedtuple("_Shardings",
                                    ["mesh", "params", "cache", "rep",
                                     "scale", "adapters"],
                                    defaults=(None, None))


def _next_bucket(n, cap):
    """Smallest power-of-two >= n, clamped to cap."""
    b = 1
    while b < n:
        b *= 2
    return min(b, cap)


# -- per-request sampling-parameter validation (submit + Engine defaults) ----
def _valid_temperature(t):
    t = float(t)
    if not np.isfinite(t) or t < 0.0:
        raise ValueError(f"temperature must be finite and >= 0 (got {t})")
    return t


def _valid_top_p(p):
    p = float(p)
    if not np.isfinite(p) or not 0.0 < p <= 1.0:
        raise ValueError(f"top_p must be in (0, 1] (got {p})")
    return p


def _valid_top_k(k):
    """None/0 = off; else a positive int (values past the engine's
    ``sample_cap`` behave as the cap — documented in serve.md)."""
    if k is None or k == 0:
        return None
    k = int(k)
    if k < 1:
        raise ValueError(f"top_k must be None/0 or >= 1 (got {k})")
    return k


def _cfg_fp_fields(cfg):
    """``_ModelCfg`` -> AOT-fingerprint fields.  The sampling-mode
    fields follow the only-when-on rule: a sampling-off cfg re-emits
    the historical ``temperature=0.0``/``top_k=None`` trace-key fields
    (dropping sampling/sample_cap), so a greedy engine's digests are
    byte-identical to pre-operand releases and an upgraded greedy
    fleet keeps loading its existing artifacts and manifests."""
    d = dict(cfg._asdict())
    if not d.get("sampling"):
        d.pop("sampling", None)
        d.pop("sample_cap", None)
        d["temperature"] = 0.0
        d["top_k"] = None
    if not d.get("adapters"):
        # same only-when-on rule: adapters-off keeps pre-LoRA digests
        d.pop("adapters", None)
        d.pop("adapter_rank", None)
    return d


def _rope(u, pos, base=10000.0):
    """Rotate (N, H, Dh) rows by their own positions (N,) — matches
    ops/attention.py RoPEOp / generate.py's scalar-position _rot."""
    half = u.shape[-1] // 2
    inv = base ** (-jnp.arange(half, dtype=jnp.float32) / half)
    ang = pos.astype(jnp.float32)[:, None] * inv          # (N, half)
    cos = jnp.cos(ang)[:, None, :]
    sin = jnp.sin(ang)[:, None, :]
    uf = u.astype(jnp.float32)
    u1, u2 = uf[..., :half], uf[..., half:]
    return jnp.concatenate([u1 * cos - u2 * sin,
                            u1 * sin + u2 * cos], axis=-1).astype(u.dtype)


class Engine:
    """Continuous-batching inference engine over a paged KV-cache.

    Args:
      params: gpt() parameter dict (numpy or jax arrays; quantized and
        fused-qkv checkpoints are normalized at load).
      num_heads / window: decode config not recoverable from weight
        shapes; pass them or pass ``symbol=`` (the trained graph) to
        read both, exactly like ``gpt_generate``.
      block_size: tokens per KV-cache block
        (env ``MXTPU_SERVE_BLOCK_SIZE``, default 16).
      num_blocks: physical blocks in the cache, incl. the reserved
        null block (env ``MXTPU_SERVE_NUM_BLOCKS``, default 512).
      max_batch: decode batch ceiling
        (env ``MXTPU_SERVE_MAX_BATCH``, default 8).
      max_queue: admission-queue bound; ``submit`` beyond it raises
        ``QueueFull`` (env ``MXTPU_SERVE_MAX_QUEUE``, default 64).
      max_model_len: longest prompt+generation length served; defaults
        to the positional-table length (learned positions) or the
        cache capacity at ``max_batch`` concurrency (rope).
      max_prefills_per_step: prompt prefills interleaved per iteration
        ahead of the batched decode (default 1).
      temperature/top_p/top_k/seed: the PER-REQUEST sampling defaults
        (``submit()`` overrides them per request).  0.0/1.0/None is
        greedy argmax — deterministic, which preemption-resume
        equivalence relies on.  Any stochastic default flips the
        engine into sampling mode (see ``sampling``).
      sampling: per-request sampling mode (env ``MXTPU_SERVE_SAMPLING``;
        auto-on when the defaults above are stochastic).  In sampling
        mode temperature/top-p/top-k ride every program as
        ``(B,)``-shaped traced OPERANDS — one bucketed program serves
        any mix of per-request configs (greedy rows included) with
        zero fresh traces, and every emitted token returns its
        logprob (+ top-``TOP_LOGPROBS`` candidates).  Off (the
        default) is the historical greedy-only engine, byte-for-byte:
        same programs, same AOT fingerprints, same tokens.
      sample_cap: top-k/top-p candidate cap of the sampling-mode
        programs (env ``MXTPU_SERVE_SAMPLE_CAP``, default 64): the
        warp ranks the leading ``sample_cap`` logits with one
        ``jax.lax.top_k`` instead of a full-vocab sort and samples
        within them — ``top_k`` values past the cap behave as the
        cap, and a nucleus needing more than ``cap`` candidates is
        truncated there (exact whenever cap >= vocab).
      clock: injectable monotonic clock (tests drive deadlines with a
        fake clock).
      aot_dir: exported-executable store for AOT restart
        (env ``MXTPU_AOT_DIR``; see mxnet_tpu/aot/).  When set, bucket
        programs are serialized on first build and restarted engines
        load them instead of re-tracing; ``warmup()`` replays a traffic
        manifest (env ``MXTPU_WARMUP_MANIFEST`` records one) so every
        program is ready before the first request.
      tp: tensor-parallel degree (env ``MXTPU_SERVE_TP``, default 1).
        ``tp > 1`` builds a ``{'tp': tp}`` device mesh, shards the
        parameter dict per the partition rules (attention heads and
        MLP hidden split across chips, GSPMD inserting two all-reduces
        per layer) and head-shards the paged KV-cache, so each chip
        holds ``kv_heads/tp`` of every block — per-chip KV bytes drop
        by ``tp`` and a model larger than one chip's HBM serves at
        all.  ``num_heads`` and ``kv_heads`` must divide by ``tp``.
      partition_rules: tensor-parallel sharding rules — a list of
        ``(regex, PartitionSpec)`` pairs, or a string in the
        ``MXTPU_SERVE_PARTITION_RULES`` syntax
        (``parallel.partition.parse_rules``).  Default: the env var,
        else ``parallel.partition.gpt_partition_rules`` keyed to this
        checkpoint's naming.  Ignored at ``tp=1``.
      prefix_cache: content-addressed KV-block sharing across requests
        (env ``MXTPU_SERVE_PREFIX_CACHE``, default on): a new prompt's
        longest block-aligned cached prefix is reused and only the
        suffix is prefilled (RadixAttention-style; see
        ``kv_block_manager`` and docs/how_to/serve.md).
      prefill_chunk: chunked-prefill threshold in tokens (env
        ``MXTPU_SERVE_PREFILL_CHUNK``, default 512): a prompt whose
        uncached remainder exceeds it prefills one chunk per iteration
        interleaved with decode steps, so a very long prompt cannot
        stall the decode batch for a whole-prompt prefill.  0 disables
        chunking (whole-prompt prefills only).
      spec_k: draft-model speculative decoding (env ``MXTPU_SERVE_SPEC``,
        default 0 — off and byte-for-byte inert): each decode iteration
        a small draft model proposes ``spec_k`` tokens per running
        request (one dispatch, the k-step loop unrolled) and the target
        model verifies all ``k+1`` positions in ONE bucketed dispatch,
        emitting the longest agreeing prefix plus one corrected token.
        Greedy engines use exact argmax-prefix acceptance
        (token-identical to plain decode); sampling-mode engines use
        rejection-sampling acceptance — distribution-identical to
        plain sampling at any temperature/top-p/top-k.  See
        ``serve/spec.py`` and docs/how_to/serve.md.
      draft_params: the draft model's gpt() parameter dict (required
        when ``spec_k > 0``; same vocab as the target — token ids
        cross between the two models).  ``draft_num_heads`` /
        ``draft_window`` / ``draft_symbol`` mirror the target-side
        decode-config arguments; ``draft_name`` is the draft
        checkpoint's symbol-name prefix (default: the target's).
      quantize: weight-only quantized serving (env
        ``MXTPU_SERVE_QUANT``, default off — and off is byte-for-byte
        inert): ``"int8"`` quantizes every matmul projection of the
        checkpoint per-output-channel at load
        (``contrib.quantization.quantize_weight``) and the compiled
        programs dequantize on the fly — 4x smaller weight reads on
        the memory-bandwidth-bound decode loop.  Embeddings, norms,
        biases and a tied LM head stay fp.  Tokens may differ from
        the fp engine (weight rounding); greedy agreement is gated in
        serve_bench's quant workload.
      kv_dtype: ``"int8"`` (env ``MXTPU_SERVE_KV_DTYPE``) stores K/V
        cache blocks as int8 with per-slot-per-head f32 scales in a
        small parallel array pair indexed by the same block tables —
        roughly half (bf16) to a quarter (f32) the per-chip KV bytes,
        so the same HBM funds proportionally more in-flight context.
        Block accounting, the prefix cache, COW and truncate are
        untouched (block identity never changes); every program
        quantizes on write and dequantizes inside attention, and
        quantization is per-slot so preemption-by-recomputation stays
        token-stable.  Default: the parameter dtype, unquantized.
      host_kv_bytes: host-DRAM offload tier for the prefix cache (env
        ``MXTPU_SERVE_HOST_KV_BYTES``, default 0 — off and byte-for-
        byte inert: same programs, same AOT fingerprints, same
        tokens).  With a byte budget set, a refcount-0 published block
        reclaimed by the prefix LRU parks its K/V (and int8 scale
        slots) device→host instead of discarding it, and a later radix
        hit on that prefix restores the block host→device — an async
        ``device_put`` dispatched ahead of the first program that
        reads it — instead of recomputing.  DRAM is 10-100x HBM, so
        the prefix cache's effective capacity scales with host memory;
        restored spans are token-identical to recompute by
        construction (content-addressed keys, per-slot quantization).
        The pool runs its own LRU under the budget with the same
        leaf-only radix discipline.
    """

    def __init__(self, params, num_heads=None, window=None, symbol=None,
                 name="gpt", block_size=None, num_blocks=None,
                 max_batch=None, max_queue=None, max_model_len=None,
                 max_prefills_per_step=1, temperature=0.0, top_k=None,
                 top_p=None, sampling=None, sample_cap=None,
                 seed=0, clock=time.monotonic, aot_dir=None, tp=None,
                 partition_rules=None, tenant_share=None,
                 prefix_cache=None, prefill_chunk=None, spec_k=None,
                 draft_params=None, draft_num_heads=None,
                 draft_window=None, draft_symbol=None, draft_name=None,
                 quantize=None, kv_dtype=None, host_kv_bytes=None,
                 adapters=None, adapter_rank=None,
                 adapter_host_bytes=None):
        if symbol is not None:
            num_heads, window = reconcile_decode_config(symbol, num_heads,
                                                        window)
        if num_heads is None:
            raise ValueError("num_heads is required (pass it, or pass "
                             "symbol= to read it from the trained graph)")
        window = 0 if window is None else int(window)
        if window < 0:
            raise ValueError(f"window must be >= 0 (got {window})")

        self.block_size = (int(block_size) if block_size is not None
                           else env_int("MXTPU_SERVE_BLOCK_SIZE", 16))
        self.num_blocks = (int(num_blocks) if num_blocks is not None
                           else env_int("MXTPU_SERVE_NUM_BLOCKS", 512))
        self.max_batch = (int(max_batch) if max_batch is not None
                          else env_int("MXTPU_SERVE_MAX_BATCH", 8))
        max_queue = (int(max_queue) if max_queue is not None
                     else env_int("MXTPU_SERVE_MAX_QUEUE", 64))

        params = normalize_gpt_params(params, name)
        self.spec = detect_gpt_variant(params, num_heads, name)
        self.name = name
        self.num_heads = int(num_heads)
        self.window = window
        # -- sampling mode (params as traced OPERANDS, never trace keys) ----
        # the engine-level temperature/top_p/top_k are per-request
        # DEFAULTS applied at submit(); any stochastic default (or an
        # explicit sampling=True / MXTPU_SERVE_SAMPLING=1) flips the
        # engine into sampling mode, where every program threads
        # (B,)-shaped temperature/top-p/top-k operands and returns
        # per-token logprobs — one program per bucket serves any mix
        # of sampling configs with zero retraces.  sampling=False is
        # the historical greedy engine, byte-for-byte: same programs,
        # same AOT fingerprints, same tokens.
        self.temperature = _valid_temperature(temperature)
        self.top_p = _valid_top_p(1.0 if top_p is None else top_p)
        self.top_k = _valid_top_k(top_k)
        stochastic_defaults = (self.temperature > 0.0 or self.top_p < 1.0
                               or self.top_k is not None)
        if sampling is None:
            sampling = (env_flag("MXTPU_SERVE_SAMPLING", False)
                        or stochastic_defaults)
        self._sampling = bool(sampling)
        if not self._sampling and stochastic_defaults:
            raise ValueError(
                "sampling=False forces the greedy-only programs, which "
                "cannot serve temperature/top_p/top_k defaults — drop "
                "sampling=False or the stochastic defaults")
        self.sample_cap = (int(sample_cap) if sample_cap is not None
                           else env_int("MXTPU_SERVE_SAMPLE_CAP", 64))
        if self.sample_cap < 1:
            raise ValueError(
                f"sample_cap must be >= 1 (got {self.sample_cap})")
        # -- quantized serving (weight-only int8 + int8 KV blocks) ---------
        # both default OFF and off is byte-for-byte inert: the traced
        # programs, the warmup grid, the AOT fingerprints and every
        # emitted token are identical to a pre-quant engine's
        if quantize is None:
            quantize = os.environ.get("MXTPU_SERVE_QUANT") or None
        if quantize not in (None, "int8"):
            raise ValueError(
                f"quantize must be None or 'int8' (got {quantize!r})")
        self.quantize = quantize
        if kv_dtype is None:
            kv_dtype = os.environ.get("MXTPU_SERVE_KV_DTYPE") or None
        if kv_dtype not in (None, "int8"):
            raise ValueError(
                f"kv_dtype must be None or 'int8' (got {kv_dtype!r})")
        self._kv_quant = kv_dtype == "int8"
        if self.quantize:
            # per-output-channel int8 + *_wscale vectors; detection ran
            # on the fp checkpoint, the programs dequantize on the fly
            params = _quantize_gpt_params(params, name, self.spec)
        # -- paged LoRA adapter multiplexing (serve/adapters.py) -----------
        # default OFF and off is byte-for-byte inert: no slot operand,
        # unchanged program-cache keys, unchanged AOT fingerprints,
        # identical tokens.  ``adapters`` counts device slots INCLUDING
        # the reserved all-zero base slot 0
        self._adapters = (int(adapters) if adapters is not None
                          else env_int("MXTPU_SERVE_ADAPTERS", 0))
        if self._adapters < 0 or self._adapters == 1:
            raise ValueError(
                f"adapters must be 0 (off) or >= 2 slots including the "
                f"reserved base slot 0 (got {self._adapters})")
        self.adapter_rank = (int(adapter_rank) if adapter_rank is not None
                             else env_int("MXTPU_SERVE_ADAPTER_RANK", 8))
        if self._adapters and self.adapter_rank < 1:
            raise ValueError(
                f"adapter_rank must be >= 1 (got {self.adapter_rank})")
        self.adapter_host_bytes = (
            int(adapter_host_bytes) if adapter_host_bytes is not None
            else env_int("MXTPU_SERVE_ADAPTER_HOST_BYTES", 0)) or None
        adapter_stems = None
        if self._adapters:
            adapter_stems = adapters_mod.gpt_stems(
                name, self.spec["n_layers"], self.spec["swiglu"],
                self.spec["tied"], params)
        # -- tensor-parallel mesh + partition rules ------------------------
        self.tp = (int(tp) if tp is not None
                   else env_int("MXTPU_SERVE_TP", 1))
        if self.tp < 1:
            raise ValueError(f"tp must be >= 1 (got {self.tp})")
        self.mesh = None
        self._shardings = None
        self._rules = None
        self._rules_digest = None
        if self.tp > 1:
            if self.tp > jax.device_count():
                raise ValueError(
                    f"tp={self.tp} exceeds the {jax.device_count()} "
                    f"visible {jax.default_backend()} devices")
            if self.num_heads % self.tp or self.spec["kv_heads"] % self.tp:
                raise ValueError(
                    f"tp={self.tp} must divide num_heads="
                    f"{self.num_heads} and kv_heads="
                    f"{self.spec['kv_heads']} (head-sharded attention "
                    "and KV-cache)")
            if partition_rules is None:
                partition_rules = os.environ.get(
                    "MXTPU_SERVE_PARTITION_RULES") or None
            if isinstance(partition_rules, str):
                self._rules = partition_mod.parse_rules(partition_rules)
            elif partition_rules is not None:
                self._rules = list(partition_rules)
            if not self._rules:
                self._rules = partition_mod.gpt_partition_rules(
                    name=name, axis="tp")
            self._rules_digest = partition_mod.rules_digest(self._rules)
            self.mesh = make_mesh({"tp": self.tp})
            specs = partition_mod.match_partition_rules(self._rules, params)
            rep = NamedSharding(self.mesh, PartitionSpec())
            # LoRA stacks shard on the SAME axes as their parent
            # projections: an out-sharded parent ((tp, None) weight)
            # shards the B stack's d_out axis (A replicated); an
            # in-sharded parent ((None, tp)) shards the A stack's d_in
            # axis (B replicated) — the delta's partial-sum joins the
            # layer's existing all-reduce
            adapter_shardings = None
            if self._adapters:
                adapter_shardings = {}
                for stem in adapter_stems:
                    wspec = specs.get(f"{stem}_weight") or PartitionSpec()
                    out_ax = wspec[0] if len(wspec) > 0 else None
                    in_ax = wspec[1] if len(wspec) > 1 else None
                    adapter_shardings[f"{stem}_A"] = NamedSharding(
                        self.mesh, PartitionSpec(None, None, in_ax))
                    adapter_shardings[f"{stem}_B"] = NamedSharding(
                        self.mesh, PartitionSpec(None, out_ax, None))
                adapter_shardings["scale"] = rep
            self._shardings = _Shardings(
                mesh=self.mesh,
                params=partition_mod.named_shardings(self.mesh, specs),
                adapters=adapter_shardings,
                # each chip holds kv_heads/tp of EVERY block: block
                # accounting (BlockManager) is unchanged, per-chip KV
                # bytes drop by tp
                cache=NamedSharding(self.mesh, PartitionSpec(
                    None, None, None, "tp", None)),
                rep=rep,
                # int8-KV scale arrays shard on the SAME head axis as
                # the cache blocks they dequantize (kv_heads % tp is
                # already enforced above)
                scale=NamedSharding(self.mesh, PartitionSpec(
                    None, None, None, "tp")))
        cache_tokens = (self.num_blocks - 1) * self.block_size
        if max_model_len is None:
            # learned positions cap the servable length at the table;
            # rope has no trained limit, so cap where max_batch peers
            # can still coexist in the cache (pure heuristic — override
            # freely; admission re-checks the cache either way)
            max_model_len = (self.spec["pos_table"]
                             or max(self.block_size,
                                    cache_tokens // max(1, self.max_batch)))
        self.max_model_len = int(min(max_model_len, cache_tokens))
        if (self.spec["pos_table"] is not None
                and self.max_model_len > self.spec["pos_table"]):
            raise ValueError(
                f"max_model_len={self.max_model_len} exceeds the "
                f"positional table ({self.spec['pos_table']})")
        # fixed block-table width: one decode program per batch bucket
        self.table_width = -(-self.max_model_len // self.block_size)

        # -- speculative decoding (serve/spec.py) --------------------------
        self.spec_k = (int(spec_k) if spec_k is not None
                       else env_int("MXTPU_SERVE_SPEC", 0))
        if self.spec_k < 0:
            raise ValueError(f"spec_k must be >= 0 (got {self.spec_k})")
        if self.spec_k:
            # temperature > 0 is served by REJECTION-SAMPLING
            # acceptance (Leviathan/Chen 2023): accept a drafted token
            # with prob min(1, p_target/p_draft), resample from the
            # normalized residual on reject — distribution-identical
            # to plain sampling, so the spec speedup covers stochastic
            # traffic too.  Greedy engines keep the exact argmax-
            # prefix acceptance (byte-identical to plain decode).
            if draft_params is None:
                raise ValueError(
                    "spec_k > 0 requires draft_params (a small gpt() "
                    "checkpoint whose vocab matches the target's)")
        self._spec = None           # DraftWorker, attached below

        # -- host-DRAM KV offload tier (kv_block_manager.HostKVPool) -------
        # default OFF and off is byte-for-byte inert: no restore
        # program family, unchanged warmup grid, unchanged AOT
        # fingerprints, identical tokens
        self.host_kv_bytes = (int(host_kv_bytes)
                              if host_kv_bytes is not None
                              else env_int("MXTPU_SERVE_HOST_KV_BYTES", 0))
        if self.host_kv_bytes < 0:
            raise ValueError(
                f"host_kv_bytes must be >= 0 (got {self.host_kv_bytes})")
        self._host_pool = (HostKVPool(self.host_kv_bytes,
                                      block_tokens=self.block_size)
                           if self.host_kv_bytes else None)
        self.blocks = BlockManager(self.num_blocks, self.block_size,
                                   prefix_cache=prefix_cache,
                                   host_pool=self._host_pool)
        # always registered: the eviction path only offloads with a
        # pool attached, but export_blocks (the prefill→decode handoff
        # serializer) gathers device blocks D2H through the same fetch
        # on pool-less prefill replicas too — pure numpy, no program or
        # fingerprint changes, byte-for-byte inert for plain serving
        self.blocks.set_offload_source(self._host_kv_fetch)
        # request-scoped observability: the tracer threads every
        # lifecycle event (scheduler decisions included) into the
        # flight-recorder ring, the optional JSONL export
        # (MXTPU_REQUEST_TRACE) and the Chrome-trace request tracks
        self._rtrace = RequestTracer()
        self._rtrace.on_terminal = self._on_request_terminal
        self.scheduler = Scheduler(self.blocks, self.max_batch, max_queue,
                                   max_prefills_per_step, clock=clock,
                                   trace=self._rtrace,
                                   tenant_share=tenant_share,
                                   prefill_chunk=prefill_chunk,
                                   spec_slots=self.spec_k)
        self._stats = StatsRecorder(clock=clock)
        self.clock = clock
        self._step_id = 0
        # n>1 sample groups whose siblings wait for the primary's
        # prefill to publish the prompt's blocks (submit() appends from
        # handler threads, the step thread drains)
        self._fanout_lock = threading.Lock()
        self._pending_fanout = []      # guarded-by: _fanout_lock
        # SLO breach -> flight dump: deadline misses always (rate-
        # limited by the recorder), rejection rate when the env
        # threshold is set (fraction of the last 100 terminal requests)
        self._slo_window = collections.deque(maxlen=100)
        try:
            self._reject_rate_thr = float(
                os.environ.get(flight_mod.ENV_REJECT_RATE, "") or 0.0)
        except ValueError:
            self._reject_rate_thr = 0.0
        self._numeric_watch = env_flag("MXTPU_NUMERIC_WATCH", False)

        # place weights (sharded per the rules when tp > 1) and track
        # which device arrays THIS engine materialized: shutdown()
        # deletes exactly those, deterministically, without ever
        # invalidating caller-owned jax arrays that passed through
        self._owned = []
        placed = {}
        for k, v in params.items():
            if self._shardings is not None:
                # device_put straight from the source array: each chip
                # receives only its shard — no transient full-size copy
                # on device 0 (which could OOM exactly the models tp
                # exists to serve)
                arr = jax.device_put(v, self._shardings.params[k])
            else:
                arr = jnp.asarray(v)
            if arr is not v:
                self._owned.append(arr)
            placed[k] = arr
        self.params = placed
        dt = self.params[f"{name}_tok_embed_weight"].dtype
        # paged LoRA slots live in engine-owned device stacks shaped by
        # the checkpoint (A/B in the activation dtype — the base may be
        # int8-quantized, the deltas never are); slot 0 stays all-zero
        self.adapter_store = None
        if self._adapters:
            self.adapter_store = adapters_mod.AdapterStore(
                adapter_stems, self.adapter_rank, self._adapters,
                dtype=np.dtype(str(dt)),
                host_bytes=self.adapter_host_bytes,
                shardings=(None if self._shardings is None
                           else self._shardings.adapters))
        L = self.spec["n_layers"]
        # int8 KV blocks store quantized slots plus per-slot-per-head
        # f32 scales in a small parallel array pair indexed by the SAME
        # block ids — BlockManager accounting, the radix prefix cache,
        # COW and truncate are untouched because block identity and
        # refcounts never change
        cache_dt = jnp.dtype(jnp.int8) if self._kv_quant else dt
        shape = (L, self.num_blocks, self.block_size,
                 self.spec["kv_heads"], self.spec["head_dim"])
        sshape = shape[:-1]
        self._scale_k = self._scale_v = None
        if self._shardings is not None:
            # allocate the cache BORN sharded: a jnp.zeros-then-reshard
            # would transiently hold the whole cache on device 0, which
            # OOMs exactly the aggregate-HBM-sized configs tp unlocks
            zeros = jax.jit(lambda: jnp.zeros(shape, cache_dt),
                            out_shardings=self._shardings.cache)
            self._cache_k = zeros()
            self._cache_v = zeros()
            if self._kv_quant:
                szeros = jax.jit(lambda: jnp.zeros(sshape, jnp.float32),
                                 out_shardings=self._shardings.scale)
                self._scale_k = szeros()
                self._scale_v = szeros()
        else:
            self._cache_k = jnp.zeros(shape, cache_dt)
            self._cache_v = jnp.zeros(shape, cache_dt)
            if self._kv_quant:
                self._scale_k = jnp.zeros(sshape, jnp.float32)
                self._scale_v = jnp.zeros(sshape, jnp.float32)
        self._key = jax.random.PRNGKey(seed)
        # donating the cache through each step avoids a full cache copy
        # per token; CPU PJRT can't donate (it would warn every call)
        self._donate = (jax.default_backend() != "cpu")
        self._cfg = _ModelCfg(
            name=name, n_layers=L, num_heads=self.num_heads,
            head_dim=self.spec["head_dim"], kv_heads=self.spec["kv_heads"],
            pos_table=self.spec["pos_table"], swiglu=self.spec["swiglu"],
            tied=self.spec["tied"], rmsnorm=self.spec["rmsnorm"],
            window=self.window, block_size=self.block_size,
            sampling=self._sampling,
            sample_cap=self.sample_cap if self._sampling else 0,
            numeric_watch=self._numeric_watch,
            kv_quant=self._kv_quant,
            adapters=self._adapters,
            adapter_rank=self.adapter_rank if self._adapters else 0)
        # draft worker last among the device placements: params, then
        # the target cache, then the (much smaller) draft side — the
        # same one-model-at-a-time HBM discipline shutdown() preserves
        self._draft_shardings = None
        if self.spec_k:
            from .spec import DraftWorker

            self._spec = DraftWorker(
                self, draft_params, num_heads=draft_num_heads,
                window=draft_window, symbol=draft_symbol,
                name=draft_name or name)
            if self._shardings is not None:
                # the draft replicates under tensor parallelism (its
                # params and cache are small by design); its programs
                # still need mesh-aware jit kwargs so GSPMD sees one
                # consistent layout
                rep = self._shardings.rep
                self._draft_shardings = _Shardings(
                    mesh=self.mesh, params=rep, cache=rep, rep=rep)
        # -- AOT startup wiring (mxnet_tpu/aot/) ---------------------------
        self._aot = (aot_store.ExportStore(aot_dir) if aot_dir is not None
                     else aot_store.default_store())
        self._spec_digest = aot_store.digest(self._aot_base_fp())[:16]
        self._manifest = aot_warmup.ManifestRecorder(
            self._spec_digest, os.environ.get(aot_warmup.ENV_MANIFEST))
        self._warming = False
        self._alive = True
        self._noop_steps = 0
        # per-program performance attribution (telemetry/perf_attrib):
        # cost table fills at program-resolve cadence (default on),
        # sampled device timing rides the step cadence behind
        # MXTPU_PERF_ATTRIB_SAMPLE.  Constructed here — after
        # telemetry.enable() in the usual ordering — because it caches
        # its metric handles at construction (the handle-caching
        # asymmetry), and NEVER enters _spec_key/_aot_base_fp: both
        # knobs in any combination leave tokens, program cache keys
        # and AOT fingerprints byte-identical
        self._perf = PerfAttrib()
        # per-step host-overhead decomposition (telemetry/profiling):
        # same construction ordering + inertness rule as PerfAttrib —
        # caches its histogram handle here, never enters
        # _spec_key/_aot_base_fp.  Default on (MXTPU_STEP_PROFILE=0
        # swaps in the NOOP recorder)
        self._sprof = profiling.make_step_profiler()
        # live-state gauges stamped once per step (no-op when telemetry
        # is disabled); cumulative serve counters live in StatsRecorder
        self._tel_queue = telemetry.gauge(
            "mxtpu_serve_queue_depth", "requests waiting for admission")
        self._tel_running = telemetry.gauge(
            "mxtpu_serve_running", "requests in the decode batch")
        self._tel_blocks = telemetry.gauge(
            "mxtpu_serve_blocks_in_use", "KV-cache blocks allocated")
        self._tel_block_util = telemetry.gauge(
            "mxtpu_serve_block_utilization", "KV-cache block fraction used")
        self._tel_preempt = telemetry.gauge(
            "mxtpu_serve_preemptions", "scheduler preemptions (lifetime)")
        self._tel_evict = telemetry.gauge(
            "mxtpu_serve_evictions", "retained-block evictions (lifetime)")
        self._tel_rejected = telemetry.gauge(
            "mxtpu_serve_rejected", "rejected requests (lifetime)")
        telemetry.gauge("mxtpu_serve_blocks_total",
                        "allocatable KV-cache blocks").set(
            self.blocks.total_blocks)
        # live introspection: /statusz shows this engine while it is
        # alive (weakref — a retired engine drops off the page)
        self._statusz_name = statusz_mod.register_weak(self, "serve.engine")

    # -- static config key for the shared program cache ----------------------
    def _spec_key(self):
        # _ModelCfg pins the math; the extras pin the traced SHAPES
        # (cache geometry + dtype), the donation policy, and the
        # sharding layout (tp degree + partition-rule digest) — a tp=2
        # program must never be served to a tp=4 engine
        return (self._cfg, self.num_blocks, self.table_width,
                str(self._cache_k.dtype), self._donate, self.tp,
                self._rules_digest, self.spec_k,
                None if self._spec is None else
                (self._spec.cfg, str(self._spec.cache_k.dtype)),
                # weight-only quant changes the params PYTREE (the
                # *_wscale leaves), so a quantized engine's programs
                # must never be served to an unquantized twin
                self.quantize,
                # the paged-attention lowering is chosen at trace time
                # (env + backend + geometry): a kernel-decode program
                # must never be served to an engine whose env pinned
                # the jnp formulation, and vice versa
                self._paged_impl())

    def _aot_base_fp(self):
        """The on-disk form of _spec_key(): same fields, JSON-stable,
        plus jax version + backend (aot.fingerprint), so an artifact
        from an incompatible process can never be loaded."""
        # sharding fields enter the fingerprint ONLY at tp > 1: a tp=1
        # engine's digest is unchanged from pre-sharding releases, so
        # an upgraded fleet keeps loading its existing artifacts and
        # manifests instead of silently cold-compiling once per upgrade
        sharded = ({} if self.tp == 1 else dict(
            tp=self.tp, mesh_shape=dict(self.mesh.shape),
            partition_rules=self._rules_digest))
        # like the sharding fields, spec enters the fingerprint ONLY
        # when on: a spec-off engine keeps its pre-spec digests, so an
        # upgraded fleet keeps loading its existing artifacts/manifests
        spec = ({} if self._spec is None else dict(
            spec_k=self.spec_k,
            draft=dict(_cfg_fp_fields(self._spec.cfg),
                       cache_dtype=str(self._spec.cache_k.dtype))))
        # quant fields follow the same only-when-on rule: kv_quant=False
        # leaves the cfg dict (and cache_dtype) exactly as pre-quant
        # releases emitted them, and weight-only off adds no key — an
        # upgraded quant-off fleet keeps its artifacts and manifests.
        # _cfg_fp_fields applies the sampling-mode only-when-on rule:
        # a sampling-off cfg re-emits the historical temperature/top_k
        # trace-key fields, so greedy digests never move
        cfg_d = {k: v for k, v in _cfg_fp_fields(self._cfg).items()
                 if k != "kv_quant" or v}
        draft_d = spec.get("draft")
        if draft_d is not None and not draft_d.get("kv_quant"):
            del draft_d["kv_quant"]
        quant = {} if not self.quantize else dict(quantize=self.quantize)
        # the Mosaic paged-decode kernel follows the only-when-on rule
        # too: "jnp" is the historical program (digests keep), but an
        # exported artifact BAKES the lowering and replays it whatever
        # the env says at load — without this key, a TPU fleet that
        # upgrades into the kernel (or escapes it via
        # MXTPU_PAGED_ATTENTION=jnp after a kernel bug) would silently
        # warm-load the other implementation's artifacts forever
        paged = ({} if self._paged_impl() != "pallas"
                 else dict(paged_attention="pallas"))
        return aot_store.fingerprint(
            subsystem="serve", cfg=cfg_d,
            num_blocks=self.num_blocks, table_width=self.table_width,
            cache_dtype=str(self._cache_k.dtype), donate=self._donate,
            **sharded, **spec, **quant, **paged)

    def _paged_impl(self):
        """The paged-attention implementation this engine's programs
        trace ("pallas" or "jnp") — resolved from the env/backend/cache
        geometry exactly as ``ops.attention.paged_attention`` will."""
        from ..ops.attention import resolve_paged_impl
        return resolve_paged_impl(self.block_size,
                                  self.spec["head_dim"])

    # -- public API ----------------------------------------------------------
    def submit(self, prompt, max_new_tokens=64, deadline_s=None,
               tenant=None, trace_id=None, handoff=False,
               temperature=None, top_p=None, top_k=None, n=1,
               logprobs=0, adapter_id=None):
        """Queue one generation request; returns its ``Request`` handle.

        Raises ``QueueFull`` when the admission queue is at capacity
        (back-pressure — retry later).  A request that could never fit
        (longer than ``max_model_len`` or the whole cache) is returned
        already REJECTED rather than queued to deadlock.

        ``tenant`` labels the request for fair-share admission and the
        per-tenant telemetry series; ``trace_id`` pre-stamps the trace
        identity (a fleet router propagates one so a request retried
        across replicas stitches into a single cross-process timeline);
        ``handoff`` marks a prefill→decode handoff ingest (the decode
        replica's re-submission) for the admit trace event and the
        scheduler's ``waiting_handoffs`` load signal.

        ``temperature``/``top_p``/``top_k`` are PER-REQUEST sampling
        params (None defers to the engine defaults): on a sampling-mode
        engine they ride the decode batch as traced operands, so any
        mix of configs shares one bucketed program — a greedy-only
        engine (``sampling=False``) rejects non-greedy values with
        ``ValueError``.  ``n > 1`` serves that many independent samples
        of the same prompt, sharing the prompt's radix-cached prefix
        blocks copy-on-write (one prefill pays for all ``n``; the
        handles are on ``req.samples``).  ``logprobs`` (0..5) returns
        that many top-logprob candidates per emitted token alongside
        each token's own logprob (``req.token_logprobs`` /
        ``req.top_logprobs``).

        ``adapter_id`` serves the request through a registered LoRA
        adapter (adapters mode only — ``Engine(adapters=S)`` /
        ``MXTPU_SERVE_ADAPTERS``): the request pins the adapter's
        device slot until it terminates and its rows add the adapter's
        low-rank delta inside the SAME bucketed programs base rows use
        (the slot index is a traced operand — any adapter mix shares
        one program with zero retraces).  Unknown ids raise
        ``ValueError``; a fully-pinned slot table rejects with the
        retriable ``adapter_slots`` reason.
        """
        if not self._alive:
            raise RuntimeError("engine is shut down")
        temperature = (self.temperature if temperature is None
                       else _valid_temperature(temperature))
        top_p = self.top_p if top_p is None else _valid_top_p(top_p)
        top_k = self.top_k if top_k is None else _valid_top_k(top_k)
        logprobs = int(logprobs)
        if not 0 <= logprobs <= TOP_LOGPROBS:
            raise ValueError(
                f"logprobs must be in [0, {TOP_LOGPROBS}] "
                f"(got {logprobs})")
        n = int(n)
        if not 1 <= n <= 64:
            raise ValueError(f"n must be in [1, 64] (got {n})")
        if not self._sampling and (temperature > 0.0 or top_p < 1.0
                                   or top_k is not None or logprobs):
            raise ValueError(
                "per-request sampling/logprobs require a sampling-mode "
                "engine (Engine(sampling=True) / MXTPU_SERVE_SAMPLING=1 "
                "or stochastic engine defaults) — greedy-only engines "
                "keep the historical programs byte-for-byte")
        if n > 1 and not self.blocks.prefix_cache:
            raise ValueError(
                "n > 1 requires the prefix cache (siblings share the "
                "prompt's radix-cached blocks copy-on-write — one "
                "prefill, n samples)")
        if adapter_id is not None:
            if not self._adapters:
                raise ValueError(
                    "adapter_id requires an adapters-mode engine "
                    "(Engine(adapters=S) / MXTPU_SERVE_ADAPTERS) — "
                    "adapters-off engines keep the historical programs "
                    "byte-for-byte")
            if (not isinstance(adapter_id, str)
                    or not self.adapter_store.known(adapter_id)):
                raise ValueError(f"unknown adapter: {adapter_id!r}")
        kw = dict(deadline_s=deadline_s, tenant=tenant, handoff=handoff,
                  temperature=temperature, top_p=top_p, top_k=top_k,
                  logprobs=logprobs, adapter_id=adapter_id)
        req = Request(prompt, max_new_tokens, **kw)
        if trace_id:
            req.trace_id = str(trace_id)
        if n > 1:
            sibs = []
            for i in range(1, n):
                s = Request(prompt, max_new_tokens, **kw)
                s.group, s.sample_index = req.rid, i
                if trace_id:
                    s.trace_id = str(trace_id)
                sibs.append(s)
            req.group, req.sample_index = req.rid, 0
            req.samples = [req] + sibs
        if req.target_len() > self.max_model_len:
            for r in (req.samples or [req]):
                self.scheduler._reject(r, "exceeds_max_len")
            return req
        if adapter_id is not None:
            # every row (primary + siblings) pins the slot once: the
            # pin survives preemption (preempt never fires the terminal
            # trace hook) and drops in _on_request_terminal.  All slots
            # pinned is TRANSIENT capacity pressure — the retriable
            # adapter_slots rejection (fleet replicas 503, not 400)
            try:
                for r in (req.samples or [req]):
                    r.adapter_slot = self.adapter_store.acquire(adapter_id)
            except adapters_mod.NoAdapterSlots:
                for r in (req.samples or [req]):
                    self.scheduler._reject(r, "adapter_slots")
                return req
        try:
            out = self.scheduler.submit(req)
        except QueueFull:
            self._stats.on_reject()      # back-pressure event counter
            if req.samples:
                for s in req.samples[1:]:
                    # each sibling is one more back-pressure event —
                    # the Prometheus series and the rejection-rate
                    # breach window must see the whole group
                    self.scheduler._reject(s, "queue_full")
                    self._stats.on_reject()
            raise
        if req.samples:
            if req.status == REJECTED:
                for s in req.samples[1:]:
                    self.scheduler._reject(s, req.reject_reason
                                           or "rejected")
            else:
                # siblings queue ENGINE-side until the primary's
                # prefill publishes the prompt's blocks — only then
                # does their radix walk share the whole block-aligned
                # prefix (released by _release_fanout each step)
                with self._fanout_lock:
                    self._pending_fanout.append((req,
                                                 list(req.samples[1:])))
        return out

    def step(self):
        """One scheduler iteration: admit + prefill, then one batched
        decode.  Returns the number of tokens emitted.

        An unhandled exception dumps the flight-recorder ring to
        ``MXTPU_FLIGHT_DIR`` before propagating — the post-mortem
        exists even when nobody had tracing on."""
        if not self._alive:
            # caller usage error, not an engine failure: raise without
            # the force-dump (a retry loop on a dead engine must not
            # write one full post-mortem per call)
            raise RuntimeError("engine is shut down")
        try:
            return self._step_inner()
        except Exception:
            rec = flight_mod.recorder()
            rec.record("error", site="engine.step",
                       error=traceback.format_exc(limit=4))
            # spec/sharding digests identify WHICH compiled (possibly
            # sharded) program was live when the process died
            rec.dump("engine_exception", force=True,
                     extra={"traceback": traceback.format_exc(limit=30),
                            "spec_digest": self._spec_digest,
                            "tp": self.tp,
                            "sharding_rules_digest": self._rules_digest})
            raise

    def _has_pending_fanout(self):
        with self._fanout_lock:
            return bool(self._pending_fanout)

    def _release_fanout(self):
        """Move n>1 siblings into the scheduler once their primary's
        prefill has published the prompt's blocks: each sibling's
        radix walk then reuses the whole block-aligned prefix
        copy-on-write (the final span recomputes into a fresh private
        block — recomputation is the copy), so n samples pay ONE
        prefill however the admission interleaves."""
        with self._fanout_lock:
            if not self._pending_fanout:
                return
            pending, self._pending_fanout = self._pending_fanout, []
        keep = []
        for primary, sibs in pending:
            if not primary.tokens and not primary.done:
                keep.append((primary, sibs))
                continue
            rest = []
            for i, s in enumerate(sibs):
                if self.scheduler.queue_depth >= self.scheduler.max_queue:
                    rest = sibs[i:]      # queue full: retry next step
                    break
                try:
                    self.scheduler.submit(s)
                except QueueFull:
                    # raced a handler thread into the last queue slot:
                    # the scheduler already counted + traced the
                    # rejection — finalize the handle and count the
                    # back-pressure event like any other queue-full
                    s.status = REJECTED
                    s.reject_reason = "queue_full"
                    s.finish_t = self.clock()
                    self._stats.on_reject()
            if rest:
                keep.append((primary, rest))
        if keep:
            with self._fanout_lock:
                self._pending_fanout = keep + self._pending_fanout

    @hot_path
    def _step_inner(self):
        self._step_id += 1
        # arm (or not) this step's dispatch timing — with sampling off
        # (the default) every t0() below returns None and no dispatch
        # gains a sync
        self._perf.arm(self._step_id)
        # step decomposition: begin/lap/commit bracket the whole
        # iteration; laps inside _run_prefill/_run_decode/_run_spec_
        # decode split dispatch / device-wait / host bookkeeping (see
        # telemetry/profiling.py for the phase map)
        sprof = self._sprof
        sprof.begin(self._step_id)
        with telemetry.span("serve.step"):
            self._release_fanout()
            prefills, decodes = self.scheduler.schedule()
            if self._host_pool is not None:
                # host-tier hits allocated by this schedule() queue
                # their restores; dispatch them NOW, before the first
                # prefill/decode program that reads the blocks
                self._restore_pending()
            # blocks for this iteration are all held right now — the
            # honest high-water sample (post-drain reads would be ~0)
            self._stats.on_utilization(self.blocks.utilization())
            sprof.lap("schedule")
            emitted = 0
            for req in prefills:
                with telemetry.span("serve.prefill", rid=req.rid):
                    # the per-iteration prefill token budget is shared
                    # with the decode slots: each decode slot emits up
                    # to 1 + spec_k tokens this step (one, without
                    # speculative decoding), so a chunk shrinks by the
                    # batch's worst-case token count
                    emitted += self._run_prefill(
                        req,
                        decode_slots=len(decodes) * (1 + self.spec_k))
            if decodes:
                with telemetry.span("serve.decode", batch=len(decodes)):
                    if self._spec is not None:
                        emitted += self._run_spec_decode(decodes)
                    else:
                        emitted += self._run_decode(decodes)
            if prefills or decodes:
                # scheduler decisions ride the flight ring (bounded,
                # always on) so post-mortems see the recent schedule;
                # with the host tier live its occupancy rides along
                # (off-path records stay byte-identical)
                step_fields = dict(
                    id=self._step_id, prefills=len(prefills),
                    decodes=len(decodes),
                    queue=self.scheduler.queue_depth,
                    blocks_in_use=self.blocks.blocks_in_use)
                if self._host_pool is not None:
                    step_fields["host_kv_entries"] = len(self._host_pool)
                    step_fields["host_kv_bytes"] = \
                        self._host_pool.bytes_used
                flight_mod.recorder().record("step", **step_fields)
            if emitted == 0 and not prefills and not decodes:
                self._noop_steps += 1
                if self._noop_steps > 1000 and self.scheduler.has_work():
                    raise RuntimeError(
                        "scheduler stalled: work queued but 1000 consecutive "
                        "steps scheduled nothing (cache/queue misconfigured?)")
            else:
                self._noop_steps = 0
            self._stats.on_step(emitted, decode_batch=len(decodes))
            self._perf.on_step(emitted)
            if self._spec is not None:
                # bound the draft ingest ledger by the LIVE running
                # set: a request that leaves the engine between decodes
                # (preempted, then deadline-rejected or cancelled)
                # never reaches the forget() in _run_spec_decode.  A
                # pruned-then-resumed request simply re-ingests.
                self._spec.prune({r.rid for r in self.scheduler.running})
            self._tel_queue.set(self.scheduler.queue_depth)
            self._tel_running.set(len(self.scheduler.running))
            self._tel_blocks.set(self.blocks.blocks_in_use)
            self._tel_block_util.set(self.blocks.utilization())
            self._tel_preempt.set(self.scheduler.preemptions)
            self._tel_evict.set(self.blocks.evictions)
            self._tel_rejected.set(self.scheduler.rejections)
        sprof.commit(emitted, prefills=len(prefills), decodes=len(decodes))
        return emitted

    def has_work(self):
        """Whether ``step()`` still has anything to do: scheduler
        queues/batches, OR n>1 siblings awaiting release — a step-loop
        driver that only polled ``scheduler.has_work()`` would park
        with fanout siblings still pending (the fleet replica's pump
        reads this)."""
        return self.scheduler.has_work() or self._has_pending_fanout()

    def run(self):
        """Pump ``step()`` until every queued request resolves."""
        while self.has_work():
            self.step()

    def stream(self, req):
        """Yield ``req``'s tokens as they are generated, pumping the
        engine as needed (every co-scheduled request advances too)."""
        sent = 0
        while True:
            while sent < len(req.tokens):
                yield int(req.tokens[sent])
                sent += 1
            if req.done or not self.has_work():
                return
            self.step()

    def stats(self):
        """Immutable ``ServeStats`` snapshot of the engine right now."""
        return self._stats.snapshot(self.scheduler, self.blocks)

    # -- SLO breach detection (flight-recorder triggers) ---------------------
    def _on_request_terminal(self, req, name, args):
        """Runs on every request's terminal trace event: a deadline
        miss dumps the flight ring immediately (rate-limited), and a
        rejection rate over ``MXTPU_FLIGHT_REJECT_RATE`` across the
        recent-terminal window dumps too."""
        slot = getattr(req, "adapter_slot", 0)
        if slot and self.adapter_store is not None:
            # drop the request's adapter pin exactly once per lifetime
            # (terminal events never fire twice for one request; the
            # zeroed slot makes a double-call a no-op anyway)
            self.adapter_store.release(slot)
            req.adapter_slot = 0
        rejected = name == "rejected"
        self._slo_window.append(1 if rejected else 0)
        if rejected and args.get("reason") == "deadline":
            flight_mod.recorder().dump(
                "deadline_miss", extra={"rid": req.rid,
                                        "deadline_s": req.deadline_s})
        thr = self._reject_rate_thr
        if thr and len(self._slo_window) >= 20:
            rate = sum(self._slo_window) / len(self._slo_window)
            if rate >= thr:
                flight_mod.recorder().dump(
                    "rejection_rate",
                    extra={"rate": round(rate, 4), "threshold": thr,
                           "window": len(self._slo_window)})

    # -- live introspection (/statusz provider) ------------------------------
    def statusz(self):
        """Live engine state for the ``/statusz`` endpoint: in-flight
        requests with ages and phases, queue/cache occupancy, program
        and AOT-store state."""
        now = self.clock()
        reqs = []
        mid_prefill = {id(r) for r in self.scheduler.prefilling}
        for req in (list(self.scheduler.running)
                    + list(self.scheduler.prefilling)
                    + list(self.scheduler.waiting)):
            if req.status == WAITING:
                phase = "queued" if req.n_preemptions == 0 else "preempted"
            elif id(req) in mid_prefill or not req.tokens:
                phase = "prefill"
            else:
                phase = "decode"
            reqs.append({
                "rid": req.rid, "trace_id": req.trace_id,
                "tenant": req.tenant, "status": req.status, "phase": phase,
                "age_s": (round(now - req.submit_t, 3)
                          if req.submit_t is not None else None),
                "prompt_tokens": int(req.prompt.size),
                "generated": len(req.tokens),
                "target": req.target_len(),
                # how a mid-prefill request is progressing: slots
                # reused from the prefix cache at admission, slots
                # written so far, and the admission-time prefill goal
                # (None while waiting)
                "cached_tokens": req.cached_prefix_len,
                "host_tokens": req.host_restored_len,
                "prefill_done": int(req.cache_len),
                "prefill_target": req.prefill_target,
                "n_preemptions": req.n_preemptions})
        aot = {"dir": getattr(self._aot, "dir", None)}
        if self._aot is not None:
            entries = self._aot.entries()
            aot.update(artifacts=len(entries),
                       bytes=sum(b for _, b in entries))
        return {
            "alive": self._alive,
            "steps": self._step_id,
            "queue_depth": self.scheduler.queue_depth,
            "running": len(self.scheduler.running),
            "in_flight": reqs,
            "completed": self._stats.completed,
            "preemptions": self.scheduler.preemptions,
            "reject_reasons": dict(self.scheduler.reject_reasons),
            "tenants": self.scheduler.tenant_stats(),
            "kv_blocks": self.blocks.occupancy(),
            # the prefix-cache section an operator reads to explain a
            # cache-cold replica (also nested in kv_blocks.prefix_cache)
            "prefix_cache": self.blocks.prefix_stats(),
            "kv_cache": self.kv_cache_stats(),
            # host-DRAM offload tier occupancy and hit/restore counters
            # (None when the tier is off — the inert default)
            "host_kv": self.host_kv_stats(),
            # quantized serving: which of the two int8 modes are live
            # (None when both are off — the inert default)
            "quant": self.quant_info(),
            # sampling mode: per-request params as traced operands
            # (None on greedy-only engines — the inert default)
            "sampling": self.sampling_info(),
            # paged LoRA multiplexing: slot occupancy, refcounts and
            # the loaded-adapter set (None when off — the inert default)
            "adapters": self.adapter_info(),
            "sharding": self.sharding_info(),
            # speculative decoding: k, the draft model's shape/bytes,
            # the rolling acceptance rate and the verify bucket grid
            # (None with spec off)
            "spec": (None if self._spec is None
                     else self._spec.statusz(self)),
            # per-program cost/timing attribution: cost table always
            # (default-on), device-time columns once sampling has run
            # (None with MXTPU_PERF_ATTRIB=0 — the inert default rule)
            "perf": self._perf.statusz(),
            # per-step host-overhead decomposition: ring tail + phase
            # fractions + the perf↔epoch clock anchor timeline_report
            # stitches with ({"enabled": False} with
            # MXTPU_STEP_PROFILE=0 — this knob is default-on)
            "step_profile": self._sprof.statusz(),
            "max_batch": self.max_batch,
            "max_model_len": self.max_model_len,
            "programs_recorded": len(self._manifest.entries()),
            "request_trace": {"enabled": self._rtrace.enabled,
                              "sample": self._rtrace.sample,
                              "traced": self._rtrace.traced,
                              "written": self._rtrace.written,
                              "path": self._rtrace.path},
            "numeric_watch": self._numeric_watch,
            "aot": aot,
        }

    def perf_summary(self):
        """Compact performance-attribution summary — sampled dispatch
        count, MFU/achieved-TFLOP/s, flops-per-token and device cost
        per 1k tokens (None with ``MXTPU_PERF_ATTRIB=0``).  The
        ServeMonitor tail and the fleet replica scrape row read this."""
        return self._perf.summary()

    def adapter_info(self):
        """The ``/statusz`` ``adapters`` section: slot occupancy,
        refcounts and the loaded-adapter ids (None when off — the
        inert default)."""
        if not self._adapters:
            return None
        return self.adapter_store.stats()

    def sampling_info(self):
        """The ``/statusz`` ``sampling`` section: cap, engine defaults
        and the greedy-vs-stochastic spec acceptance split (None on
        greedy-only engines — the inert default)."""
        if not self._sampling:
            return None
        info = {"enabled": True, "sample_cap": self.sample_cap,
                "top_logprobs": TOP_LOGPROBS,
                "defaults": {"temperature": self.temperature,
                             "top_p": self.top_p, "top_k": self.top_k}}
        return info

    def quant_info(self):
        """The ``/statusz`` ``quant`` section: weight-only mode, KV
        dtype, and the byte savings each one buys (None when quantized
        serving is off entirely)."""
        if not self.quantize and not self._kv_quant:
            return None
        info = {"weights": self.quantize,
                "kv_dtype": str(self._cache_k.dtype)
                if self._cache_k is not None else None}
        if self.quantize and self.params:
            info["quantized_weights"] = sum(
                1 for k in self.params if k.endswith("_wscale"))
            info["weight_bytes"] = sum(
                int(v.nbytes) for k, v in self.params.items()
                if k.endswith("_weight") or k.endswith("_wscale"))
        if self._kv_quant and self._scale_k is not None:
            info["kv_scale_bytes"] = 2 * int(self._scale_k.nbytes)
        return info

    def host_block_spec(self):
        """Shapes/dtypes of ONE block's host-copy arrays — the layout
        ``_host_kv_fetch`` produces and the restore program consumes:
        K and V ``(layers, block_size, kv_heads, head_dim)`` in the
        cache dtype, plus the two f32 scale-slot arrays under int8 KV.
        This is the prefill→decode handoff wire decoder's contract: a
        receiving replica validates every record's raw bytes against
        these specs before trusting them."""
        L, bs = self._cfg.n_layers, self.block_size
        Hkv, Dh = self._cfg.kv_heads, self._cfg.head_dim
        dt = np.dtype(str(self._cache_k.dtype))
        specs = [((L, bs, Hkv, Dh), dt), ((L, bs, Hkv, Dh), dt)]
        if self._kv_quant:
            f32 = np.dtype(np.float32)
            specs += [((L, bs, Hkv), f32), ((L, bs, Hkv), f32)]
        return specs

    def host_kv_stats(self):
        """The ``/statusz`` ``host_kv`` section: DRAM budget and
        occupancy, offload/restore/eviction counters and the per-block
        host bytes (None when the tier is off).  The fleet replica's
        load signal reads the same snapshot — a replica whose host tier
        is saturated re-pays recompute on every further eviction."""
        if self._host_pool is None:
            return None
        out = self._host_pool.stats()
        # bytes one parked block costs in DRAM: K + V (+ scale slots)
        per_block = 2 * (self._cache_k.nbytes // self.num_blocks
                         if self._cache_k is not None else 0)
        if self._kv_quant and self._scale_k is not None:
            per_block += 2 * (self._scale_k.nbytes // self.num_blocks)
        out["block_bytes"] = int(per_block)
        return out

    def kv_summary(self):
        """The routable-cache advertisement: the BlockManager's
        ``RadixSummary`` snapshot (counting bloom over every published
        block key in both tiers + the top-K recently published chain
        keys; None with the prefix cache off).  Size-bounded and
        incremental — safe for the fleet replica to publish on every
        ``/healthz``/``/statusz`` scrape at any cache size."""
        return self.blocks.summary()

    def ingest_pulled_blocks(self, records, salt=None):
        """Land a peer-pulled KV chain in the host tier — the engine
        half of the fleet fabric's peer-to-peer pull.  ``records`` is
        the decoded handoff wire shape; ingestion is the SAME
        chain-hash-verified ``import_blocks`` path a prefill→decode
        handoff uses, so a truncated or corrupted pull breaks the
        chain and the suffix recomputes (degradation, never
        corruption).  Returns ``(imported, deduped, rejected)``."""
        return self.blocks.import_blocks(records, salt=salt)

    def sharding_info(self):
        """Live sharding layout: tp degree, mesh shape/devices, rule
        digest, and per-device HBM-resident parameter bytes — the
        /statusz "where do the bytes live" section (replicated arrays
        count once per device, which is exactly their real footprint)."""
        info = {"tp": self.tp,
                "rules_digest": self._rules_digest,
                "spec_digest": self._spec_digest}
        if self.mesh is not None:
            info["mesh"] = {
                "axes": {k: int(v) for k, v in self.mesh.shape.items()},
                "devices": [int(d.id) for d in self.mesh.devices.flat]}
        if self.params:
            info["params_bytes_per_device"] = statusz_mod.bytes_by_device(
                self.params.values())
        return info

    def kv_cache_stats(self):
        """KV-cache memory accounting, global and per chip.  Block
        ACCOUNTING never changes with tp — each chip holds
        ``kv_heads/tp`` of every block, so per-chip bytes (total and
        in-use) drop by the tp degree and the same per-chip HBM budget
        funds ``tp``x the blocks."""
        if self._cache_k is None:
            return None
        total = 2 * int(self._cache_k.nbytes)          # K and V
        per_dev = total // self.tp
        per_block = per_dev // self.num_blocks
        out = {"bytes_total": total,
               "bytes_per_device": per_dev,
               "bytes_per_block_per_device": per_block,
               "bytes_in_use_per_device":
                   per_block * self.blocks.blocks_in_use,
               "dtype": str(self._cache_k.dtype)}
        if self._kv_quant:
            # the dequantization scales are real HBM too: the honest
            # per-chip KV footprint is bytes + scale_bytes — an f32
            # scale per head_dim int8 elements, so the reduction is
            # dtype_bytes / (1 + 4/head_dim): at head_dim 64 that is
            # 3.76x from f32 and 1.88x from bf16 (the CPU smoke's
            # 3.56x is f32 at head_dim 32)
            sb = 2 * int(self._scale_k.nbytes)
            out["scale_bytes_total"] = sb
            out["scale_bytes_per_device"] = sb // self.tp
        return out

    def shutdown(self):
        """Cancel in-flight work and release the device cache.

        Device buffers this engine materialized — sharded or
        replicated parameter placements and the KV cache — are deleted
        explicitly (not left to GC), so constructing engines
        back-to-back in one process can never transiently hold two
        models' HBM.  Arrays the caller passed in that were adopted
        as-is are never touched."""
        if not self._alive:
            return
        for req in (list(self.scheduler.running)
                    + list(self.scheduler.prefilling)):
            self.scheduler.finish(req, status=CANCELLED)
        for req in self.scheduler.drain_waiting():
            req.status = CANCELLED
            req.finish_t = self.clock()
            self._rtrace.terminal(req, CANCELLED)
        with self._fanout_lock:
            pending, self._pending_fanout = self._pending_fanout, []
        for _, sibs in pending:
            # n>1 siblings still engine-side (their primary never
            # finished prefill) resolve like drained waiters
            for s in sibs:
                s.status = CANCELLED
                s.finish_t = self.clock()
                self._rtrace.terminal(s, CANCELLED)
        self._rtrace.close()
        statusz_mod.unregister(self._statusz_name)
        if self._spec is not None:
            self._spec.shutdown()
            self._spec = None
        for arr in (self._owned + [self._cache_k, self._cache_v]
                    + ([self._scale_k, self._scale_v]
                       if self._scale_k is not None else [])):
            try:
                arr.delete()
            except (RuntimeError, ValueError):
                pass              # already donated-away or deleted
        self._owned = []
        self._cache_k = self._cache_v = None
        self._scale_k = self._scale_v = None
        if self._host_pool is not None:
            # the DRAM tier releases WITH the device buffers: two
            # engines back-to-back must never transiently hold two
            # host pools' worth of parked K/V either
            self._host_pool.clear()
            self._host_pool = None
        self.params = None            # free the device-resident weights
        self._alive = False

    # -- execution -----------------------------------------------------------
    def _req_sampling_operands(self, req):
        """(1,)-shaped per-request sampling operands for the prefill
        and chunk programs (empty on greedy-only engines — their
        program signatures are the historical ones)."""
        if not self._sampling:
            return ()
        return (jnp.asarray([req.temperature], jnp.float32),
                jnp.asarray([req.top_p], jnp.float32),
                jnp.asarray([req.top_k or 0], jnp.int32))

    def _batch_sampling_operands(self, reqs, bucket):
        """(B,)-shaped per-SLOT sampling operands for the decode /
        draft / verify programs — THE tentpole mechanism: temperature,
        top-p and top-k ride the batch as data, so one bucketed
        program serves any mix of sampling configs with zero fresh
        traces (padding rows are greedy — harmless, their outputs are
        dropped)."""
        if not self._sampling:
            return ()
        temp = np.zeros(bucket, np.float32)
        topp = np.ones(bucket, np.float32)
        topk = np.zeros(bucket, np.int32)
        for i, req in enumerate(reqs):
            temp[i] = req.temperature
            topp[i] = req.top_p
            topk[i] = req.top_k or 0
        return (jnp.asarray(temp), jnp.asarray(topp), jnp.asarray(topk))

    def _adapter_args(self):
        """The LoRA device-stack operand every target-model program
        takes right after the params (empty on adapters-off engines —
        their program signatures are the historical ones)."""
        if not self._adapters:
            return ()
        return (self.adapter_store.device,)

    def _req_adapter_operand(self, req):
        """Scalar adapter-slot operand for the prefill/chunk programs
        (empty when off)."""
        if not self._adapters:
            return ()
        return (jnp.asarray(req.adapter_slot, jnp.int32),)

    def _batch_adapter_operands(self, reqs, bucket):
        """(B,)-shaped per-slot adapter indices for the decode/verify
        programs — the second traced-operand family after sampling:
        each row gathers its own A/B slices, so one bucketed program
        serves any adapter mix (padding and base rows are slot 0, the
        true zero delta)."""
        if not self._adapters:
            return ()
        slots = np.zeros(bucket, np.int32)
        for i, req in enumerate(reqs):
            slots[i] = req.adapter_slot
        return (jnp.asarray(slots),)

    def _note_logprobs(self, req, chosen, tv, ti):
        """Record emitted tokens' logprob outputs on the request: the
        chosen-token logprob always (sampling mode), the top view
        trimmed to the request's ``logprobs`` ask."""
        for j in range(len(chosen)):
            # mxtpu-lint: disable=host-sync (host numpy already: the
            # logprob views arrived in _unpack_outs's batched read)
            req.token_logprobs.append(float(chosen[j]))
            if req.logprobs:
                req.top_logprobs.append(
                    # mxtpu-lint: disable=host-sync (host numpy
                    # already — same batched read as above)
                    [[int(t), float(v)]
                     for t, v in zip(ti[j][:req.logprobs],
                                     tv[j][:req.logprobs])])

    def _unpack_outs(self, outs, n_lead, anomaly, **fields):
        """Split a program's output tuple: adopt the donated-through
        caches, bring the ``n_lead`` host-bound outputs (sampled
        tokens, and in sampling mode the logprob views) to the host in
        ONE batched read, and fire the numeric-watchdog anomaly when
        the logits-finite flag rode along false."""
        if self._cfg.numeric_watch:
            lead, ok = outs[:n_lead], outs[n_lead]
            self._set_caches(outs[n_lead + 1:])
            # one batched read: the sampled tokens must reach the host
            # anyway, so the watchdog flag rides the same sync instead
            # of forcing a second one
            # mxtpu-lint: disable=host-sync (designed sync point: the
            # scheduler needs the sampled tokens on the host)
            got = jax.device_get(tuple(lead) + (ok,))
            if not got[-1]:
                flight_mod.record_anomaly(anomaly, step=self._step_id,
                                          **fields)
            return got[:-1]
        self._set_caches(outs[n_lead:])
        # mxtpu-lint: disable=host-sync (designed sync point: the
        # scheduler needs the sampled tokens on the host)
        return jax.device_get(tuple(outs[:n_lead]))

    def _cache_args(self):
        """The device cache operands every target-model program takes:
        (k, v) — plus the int8-KV scale pair when quantized (the same
        order the program builders and ``_program_specs`` use)."""
        if self._kv_quant:
            return (self._cache_k, self._cache_v,
                    self._scale_k, self._scale_v)
        return (self._cache_k, self._cache_v)

    def _set_caches(self, arrs):
        """Adopt a program's returned (donated-through) cache operands
        — the tail of its output tuple, mirroring :meth:`_cache_args`."""
        if self._kv_quant:
            (self._cache_k, self._cache_v,
             self._scale_k, self._scale_v) = arrs
        else:
            self._cache_k, self._cache_v = arrs

    def _host_kv_fetch(self, blk):
        """Device→host copy of ONE block's K/V (and int8 scale slots)
        for the offload tier — called by the BlockManager's prefix-LRU
        eviction just before the device block is recycled.  The copies
        start asynchronously and the sync covers one block only (tens
        of KB), a bounded, designed cost on the eviction path; under tp
        the gather round-trips each chip's head shard into one full
        host block."""
        if self._cache_k is None:
            return None
        parts = [self._cache_k[:, blk], self._cache_v[:, blk]]
        if self._kv_quant:
            parts += [self._scale_k[:, blk], self._scale_v[:, blk]]
        for a in parts:
            start = getattr(a, "copy_to_host_async", None)
            if start is not None:
                start()
        # mxtpu-lint: disable=host-sync (designed sync point: the
        # evicted block's bytes must reach DRAM before its device
        # buffer is reused — one small bounded copy per eviction)
        return tuple(np.asarray(a) for a in parts)

    @hot_path
    def _restore_pending(self):
        """Dispatch the queued host→device restores as ONE bucketed
        ``restore`` program per batch: the copies ride the async
        dispatch stream AHEAD of this iteration's prefill/decode
        programs, so the cache dataflow (the restored arrays feed the
        next program's cache operands) fences them before the first
        read and the step loop never blocks on a copy."""
        pending = self.blocks.take_pending_restores()
        if not pending:
            return
        L, bs = self._cfg.n_layers, self.block_size
        Hkv, Dh = self._cfg.kv_heads, self._cfg.head_dim
        cap = self.table_width
        while pending:
            batch, pending = pending[:cap], pending[cap:]
            bucket = _next_bucket(len(batch), cap)
            blks = np.zeros(bucket, np.int32)   # pad rows -> null block
            hk = np.zeros((L, bucket, bs, Hkv, Dh), self._cache_k.dtype)
            hv = np.zeros_like(hk)
            if self._kv_quant:
                hks = np.zeros((L, bucket, bs, Hkv), np.float32)
                hvs = np.zeros_like(hks)
            for i, (blk, arrays) in enumerate(batch):
                blks[i] = blk
                hk[:, i] = arrays[0]
                hv[:, i] = arrays[1]
                if self._kv_quant:
                    hks[:, i] = arrays[2]
                    hvs[:, i] = arrays[3]
            args = self._cache_args() + (jnp.asarray(blks),
                                         jnp.asarray(hk),
                                         jnp.asarray(hv))
            if self._kv_quant:
                args += (jnp.asarray(hks), jnp.asarray(hvs))
            with telemetry.span("serve.host_kv_restore",
                                blocks=len(batch)):
                t0 = self._perf.t0()
                outs = self._program("restore", bucket)(*args)
                self._perf.done(t0, "restore", bucket, outs)
                self._set_caches(outs)

    def _slots(self, table, n, pad_to):
        """(block, offset) scatter targets for logical slots [0, n),
        padded to ``pad_to`` with null-block writes."""
        blk = np.zeros(pad_to, np.int32)
        off = np.arange(pad_to, dtype=np.int32) % self.block_size
        pos = np.arange(n)
        # mxtpu-lint: disable=host-sync (block tables are host lists —
        # pure host-side scatter-target math, no device values)
        blk[:n] = np.asarray(table, np.int32)[pos // self.block_size]
        return blk, off

    @hot_path
    def _run_prefill(self, req, decode_slots=0):
        """Run one prefill pass for ``req``: the whole uncached suffix
        (cold path, or a prefix-cache hit's remainder), or — when the
        scheduler put it in the chunked-prefill lane — ONE budget-sized
        chunk.  Returns the tokens emitted (1 on the pass that samples
        the first token, 0 for an intermediate chunk)."""
        ids = req.prefill_ids()
        n = int(ids.size)
        start = int(req.cache_len)     # cached prefix + finished chunks
        resume = req.n_preemptions > 0
        chunked = self.scheduler.is_prefilling(req)
        if chunked:
            budget = max(1, self.scheduler.prefill_chunk - decode_slots)
            end = min(n, start + budget)
        else:
            end = n
        if not req._prefill_started:
            req._prefill_started = True
            self._rtrace.event(req, "prefill_start", tokens=int(n - start),
                               cached=start, chunked=chunked,
                               resume=resume)
        span = end - start
        self._key, sub = jax.random.split(self._key)
        if start == 0 and end == n:
            # cold whole-prompt pass: the dense O(n^2)-attention
            # program (exactly the pre-prefix-cache path)
            bucket = _next_bucket(n, self.max_model_len)
            toks = np.zeros(bucket, np.int32)
            toks[:n] = ids
            blk, off = self._slots(self.blocks.table(req.rid), n, bucket)
            pkind = "prefill"
            fn = self._prefill_fn(bucket)
            args = (self.params,) + self._adapter_args() \
                + self._cache_args() + (
                    jnp.asarray(toks), jnp.asarray(n, jnp.int32),
                    jnp.asarray(blk), jnp.asarray(off)) \
                + self._req_adapter_operand(req) \
                + self._req_sampling_operands(req) + (sub,)
        else:
            # suffix/chunk pass: positions [start, end) attend through
            # the block table to the K/V already in the cache (cached
            # prefix + earlier chunks) — cached positions are never
            # recomputed and shared blocks are never written
            bucket = _next_bucket(span, self._chunk_cap())
            toks = np.zeros(bucket, np.int32)
            toks[:span] = ids[start:end]
            table = self.blocks.table(req.rid)
            tw = np.zeros(self.table_width, np.int32)
            tw[:len(table)] = table
            pos = start + np.arange(span)
            blk = np.zeros(bucket, np.int32)   # padded rows -> null blk
            blk[:span] = tw[pos // self.block_size]
            off = ((start + np.arange(bucket))
                   % self.block_size).astype(np.int32)
            pkind = "chunk"
            fn = self._chunk_fn(bucket)
            args = (self.params,) + self._adapter_args() \
                + self._cache_args() + (
                    jnp.asarray(toks), jnp.asarray(start, jnp.int32),
                    jnp.asarray(span, jnp.int32), jnp.asarray(tw),
                    jnp.asarray(blk), jnp.asarray(off)) \
                + self._req_adapter_operand(req) \
                + self._req_sampling_operands(req) + (sub,)
        t0 = self._perf.t0()
        outs = fn(*args)
        self._perf.done(t0, pkind, bucket, outs)
        self._sprof.lap("prefill_dispatch")
        lead = self._unpack_outs(outs, 4 if self._sampling else 1,
                                 "prefill_logits", rid=req.rid)
        self._sprof.lap("device_wait")
        tok = lead[0]
        req.cache_len = end
        self._stats.on_prefill(span)
        # publish the newly-FULL blocks under their chain keys so later
        # prompts (or this request's own post-preemption resume) can
        # reuse them — host-side dict work only
        # the request's adapter id salts the chain: adapter K/V is
        # content-disjoint from base (and other-adapter) K/V
        self.blocks.note_tokens(req.rid, ids[:end], salt=req.adapter_id)
        if end < n:
            # intermediate chunk: the sampled token is bogus (mid-
            # prompt) and dropped; the request stays in the prefilling
            # lane and owns the next iteration's prefill budget
            self._rtrace.event(req, "prefill_chunk", done=int(end),
                               target=int(n), tokens=int(span))
            self._sprof.lap("host_sync")
            return 0
        self._rtrace.event(req, "prefill_end", tokens=int(n - start),
                           resume=resume)
        self.scheduler.prefill_done(req)
        self.scheduler.admit_running(req)
        now = self.clock()
        if req.first_token_t is None:
            req.first_token_t = now
            self._stats.on_first_token(req.ttft() or 0.0)
        else:
            # resume prefill after preemption: the re-emitted token's
            # gap (spanning the preempted wait) IS the client-visible
            # inter-token latency — it belongs in the TPOT tail
            self._stats.on_tokens(req, 1, now=now)
        req.tokens.append(int(tok))
        if self._sampling:
            self._note_logprobs(req, [lead[1]], [lead[2]], [lead[3]])
        self._maybe_finish(req)
        self._sprof.lap("host_sync")
        return 1

    @hot_path
    def _run_decode(self, reqs):
        B = len(reqs)
        bucket = _next_bucket(B, self.max_batch)
        toks = np.zeros(bucket, np.int32)
        pos = np.zeros(bucket, np.int32)
        tables = np.zeros((bucket, self.table_width), np.int32)
        for i, req in enumerate(reqs):
            toks[i] = req.tokens[-1]
            pos[i] = req.cache_len
            t = self.blocks.table(req.rid)
            tables[i, :len(t)] = t
        fn = self._decode_fn(bucket)
        self._key, sub = jax.random.split(self._key)
        t0 = self._perf.t0()
        outs = fn(self.params, *self._adapter_args(),
                  *self._cache_args(),
                  jnp.asarray(toks), jnp.asarray(pos),
                  jnp.asarray(tables),
                  *self._batch_adapter_operands(reqs, bucket),
                  *self._batch_sampling_operands(reqs, bucket), sub)
        self._perf.done(t0, "decode", bucket, outs)
        self._sprof.lap("decode_dispatch")
        lead = self._unpack_outs(outs, 4 if self._sampling else 1,
                                 "decode_logits", batch_size=B,
                                 rids=[r.rid for r in reqs])
        self._sprof.lap("device_wait")
        out = lead[0]
        now = self.clock()
        for i, req in enumerate(reqs):
            req.cache_len += 1
            req.tokens.append(int(out[i]))
            if self._sampling:
                self._note_logprobs(req, lead[1][i:i + 1],
                                    lead[2][i:i + 1], lead[3][i:i + 1])
            self._stats.on_tokens(req, 1, now=now)
            self._rtrace.event(req, "decode", batch=self._step_id,
                               batch_size=B, tokens=len(req.tokens),
                               emitted=1)
            self._maybe_finish(req)
        self._sprof.lap("host_sync")
        return B

    def _spec_ingest(self, req):
        """Bring the draft cache up to date with ``req``'s context —
        positions ``[0, cache_len)`` run through the draft model's
        chunk program in one dispatch.  Needed at admission and after
        a preemption-resume (the draft side re-ingests into the new
        block table; a prefix-cache hit's shared blocks are simply
        rewritten with recomputed values, which can only perturb the
        ACCEPTANCE rate of other sharers, never any emitted token)."""
        span = self._spec.context_gap(req)
        if span <= 0:
            return
        ids = req.prefill_ids()[:span]
        bucket = _next_bucket(span, self.max_model_len)
        toks = np.zeros(bucket, np.int32)
        toks[:span] = ids
        table = self.blocks.table(req.rid)
        tw = np.zeros(self.table_width, np.int32)
        tw[:len(table)] = table
        pos = np.arange(span)
        blk = np.zeros(bucket, np.int32)       # padded rows -> null blk
        blk[:span] = tw[pos // self.block_size]
        off = (np.arange(bucket) % self.block_size).astype(np.int32)
        self._key, sub = jax.random.split(self._key)
        sw = self._spec
        with telemetry.span("serve.spec_ingest", rid=req.rid,
                            tokens=span):
            # the chunk program built over the DRAFT config: same
            # write-then-attend body, draft params and draft caches
            t0 = self._perf.t0()
            outs = self._program("draft_chunk", bucket)(
                sw.params, sw.cache_k, sw.cache_v,
                jnp.asarray(toks), jnp.asarray(0, jnp.int32),
                jnp.asarray(span, jnp.int32), jnp.asarray(tw),
                jnp.asarray(blk), jnp.asarray(off), sub)
            self._perf.done(t0, "draft_chunk", bucket, outs)
            _, sw.cache_k, sw.cache_v = outs
        sw.note_ingested(req, span)

    @hot_path
    def _run_spec_decode(self, reqs):
        """One speculative decode iteration over the batch: one draft
        dispatch proposes ``spec_k`` tokens per request, one verify
        dispatch scores all ``k+1`` positions through the block tables,
        and acceptance emits between 1 and ``k+1`` tokens per request.

        Greedy engines use exact argmax-prefix acceptance (host-side
        ``accept_greedy`` — byte-identical to plain decode).  Sampling
        engines use REJECTION-SAMPLING acceptance (Leviathan/Chen
        2023), entirely on device: the draft SAMPLES each proposal
        from its warped distribution q and ships q with the tokens
        (device-to-device), the verify accepts draft j with prob
        ``min(1, p/q)`` and resamples the first rejection from the
        normalized residual ``max(p - q, 0)`` — the emitted stream is
        distribution-identical to plain sampling from p, whatever the
        draft proposes (greedy rows degenerate to argmax-prefix
        acceptance exactly: p and q are one-hot there)."""
        B = len(reqs)
        k = self.spec_k
        sw = self._spec
        for req in reqs:
            self._spec_ingest(req)
        bucket = _next_bucket(B, self.max_batch)
        toks = np.zeros(bucket, np.int32)
        pos = np.zeros(bucket, np.int32)
        tables = np.zeros((bucket, self.table_width), np.int32)
        for i, req in enumerate(reqs):
            toks[i] = req.tokens[-1]
            pos[i] = req.cache_len
            t = self.blocks.table(req.rid)
            tables[i, :len(t)] = t
        jp, jtab = jnp.asarray(pos), jnp.asarray(tables)
        self._key, sub = jax.random.split(self._key)
        if self._sampling:
            samp = self._batch_sampling_operands(reqs, bucket)
            with telemetry.span("serve.draft", batch=B, k=k):
                t0 = self._perf.t0()
                douts = self._draft_fn(bucket)(
                    sw.params, sw.cache_k, sw.cache_v,
                    jnp.asarray(toks), jp, jtab, *samp, sub)
                self._perf.done(t0, "draft", bucket, douts)
                drafted, q_at, q_vals, q_idx, sw.cache_k, sw.cache_v = \
                    douts
            self._sprof.lap("decode_dispatch")
            # drafted ids and their candidate-space q views stay ON
            # DEVICE: acceptance runs inside the verify program, so
            # the only host sync this iteration is the emitted rows
            fn = self._verify_fn(bucket)
            self._key, sub = jax.random.split(self._key)
            with telemetry.span("serve.verify", batch=B, k=k):
                t0 = self._perf.t0()
                outs = fn(self.params, *self._adapter_args(),
                          *self._cache_args(),
                          jnp.asarray(toks), drafted, q_at, q_vals,
                          q_idx, jp, jtab,
                          *self._batch_adapter_operands(reqs, bucket),
                          *samp, sub)
                self._perf.done(t0, "verify", bucket, outs)
                self._sprof.lap("decode_dispatch")
                emit_rows, acc, lp, tv, ti = self._unpack_outs(
                    outs, 5, "verify_logits", batch_size=B,
                    rids=[r.rid for r in reqs])
                self._sprof.lap("device_wait")
            emitted = 0
            now = self.clock()
            for i, req in enumerate(reqs):
                accepted = int(acc[i])
                emit = [int(x) for x in emit_rows[i][:accepted + 1]]
                # the verify wrote every candidate position's K/V —
                # the draft loop did too, so the next draft never has
                # an ingest gap
                sw.note_drafted(req, int(pos[i]) + k + 1)
                emit = emit[:req.max_new_tokens - len(req.tokens)]
                accepted = min(accepted, len(emit))
                sw.on_verify(k, accepted)
                self._stats.on_verify(k, accepted,
                                      stochastic=req.temperature > 0.0)
                req.tokens.extend(emit)
                self._note_logprobs(req, lp[i][:len(emit)],
                                    tv[i][:len(emit)],
                                    ti[i][:len(emit)])
                req.cache_len += len(emit)
                emitted += len(emit)
                self._stats.on_tokens(req, len(emit), now=now)
                self._rtrace.event(req, "decode", batch=self._step_id,
                                   batch_size=B,
                                   tokens=len(req.tokens),
                                   emitted=len(emit), accepted=accepted)
                self._maybe_finish(req)
                if req.done:
                    sw.forget(req.rid)
                else:
                    self.blocks.truncate(req.rid, req.cache_len)
            self._sprof.lap("host_sync")
            return emitted
        with telemetry.span("serve.draft", batch=B, k=k):
            t0 = self._perf.t0()
            douts = self._draft_fn(bucket)(
                sw.params, sw.cache_k, sw.cache_v, jnp.asarray(toks),
                jp, jtab, sub)
            self._perf.done(t0, "draft", bucket, douts)
            drafted, sw.cache_k, sw.cache_v = douts
            self._sprof.lap("decode_dispatch")
            # mxtpu-lint: disable=host-sync (designed sync point: the
            # drafted ids feed the verify dispatch's host-built rows)
            drafted = np.asarray(drafted)
            self._sprof.lap("device_wait")
        rows = np.zeros((bucket, k + 1), np.int32)
        rows[:, 0] = toks
        rows[:, 1:] = drafted
        fn = self._verify_fn(bucket)
        self._key, sub = jax.random.split(self._key)
        with telemetry.span("serve.verify", batch=B, k=k):
            t0 = self._perf.t0()
            outs = fn(self.params, *self._adapter_args(),
                      *self._cache_args(),
                      jnp.asarray(rows), jp, jtab,
                      *self._batch_adapter_operands(reqs, bucket), sub)
            self._perf.done(t0, "verify", bucket, outs)
            if self._cfg.numeric_watch:
                out, ok = outs[0], outs[1]
                self._set_caches(outs[2:])
                # one batched read for tokens + watchdog flag
                # mxtpu-lint: disable=host-sync (designed sync point:
                # acceptance needs the target tokens on the host)
                out, ok = jax.device_get((out, ok))
                if not ok:
                    flight_mod.record_anomaly(
                        "verify_logits", step=self._step_id,
                        batch_size=B, rids=[r.rid for r in reqs])
            else:
                out = outs[0]
                self._set_caches(outs[1:])
                # mxtpu-lint: disable=host-sync (designed sync point:
                # acceptance needs the target tokens on the host)
                out = np.asarray(out)
        self._sprof.lap("device_wait")
        emitted = 0
        for i, req in enumerate(reqs):
            accepted, emit = spec_mod.accept_greedy(drafted[i], out[i], k)
            # the verify wrote every candidate position's K/V — the
            # draft loop did too, so the next draft never has a gap
            sw.note_drafted(req, int(pos[i]) + k + 1)
            # a run that would overshoot the generation quota is capped
            # exactly where plain decode would have stopped
            emit = emit[:req.max_new_tokens - len(req.tokens)]
            # acceptance accounting counts only drafts that were
            # actually EMITTED — a quota-capped final iteration must
            # not inflate the rate with agreed-but-discarded drafts
            accepted = min(accepted, len(emit))
            sw.on_verify(k, accepted)
            self._stats.on_verify(k, accepted)
            req.tokens.extend(emit)
            req.cache_len += len(emit)
            emitted += len(emit)
            self._stats.on_tokens(req, len(emit))
            self._rtrace.event(req, "decode", batch=self._step_id,
                               batch_size=B, tokens=len(req.tokens),
                               emitted=len(emit), accepted=accepted)
            self._maybe_finish(req)
            if req.done:
                sw.forget(req.rid)
            else:
                # roll back the speculative tail: blocks reserved past
                # the accepted sequence return to the free list (never
                # a shared prefix block — truncate stops at refcount>1)
                self.blocks.truncate(req.rid, req.cache_len)
        self._sprof.lap("host_sync")
        return emitted

    def _maybe_finish(self, req):
        if len(req.tokens) >= req.max_new_tokens:
            self.scheduler.finish(req, status=FINISHED)
            self._stats.on_complete(req)

    # -- AOT warmup / manifests (mxnet_tpu/aot/) -----------------------------
    def manifest(self):
        """The (kind, bucket) programs this engine has executed so far
        — the traffic-replay warmup manifest (list of entry dicts)."""
        return self._manifest.entries()

    def save_manifest(self, path):
        """Write the manifest as JSONL for a later ``warmup(path)``."""
        with open(path, "w") as f:
            for e in self._manifest.entries():
                f.write(json.dumps(e) + "\n")
        return path

    def warmup(self, manifest=None):
        """Compile (or AOT-load) every program ``manifest`` lists,
        before traffic arrives.

        ``manifest`` is a JSONL path, an iterable of entry dicts
        (another engine's :meth:`manifest`), or None — which replays
        ``MXTPU_WARMUP_MANIFEST`` when set, else warms the full bucket
        grid (every decode batch bucket and power-of-two prompt bucket
        this config can serve).  Entries recorded by an incompatibly-
        configured engine, or outside this engine's bucket range, are
        skipped.  Returns the number of programs made ready.
        """
        if not self._alive:
            raise RuntimeError("engine is shut down")
        entries = aot_warmup.load_manifest(manifest, self._spec_digest)
        if not entries and manifest is None:
            entries = self._warmup_grid()
        elif self._host_pool is not None:
            # the host tier shares the tier-off engines' programs AND
            # fingerprints (it changes no existing program), so a
            # manifest recorded by a tier-off predecessor replays
            # cleanly — but it lists no restore programs.  Force the
            # (small) restore ladder in, or the first host-tier radix
            # hit after an upgrade would trace mid-step
            entries = list(entries) + [
                {"kind": "restore", "bucket": b}
                for b in self._bucket_ladder(self.table_width)]
        ready = 0
        self._warming = True   # warmup must not re-record the manifest
        try:
            with telemetry.span("serve.warmup", programs=len(entries)):
                for e in entries:
                    kind, bucket = e["kind"], int(e["bucket"])
                    if kind == "decode" and 1 <= bucket <= self.max_batch:
                        self._decode_fn(_next_bucket(bucket, self.max_batch))
                    elif (kind == "prefill"
                          and 1 <= bucket <= self.max_model_len):
                        self._prefill_fn(
                            _next_bucket(bucket, self.max_model_len))
                    elif (kind == "chunk"
                          and 1 <= bucket <= self._chunk_cap()):
                        self._chunk_fn(
                            _next_bucket(bucket, self._chunk_cap()))
                    elif (kind in ("verify", "draft")
                          and self._spec is not None
                          and 1 <= bucket <= self.max_batch):
                        self._program(kind,
                                      _next_bucket(bucket, self.max_batch))
                    elif (kind == "draft_chunk"
                          and self._spec is not None
                          and 1 <= bucket <= self.max_model_len):
                        self._program(
                            "draft_chunk",
                            _next_bucket(bucket, self.max_model_len))
                    elif (kind == "restore"
                          and self._host_pool is not None
                          and 1 <= bucket <= self.table_width):
                        self._program(
                            "restore",
                            _next_bucket(bucket, self.table_width))
                    else:
                        continue
                    ready += 1
        finally:
            self._warming = False
        return ready

    def _warmup_grid(self):
        """Every program this config can ever run: the offline pre-bake
        default when no traffic manifest exists yet.  Reachable buckets
        are the powers of two below each cap PLUS the cap itself —
        ``_next_bucket`` clamps, so a non-power-of-two cap is a real
        bucket live traffic hits."""
        buckets = self._bucket_ladder
        grid = ([{"kind": "decode", "bucket": b}
                 for b in buckets(self.max_batch)]
                + [{"kind": "prefill", "bucket": p}
                   for p in buckets(self.max_model_len)]
                # suffix/chunk prefills (prefix-cache hits + chunked
                # long prompts) run their own program family — a warm
                # restart must be zero-fresh-trace for those too
                + [{"kind": "chunk", "bucket": c}
                   for c in buckets(self._chunk_cap())])
        if self._spec is not None:
            # speculative decoding adds three families: the target
            # verify pass and the draft's propose/ingest programs — a
            # spec-enabled warm restart must be zero-fresh-trace too
            grid += ([{"kind": "verify", "bucket": b}
                      for b in buckets(self.max_batch)]
                     + [{"kind": "draft", "bucket": b}
                        for b in buckets(self.max_batch)]
                     + [{"kind": "draft_chunk", "bucket": c}
                        for c in buckets(self.max_model_len)])
        if self._host_pool is not None:
            # the host tier's restore family exists ONLY when the tier
            # is on (the only-when-on rule: a tier-off engine's grid,
            # manifests and fingerprints are untouched)
            grid += [{"kind": "restore", "bucket": b}
                     for b in buckets(self.table_width)]
        return grid

    # -- compiled programs ---------------------------------------------------
    def _decode_fn(self, B):
        return self._program("decode", B)

    def _prefill_fn(self, P):
        return self._program("prefill", P)

    def _chunk_fn(self, C):
        return self._program("chunk", C)

    def _verify_fn(self, B):
        return self._program("verify", B)

    def _draft_fn(self, B):
        return self._program("draft", B)

    @staticmethod
    def _bucket_ladder(cap):
        """Power-of-two buckets up to (and always including) ``cap`` —
        THE bucket enumeration: the warmup grid and every bucket view
        (statusz verify_buckets) must agree with what live traffic's
        ``_next_bucket`` clamp can hit."""
        out, b = [], 1
        while b < cap:
            out.append(b)
            b *= 2
        return out + [cap]

    def verify_buckets(self):
        """The verify program family's bucket grid (empty when
        speculative decoding is off) — the /statusz ``spec`` section's
        'which programs exist' view."""
        if self._spec is None:
            return []
        return self._bucket_ladder(self.max_batch)

    def _chunk_cap(self):
        """Largest chunk-program bucket live traffic can hit.  With
        chunking on, a non-chunked suffix is <= prefill_chunk by the
        scheduler's lane test and a chunk is <= the budget; with
        chunking off only prefix-hit suffixes use the chunk program,
        and those can reach the full model length."""
        chunk = self.scheduler.prefill_chunk
        if chunk > 0:
            return _next_bucket(chunk, self.max_model_len)
        return self.max_model_len

    def _program(self, kind, bucket):
        key = (self._spec_key(), kind, bucket)
        fn = _STEP_CACHE.get(key)
        if fn is None:
            fn = self._resolve_program(kind, bucket)
            _STEP_CACHE[key] = fn
        if not self._warming:
            self._manifest.record(kind, bucket)
        if self._perf.enabled and self._perf.cost(kind, bucket) is None:
            # cost-table capture sits HERE — the one chokepoint all
            # three resolve paths share (fresh trace, warm AOT load,
            # and a process-local _STEP_CACHE hit from a twin engine),
            # so a warm-started engine never reports an empty perf
            # section.  Idempotent per (kind, bucket): after the first
            # capture this is one dict probe per dispatch.
            af, ab = self._analytic_cost(kind, bucket)
            self._perf.note_cost(kind, bucket, fn,
                                 fallback_flops=af, fallback_bytes=ab)
        return fn

    def _analytic_cost(self, kind, bucket):
        """Analytic (flops, bytes) estimate for one (kind, bucket)
        dispatch, from the GQA-aware closed forms in ``flops.py`` over
        the PADDED program shapes (bucket rows, table-capacity
        context).  The cost-table fallback when a backend exposes no
        ``cost_analysis()``, and the cross-check pinned against it in
        tests/test_perf_contract.py."""
        from .. import flops as flops_mod

        if kind in ("draft", "draft_chunk") and self._spec is not None:
            cfg, params = self._spec.cfg, self._spec.params
        else:
            cfg, params = self._cfg, self.params
        try:
            tok_w = params[f"{cfg.name}_tok_embed_weight"]
            ffw = params.get(f"{cfg.name}_l0_ff_up_weight")
            kw = dict(n_layers=cfg.n_layers,
                      d_model=int(tok_w.shape[1]),
                      num_heads=cfg.num_heads, head_dim=cfg.head_dim,
                      kv_heads=cfg.kv_heads, vocab=int(tok_w.shape[0]),
                      d_ff=int(ffw.shape[0]) if ffw is not None else None,
                      swiglu=cfg.swiglu)
        except Exception:
            return None, None          # params already freed (shutdown)
        ctx = self.table_width * self.block_size
        per_tok = flops_mod.gpt_token_flops(context=ctx, **kw)
        if kind == "prefill":
            return flops_mod.gpt_prefill_flops(seq_len=bucket, **kw), None
        if kind in ("chunk", "draft_chunk"):
            return bucket * per_tok, None
        if kind == "verify":
            return bucket * (self.spec_k + 1) * per_tok, None
        if kind == "draft":
            return bucket * self.spec_k * per_tok, None
        if kind == "restore":
            # pure copy program: no matmuls — bytes are the K+V block
            # payload in and out (the MBU numerator)
            L, bs = self._cfg.n_layers, self.block_size
            Hkv, Dh = self._cfg.kv_heads, self._cfg.head_dim
            payload = (2 * L * bucket * bs * Hkv * Dh
                       * self._cache_k.dtype.itemsize)
            return None, 2 * payload
        return bucket * per_tok, None      # decode

    def _program_specs(self, kind, bucket):
        """ShapeDtypeStructs matching exactly what _run_prefill /
        _run_decode pass — the export/AOT-compile signature.  Under
        tensor parallelism each spec carries its NamedSharding: that is
        what lets ``.lower(specs).compile()`` AOT-compile the sharded
        program (and export/reload it) without example arrays."""
        i32 = jnp.dtype(jnp.int32)
        sh = self._shardings

        def sds(shape, dtype, sharding=None):
            if sh is None:
                return jax.ShapeDtypeStruct(shape, dtype)
            return jax.ShapeDtypeStruct(shape, dtype,
                                        sharding=sharding or sh.rep)

        kspec = sds(self._key.shape, self._key.dtype)
        f32 = jnp.dtype(jnp.float32)

        def samp(shape):
            # the sampling-mode programs' per-request operand triple
            # (temperature, top_p, top_k) — absent on greedy engines,
            # whose program signatures are the historical ones
            if not self._cfg.sampling:
                return ()
            return (sds(shape, f32), sds(shape, f32), sds(shape, i32))

        def adp():
            # the LoRA device-stack pytree right after the params —
            # absent on adapters-off engines (historical signatures)
            if not self._cfg.adapters:
                return ()
            ash = self.adapter_store.sharding or {}
            return ({k: sds(v.shape, v.dtype,
                            ash.get(k) if sh is not None else None)
                     for k, v in self.adapter_store.device.items()},)

        def aslot(shape):
            # the per-request adapter-slot index operand (scalar for
            # prefill/chunk, (B,) for decode/verify)
            if not self._cfg.adapters:
                return ()
            return (sds(shape, i32),)

        if kind in ("draft", "draft_chunk"):
            # draft-side programs: the draft checkpoint's params and
            # its own (replicated-under-tp) cache pair, the target's
            # table geometry
            sw = self._spec
            dpspec = {k: sds(v.shape, v.dtype)
                      for k, v in sw.params.items()}
            dcspec = sds(sw.cache_k.shape, sw.cache_k.dtype)
            if kind == "draft":
                return (dpspec, dcspec, dcspec, sds((bucket,), i32),
                        sds((bucket,), i32),
                        sds((bucket, self.table_width), i32)) \
                    + samp((bucket,)) + (kspec,)
            # draft_chunk: toks, start, n_valid, table, blk, off, rng
            return (dpspec, dcspec, dcspec, sds((bucket,), i32),
                    sds((), i32), sds((), i32),
                    sds((self.table_width,), i32),
                    sds((bucket,), i32), sds((bucket,), i32), kspec)
        pspec = {k: sds(v.shape, v.dtype,
                        sh.params[k] if sh is not None else None)
                 for k, v in self.params.items()}
        cspec = sds(self._cache_k.shape, self._cache_k.dtype,
                    sh.cache if sh is not None else None)
        # int8-KV engines thread the two scale arrays right after the
        # caches in every target-model program (same order as
        # _cache_args)
        caches = (cspec, cspec)
        if self._kv_quant:
            sspec = sds(self._scale_k.shape, self._scale_k.dtype,
                        sh.scale if sh is not None else None)
            caches = (cspec, cspec, sspec, sspec)
        if kind == "restore":
            # host-tier restore: caches first (no params, no rng),
            # then the block ids and the replicated host copies —
            # blks, hk, hv[, hks, hvs] (same order as _restore_pending)
            L, bs = self._cfg.n_layers, self.block_size
            Hkv, Dh = self._cfg.kv_heads, self._cfg.head_dim
            hspec = sds((L, bucket, bs, Hkv, Dh), self._cache_k.dtype)
            specs = caches + (sds((bucket,), i32), hspec, hspec)
            if self._kv_quant:
                s = sds((L, bucket, bs, Hkv), jnp.dtype(jnp.float32))
                specs += (s, s)
            return specs
        if kind == "decode":
            return (pspec,) + adp() + caches + (sds((bucket,), i32),
                    sds((bucket,), i32),
                    sds((bucket, self.table_width), i32)) \
                + aslot((bucket,)) + samp((bucket,)) + (kspec,)
        if kind == "verify":
            if self._cfg.sampling:
                # toks (B,), drafted (B, k), then the draft's q in
                # candidate space — q_at (B, k), q_vals/q_idx
                # (B, k, cap) — device-to-device from the draft
                # dispatch; pos0, tables, the operand triple, rng
                cap = min(self.sample_cap, self.spec["vocab"])
                return (pspec,) + adp() + caches + (
                        sds((bucket,), i32),
                        sds((bucket, self.spec_k), i32),
                        sds((bucket, self.spec_k), f32),
                        sds((bucket, self.spec_k, cap), f32),
                        sds((bucket, self.spec_k, cap), i32),
                        sds((bucket,), i32),
                        sds((bucket, self.table_width), i32)) \
                    + aslot((bucket,)) + samp((bucket,)) + (kspec,)
            # rows (B, k+1), pos0 (B,), tables (B, W), rng
            return (pspec,) + adp() + caches + (
                    sds((bucket, self.spec_k + 1), i32),
                    sds((bucket,), i32),
                    sds((bucket, self.table_width), i32)) \
                + aslot((bucket,)) + (kspec,)
        if kind == "chunk":
            # toks, start, n_valid, table, blk, off, rng
            return (pspec,) + adp() + caches + (sds((bucket,), i32),
                    sds((), i32), sds((), i32),
                    sds((self.table_width,), i32),
                    sds((bucket,), i32), sds((bucket,), i32)) \
                + aslot(()) + samp((1,)) + (kspec,)
        return (pspec,) + adp() + caches + (sds((bucket,), i32),
                sds((), i32),
                sds((bucket,), i32), sds((bucket,), i32)) \
            + aslot(()) + samp((1,)) + (kspec,)

    def _program_builder(self, kind, bucket):
        """The freshly-traced jitted program for (kind, bucket) — the
        switch over program families, shared by ``_resolve_program``
        and ``tools/hlo_audit.py``'s serve lowering (which audits the
        exact builders traffic runs, not a reconstruction).  The
        builders close over immutable ``_ModelCfg``s only — never an
        Engine (the _STEP_CACHE retention rule)."""
        if kind == "decode":
            return _build_decode(self._cfg, self._donate,
                                 self._shardings)
        if kind == "chunk":
            return _build_chunk(self._cfg, bucket, self._donate,
                                self._shardings)
        if kind == "verify":
            return spec_mod._build_verify(self._cfg, self.spec_k,
                                          self._donate,
                                          self._shardings)
        if kind == "draft":
            # sampling engines draft by SAMPLING from the warped
            # distribution (sample_cfg carries the target cfg's
            # cap/operand layout); greedy engines keep the
            # historical argmax draft program byte-for-byte
            return spec_mod._build_draft(
                self._spec.cfg, self.spec_k, self._donate,
                self._draft_shardings,
                sample_cfg=(self._cfg if self._cfg.sampling
                            else None))
        if kind == "draft_chunk":
            return _build_chunk(self._spec.cfg, bucket, self._donate,
                                self._draft_shardings)
        if kind == "restore":
            return _build_restore(self._cfg, self._donate,
                                  self._shardings)
        return _build_prefill(self._cfg, bucket, self._donate,
                              self._shardings)

    def _resolve_program(self, kind, bucket):
        """One bucket program: AOT-load it from the export store, or
        trace it fresh (and write it through for the next restart).
        ``mxtpu_aot_programs_total{kind,source}`` counts which happened
        — ``source="trace"`` is exactly a cold-start compile the warm
        path is supposed to avoid.

        Every path eagerly compiles (``.lower(specs).compile()``): on
        the hot path the compile was due this very step anyway, and
        eagerness is what makes ``warmup()`` mean "ready" rather than
        "will compile at the first unlucky request"."""
        specs = self._program_specs(kind, bucket)

        def build():
            telemetry.counter(
                "mxtpu_aot_programs_total", "bucket-program resolutions",
                ("kind", "source")).labels(kind=kind, source="trace").inc()
            return self._program_builder(kind, bucket)

        def compiled(jitted):
            try:
                return jitted.lower(*specs).compile()
            except Exception:
                return jitted          # lazy compile on first call

        if self._aot is None:
            return compiled(build())
        fp = dict(self._aot_base_fp(), kind=kind, bucket=int(bucket))
        label = f"serve-{kind}{bucket}"
        exported = self._aot.load(fp, label=label)
        if exported is None:
            jitted = build()
            try:
                exported = jax_compat.export_fn(jitted, *specs)
            except Exception:
                # this jax cannot export: fall back to the plain jit,
                # but count it — a fleet silently serving unexportable
                # programs loses its warm-restart story
                telemetry.counter(
                    "mxtpu_aot_errors_total", "AOT artifact failures",
                    ("kind",)).labels(kind="export").inc()
                return compiled(jitted)
            self._aot.save(fp, exported, label=label)
        else:
            telemetry.counter(
                "mxtpu_aot_programs_total", "bucket-program resolutions",
                ("kind", "source")).labels(kind=kind,
                                           source="artifact").inc()
        # both the cold and the warm process execute the round-tripped
        # module, so the XLA compile below has the same persistent-cache
        # key in both — a warm start's compile is a disk read
        n_caches = (4 if self._cfg.kv_quant
                    and kind not in ("draft", "draft_chunk") else 2)
        # the restore program has no params operand: its donated cache
        # arguments START the signature instead of following the pytree.
        # Adapter-mode target programs carry the LoRA stack pytree
        # between the params and the caches, shifting the donated
        # argnums by one more (draft programs stay base-model)
        if kind == "restore":
            first = 0
        elif self._cfg.adapters and kind not in ("draft", "draft_chunk"):
            first = 2
        else:
            first = 1
        return compiled(jax.jit(
            exported.call,
            donate_argnums=(tuple(range(first, first + n_caches))
                            if self._donate else ())))


# -- quantized serving helpers ------------------------------------------------
def _quantize_gpt_params(params, name, spec):
    """Weight-only int8 at load: every matmul projection of the
    normalized gpt() checkpoint gets per-output-channel symmetric int8
    weights (``contrib.quantization.quantize_weight``) plus a
    ``*_wscale`` f32 vector that ``_wfc`` dequantizes on the fly —
    4x smaller weight reads on the decode hot loop, the
    ``ops/quantized.py`` weight-only convention.  Embeddings, norms
    and biases stay fp; a tied LM head IS the embedding matrix, so it
    stays fp too (quantizing it would also perturb every input
    embedding lookup)."""
    from ..contrib.quantization import quantize_weight

    out = dict(params)
    stems = []
    for i in range(spec["n_layers"]):
        p = f"{name}_l{i}"
        stems += [f"{p}_q", f"{p}_k", f"{p}_v", f"{p}_proj",
                  f"{p}_ff_up", f"{p}_ff_down"]
        if spec["swiglu"]:
            stems.append(f"{p}_ff_gate")
    if not spec["tied"]:
        stems.append(f"{name}_head")
    for stem in stems:
        w = out.get(f"{stem}_weight")
        if w is None:
            continue
        # mxtpu-lint: disable=host-sync (load path, runs once at
        # engine construction: the checkpoint must reach the host to
        # quantize before placement)
        wq, sc = quantize_weight(np.asarray(w, np.float32))
        out[f"{stem}_weight"] = wq
        out[f"{stem}_wscale"] = sc
    return out


def _wfc(params, stem, x):
    """``_fc`` through a possibly weight-only-int8 checkpoint entry:
    when ``<stem>_wscale`` exists the int8 weight dequantizes on the
    fly (``ops/quantized.py``'s weight-only mode — activation-dtype
    math, 4x smaller weight reads); without it this is exactly
    ``_fc`` on the fp entry, so quant-off traced programs are
    byte-for-byte what they were before quantized serving existed."""
    w = params[f"{stem}_weight"]
    sc = params.get(f"{stem}_wscale")
    if sc is not None:
        w = w.astype(x.dtype) * sc.astype(x.dtype)[:, None]
    return _fc(x, w, params[f"{stem}_bias"])


def _lora_delta(adp, stem, x, slots):
    """The paged-LoRA low-rank delta for one projection: gather each
    row's (A, B) slices from the device stacks by its slot operand and
    compute ``scale * x @ A.T @ B.T`` — never materializing a merged
    weight.  Slot 0's rows and scale are true zeros, so base rows add
    exactly ``+0.0`` (token-identical to an adapters-off engine).

    ``slots`` is a scalar for the one-request prefill/chunk programs,
    ``(B,)`` for decode (2-D ``x``) and verify (3-D ``(B, K+1, D)``
    ``x`` — the slot broadcasts over the candidate positions)."""
    a = adp[f"{stem}_A"].astype(x.dtype)          # (S, r, d_in)
    b = adp[f"{stem}_B"].astype(x.dtype)          # (S, d_out, r)
    sc = adp["scale"]
    if slots.ndim == 0:
        u = x @ a[slots].T                        # (..., r)
        return (u @ b[slots].T) * sc[slots].astype(x.dtype)
    ga, gb = a[slots], b[slots]
    s = sc[slots].astype(x.dtype)
    if x.ndim == 2:
        u = jnp.einsum("bi,bri->br", x, ga)
        return jnp.einsum("br,bor->bo", u, gb) * s[:, None]
    u = jnp.einsum("bki,bri->bkr", x, ga)
    return jnp.einsum("bkr,bor->bko", u, gb) * s[:, None, None]


def _awfc(cfg, params, adp, stem, x, slots):
    """:func:`_wfc` plus the request's LoRA delta when the program
    threads the adapter stacks.  ``adp`` is None on adapters-off
    engines — a Python-level branch, so their traced programs stay
    byte-for-byte the historical ones."""
    base = _wfc(params, stem, x)
    if adp is None:
        return base
    return base + _lora_delta(adp, stem, x, slots)


def _kv_quant_vals(vals):
    """Per-slot-per-head symmetric int8 for K/V rows ``(..., Hkv, Dh)``
    -> ``(int8 rows, f32 scales (..., Hkv))``.  Each written slot
    quantizes independently over its own head vector, so the cache
    contents are a pure function of the fp values written — write
    ORDER cannot change them, which is what keeps preemption-by-
    recomputation and chunked re-prefill token-stable under int8 KV
    (a block-granular scale would re-scale earlier slots on every
    later write).  Zero vectors keep scale 1.0, ``quantize_weight``'s
    convention, so untouched cache stays exactly zero."""
    vf = vals.astype(jnp.float32)
    amax = jnp.max(jnp.abs(vf), axis=-1)
    sc = jnp.where(amax > 0, amax / 127.0, 1.0)
    q = jnp.clip(jnp.round(vf / sc[..., None]), -127, 127).astype(jnp.int8)
    return q, sc


def _kv_dequant(q, sc, dtype):
    """Invert :func:`_kv_quant_vals`: ``(..., Hkv, Dh)`` int8 plus
    ``(..., Hkv)`` scales -> fp rows in ``dtype``."""
    return (q.astype(jnp.float32)
            * sc.astype(jnp.float32)[..., None]).astype(dtype)


# -- compiled-program bodies (close over _ModelCfg ONLY — never an
# Engine, so the shared _STEP_CACHE cannot retain a retired engine's
# parameter dict) -------------------------------------------------------------
def _sample(cfg, logits, key):
    """Greedy argmax — the sampling-OFF programs' sampler, exactly the
    historical temperature-0 path (``key`` stays in the signature so
    the greedy program's operand list never moves).  Stochastic
    serving threads per-request operands through :func:`_sample_ops`
    inside the sampling-mode programs instead — temperature/top-k are
    no longer trace keys anywhere."""
    del key
    return jnp.argmax(logits, axis=-1).astype(jnp.int32)


# -- operand sampling (the sampling-mode programs' warp + sample) ------------
def _filter_logits(cfg, logits, temp, top_p, top_k):
    """Temperature/top-k/top-p warping with PER-ROW traced operands.

    ``logits`` (..., V); ``temp``/``top_p`` f32 and ``top_k`` int32
    broadcastable over the leading dims (0 = filter off for top_k).
    Returns ``(masked, idx)``: the top-``sample_cap`` candidates'
    warped logits (filtered positions at -inf) in descending order,
    and their vocab ids.  ``jax.lax.top_k`` replaces the old
    full-vocab ``jnp.sort``: the kth-largest threshold only ever
    needs the leading ``cap`` candidates, and top-p needs the same
    descending slice — one top_k call serves both (numerical
    equivalence vs the sort formulation is pinned in
    tests/test_sampling.py).  Candidates past the cap are never
    sampled — the cap itself acts as a top-``cap`` filter (exact
    whenever cap >= vocab, e.g. the tiny-vocab statistical pins).
    Greedy rows (temp <= 0) come out one-hot on the argmax, so a
    categorical draw over ``masked`` IS argmax there — every other
    candidate sits at -inf.
    """
    V = logits.shape[-1]
    cap = min(cfg.sample_cap, V) if cfg.sample_cap else V
    greedy = temp <= 0.0
    lg = logits.astype(jnp.float32)
    scaled = lg / jnp.where(greedy, 1.0, temp)[..., None]
    vals, idx = jax.lax.top_k(scaled, cap)             # descending
    # fence the sort's outputs: XLA-CPU's producer-duplicating fusion
    # otherwise re-runs the whole top-k sort inside every consumer of
    # ``idx`` (measured 15x on the verify program's acceptance gather)
    vals, idx = jax.lax.optimization_barrier((vals, idx))
    j = jnp.arange(cap)
    k_eff = jnp.where(top_k > 0, jnp.minimum(top_k, cap), cap)
    keep = j < k_eff[..., None]
    probs = jax.nn.softmax(jnp.where(keep, vals, -jnp.inf), axis=-1)
    csum = jnp.cumsum(probs, axis=-1)
    # nucleus: the smallest candidate set whose mass reaches top_p —
    # a candidate stays while the mass BEFORE it is under top_p
    keep = jnp.logical_and(keep, (csum - probs) < top_p[..., None])
    masked = jnp.where(keep, vals, -jnp.inf)
    return jnp.where(greedy[..., None],
                     jnp.where(j == 0, 0.0, -jnp.inf), masked), idx


def _sample_ops(cfg, logits, key, temp, top_p, top_k):
    """Sample one token per row from the warped distribution (greedy
    rows are exact argmax); int32 ids of the leading shape."""
    masked, idx = _filter_logits(cfg, logits, temp, top_p, top_k)
    choice = jax.random.categorical(key, masked, axis=-1)
    return jnp.take_along_axis(
        idx, choice[..., None], axis=-1)[..., 0].astype(jnp.int32)


def _scatter_probs(probs, idx, V):
    """Scatter per-candidate probabilities ``(..., cap)`` back onto
    their vocab ids -> a full ``(..., V)`` probability vector (zeros
    off the candidate set)."""
    lead = probs.shape[:-1]
    flat_p = probs.reshape((-1, probs.shape[-1]))
    flat_i = idx.reshape((-1, idx.shape[-1]))
    n = flat_p.shape[0]
    full = jnp.zeros((n, V), jnp.float32).at[
        jnp.arange(n)[:, None], flat_i].set(flat_p)
    return full.reshape(lead + (V,))


def _filtered_probs_full(cfg, logits, temp, top_p, top_k):
    """The warped SAMPLING distribution as a full-vocab probability
    vector ``(..., V)`` — the REFERENCE view of the warp, used by the
    test suite's sort-equivalence and distribution pins.  The serving
    hot path never materializes it: the programs sample straight from
    the candidate representation (`_filter_logits` + categorical) and
    the verify program's rejection-sampling acceptance evaluates p and
    q purely at candidate ids (serve/spec.py)."""
    masked, idx = _filter_logits(cfg, logits, temp, top_p, top_k)
    return _scatter_probs(jax.nn.softmax(masked, axis=-1), idx,
                          logits.shape[-1])


def _safe_log(p):
    """log(p) with exact -inf at p == 0 (a zero-probability token can
    never win a categorical draw, and a one-hot row samples its hot
    token deterministically)."""
    return jnp.where(p > 0, jnp.log(jnp.maximum(p, 1e-38)), -jnp.inf)


def _logprob_outs(logits, toks):
    """The logprob outputs every sampling-mode program returns for its
    sampled positions: the chosen token's log-softmax plus the
    ``TOP_LOGPROBS`` best candidates (values + ids).  RAW model
    logprobs (pre-temperature/filtering, the OpenAI-style convention)
    — greedy and stochastic rows report the same quantity."""
    lp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    chosen = jnp.take_along_axis(
        lp, toks[..., None].astype(jnp.int32), axis=-1)[..., 0]
    tv, ti = jax.lax.top_k(lp, min(TOP_LOGPROBS, lp.shape[-1]))
    return chosen, tv, ti.astype(jnp.int32)


def _mlp(cfg, params, p, x, adp=None, slots=None):
    h2 = _ln(x, params[f"{p}_ln2_gamma"],
             None if cfg.rmsnorm else params[f"{p}_ln2_beta"])
    if cfg.swiglu:
        g = _awfc(cfg, params, adp, f"{p}_ff_gate", h2, slots)
        gf = g.astype(jnp.float32)               # f32 silu == sym.silu
        up = ((gf * jax.nn.sigmoid(gf)).astype(g.dtype)
              * _awfc(cfg, params, adp, f"{p}_ff_up", h2, slots))
    else:
        up = _gelu(_awfc(cfg, params, adp, f"{p}_ff_up", h2, slots))
    return _awfc(cfg, params, adp, f"{p}_ff_down", up, slots)


def _logits(cfg, params, x):
    name = cfg.name
    final = _ln(x, params[f"{name}_ln_f_gamma"],
                None if cfg.rmsnorm else params[f"{name}_ln_f_beta"])
    if cfg.tied:
        return final @ params[f"{name}_tok_embed_weight"].T.astype(
            final.dtype)
    return _wfc(params, f"{name}_head", final)


def _forward_token_batch(cfg, params, ck, cv, ksc, vsc, toks, pos, tables,
                         adp=None, slots=None):
    """Shared decode math: write each row's K/V at its position,
    attend through the block tables, return logits (B, V).  With
    ``cfg.kv_quant`` the caches are int8 and ``ksc``/``vsc`` carry the
    per-slot-per-head f32 scales (None otherwise): writes quantize,
    attention dequantizes through the same tables."""
    name = cfg.name
    Hq, Hkv, Dh = cfg.num_heads, cfg.kv_heads, cfg.head_dim
    d_model = Hq * Dh
    B = toks.shape[0]
    x = params[f"{name}_tok_embed_weight"][toks]           # (B, D)
    if cfg.pos_table is not None:
        x = x + params[f"{name}_pos_embed_weight"][0, pos]
    blk = jnp.take_along_axis(tables, (pos // cfg.block_size)[:, None],
                              axis=1)[:, 0]
    off = pos % cfg.block_size
    ctx = pos + 1
    for i in range(cfg.n_layers):
        p = f"{name}_l{i}"
        h = _ln(x, params[f"{p}_ln1_gamma"],
                None if cfg.rmsnorm else params[f"{p}_ln1_beta"])
        q = _awfc(cfg, params, adp, f"{p}_q", h, slots)
        k = _awfc(cfg, params, adp, f"{p}_k", h, slots)
        v = _awfc(cfg, params, adp, f"{p}_v", h, slots)
        qh = q.reshape(B, Hq, Dh)
        kh = k.reshape(B, Hkv, Dh)
        vh = v.reshape(B, Hkv, Dh)
        if cfg.pos_table is None:
            qh, kh = _rope(qh, pos), _rope(kh, pos)
        if cfg.kv_quant:
            kq, ks = _kv_quant_vals(kh)
            vq, vs = _kv_quant_vals(vh)
            ck = ck.at[i, blk, off].set(kq)
            ksc = ksc.at[i, blk, off].set(ks)
            cv = cv.at[i, blk, off].set(vq)
            vsc = vsc.at[i, blk, off].set(vs)
            attn = paged_attention(qh, ck[i], cv[i], tables, ctx,
                                   window=cfg.window,
                                   k_scale=ksc[i], v_scale=vsc[i])
        else:
            ck = ck.at[i, blk, off].set(kh)
            cv = cv.at[i, blk, off].set(vh)
            attn = paged_attention(qh, ck[i], cv[i], tables, ctx,
                                   window=cfg.window)
        x = x + _awfc(cfg, params, adp, f"{p}_proj",
                      attn.reshape(B, d_model), slots)
        x = x + _mlp(cfg, params, p, x, adp=adp, slots=slots)
    return _logits(cfg, params, x), ck, cv, ksc, vsc


def _split_cache_args(cfg, rest):
    """Unpack a program's post-params positional args: the cache
    operands (2, or 4 with int8-KV scales) then the host-fed args.
    Returns ``(ck, cv, ksc, vsc, tail)`` with None scales when not
    quantized — the builders' one place to agree with _cache_args."""
    if cfg.kv_quant:
        return rest[0], rest[1], rest[2], rest[3], rest[4:]
    return rest[0], rest[1], None, None, rest[2:]


def _cache_outs(cfg, ck, cv, ksc, vsc):
    """The cache tail of a program's output tuple (mirrors
    :func:`_split_cache_args`)."""
    if cfg.kv_quant:
        return (ck, cv, ksc, vsc)
    return (ck, cv)


def _jit_kwargs(cfg, donate, shardings, n_token_args, n_lead=None):
    """Shared jit options for the bucket programs.  With a tp mesh the
    in/out shardings are pinned explicitly — params per the partition
    rules, KV-cache head-sharded (scale arrays too, under int8 KV),
    everything host-fed replicated — so GSPMD partitions the program
    (inserting the two all-reduces per layer) instead of inferring a
    layout per call site.

    ``n_token_args`` counts the host-fed operands between the caches
    and the rng key AS THE GREEDY PROGRAM takes them; sampling-mode
    programs append the (temp, top_p, top_k) triple, counted here.
    ``n_lead`` is the host-bound output count ahead of the watchdog
    flag/caches (default: 1 sampled-token output, +3 logprob views in
    sampling mode)."""
    n_caches = 4 if cfg.kv_quant else 2
    if cfg.sampling:
        n_token_args += 3
    if cfg.adapters:
        n_token_args += 1            # the per-row adapter-slot operand
    if n_lead is None:
        n_lead = 4 if cfg.sampling else 1
    first = 2 if cfg.adapters else 1  # adp stacks sit after params
    kw = {"donate_argnums": (tuple(range(first, first + n_caches))
                             if donate else ())}
    if shardings is not None:
        rep = shardings.rep
        caches = (shardings.cache,) * 2
        if cfg.kv_quant:
            caches += (shardings.scale,) * 2
        lead_in = (shardings.params,)
        if cfg.adapters:
            lead_in += (shardings.adapters
                        if shardings.adapters is not None else rep,)
        kw["in_shardings"] = (lead_in + caches
                              + (rep,) * n_token_args + (rep,))
        out = (rep,) * n_lead
        if cfg.numeric_watch:
            out += (rep,)
        kw["out_shardings"] = out + caches
    return kw


def _build_decode(cfg, donate, shardings=None):
    def decode(params, *rest):
        adp = slots = None
        if cfg.adapters:
            adp, rest = rest[0], rest[1:]
        ck, cv, ksc, vsc, tail = _split_cache_args(cfg, rest)
        toks, pos, tables = tail[:3]
        tail = tail[3:]
        if cfg.adapters:
            slots, tail = tail[0], tail[1:]
        if cfg.sampling:
            temp, topp, topk, rng = tail
        else:
            rng, = tail
        logits, ck, cv, ksc, vsc = _forward_token_batch(
            cfg, params, ck, cv, ksc, vsc, toks, pos, tables,
            adp=adp, slots=slots)
        if cfg.sampling:
            tok = _sample_ops(cfg, logits, rng, temp, topp, topk)
            lead = (tok,) + _logprob_outs(logits, tok)
        else:
            tok = _sample(cfg, logits, rng)
            lead = (tok,)
        caches = _cache_outs(cfg, ck, cv, ksc, vsc)
        if cfg.numeric_watch:
            # one extra all-reduce over the logits: the watchdog flag
            # rides back with the sampled tokens (the host syncs on
            # them anyway), so a NaN fires the flight recorder instead
            # of silently poisoning every later token
            return lead + (jnp.isfinite(logits).all(),) + caches
        return lead + caches

    return jax.jit(decode, **_jit_kwargs(cfg, donate, shardings, 3))


def _build_prefill(cfg, P, donate, shardings=None):
    name = cfg.name
    Hq, Hkv, Dh = cfg.num_heads, cfg.kv_heads, cfg.head_dim
    group = Hq // Hkv
    d_model = Hq * Dh
    window = cfg.window

    def prefill(params, *rest):
        """Whole-prompt pass at padded length P for ONE request:
        writes K/V for positions [0, plen) through the block
        table and samples the token after position plen-1."""
        adp = slots = None
        if cfg.adapters:
            adp, rest = rest[0], rest[1:]
        ck, cv, ksc, vsc, tail = _split_cache_args(cfg, rest)
        toks, plen, blk, off = tail[:4]
        tail = tail[4:]
        if cfg.adapters:
            slots, tail = tail[0], tail[1:]
        if cfg.sampling:
            temp, topp, topk, rng = tail
        else:
            rng, = tail
        pos = jnp.arange(P)
        x = params[f"{name}_tok_embed_weight"][toks]       # (P, D)
        if cfg.pos_table is not None:
            x = x + params[f"{name}_pos_embed_weight"][0, :P]
        qp = pos[:, None]
        kp = pos[None, :]
        keep = qp >= kp                                    # causal
        if window:
            keep = jnp.logical_and(keep, qp - kp < window)
        for i in range(cfg.n_layers):
            p = f"{name}_l{i}"
            h = _ln(x, params[f"{p}_ln1_gamma"],
                    None if cfg.rmsnorm else params[f"{p}_ln1_beta"])
            q = _awfc(cfg, params, adp, f"{p}_q", h, slots)
            k = _awfc(cfg, params, adp, f"{p}_k", h, slots)
            v = _awfc(cfg, params, adp, f"{p}_v", h, slots)
            qh = q.reshape(P, Hq, Dh)
            kh = k.reshape(P, Hkv, Dh)
            vh = v.reshape(P, Hkv, Dh)
            if cfg.pos_table is None:
                qh, kh = _rope(qh, pos), _rope(kh, pos)
            if cfg.kv_quant:
                kq, ks = _kv_quant_vals(kh)
                vq, vs = _kv_quant_vals(vh)
                ck = ck.at[i, blk, off].set(kq)
                ksc = ksc.at[i, blk, off].set(ks)
                cv = cv.at[i, blk, off].set(vq)
                vsc = vsc.at[i, blk, off].set(vs)
                # attend to the DEQUANTIZED values: every path must
                # see the cache's int8 round-trip, or a later chunk /
                # decode step reading the cache would diverge from the
                # hidden states this very pass computed
                kh = _kv_dequant(kq, ks, x.dtype)
                vh = _kv_dequant(vq, vs, x.dtype)
            else:
                ck = ck.at[i, blk, off].set(kh)
                cv = cv.at[i, blk, off].set(vh)
            # grouped-query dense causal attention within the
            # prompt (same head grouping as paged_attention)
            qg = qh.reshape(P, Hkv, group, Dh)
            sc = jnp.einsum("qkgd,skd->kgqs", qg, kh)
            sc = sc / np.sqrt(Dh)
            sc = jnp.where(keep[None, None], sc,
                           jnp.asarray(-jnp.inf, sc.dtype))
            pr = jax.nn.softmax(sc.astype(jnp.float32),
                                axis=-1).astype(x.dtype)
            at = jnp.einsum("kgqs,skd->qkgd", pr, vh)
            x = x + _awfc(cfg, params, adp, f"{p}_proj",
                          at.reshape(P, d_model), slots)
            x = x + _mlp(cfg, params, p, x, adp=adp, slots=slots)
        logits = _logits(cfg, params, x[plen - 1][None])
        caches = _cache_outs(cfg, ck, cv, ksc, vsc)
        if cfg.sampling:
            tok = _sample_ops(cfg, logits, rng, temp, topp, topk)
            lp, tv, ti = _logprob_outs(logits, tok)
            lead = (tok[0], lp[0], tv[0], ti[0])
        else:
            tok = _sample(cfg, logits, rng)[0]
            lead = (tok,)
        if cfg.numeric_watch:
            return lead + (jnp.isfinite(logits).all(),) + caches
        return lead + caches

    return jax.jit(prefill, **_jit_kwargs(cfg, donate, shardings, 4))


def _build_restore(cfg, donate, shardings=None):
    """Host-tier restore program: scatter R parked blocks' host copies
    back into the device cache through their (freshly allocated) block
    ids.  Pure data movement — no params, no sampling: the caches are
    donated through so the copy is in-place, padding rows write zeros
    into the null block (contents garbage by design), and under tp the
    replicated host operands scatter onto the head-sharded cache."""

    def restore(*args):
        if cfg.kv_quant:
            ck, cv, ksc, vsc = args[:4]
            blks, hk, hv, hks, hvs = args[4:]
        else:
            ck, cv = args[:2]
            ksc = vsc = None
            blks, hk, hv = args[2:]
        ck = ck.at[:, blks].set(hk)
        cv = cv.at[:, blks].set(hv)
        if cfg.kv_quant:
            ksc = ksc.at[:, blks].set(hks)
            vsc = vsc.at[:, blks].set(hvs)
        return _cache_outs(cfg, ck, cv, ksc, vsc)

    n_caches = 4 if cfg.kv_quant else 2
    kw = {"donate_argnums": (tuple(range(n_caches)) if donate else ())}
    if shardings is not None:
        rep = shardings.rep
        caches = (shardings.cache,) * 2
        if cfg.kv_quant:
            caches += (shardings.scale,) * 2
        n_host = 5 if cfg.kv_quant else 3
        kw["in_shardings"] = caches + (rep,) * n_host
        kw["out_shardings"] = caches
    return jax.jit(restore, **kw)


def _build_chunk(cfg, C, donate, shardings=None):
    """Suffix/chunk prefill program: C token rows of ONE request whose
    earlier positions' K/V already sit in the cache (a prefix-cache hit
    or previous chunks of the same prompt).  The rows' K/V is written
    through the block table FIRST and each row then attends to every
    cache position <= its own through the table — the same
    write-then-attend trick the decode program uses, which makes
    in-chunk causality exact without a dense (P, P) score matrix."""
    name = cfg.name
    Hq, Hkv, Dh = cfg.num_heads, cfg.kv_heads, cfg.head_dim
    group = Hq // Hkv
    d_model = Hq * Dh
    window = cfg.window

    def chunk(params, *rest):
        """Rows hold positions [start, start+n_valid) (rows past
        n_valid are padding: they write into the null block and their
        outputs are discarded).  Samples the token after position
        start+n_valid-1 — meaningful on the final chunk only."""
        adp = slots = None
        if cfg.adapters:
            adp, rest = rest[0], rest[1:]
        ck, cv, ksc, vsc, tail = _split_cache_args(cfg, rest)
        toks, start, n_valid, table, blk, off = tail[:6]
        tail = tail[6:]
        if cfg.adapters:
            slots, tail = tail[0], tail[1:]
        if cfg.sampling:
            temp, topp, topk, rng = tail
        else:
            rng, = tail
        pos = start + jnp.arange(C)
        x = params[f"{name}_tok_embed_weight"][toks]       # (C, D)
        if cfg.pos_table is not None:
            # clamp padded rows: their position may exceed the table
            pidx = jnp.minimum(pos, cfg.pos_table - 1)
            x = x + params[f"{name}_pos_embed_weight"][0, pidx]
        S = table.shape[0] * cfg.block_size
        spos = jnp.arange(S)[None, :]          # logical cache positions
        keep = spos <= pos[:, None]            # causal, self included
        if window:
            keep = jnp.logical_and(keep, spos > pos[:, None] - window)
        for i in range(cfg.n_layers):
            p = f"{name}_l{i}"
            h = _ln(x, params[f"{p}_ln1_gamma"],
                    None if cfg.rmsnorm else params[f"{p}_ln1_beta"])
            q = _awfc(cfg, params, adp, f"{p}_q", h, slots)
            k = _awfc(cfg, params, adp, f"{p}_k", h, slots)
            v = _awfc(cfg, params, adp, f"{p}_v", h, slots)
            qh = q.reshape(C, Hq, Dh)
            kh = k.reshape(C, Hkv, Dh)
            vh = v.reshape(C, Hkv, Dh)
            if cfg.pos_table is None:
                qh, kh = _rope(qh, pos), _rope(kh, pos)
            if cfg.kv_quant:
                kq, ks = _kv_quant_vals(kh)
                vq, vs = _kv_quant_vals(vh)
                ck = ck.at[i, blk, off].set(kq)
                ksc = ksc.at[i, blk, off].set(ks)
                cv = cv.at[i, blk, off].set(vq)
                vsc = vsc.at[i, blk, off].set(vs)
            else:
                ck = ck.at[i, blk, off].set(kh)
                cv = cv.at[i, blk, off].set(vh)
            # all rows share one table: gather the request's logical
            # cache view ONCE per layer, then mask per-row by position
            kb = ck[i][table].reshape(S, Hkv, Dh)
            vb = cv[i][table].reshape(S, Hkv, Dh)
            if cfg.kv_quant:
                kb = _kv_dequant(kb, ksc[i][table].reshape(S, Hkv),
                                 x.dtype)
                vb = _kv_dequant(vb, vsc[i][table].reshape(S, Hkv),
                                 x.dtype)
            qg = qh.reshape(C, Hkv, group, Dh)
            sc = jnp.einsum("ckgd,skd->kgcs", qg, kb)
            sc = sc / np.sqrt(Dh)
            sc = jnp.where(keep[None, None], sc,
                           jnp.asarray(-jnp.inf, sc.dtype))
            pr = jax.nn.softmax(sc.astype(jnp.float32),
                                axis=-1).astype(x.dtype)
            at = jnp.einsum("kgcs,skd->ckgd", pr, vb)
            x = x + _awfc(cfg, params, adp, f"{p}_proj",
                          at.reshape(C, d_model), slots)
            x = x + _mlp(cfg, params, p, x, adp=adp, slots=slots)
        logits = _logits(cfg, params, x[n_valid - 1][None])
        caches = _cache_outs(cfg, ck, cv, ksc, vsc)
        if cfg.sampling:
            tok = _sample_ops(cfg, logits, rng, temp, topp, topk)
            lp, tv, ti = _logprob_outs(logits, tok)
            lead = (tok[0], lp[0], tv[0], ti[0])
        else:
            tok = _sample(cfg, logits, rng)[0]
            lead = (tok,)
        if cfg.numeric_watch:
            return lead + (jnp.isfinite(logits).all(),) + caches
        return lead + caches

    return jax.jit(chunk, **_jit_kwargs(cfg, donate, shardings, 6))
