"""Iteration-level continuous-batching scheduler (Orca-style).

Every engine step the scheduler re-decides the batch from scratch:
finished requests leave between iterations, waiting requests join as
soon as a decode slot AND cache blocks open up, so the device batch
stays full without waiting for stragglers (continuous batching, vs the
static-batch serving of the reference's predictor).

Admission is a bounded FIFO queue — ``submit`` on a full queue raises
``QueueFull`` (back-pressure to the caller) and a request whose
``deadline_s`` expires before its prefill is rejected, never silently
dropped.  When decode outgrows the cache mid-flight the LOWEST-priority
running request (latest arrival) is preempted: its blocks are freed
(refcount-decremented — blocks shared through the prefix cache with a
still-running request are never reclaimed) and the request re-enters
the front of the waiting queue to resume by recomputation — prompt plus
already-generated tokens re-prefill together (minus whatever prefix the
cache still holds), which greedy decoding makes token-exact (tested by
test_serve.py's resume-equivalence case).

Chunked prefill: a prompt whose uncached remainder exceeds
``prefill_chunk`` tokens (env ``MXTPU_SERVE_PREFILL_CHUNK``) is
admitted into the ``prefilling`` lane and prefilled one chunk per
iteration, interleaved with the batched decode — one 32k-token prompt
can no longer stall every running request for a whole-prompt prefill.
The per-iteration prefill token budget is shared between the decode
slots and AT MOST ONE chunk (the engine shrinks the chunk by the decode
batch size), and while a chunked prefill is in flight no new request is
admitted — the chunk owns the prefill budget.
"""

from __future__ import annotations

import itertools
import threading
import time

import numpy as np

from ..telemetry.request_trace import NOOP_TRACER
from .kv_block_manager import NoFreeBlocks, blocks_for

__all__ = ["Request", "Scheduler", "QueueFull",
           "WAITING", "RUNNING", "FINISHED", "REJECTED", "CANCELLED"]

WAITING = "waiting"        # in the admission queue (incl. preempted)
RUNNING = "running"        # holds cache blocks, in the decode batch
FINISHED = "finished"      # produced max_new_tokens
REJECTED = "rejected"      # back-pressure: deadline/capacity, never ran to completion
CANCELLED = "cancelled"    # engine shutdown with the request in flight


class QueueFull(Exception):
    """Admission queue at capacity — back-pressure; resubmit later."""


_rid_counter = itertools.count()


class Request:
    """One generation request and its serving-side bookkeeping."""

    def __init__(self, prompt, max_new_tokens, deadline_s=None, tenant=None,
                 handoff=False, temperature=0.0, top_p=1.0, top_k=None,
                 logprobs=0, adapter_id=None):
        self.rid = next(_rid_counter)
        # prefill→decode handoff ingest (disaggregated fleets): the
        # decode replica marks the re-submitted request so the admit
        # trace and the /healthz waiting_handoffs load signal can tell
        # an in-flight ingest from a plain prompt
        self.handoff = bool(handoff)
        self.prompt = np.asarray(prompt, np.int32).reshape(-1)
        if self.prompt.size < 1:
            raise ValueError("prompt must hold at least one token")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        self.max_new_tokens = int(max_new_tokens)
        self.deadline_s = deadline_s
        self.tenant = str(tenant) if tenant is not None else None
        # per-request sampling params: OPERANDS of the engine's
        # sampling-mode programs, never trace keys (Engine.submit
        # validates; the greedy defaults here keep bare Request users
        # on the historical path)
        self.temperature = float(temperature)
        self.top_p = float(top_p)
        self.top_k = int(top_k) if top_k else None
        self.logprobs = int(logprobs)
        # multi-tenant LoRA: adapter_id names a registered adapter on
        # the engine's AdapterStore; adapter_slot is the pinned device
        # slot (0 = base model, a true zero delta) — an OPERAND of the
        # bucket programs like the sampling params, never a trace key
        self.adapter_id = str(adapter_id) if adapter_id is not None else None
        self.adapter_slot = 0
        # n>1 sample-group bookkeeping (stamped by Engine.submit):
        # every member shares the primary's rid as ``group`` and the
        # primary carries the full handle list on ``samples``
        self.group = None
        self.sample_index = 0
        self.samples = None
        self.status = WAITING
        self.trace_id = None           # stamped by the request tracer
        self.tokens = []           # generated ids (ints)
        self.token_logprobs = []   # per emitted token (sampling mode)
        self.top_logprobs = []     # [[token, logprob] x logprobs] rows
        self.cache_len = 0         # K/V slots valid for this request
        self.cached_prefix_len = 0  # slots reused from the prefix cache
        # of cached_prefix_len, the slots restored host->device from
        # the DRAM offload tier (0 means all device-resident hits)
        self.host_restored_len = 0
        self.prefill_target = None  # prefill length at admission
        self._prefill_started = False
        self.submit_t = None       # stamped by the scheduler
        self.first_token_t = None
        self.finish_t = None
        self.n_preemptions = 0
        self.reject_reason = None

    # -- derived -------------------------------------------------------------
    @property
    def done(self):
        return self.status in (FINISHED, REJECTED, CANCELLED)

    def prefill_ids(self):
        """Token ids the next prefill must run over: the prompt plus —
        after a preemption — everything already generated (resume by
        recomputation)."""
        if self.tokens:
            return np.concatenate(
                [self.prompt, np.asarray(self.tokens, np.int32)])
        return self.prompt

    def target_len(self):
        """Total sequence length when this request completes."""
        return self.prompt.size + self.max_new_tokens

    def ttft(self):
        if self.first_token_t is None or self.submit_t is None:
            return None
        return self.first_token_t - self.submit_t

    def trace_sampling(self):
        """Admit-event trace fields for per-request sampling params —
        only-when-on, so plain greedy requests' trace lines stay
        byte-identical to pre-sampling releases."""
        if (self.temperature == 0.0 and self.top_p >= 1.0
                and not self.top_k and not self.logprobs
                and self.group is None):
            return {}
        samp = {"temperature": self.temperature, "top_p": self.top_p,
                "top_k": self.top_k, "logprobs": self.logprobs}
        if self.group is not None:
            samp["group"] = self.group
            samp["sample_index"] = self.sample_index
        return {"sampling": samp}

    def trace_adapter(self):
        """Admit-event trace field for the request's adapter —
        only-when-set (same rule as :meth:`trace_sampling`)."""
        if self.adapter_id is None:
            return {}
        return {"adapter": self.adapter_id}


class Scheduler:
    """Iteration scheduler.  ``submit()`` may be called from request-
    handler threads while the engine's step thread runs ``schedule()``;
    the RLock below covers every mutation of the shared queues and
    counters (reentrant, because ``schedule`` preempts inline).  The
    ``# guarded-by`` annotations are enforced lexically by mxtpu-lint's
    unlocked-shared-state checker."""

    def __init__(self, block_mgr, max_batch, max_queue,
                 max_prefills_per_step=1, clock=time.monotonic,
                 trace=None, tenant_share=None, prefill_chunk=None,
                 spec_slots=0):
        self.blocks = block_mgr
        self.max_batch = int(max_batch)
        self.max_queue = int(max_queue)
        self.max_prefills_per_step = int(max_prefills_per_step)
        self.clock = clock
        # speculative decoding: each decode iteration may write up to
        # 1 + spec_slots cache positions per running request (the last
        # token plus k drafted tokens through the verify program), so
        # capacity checks reserve that many slots ahead instead of the
        # plain-decode 1.  0 = plain decode (byte-for-byte the old
        # arithmetic).
        self.spec_slots = max(0, int(spec_slots))
        # chunked prefill: a prompt whose uncached remainder exceeds
        # this many tokens prefills one chunk per iteration instead of
        # monopolizing a step (0 = whole-prompt prefills only)
        if prefill_chunk is None:
            from ..base import env_int

            prefill_chunk = env_int("MXTPU_SERVE_PREFILL_CHUNK", 512)
        self.prefill_chunk = max(0, int(prefill_chunk))
        # fair-share admission: one tenant may hold at most this
        # fraction of the queue (1.0 = off, the strict-FIFO default);
        # below 1.0 admission also interleaves tenants round-robin
        if tenant_share is None:
            from ..base import env_float

            tenant_share = env_float("MXTPU_SERVE_TENANT_SHARE", 1.0)
        self.tenant_share = min(1.0, max(0.0, float(tenant_share)))
        # request tracer (telemetry.request_trace) — every lifecycle
        # decision this scheduler makes is an event on it; the default
        # no-op keeps bare Scheduler tests wiring-free
        self.trace = trace if trace is not None else NOOP_TRACER
        self._lock = threading.RLock()
        self.waiting = []          # guarded-by: _lock
        self.running = []          # guarded-by: _lock
        # admitted requests still mid-chunked-prefill: they hold cache
        # blocks and a batch slot but are not yet in the decode batch
        self.prefilling = []       # guarded-by: _lock
        self.preemptions = 0       # guarded-by: _lock
        self.rejections = 0        # guarded-by: _lock
        self.reject_reasons = {}   # guarded-by: _lock
        # per-tenant admission/outcome/latency accounting (statusz +
        # ServeStats.tenants; the telemetry tenant series mirror it).
        # Bounded: client-supplied tenant strings must not grow
        # scheduler state without limit (oldest-seen evicted past cap)
        self.tenants = {}          # guarded-by: _lock
        self.max_tenants = 1024
        # tenant label values ever exported to the telemetry registry:
        # metric children are never evicted there, so past the cap new
        # tenants fold into one "other" label (bounded cardinality)
        self._tenant_labels = set()  # guarded-by: _lock
        # fair-share rotation cursor over the (bounded, rebuilt per
        # admission) list of tenants currently waiting
        self._rr_idx = 0           # guarded-by: _lock

    # -- admission -----------------------------------------------------------
    def submit(self, req):
        self.trace.submitted(req)
        if req.deadline_s is not None and req.deadline_s <= 0:
            # already expired when handed to us: reject at admission
            # (same three-view accounting as a queue-expired deadline)
            # instead of queuing work whose answer nobody can use
            self._reject(req, "deadline_at_submit")
            return req
        with self._lock:
            if len(self.waiting) >= self.max_queue:
                # back-pressure raise: the request never queues, but it
                # counts in rejections/reject_reasons and its trace
                # closes with the same reason code — the scheduler is
                # the single owner of the rejected total, so every view
                # (ServeStats, monitor bracket, trace) agrees even for
                # callers driving a bare Scheduler (the caller may
                # retry with a NEW Request)
                self.rejections += 1
                self.reject_reasons["queue_full"] = \
                    self.reject_reasons.get("queue_full", 0) + 1
                outcome = "queue_full"
            elif self.tenant_share < 1.0 and self._over_share(req):
                # fair share: this tenant already holds its fraction of
                # the queue — rejecting IT (retriable) leaves headroom
                # for every other tenant, so one abusive client cannot
                # starve the rest into QueueFull
                outcome = "tenant_share"
            elif not self.blocks.fits_at_all(req.target_len()):
                # would OOM the cache even running alone: reject NOW,
                # at submit, rather than deadlock in the waiting queue
                outcome = "exceeds_cache"
            else:
                req.submit_t = self.clock()
                self.waiting.append(req)
                outcome = None
        # trace/telemetry emission stays OUTSIDE the lock: the step
        # thread's schedule()/finish() must never contend with an
        # admission's metric-registry work
        if outcome == "queue_full":
            self._tenant_event(req, "rejected", reason="queue_full")
            self.trace.terminal(req, "rejected", reason="queue_full")
            raise QueueFull(
                f"admission queue full ({self.max_queue} waiting)")
        if outcome is not None:
            self._reject(req, outcome)
            return req
        self._tenant_event(req, "submitted")
        return req

    def _over_share(self, req):
        """Whether admitting ``req`` would push its tenant past its
        fair share of the waiting queue (called under ``_lock``).
        Tenant identity uses the same ``None -> "default"`` coalescing
        as admission rotation and tenant_stats — an untagged request
        and an explicit "default" are ONE tenant sharing one cap."""
        cap = max(1, int(self.max_queue * self.tenant_share))
        tenant = req.tenant or "default"
        held = sum(1 for r in self.waiting
                   if (r.tenant or "default") == tenant)
        return held >= cap

    def _tenant_event(self, req, outcome, reason=None, latency_s=None):
        """Fold one lifecycle outcome into the per-tenant table and the
        telemetry tenant series (no-ops unless MXTPU_TELEMETRY)."""
        tenant = req.tenant or "default"
        with self._lock:
            t = self.tenants.setdefault(
                tenant, {"submitted": 0, "completed": 0, "rejected": 0,
                         "latency_s_sum": 0.0, "latency_s_max": 0.0})
            if outcome in t:
                t[outcome] += 1
            if latency_s is not None:
                t["latency_s_sum"] += latency_s
                t["latency_s_max"] = max(t["latency_s_max"], latency_s)
            while len(self.tenants) > self.max_tenants:
                # oldest-seen eviction (insertion-ordered dict): an
                # attacker minting fresh tenant strings loses history,
                # never grows the table
                self.tenants.pop(next(iter(self.tenants)))
            if tenant in self._tenant_labels \
                    or len(self._tenant_labels) < self.max_tenants:
                self._tenant_labels.add(tenant)
                label = tenant
            else:
                label = "other"    # registry children never evict
        from .. import telemetry

        if outcome == "rejected":
            telemetry.counter(
                "mxtpu_serve_tenant_rejections_total",
                "per-tenant rejected requests",
                ("tenant", "reason")).labels(
                    tenant=label, reason=reason or "unknown").inc()
        elif outcome == "completed":
            telemetry.counter(
                "mxtpu_serve_tenant_completed_total",
                "per-tenant finished requests",
                ("tenant",)).labels(tenant=label).inc()
            if latency_s is not None:
                telemetry.histogram(
                    "mxtpu_serve_tenant_latency_seconds",
                    "per-tenant submit-to-finish latency",
                    ("tenant",)).labels(tenant=label).observe(latency_s)

    def tenant_stats(self):
        """Immutable per-tenant snapshot: submitted/completed/rejected
        counts plus mean/max end-to-end latency of finished requests."""
        with self._lock:
            out = {}
            for tenant, t in self.tenants.items():
                row = dict(t)
                done = row["completed"]
                lat_sum = row.pop("latency_s_sum")
                row["latency_s_mean"] = (round(lat_sum / done, 6)
                                         if done else None)
                row["latency_s_max"] = (round(row["latency_s_max"], 6)
                                        if done else None)
                out[tenant] = row
            return out

    def _reject(self, req, reason):
        req.status = REJECTED
        req.reject_reason = reason
        req.finish_t = self.clock()
        with self._lock:
            self.rejections += 1
            self.reject_reasons[reason] = \
                self.reject_reasons.get(reason, 0) + 1
        self._tenant_event(req, "rejected", reason=reason)
        if getattr(req, "_trace_sampled", None) is None:
            # rejected before the TRACER ever saw it (the engine's
            # exceeds_max_len guard): open the trace so the timeline is
            # still submitted -> rejected.  Keyed on the tracer's own
            # sampling mark, not on trace_id — a fleet router
            # pre-stamps trace ids, and those requests still need
            # their JSONL line
            self.trace.submitted(req)
        self.trace.terminal(req, "rejected", reason=reason)

    @property
    def queue_depth(self):
        return len(self.waiting)

    def waiting_handoffs(self):
        """Handoff-ingested requests still awaiting admission — the
        decode replica's /healthz load signal: a router's least-loaded
        pick must see in-flight ingests, not just decode occupancy."""
        with self._lock:
            return sum(1 for r in self.waiting if r.handoff)

    def has_work(self):
        return bool(self.waiting or self.running or self.prefilling)

    # -- one iteration's decisions -------------------------------------------
    def schedule(self):
        """Decide this iteration's work: ``(prefills, decodes)``.

        1. Expire overdue waiting requests (deadline -> REJECTED).
        2. Secure the next cache slot for every running request,
           preempting latest arrivals when blocks run out.
        3. Continue any in-flight chunked prefill: its request leads
           ``prefills`` (the engine runs ONE chunk) and owns this
           iteration's prefill budget — no new admissions until it
           finishes.
        4. Admit from the queue front while a batch slot, the prefill
           budget, and blocks for prompt+1 tokens are all available
           (the +1 guarantees the first decode step cannot be the one
           that discovers the cache is full).  Allocation walks the
           prefix cache: cached blocks head the request's table and
           ``cache_len`` starts at the cached span, so the engine
           prefills only the suffix.  A request whose uncached
           remainder exceeds ``prefill_chunk`` enters the
           ``prefilling`` lane instead of prefilling whole.  Decode
           slots were secured FIRST, so admission never steals a
           running request's block and a just-admitted request is
           never the same iteration's preemption victim.
        """
        now = self.clock()
        with self._lock:
            keep = []
            for req in self.waiting:
                if (req.deadline_s is not None
                        and now - req.submit_t > req.deadline_s):
                    self._reject(req, "deadline")
                else:
                    keep.append(req)
            self.waiting = keep

            decodes = []
            for req in list(self.running):
                if req not in self.running:
                    continue       # preempted as an earlier victim
                # with speculative decoding the verify program writes
                # up to spec_slots positions past the plain-decode one
                # — reserve them NOW so the dispatch can never be the
                # step that discovers the cache is full.  Capped at the
                # request's final length: speculative positions beyond
                # it route to the null block inside the programs, so
                # they never need (and must never allocate — the block
                # table has exactly max_model_len/block_size slots)
                # real blocks
                need = min(req.cache_len + 1 + self.spec_slots,
                           req.target_len())
                try:
                    self.blocks.ensure_capacity(req.rid, need)
                except NoFreeBlocks:
                    victim = self._pick_victim(req)
                    self.preempt(victim)
                    if victim is not req:
                        # retry once with the victim's blocks reclaimed
                        try:
                            self.blocks.ensure_capacity(req.rid, need)
                        except NoFreeBlocks:
                            self.preempt(req)
                            continue
                    else:
                        continue
                decodes.append(req)
            # a request scheduled early in the loop can still become a
            # later request's preemption victim — keep only survivors
            decodes = [r for r in decodes if r in self.running]

            prefills = []
            if self.prefilling:
                # one chunk per iteration, and it owns the prefill
                # budget: no whole-prefill admissions ride along
                prefills.append(self.prefilling[0])
                return prefills, decodes
            while (self.waiting
                   and (len(self.running) + len(prefills)
                        < self.max_batch)
                   and len(prefills) < self.max_prefills_per_step):
                req = self._next_admission()
                ids = req.prefill_ids()
                # same target_len() cap as the decode loop above (and
                # ids.size + 1 <= target_len() always, so the cap can
                # never starve the plain prompt+1 reservation)
                need = min(ids.size + 1 + self.spec_slots,
                           req.target_len())
                try:
                    # one call, one prefix walk: allocate prechecks the
                    # clear miss itself (nothing mutated or evicted on
                    # that path — FIFO head-of-line, no skipping ahead).
                    # It can also fail AFTER partial eviction: its
                    # fit estimate is optimistic under sharing (the
                    # blocks a prefix walk would reuse may BE the
                    # reclaimable blocks it counted, and an LRU interior
                    # pinned by a cached child is counted free but not
                    # evictable).  A failed allocate undoes its hit
                    # refs, so treating both as does-not-fit-yet is
                    # safe — the request stays at the queue head
                    _, cached = self.blocks.allocate(
                        req.rid, need, token_ids=ids,
                        # adapter-salted radix chain: an adapter row
                        # can only ever reuse same-adapter K/V
                        salt=req.adapter_id)
                except NoFreeBlocks:
                    break
                self.waiting.remove(req)
                req.cache_len = cached
                req.cached_prefix_len = cached
                req.host_restored_len = self.blocks.host_tokens(req.rid)
                req.prefill_target = int(ids.size)
                if self.tenant_share < 1.0:
                    self._rr_idx += 1    # rotation advances on ADMIT
                req.status = RUNNING
                chunked = (self.prefill_chunk > 0
                           and ids.size - cached > self.prefill_chunk)
                self.trace.event(
                    req, "resumed" if req.n_preemptions else "admitted",
                    queue_depth=len(self.waiting),
                    n_preemptions=req.n_preemptions,
                    cached_tokens=cached,
                    host_tokens=req.host_restored_len, chunked=chunked,
                    # only-when-on: plain requests' trace lines stay
                    # byte-identical to pre-handoff releases
                    **({"handoff": True} if req.handoff else {}),
                    # per-request sampling params (only-when-on too)
                    **req.trace_sampling(),
                    # the request's LoRA adapter (only-when-set)
                    **req.trace_adapter())
                prefills.append(req)
                if chunked:
                    self.prefilling.append(req)
                    break          # the chunk consumed the budget
            return prefills, decodes

    def _next_admission(self):
        """The next waiting request to consider (called under ``_lock``
        with ``waiting`` non-empty).  Strict FIFO by default; under
        fair share (``tenant_share < 1.0``) admission rotates
        round-robin across the tenants CURRENTLY waiting — FIFO within
        each tenant — so a deep single-tenant backlog cannot
        head-of-line-block everyone else's first request.  The tenant
        list is rebuilt from the waiting queue each call (bounded by
        ``max_queue``, so cost is O(queue), never O(tenants-ever-seen)).

        The ``_rr_idx`` cursor advances in the admission loop, only
        AFTER a candidate actually got its blocks: when the picked
        request cannot allocate, the same tenant's head is retried
        first on every following step — other tenants cannot leapfrog
        and refill the cache indefinitely, so strict FIFO's progress
        guarantee (a big request eventually fits as running work
        drains) survives inside each rotation slot."""
        with self._lock:           # reentrant: schedule() holds it
            if self.tenant_share >= 1.0:
                return self.waiting[0]
            tenants = []
            for r in self.waiting:
                t = r.tenant or "default"
                if t not in tenants:
                    tenants.append(t)
            tenant = tenants[self._rr_idx % len(tenants)]
            for r in self.waiting:
                if (r.tenant or "default") == tenant:
                    return r
            return self.waiting[0]

    def _pick_victim(self, needy):
        """Lowest priority = latest arrival among running requests —
        but refcount-aware: a request whose blocks are ALL shared with
        other live tables reclaims nothing when preempted (``free`` is
        a decref, never a blind release), so prefer the latest arrival
        that would actually return blocks.  Falls back to plain latest
        arrival when every candidate is a pure sharer (preempting one
        still drops refcounts, unblocking a later eviction)."""
        yielding = [r for r in self.running
                    if self.blocks.reclaimable_blocks(r.rid) > 0]
        return max(yielding or self.running, key=lambda r: r.rid)

    def preempt(self, req):
        """Release ``req``'s block references and push it back to the
        FRONT of the waiting queue (it arrived before everything
        waiting behind it, so resuming it first preserves FIFO
        fairness).  Blocks shared with another running request are
        refcount-decremented, never freed from under the sharer."""
        with self._lock:
            self.running.remove(req)
            self.blocks.free(req.rid, retain=True)
            req.status = WAITING
            req.cache_len = 0
            req.cached_prefix_len = 0
            req.host_restored_len = 0
            req.prefill_target = None
            req._prefill_started = False
            req.n_preemptions += 1
            self.preemptions += 1
            self.trace.event(req, "preempted", reason="cache_pressure",
                             generated=len(req.tokens))
            self.waiting.append(req)
            self.waiting.sort(key=lambda r: r.rid)   # arrival order

    def is_prefilling(self, req):
        """Whether ``req`` is mid-chunked-prefill (holds blocks and a
        batch slot, not yet in the decode batch)."""
        with self._lock:
            return req in self.prefilling

    def prefill_done(self, req):
        """Engine hook: ``req``'s last prefill chunk ran — it leaves
        the prefilling lane (no-op for whole-prompt prefills)."""
        with self._lock:
            if req in self.prefilling:
                self.prefilling.remove(req)

    def finish(self, req, status=FINISHED):
        with self._lock:
            if req in self.running:
                self.running.remove(req)
                self.blocks.free(req.rid, retain=True)
            elif req in self.prefilling:
                # cancelled mid-chunked-prefill (engine shutdown): it
                # holds cache blocks without ever reaching the decode
                # batch — release its references like a running peer's
                self.prefilling.remove(req)
                self.blocks.free(req.rid, retain=True)
        req.status = status
        req.finish_t = self.clock()
        if status == FINISHED:
            self._tenant_event(
                req, "completed",
                latency_s=(req.finish_t - req.submit_t
                           if req.submit_t is not None else None))
        self.trace.terminal(req, status, generated=len(req.tokens))

    def admit_running(self, req):
        """Engine hook: a prefilled request enters the decode batch."""
        with self._lock:
            self.running.append(req)

    def drain_waiting(self):
        """Engine shutdown: atomically take (and clear) the waiting
        queue so a racing ``submit`` cannot land a request in a list
        nobody will ever schedule again."""
        with self._lock:
            drained, self.waiting = self.waiting, []
            return drained
