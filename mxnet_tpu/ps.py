"""Host-side parameter server: the TPU-native stand-in for ps-lite.

The collectives-backed ``dist_sync`` path (kvstore.py DistKVStore) is
the fast lane for synchronous data parallelism, but it cannot express
the reference's ``dist_async`` semantics — workers racing updates into
shared state through a server-side optimizer (kvstore_dist_server.h:
136-190: async pushes run the updater immediately; sync mode merges
exactly NumWorkers requests before replying — and kvstore.py:231-256:
the optimizer is pickled to the servers).  This module restores that
capability with a small threaded TCP server (pickle-framed messages
standing in for ps-lite's ZMQ transport):

- ``PSServer``: key -> ndarray store; per-key sync merge with
  request-counting barrier, or immediate async updates (sync/async is
  carried per push, so different stores can share servers); runs a
  frontend-supplied updater (unpickled optimizer via ``set_optimizer``
  command, reference kSetOptimizer); worker barrier; clean stop
  (reference kStopServer).
- ``PSClient``: blocking request/response connection per worker.
- Key sharding: with multiple servers, keys hash to a server and big
  arrays are striped evenly across all servers (reference EncodeKey
  big-array striping, kvstore_dist.h:260-298).

Server processes are spawned by ``tools/launch.py -s N`` (reference
tracker starting scheduler+servers) or ``python -m mxnet_tpu.ps``.
"""

from __future__ import annotations

import os
import pickle
import socket
import struct
import threading
import time
import zlib

import numpy as np

from .base import env_float

__all__ = ["PSServer", "PSClient", "ShardedPSClient", "BIGARRAY_BOUND"]

# reference MXNET_KVSTORE_BIGARRAY_BOUND default (kvstore_dist.h)
BIGARRAY_BOUND = int(os.environ.get("MXNET_KVSTORE_BIGARRAY_BOUND", 10 ** 6))

# a sync merge or barrier that outlives this is treated as a dead-worker
# failure and surfaced as an error instead of hanging the job
SYNC_TIMEOUT_S = env_float("MXTPU_PS_SYNC_TIMEOUT", 300)


def _send_msg(sock, obj):
    payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    sock.sendall(struct.pack("!I", len(payload)) + payload)


def _recv_msg(sock):
    hdr = b""
    while len(hdr) < 4:
        chunk = sock.recv(4 - len(hdr))
        if not chunk:
            return None
        hdr += chunk
    (n,) = struct.unpack("!I", hdr)
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(1 << 20, n - len(buf)))
        if not chunk:
            return None
        buf += chunk
    return pickle.loads(bytes(buf))


class PSServer:
    """Single parameter-server shard (one reference server node).

    Thread-per-connection; state guarded by a lock with per-key
    condition variables for sync-mode merge barriers.
    """

    def __init__(self, num_workers, port=0, host="127.0.0.1"):
        self.num_workers = num_workers
        self.store = {}
        self.updater = None
        self._merge = {}        # key -> (accumulated array, count)
        self._gen = {}          # key -> completed sync-round counter
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._barrier_arrivals = set()  # rank / connection tokens present
        self._barrier_gen = 0
        self._last_seen = {}    # worker rank -> monotonic last-contact
        self._stop = threading.Event()
        self._sock = socket.create_server((host, port))
        self._sock.settimeout(0.2)
        self.addr = f"{host}:{self._sock.getsockname()[1]}"
        self._thread = threading.Thread(target=self._accept_loop, daemon=True)
        self._threads = []

    def start(self):
        self._thread.start()
        return self

    def _accept_loop(self):
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            t = threading.Thread(target=self._serve, args=(conn,), daemon=True)
            t.start()
            self._threads.append(t)
        self._sock.close()

    # -- request handlers ---------------------------------------------------
    def _handle_push(self, key, value, sync):
        """Sync/async is carried per push (per-kvstore, not server-global:
        a server-global flag would let one store's creation silently flip
        the semantics of another live store on the same servers)."""
        from .gradcomp import decompress, is_compressed

        if is_compressed(value):
            # compressed gradient (kvstore gradient compression, 1- or
            # 2-bit by wire tag): expand before merge/apply — the server
            # stores full precision
            value = decompress(value)
        with self._cond:
            if sync:
                acc, count = self._merge.get(key, (None, 0))
                acc = value.copy() if acc is None else acc + value
                count += 1
                if count < self.num_workers:
                    self._merge[key] = (acc, count)
                    gen = self._gen.get(key, 0)
                    # block this worker's push until the round completes
                    # (reference: server replies after NumWorkers merged)
                    self._wait_released(
                        lambda: self._gen.get(key, 0) != gen,
                        f"sync push on key {key!r} "
                        f"({count}/{self.num_workers} pushed)")
                    return
                # last pusher applies the merged update and releases peers
                self._apply(key, acc)
                self._merge[key] = (None, 0)
                self._gen[key] = self._gen.get(key, 0) + 1
                self._cond.notify_all()
            else:
                # async: apply immediately — worker updates race, exactly
                # the reference dist_async contract
                self._apply(key, value)

    def _wait_released(self, released, what):
        """Wait (holding self._cond) until ``released()`` or stop; bounded
        so one dead worker fails the job instead of hanging every peer."""
        deadline = time.monotonic() + SYNC_TIMEOUT_S
        while not released() and not self._stop.is_set():
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"{what} timed out after {SYNC_TIMEOUT_S}s "
                    "(dead worker?)")
            self._cond.wait(timeout=0.2)

    def _apply(self, key, recved):
        if key not in self.store:
            self.store[key] = recved.copy()
        elif self.updater is not None:
            # the unpickled optimizer updater works on NDArrays
            from . import ndarray as nd

            w = nd.array(self.store[key])
            self.updater(key, nd.array(recved), w)
            self.store[key] = w.asnumpy()
        else:
            self.store[key][...] = recved

    def _handle(self, msg, rank_holder=None):
        op = msg[0]
        if op == "hello":
            # worker-rank registration for heartbeat tracking (reference
            # ps-lite Postoffice heartbeats / GetDeadNodes)
            if rank_holder is not None:
                rank_holder[0] = int(msg[1])
            with self._lock:
                self._last_seen[int(msg[1])] = time.monotonic()
            return ("ok",)
        if op == "bye":
            # explicit clean-close: only a deliberate goodbye deregisters
            # the rank — a bare EOF (crash/SIGKILL also closes the
            # socket) must keep it tracked so dead_nodes reports it
            if rank_holder is not None and rank_holder[0] is not None:
                with self._lock:
                    self._last_seen.pop(rank_holder[0], None)
                rank_holder[0] = None
            return ("ok",)
        if op == "dead_nodes":
            timeout = float(msg[1])
            now = time.monotonic()
            with self._lock:
                dead = sorted(r for r, t in self._last_seen.items()
                              if now - t > timeout)
            return ("ok", dead)
        if op == "init":
            _, key, value, force = msg
            with self._lock:
                # force (fresh jobs) overwrites; recovery inits are
                # no-ops when the key exists, so a restarted worker
                # cannot clobber trained state (reference is_recovery
                # rejoin — servers keep state, late inits are ignored).
                # Reports whether the key already existed so recovering
                # workers can verify the crash postdated startup.
                existed = key in self.store
                if force or not existed:
                    self.store[key] = np.array(value)
            return ("ok", existed)
        if op == "push":
            from .gradcomp import is_compressed

            _, key, value, sync = msg
            if not is_compressed(value):
                value = np.asarray(value)
            self._handle_push(key, value, sync)
            return ("ok",)
        if op == "pull":
            with self._lock:
                val = self.store.get(msg[1])
                # copy under the lock: the assign path mutates stored
                # arrays in place, and pickling outside the lock could
                # serialize a torn half-old/half-new value
                val = None if val is None else val.copy()
            if val is None:
                return ("err", f"key {msg[1]!r} not initialized")
            return ("ok", val)
        if op == "barrier_gen":
            # released-round counter; recovered workers resync their
            # barrier ordinal to it once startup replay is done (their
            # previous life may have passed mid-training rounds — e.g.
            # periodic checkpoints — that the new life never re-executes,
            # so program-order ordinals alone would pair rounds wrong)
            with self._cond:
                return ("ok", self._barrier_gen)
        if op == "barrier":
            # Generation-numbered + rank-keyed: the client sends its own
            # barrier ordinal; an ordinal the server has already released
            # returns immediately, which is what makes worker recovery
            # safe — a restarted worker replays its startup barriers
            # (instant no-ops for rounds its peers already passed) and
            # genuinely joins the first round still pending, instead of
            # skipping barriers wholesale and deadlocking survivors that
            # crashed mid-startup.  The pending round tracks arrivals as
            # a set keyed by rank (or connection identity for clients
            # that never sent "hello"), so a rank that crashed while
            # waiting and rejoined is counted once, not twice.
            client_gen = msg[1] if len(msg) > 1 else None
            token = (rank_holder[0]
                     if rank_holder is not None and rank_holder[0] is not None
                     else ("conn", id(rank_holder)))
            with self._cond:
                if client_gen is not None and client_gen <= self._barrier_gen:
                    return ("ok",)  # round already released
                self._barrier_arrivals.add(token)
                gen = self._barrier_gen
                if len(self._barrier_arrivals) == self.num_workers:
                    self._barrier_arrivals = set()
                    self._barrier_gen += 1
                    self._cond.notify_all()
                else:
                    self._wait_released(
                        lambda: self._barrier_gen != gen, "barrier")
            return ("ok",)
        if op == "command":
            _, head, body = msg
            if head in ("set_optimizer", "set_optimizer_if_unset"):
                from .optimizer import get_updater

                optimizer = pickle.loads(body)
                with self._lock:
                    # the _if_unset variant is the recovery path: a
                    # restarted rank 0 re-sends the optimizer, but must
                    # not wipe accumulated momentum/Adam state when the
                    # first life already installed it
                    if head == "set_optimizer" or self.updater is None:
                        self.updater = get_updater(optimizer)
            elif head == "get_states":
                # optimizer states live server-side; expose them so
                # workers can checkpoint (save_optimizer_states)
                with self._lock:
                    states = dict(self.updater.states) if self.updater else {}
                return ("ok", pickle.dumps(states))
            elif head == "set_states":
                with self._lock:
                    if self.updater is None:
                        return ("err", "optimizer not initialized on server")
                    self.updater.states.update(pickle.loads(body))
            elif head == "stop":
                self._stop.set()
                with self._cond:
                    self._cond.notify_all()
            return ("ok",)
        return ("err", f"unknown op {op!r}")

    def _serve(self, conn):
        rank_holder = [None]   # set by a "hello" message
        with conn:
            while not self._stop.is_set():
                try:
                    msg = _recv_msg(conn)
                except OSError:
                    break
                if msg is None:
                    # EOF without a "bye": a crashed worker's kernel
                    # closes the socket too — keep the rank registered
                    # so its lapsed heartbeat surfaces in dead_nodes
                    break
                if rank_holder[0] is not None:
                    with self._lock:
                        self._last_seen[rank_holder[0]] = time.monotonic()
                try:
                    reply = self._handle(msg, rank_holder)
                except Exception as e:  # surface server errors to the worker
                    reply = ("err", repr(e))
                try:
                    _send_msg(conn, reply)
                except OSError:
                    break

    def stop(self):
        self._stop.set()
        with self._cond:
            self._cond.notify_all()

    def join(self, timeout=None):
        self._thread.join(timeout)


class PSClient:
    """One worker's connection to one server shard."""

    def __init__(self, addr):
        host, port = addr.rsplit(":", 1)
        self._sock = socket.create_connection((host, int(port)))
        self._lock = threading.Lock()
        self._barrier_ordinal = 0

    def request(self, *msg):
        with self._lock:
            _send_msg(self._sock, msg)
            reply = _recv_msg(self._sock)
        if reply is None:
            raise ConnectionError("parameter server closed connection")
        if reply[0] == "err":
            raise RuntimeError(f"parameter server error: {reply[1]}")
        return reply[1] if len(reply) > 1 else None

    def close(self):
        try:
            # deliberate goodbye so the server deregisters this rank
            # (a bare socket close is indistinguishable from a crash)
            self.request("bye")
        except (OSError, ConnectionError, RuntimeError):
            pass
        try:
            self._sock.close()
        except OSError:
            pass


class ShardedPSClient:
    """Key-sharded view over all server shards (reference EncodeKey,
    kvstore_dist.h:260-298): small arrays live on hash(key) % n_servers;
    arrays over BIGARRAY_BOUND elements are striped evenly across all
    servers so no shard holds the whole tensor."""

    def __init__(self, addrs, align_barriers=True):
        self.clients = [PSClient(a) for a in addrs]
        self._no_stripe = set()
        # A SECOND store on the same servers must not replay barrier
        # rounds earlier stores already released: ordinals restart at 0
        # per connection while the server's round counter is global, so
        # every barrier of the new store would look already-released
        # and silently no-op (racing its init/push/pull ordering).
        # Start from each server's current counter instead.  RECOVERY
        # clients opt out (align_barriers=False): they must replay the
        # startup rounds their previous life passed as instant no-ops
        # and call resync_barrier() themselves once replay is done.
        if align_barriers:
            self.resync_barrier()

    def _shard(self, key):
        # stable across processes — builtin hash() is randomized per
        # process for str keys, which would send each worker's requests
        # for the same key to different shards
        h = zlib.crc32(str(key).encode())
        return self.clients[h % len(self.clients)]

    def mark_unstriped(self, key):
        """Force whole-key placement on the owner shard (used by
        gradient compression, whose whole-key payloads must land where
        the weight lives; call before ``init``)."""
        self._no_stripe.add(key)

    def _stripes(self, key, size):
        n = len(self.clients)
        if n == 1 or size < BIGARRAY_BOUND or key in self._no_stripe:
            return None
        bounds = [size * i // n for i in range(n + 1)]
        return [(f"{key}#stripe{i}", bounds[i], bounds[i + 1])
                for i in range(n)]

    def init(self, key, value, force=True):
        """Initialize ``key``; returns True when every shard already
        held it (used by recovery to verify servers kept state)."""
        value = np.asarray(value)
        stripes = self._stripes(key, value.size)
        if stripes is None:
            return bool(self._shard(key).request("init", key, value, force))
        flat = value.reshape(-1)
        existed = True
        for c, (skey, lo, hi) in zip(self.clients, stripes):
            existed &= bool(c.request("init", skey, flat[lo:hi], force))
        return existed

    def push(self, key, value, sync=False):
        from .gradcomp import is_compressed

        if is_compressed(value):
            # compressed payloads are ~16x smaller than the striping
            # threshold assumed; send whole to the owner shard
            self._shard(key).request("push", key, value, sync)
            return
        value = np.asarray(value)
        stripes = self._stripes(key, value.size)
        if stripes is None:
            self._shard(key).request("push", key, value, sync)
            return
        flat = value.reshape(-1)
        for c, (skey, lo, hi) in zip(self.clients, stripes):
            c.request("push", skey, flat[lo:hi], sync)

    def pull(self, key, shape, dtype):
        size = int(np.prod(shape)) if shape else 1
        stripes = self._stripes(key, size)
        if stripes is None:
            return np.asarray(self._shard(key).request("pull", key)
                              ).reshape(shape).astype(dtype, copy=False)
        parts = [np.asarray(c.request("pull", skey))
                 for c, (skey, _, _) in zip(self.clients, stripes)]
        return np.concatenate(parts).reshape(shape).astype(dtype, copy=False)

    def barrier(self):
        # ordinal-stamped per connection: ranks issue barriers in the
        # same (SPMD) order, so the ordinal identifies the round and a
        # recovered worker's replayed rounds return instantly
        for c in self.clients:
            c._barrier_ordinal += 1
            c.request("barrier", c._barrier_ordinal)

    def resync_barrier(self):
        """Align barrier ordinals with the servers' released-round
        counters.  A recovered worker calls this once its startup replay
        is done: the previous life may have passed extra (mid-training)
        rounds, so continuing from the replayed ordinal would make every
        later barrier look like an already-released round and no-op."""
        for c in self.clients:
            c._barrier_ordinal = int(c.request("barrier_gen"))

    def command(self, head, body):
        for c in self.clients:
            c.request("command", head, body)

    def hello(self, rank):
        """Register this worker's rank with every shard for heartbeat
        tracking (later requests on these connections refresh it)."""
        for c in self.clients:
            c.request("hello", rank)

    def dead_nodes(self, timeout=60.0):
        """Ranks not heard from within ``timeout`` seconds on ANY shard
        (a rank alive on one shard is alive)."""
        dead = None
        for c in self.clients:
            d = set(c.request("dead_nodes", timeout))
            dead = d if dead is None else (dead & d)
        return sorted(dead or ())

    def get_states(self):
        """Merged server-side optimizer states across all shards."""
        merged = {}
        for c in self.clients:
            merged.update(pickle.loads(c.request("command", "get_states",
                                                 None)))
        return merged

    def set_states(self, states):
        """Route each state entry to the shard that owns its key (same
        mapping push/pull use), so shards don't hold dead copies of
        every other shard's momentum buffers."""
        per_shard = [{} for _ in self.clients]
        n = len(self.clients)
        for k, v in states.items():
            if isinstance(k, str) and "#stripe" in k:
                idx = int(k.rsplit("#stripe", 1)[1]) % n
            else:
                idx = zlib.crc32(str(k).encode()) % n
            per_shard[idx][k] = v
        for c, d in zip(self.clients, per_shard):
            if d:
                c.request("command", "set_states", pickle.dumps(d))

    def close(self):
        for c in self.clients:
            c.close()


def main(argv=None):
    """Server-process entry: ``python -m mxnet_tpu.ps --workers N``.

    Prints ``PS_ADDR <host:port>`` on stdout for the launcher, serves
    until a stop command arrives."""
    import argparse

    # the server's updater math is host-side: pin jax to CPU before any
    # backend initialization (env vars alone do not override accelerator
    # plugins; the config update is authoritative)
    import jax

    jax.config.update("jax_platforms", "cpu")

    parser = argparse.ArgumentParser()
    parser.add_argument("--workers", type=int, required=True)
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--host", default="127.0.0.1")
    args = parser.parse_args(argv)

    server = PSServer(args.workers, port=args.port, host=args.host).start()
    print(f"PS_ADDR {server.addr}", flush=True)
    try:
        while not server._stop.wait(timeout=0.5):
            pass
    except KeyboardInterrupt:
        server.stop()
    server.join(timeout=5)


if __name__ == "__main__":
    main()
