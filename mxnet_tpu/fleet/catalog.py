"""Fleet model-catalog rebalancer: adapter placement follows traffic.

The adapter half of the multi-tenant catalog is per-replica state: a
replica serves only the LoRA adapters registered in its own
``AdapterStore`` (advertised on ``/statusz.json`` and scraped into the
collector's per-model aggregates).  Left alone, placement drifts away
from demand — a freshly scaled-up replica carries no adapters at all,
and a traffic shift can leave a hot adapter registered on one replica
while the router load-balances its requests across five.

``CatalogRebalancer`` closes that gap with the same sensor the
autoscaler uses — ``FleetCollector.fleet_view()`` — and the replica
adapter endpoints as actuators:

* **plan()** compares each model's per-adapter goodput
  (``models[tag]["adapter_goodput"]``) against placement (each fresh
  replica's advertised adapter ids) and emits moves: ``spread`` a
  hot adapter (observed traffic, missing from some replica of its
  model) from a replica that has it to each replica that doesn't;
  optionally ``retire`` idle adapters (registered, zero observed
  traffic) when ``retire_idle`` is set.
* **apply()** executes moves replica-to-replica with no shared
  filesystem: ``/adapter_export`` on the source (sha1-stamped wire
  records) piped into ``/load_adapter`` on the destination; ``retire``
  posts ``/unload_adapter`` (a 503 ``adapter_pinned`` — requests still
  running on the adapter — is reported, not retried; the next pass
  will catch it).

Moves are capped per pass (``max_moves``) so one rebalance can never
turn into a fleet-wide copy storm; what was dropped is visible in the
returned plan vs applied counts.  Every applied move increments
``mxtpu_fleet_catalog_moves_total{action,outcome}`` and lands on the
collector's fleet timeline.

The ``Supervisor.rebalance_catalog`` actuator (invoked by the
autoscaler after a scale-up, or manually) is a thin wrapper over
:meth:`rebalance`.
"""

from __future__ import annotations

import json
import time
import urllib.error
import urllib.request

from .. import telemetry

__all__ = ["CatalogRebalancer"]


def _post_json(url, path, body, timeout_s):
    req = urllib.request.Request(
        f"{url.rstrip('/')}{path}", data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    with urllib.request.urlopen(req, timeout=timeout_s) as resp:
        return json.loads(resp.read())


class CatalogRebalancer:
    """Plan/apply adapter placement moves for one fleet.

    Args:
      collector: the ``FleetCollector`` whose ``fleet_view()`` supplies
        both traffic (per-model adapter goodput) and placement (each
        replica's advertised adapter ids).
      max_moves: cap on moves applied per :meth:`rebalance` pass.
      retire_idle: also unload adapters with zero observed traffic
        (default off — goodput rings start empty, and "no traffic yet"
        must not de-catalog a freshly loaded adapter).
      timeout_s: per-HTTP-call timeout for export/load/unload.
      clock: injectable monotonic clock (tests).
    """

    def __init__(self, collector, max_moves=4, retire_idle=False,
                 timeout_s=30.0, clock=time.monotonic):
        self.collector = collector
        self.max_moves = int(max_moves)
        self.retire_idle = bool(retire_idle)
        self.timeout_s = float(timeout_s)
        self.clock = clock
        self._m_moves = telemetry.counter(
            "mxtpu_fleet_catalog_moves_total",
            "catalog rebalance moves by action and outcome",
            ("action", "outcome"))

    # -- planning ------------------------------------------------------------
    def plan(self, view=None):
        """Placement moves implied by one fleet view (no side effects).

        Returns ``[{"action", "model", "adapter", "src", "dst"}, ...]``
        ordered hot-adapters-first; ``dst`` is None for ``retire``.
        """
        if view is None:
            view = self.collector.fleet_view()
        fresh = [r for r in (view.get("replicas") or [])
                 if not r.get("stale") and r.get("adapters") is not None]
        moves = []
        for tag in sorted(view.get("models") or {}):
            m = view["models"][tag]
            carriers = [r for r in fresh if r.get("model") == tag]
            if len(carriers) < 1:
                continue
            traffic = m.get("adapter_goodput") or {}
            # spread: every adapter with observed traffic belongs on
            # every fresh replica of its model (the router can only
            # route an adapter request to a replica advertising it)
            for a in sorted(traffic, key=lambda k: -traffic[k]):
                if not traffic[a]:
                    continue
                have = [r for r in carriers if a in r["adapters"]]
                if not have:
                    continue         # traffic but no live copy: stuck
                for dst in carriers:
                    if a not in dst["adapters"]:
                        moves.append({
                            "action": "spread", "model": tag,
                            "adapter": a, "src": have[0]["url"],
                            "dst": dst["url"]})
            if self.retire_idle:
                for r in carriers:
                    for a in sorted(r["adapters"]):
                        if not traffic.get(a):
                            moves.append({
                                "action": "retire", "model": tag,
                                "adapter": a, "src": r["url"],
                                "dst": None})
        return moves

    # -- actuation -----------------------------------------------------------
    def _apply_one(self, mv):
        if mv["action"] == "spread":
            payload = _post_json(mv["src"], "/adapter_export",
                                 {"adapter": mv["adapter"]},
                                 self.timeout_s)
            _post_json(mv["dst"], "/load_adapter", payload,
                       self.timeout_s)
        else:
            _post_json(mv["src"], "/unload_adapter",
                       {"adapter": mv["adapter"]}, self.timeout_s)

    def apply(self, moves):
        """Execute up to ``max_moves`` planned moves; a failed move
        (unreachable peer, pinned adapter, corrupt wire payload) is
        reported in its result row and never aborts the rest."""
        results = []
        for mv in moves[:self.max_moves]:
            row = dict(mv, ok=True)
            try:
                self._apply_one(mv)
            except urllib.error.HTTPError as e:
                row["ok"] = False
                try:
                    row["error"] = (json.loads(e.read())
                                    .get("error") or f"http_{e.code}")
                except (ValueError, OSError):
                    row["error"] = f"http_{e.code}"
            except (urllib.error.URLError, OSError, ValueError) as e:
                row["ok"] = False
                row["error"] = str(e)[:200]
            self._m_moves.labels(
                action=mv["action"],
                outcome="ok" if row["ok"] else "error").inc()
            results.append(row)
        return results

    def rebalance(self, view=None):
        """One plan+apply pass; returns the applied result rows and
        stamps the fleet timeline with what happened (planned count
        included so capped passes are visible as planned > applied)."""
        moves = self.plan(view)
        results = self.apply(moves)
        if results:
            try:
                self.collector.annotate(
                    "catalog_rebalance", planned=len(moves),
                    applied=len(results),
                    ok=sum(1 for r in results if r["ok"]))
            # mxtpu-lint: disable=swallowed-exception (timeline is
            # observability; a broken collector endpoint must never
            # abort a rebalance mid-pass)
            except Exception:
                pass
        return results
