"""One fleet replica: a stdlib-HTTP serving front over ``serve.Engine``.

The engine (PRs 1/4/5/6) is a library; a fleet needs a *process* with a
wire protocol a router can balance over and a supervisor can manage:

  POST /generate       JSON {"prompt": [ids], "max_new_tokens": N,
                       "deadline_s"?, "tenant"?, "request_id"?} ->
                       {"tokens": [...], "rid", "trace_id", "replica"}.
                       503 + {"retriable": true, "error": reason} for
                       back-pressure/draining (retry on a sibling);
                       400 for requests that can never succeed
                       (too long for the model, expired deadline).
                       ``X-MXTPU-Trace-Id`` propagates the router's
                       trace id into the PR 5 RequestTracer so one
                       request's hops across replicas share a timeline.
  GET  /healthz        cheap liveness/readiness: {"state": "ready" |
                       "draining" | "dead", in_flight, queue_depth}.
  POST /drain          stop admitting, finish in-flight work
                       token-identically, report {"state": "draining"}.
  GET  /statusz.json   the full statusz snapshot plus a "replica"
                       section — the router's load-balancing signal
                       (queue depth + KV occupancy).

Idempotency: a ``request_id`` names the client request across retries.
A re-send of an id that already completed returns the CACHED response
(no recompute, no duplicate); a re-send while the first attempt is
still in flight attaches to it.  That is what makes router retries safe
— at-most-once execution per request id per replica, exactly-one
response per id at the client.

Disaggregated prefill/decode roles (``role=`` / ``MXTPU_FLEET_ROLE``)
---------------------------------------------------------------------

A replica serves one of three roles (default ``"both"`` — the
pre-disaggregation behavior, byte-for-byte):

* ``"both"``    — ``/generate`` runs prefill AND decode (the classic
  replica); ``/handoff`` ingests work too.
* ``"prefill"`` — ``/generate`` runs admission + (chunked) prefill
  only, then answers with a ``handoff`` envelope instead of tokens:
  the prompt's cached KV chain serialized as content-keyed records
  (``BlockManager.export_blocks`` — device blocks gathered D2H via
  the PR 12 offload path).  The router moves that payload to a decode
  replica; ``/handoff`` here is refused (503 ``wrong_role``).
* ``"decode"``  — ``/generate`` is refused (503 ``wrong_role``);
  ``POST /handoff`` ingests a prefill replica's records into the
  host-RAM KV tier under the same content keys
  (``BlockManager.import_blocks`` — requires the tier, so the role
  demands ``MXTPU_SERVE_HOST_KV_BYTES`` > 0), then serves the request
  like a normal prompt: the radix walk hits the imported chain, the
  async restore program pulls it HBM-ward ahead of the first decode
  read, and only the final span recomputes.  ``POST /handoff_probe``
  answers which record keys this replica already caches (either
  tier), so a sender skips those bytes — the radix key IS the
  transfer dedup.

Every record is verified against its chain hash at import, so a
truncated/corrupt/chaos-dropped payload degrades to recompute-from-
prompt (the body always carries the prompt) — token output stays
byte-identical to a role="both" fleet in every failure arm.

Fleet KV fabric (cache-aware routing + peer-to-peer pull)
---------------------------------------------------------

Every replica advertises a ``kv_summary`` (``BlockManager.summary()``
— a counting-bloom + top-K ``RadixSummary`` snapshot, size-bounded,
maintained incrementally off publish/evict events) on ``/healthz``
and in the ``/statusz.json`` replica section; the affinity router
(``MXTPU_ROUTE_AFFINITY`` > 0) probes it to route a prompt toward
its cached prefix.  When the router's pick holds LESS of the chain
than a sibling advertises, the ``/generate`` body carries a
``kv_pull`` hint and this replica pulls the chain from the sibling's
``POST /chain_export`` into its host-RAM tier through the same
verified import path as a handoff — sha1 payload digests plus
chain-hash verification, any failure (timeout, corruption, bloom
false positive) degrading to recompute-from-prompt.

Model catalog (multi-tenant adapters over the fleet)
----------------------------------------------------

A replica optionally declares the checkpoint it carries (``model=`` /
``MXTPU_FLEET_MODEL``) and — on an adapters-mode engine — the LoRA
adapters registered on its ``AdapterStore``.  Both ride ``/healthz``
and the ``/statusz.json`` replica section only-when-set, so untagged
fleets keep the historical schemas byte-for-byte.  ``/generate``
accepts ``"model"`` / ``"adapter"`` fields with the PR 15 sampling-
param discipline: malformed or unknown values are clean 400s (never
500s that would open breakers fleet-wide), a model mismatch is
``wrong_model``, and an adapter whose device slots are all pinned
rejects retriable (``adapter_slots`` — a sibling carrying the adapter
may still serve it).  Three catalog-management endpoints let the
supervisor's rebalancer move adapters at runtime: ``POST
/load_adapter`` (an ``export_records`` wire payload or a host path),
``POST /unload_adapter``, and ``POST /adapter_export`` (serialize a
registered adapter for a peer's load).

Faults (``faults.FaultInjector``) hook ``/generate`` AND ``/handoff``
arrivals so the chaos tests can kill/delay/refuse/hang this replica at
a deterministic request index.  A *kill* is a hard death — ``on_kill``
defaults to an in-process crash (HTTP socket torn down mid-request,
engine abandoned un-shutdown); ``tools/serve_replica.py`` passes
``os._exit`` so a process replica dies for real.  Two handoff-specific
chaos knobs ride the replica too: ``MXTPU_FAULT_HANDOFF_DELAY``
(simulated slow wire per handoff arrival) and
``MXTPU_FAULT_HANDOFF_DROP`` (the first N handoffs' KV records are
discarded — "arrived truncated" — and recomputed from the prompt).
"""

from __future__ import annotations

import base64
import collections
import glob
import json
import os
import tempfile
import threading
import time
import urllib.request
from http.server import BaseHTTPRequestHandler

import numpy as np

from .. import telemetry
from ..base import env_float, env_int
from ..serve.scheduler import FINISHED, QueueFull, REJECTED
from ..telemetry import statusz as statusz_mod
from . import faults as faults_mod

__all__ = ["ReplicaServer", "ROLES", "STARTING", "READY", "DRAINING",
           "DEAD", "RETRIABLE_REASONS", "PERMANENT_REASONS",
           "TRACE_HEADER"]

STARTING = "starting"
READY = "ready"
DRAINING = "draining"
DEAD = "dead"

# fleet roles: "both" interleaves prefill+decode on one engine (the
# inert default), "prefill"/"decode" split them across replicas with
# KV-block handoff over the wire (DistServe-style disaggregation)
ROLES = ("both", "prefill", "decode")

# rejection reasons a sibling replica might still serve (503) vs.
# requests no replica can ever serve (400) — the router's retry
# decision rides this split
RETRIABLE_REASONS = ("queue_full", "tenant_share", "deadline", "draining",
                     "adapter_slots")
PERMANENT_REASONS = ("exceeds_max_len", "exceeds_cache",
                     "deadline_at_submit")

TRACE_HEADER = "X-MXTPU-Trace-Id"

_DONE_CACHE_SIZE = 1024


def _errors(site):
    return telemetry.counter("mxtpu_fleet_replica_errors_total",
                             "replica-front internal failures",
                             ("site",)).labels(site=site)


def _handoff_bytes(direction):
    return telemetry.counter(
        "mxtpu_fleet_handoff_bytes_total",
        "KV bytes moved over prefill->decode handoffs",
        ("direction",)).labels(direction=direction)


def _handoff_blocks(result):
    return telemetry.counter(
        "mxtpu_fleet_handoff_blocks_total",
        "handoff record outcomes at the receiving replica",
        ("result",)).labels(result=result)


def _pull_result(outcome):
    return telemetry.counter(
        "mxtpu_fleet_chain_pulls_total",
        "peer-to-peer KV chain pull outcomes at the pulling replica "
        "(ok / false_positive / failed)",
        ("outcome",)).labels(outcome=outcome)


class ReplicaServer:
    """HTTP front + engine step-loop thread for one replica.

    Args:
      engine: a constructed ``serve.Engine`` (this server owns its
        lifecycle from ``start()`` on: ``stop()`` shuts it down).
      host/port: bind address (port 0 = ephemeral; read ``.port``).
      replica_id: name in responses/telemetry (default ``replica-<port>``).
      fault_injector: a ``faults.FaultInjector`` (default: env spec —
        which is empty/no-op unless ``MXTPU_FAULT_SPEC`` is set).
      on_kill: what a *kill* fault does (default: in-process hard stop;
        process replicas pass ``os._exit``).
      poll_s: completion-poll period of waiting request handlers.
      role: ``"both"`` (default) | ``"prefill"`` | ``"decode"`` — the
        disaggregation role (env ``MXTPU_FLEET_ROLE``; see the module
        docstring).  ``"decode"`` requires the engine's host-RAM KV
        tier (``host_kv_bytes`` > 0): handoff records land there.
      handoff_delay_s / handoff_drop: chaos knobs (env
        ``MXTPU_FAULT_HANDOFF_DELAY`` / ``MXTPU_FAULT_HANDOFF_DROP``):
        seconds slept per ``/handoff`` arrival (a simulated slow
        wire), and how many handoffs' KV records to discard before
        import ("arrived truncated" — degrades to recompute).
      version: deploy identity tag (checkpoint digest or a
        ``--version`` string), surfaced on /healthz and
        /statusz.json so mixed fleets mid-rollout stay tellable
        apart; None = untagged.
    """

    def __init__(self, engine, host="127.0.0.1", port=0, replica_id=None,
                 fault_injector=None, on_kill=None, poll_s=0.002,
                 role=None, handoff_delay_s=None, handoff_drop=None,
                 version=None, model=None):
        self.engine = engine
        self.host = host
        self._requested_port = int(port)
        self.port = None
        self.replica_id = replica_id
        self.faults = (fault_injector if fault_injector is not None
                       else faults_mod.FaultInjector())
        self._on_kill = on_kill if on_kill is not None else self.hard_stop
        self.poll_s = float(poll_s)
        if role is None:
            role = os.environ.get("MXTPU_FLEET_ROLE") or "both"
        if role not in ROLES:
            raise ValueError(
                f"role must be one of {ROLES} (got {role!r})")
        if role == "decode" and engine.blocks.host is None:
            raise ValueError(
                "role='decode' requires the host-RAM KV tier "
                "(Engine(host_kv_bytes=) / MXTPU_SERVE_HOST_KV_BYTES "
                "> 0): handoff records are ingested into it")
        self.role = role
        # deploy identity (checkpoint digest or --version tag): mixed
        # fleets coexist mid-rollout, so every status surface carries
        # it — the collector/deployer tell versions apart by this
        self.version = version
        # catalog identity: the checkpoint this replica carries.  The
        # router filters candidates by it; None = uncataloged (every
        # model-less request matches, model-tagged requests don't)
        if model is None:
            model = os.environ.get("MXTPU_FLEET_MODEL") or None
        self.model = str(model)[:64] if model is not None else None
        self._handoff_delay_s = (
            float(handoff_delay_s) if handoff_delay_s is not None
            else env_float(faults_mod.ENV_HANDOFF_DELAY, 0.0))
        self._lock = threading.RLock()
        # serializes engine.step() dispatches against handoff exports:
        # export_blocks gathers device cache blocks D2H from an HTTP
        # handler thread, and on TPU the step thread's programs DONATE
        # the cache buffers — a concurrent dispatch would invalidate
        # the very buffer mid-gather (CPU never donates, so only a
        # real-chip replica can hit it)
        self._step_lock = threading.Lock()
        self._handoff_drops_left = (
            int(handoff_drop) if handoff_drop is not None
            else env_int(faults_mod.ENV_HANDOFF_DROP, 0))  # guarded-by: _lock
        self._state = STARTING       # guarded-by: _lock
        self._served = 0             # guarded-by: _lock
        self._inflight = {}          # guarded-by: _lock
        self._done_cache = collections.OrderedDict()  # guarded-by: _lock
        # prefill→decode handoff accounting (the replica statusz
        # "handoff" section and the /healthz load signal)
        self._handoff_ingesting = 0      # guarded-by: _lock
        self._handoffs_received = 0      # guarded-by: _lock
        self._handoffs_exported = 0      # guarded-by: _lock
        self._handoff_imported = 0       # guarded-by: _lock
        self._handoff_deduped = 0        # guarded-by: _lock
        self._handoff_rejected = 0       # guarded-by: _lock
        self._handoff_drops = 0          # guarded-by: _lock
        self._handoff_bytes_received = 0  # guarded-by: _lock
        self._handoff_bytes_exported = 0  # guarded-by: _lock
        # fleet KV fabric: peer-to-peer chain pull accounting (the
        # statusz "pull" section CACHE_ROUTE_BENCH.json reads).  A
        # pull is the router-hinted fetch of a sibling's cached chain
        # into THIS replica's host tier; chain_export_* counts the
        # serving side of someone else's pull
        self._pull_timeout_s = env_float("MXTPU_ROUTE_PULL_TIMEOUT", 5.0)
        self._pull_attempts = 0           # guarded-by: _lock
        self._pull_imported = 0           # guarded-by: _lock
        self._pull_deduped = 0            # guarded-by: _lock
        self._pull_rejected = 0           # guarded-by: _lock
        self._pull_false_positives = 0    # guarded-by: _lock
        self._pull_failures = 0           # guarded-by: _lock
        self._pull_bytes_received = 0     # guarded-by: _lock
        self._chain_exports = 0           # guarded-by: _lock
        self._chain_export_blocks = 0     # guarded-by: _lock
        self._chain_export_bytes = 0      # guarded-by: _lock
        # on-demand profiler capture (POST /profilez): bounded-duration
        # jax.profiler windows written under per-capture ids.  One
        # window at a time (the XLA profiler is process-global) with a
        # minimum spacing between windows, so an alert storm cannot
        # keep a replica permanently profiled
        self._profilez_max_s = env_float("MXTPU_PROFILEZ_MAX_S", 10.0)
        self._profilez_interval_s = env_float(
            "MXTPU_PROFILEZ_INTERVAL_S", 30.0)
        self._profilez_dir = os.environ.get("MXTPU_PROFILEZ_DIR") or None
        self._capture_seq = 0                         # guarded-by: _lock
        self._captures = collections.OrderedDict()    # guarded-by: _lock
        self._last_capture_t = None                   # guarded-by: _lock
        self._server = None
        self._http_thread = None
        self._step_thread = None
        self._stop_evt = threading.Event()
        self._work_evt = threading.Event()
        self._health_name = None
        self._statusz_name = None

    # -- lifecycle -----------------------------------------------------------
    @property
    def state(self):
        with self._lock:
            return self._state

    @property
    def url(self):
        return f"http://{self.host}:{self.port}"

    def start(self):
        """Bind, spin up the HTTP and step threads, go READY."""
        from http.server import ThreadingHTTPServer

        replica = self

        class _Server(ThreadingHTTPServer):
            daemon_threads = True

            def handle_error(self, request, client_address):
                # torn connections are EXPECTED here (aborted handlers
                # during a kill fault, clients timing out) — count
                # instead of stack-tracing to stderr per event
                _errors("http").inc()

        self._server = _Server((self.host, self._requested_port),
                               _Handler)
        self._server.replica = self
        self.port = self._server.server_address[1]
        if self.replica_id is None:
            self.replica_id = f"replica-{self.port}"
        # stamp this replica's identity onto the engine's request
        # tracer: every trace line it writes/ships (MXTPU_TRACE_PUSH_URL
        # -> the fleet collector) names the replica that served it, so
        # the collector can attribute SLO-offending requests
        self.engine._rtrace.identity = self.replica_id
        self.engine._rtrace.model = self.model
        self._http_thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name=f"mxtpu-replica-http-{self.port}")
        self._http_thread.start()
        self._step_thread = threading.Thread(
            target=self._step_loop, daemon=True,
            name=f"mxtpu-replica-step-{self.port}")
        self._step_thread.start()
        self._health_name = statusz_mod.register_health(
            f"fleet.{self.replica_id}", self._health)
        self._statusz_name = statusz_mod.register(
            f"fleet.{self.replica_id}", self._replica_state)
        with self._lock:
            self._state = READY
        return self

    def drain(self):
        """Stop admitting new requests; in-flight work keeps stepping
        to completion (token-identically — the schedule is untouched,
        only admission closes)."""
        with self._lock:
            if self._state == READY:
                self._state = DRAINING
        telemetry.counter("mxtpu_fleet_replica_drains_total",
                          "drain requests accepted").inc()
        return self.state

    def drained(self):
        """True once draining AND no queued or in-flight work remains
        (the supervisor's terminate-safe signal).  ``engine.has_work``
        covers the scheduler AND n>1 fanout siblings still awaiting
        release — a drain must not terminate a replica whose sample
        group hasn't fully entered the scheduler yet."""
        return (self.state == DRAINING
                and not self.engine.has_work()
                and not self._inflight)

    def stop(self):
        """Clean shutdown: step thread stops, engine releases its
        device buffers, HTTP socket closes."""
        with self._lock:
            if self._state == DEAD:
                return
            self._state = DEAD
        self._teardown_http()
        self._stop_evt.set()
        self._work_evt.set()
        if self._step_thread is not None:
            self._step_thread.join(timeout=10)
        try:
            self.engine.shutdown()
        except Exception:
            _errors("shutdown").inc()

    def hard_stop(self):
        """Simulate a crash (the in-process analog of ``os._exit``):
        the HTTP socket dies mid-request, waiting handlers abort their
        connections, the engine is abandoned WITHOUT shutdown — exactly
        what a killed process leaves behind."""
        with self._lock:
            self._state = DEAD
        self._stop_evt.set()
        self._work_evt.set()
        self._teardown_http()
        # the abandoned engine must still leave the process-global
        # /statusz page NOW (a real crash takes the whole registry with
        # it; the in-process simulation has to evict explicitly rather
        # than wait for cyclic GC to collect the engine's weakref)
        statusz_mod.unregister(getattr(self.engine, "_statusz_name", ""))

    def _teardown_http(self):
        statusz_mod.unregister_health(self._health_name)
        statusz_mod.unregister(self._statusz_name)
        server, self._server = self._server, None
        if server is not None:
            # shutdown() stops serve_forever; server_close() frees the
            # port and snaps open keep-alive connections
            threading.Thread(target=server.shutdown, daemon=True).start()
            try:
                server.server_close()
            except OSError:
                _errors("server_close").inc()

    # -- engine pump ---------------------------------------------------------
    def _step_loop(self):
        # engine.has_work (not scheduler.has_work): n>1 siblings wait
        # ENGINE-side until their primary's prefill publishes the
        # prompt's blocks — a primary that finishes in its very first
        # step (max_new=1) would otherwise leave the scheduler empty,
        # park this loop, and hang the waiting /generate handler with
        # its siblings never released
        while not self._stop_evt.is_set():
            if self.engine.has_work():
                try:
                    with self._step_lock:
                        self.engine.step()
                except Exception:
                    # an engine that cannot step is a dead replica: fail
                    # fast so the router's probes see it gone (the
                    # engine already force-dumped the flight ring)
                    _errors("step").inc()
                    self.hard_stop()
                    return
            else:
                self._work_evt.wait(0.05)
                self._work_evt.clear()

    # -- request handling (called from HTTP handler threads) -----------------
    def handle_generate(self, body, trace_id=None):
        """Returns ``(http_status, payload_dict)`` or ``None`` meaning
        "abort the connection without a response" (replica died)."""
        return self._with_faults(self._serve_generate, body, trace_id)

    def handle_handoff(self, body, trace_id=None):
        """``POST /handoff``: ingest a prefill replica's exported KV
        chain, then serve the request's decode.  Same return contract
        as :meth:`handle_generate`; the fault injector counts handoff
        arrivals through the same hook, so ``kill@k`` on a decode
        replica fires mid-stream while serving its k-th handoff."""
        return self._with_faults(self._serve_handoff, body, trace_id)

    def _with_faults(self, fn, body, trace_id):
        """Apply this arrival's chaos verdict around ``fn``."""
        fault = self.faults.on_request()
        if fault is not None and fault.action == "refuse":
            return 503, {"error": "fault_refuse", "retriable": True}
        if fault is not None and fault.action == "delay":
            time.sleep(fault.arg)
        if fault is not None and fault.action == "hang":
            # hold the connection unanswered until the client gives up
            # (bounded by arg so a test teardown never waits forever)
            deadline = time.monotonic() + fault.arg
            while time.monotonic() < deadline \
                    and not self._stop_evt.is_set():
                time.sleep(min(0.05, self.poll_s * 10))
            return None
        kill = fault is not None and fault.action == "kill"
        result = fn(body, trace_id, kill)
        if kill and result is not None:
            # the arrival the fault spec kills must never be answered —
            # whatever its answer would have been (a dedup-cache hit, a
            # rejection, or a generation that finished before the
            # mid-stream threshold); deterministic chaos means the
            # replica IS dead after request k, full stop
            self._on_kill()
            return None
        return result

    def _serve_generate(self, body, trace_id, kill, handoff=False):
        if self.state != READY:
            return 503, {"error": "draining", "retriable": True,
                         "state": self.state}
        if self.role == "decode" and not handoff:
            # a decode-role replica only ingests /handoff work; a
            # misrouted prompt (stale scrape) retries on a sibling
            return 503, {"error": "wrong_role", "retriable": True,
                         "role": self.role}
        prefill_only = self.role == "prefill" and not handoff
        request_id = body.get("request_id")
        try:
            prompt = [int(t) for t in body["prompt"]]
            max_new = int(body.get("max_new_tokens", 64))
        except (KeyError, TypeError, ValueError):
            return 400, {"error": "bad_request", "retriable": False}
        deadline_s = body.get("deadline_s")
        if deadline_s is not None:
            try:
                deadline_s = float(deadline_s)
            except (TypeError, ValueError):
                return 400, {"error": "bad_request", "retriable": False}
        # per-request sampling params: malformed values are clean 400s
        # on EVERY replica — never 500s the router would count as
        # transport failures and open breakers fleet-wide
        try:
            temperature = body.get("temperature")
            temperature = (None if temperature is None
                           else float(temperature))
            top_p = body.get("top_p")
            top_p = None if top_p is None else float(top_p)
            top_k = body.get("top_k")
            top_k = None if top_k is None else int(top_k)
            n = int(body.get("n", 1))
            logprobs = int(body.get("logprobs", 0))
        except (TypeError, ValueError):
            return 400, {"error": "bad_request", "retriable": False}
        if ((temperature is not None
             and not (np.isfinite(temperature) and temperature >= 0))
                or (top_p is not None
                    and not (np.isfinite(top_p) and 0 < top_p <= 1))
                or (top_k is not None and top_k < 0)
                or not 1 <= n <= 64 or not 0 <= logprobs <= 5):
            return 400, {"error": "bad_request", "retriable": False}
        if not prompt or max_new < 1:
            # invalid on EVERY replica: a clean 400, never a 500 the
            # router would count as a transport failure and retry
            # fleet-wide (three such requests would otherwise open
            # every breaker)
            return 400, {"error": "bad_request", "retriable": False}
        if prefill_only \
                and len(prompt) + max_new > self.engine.max_model_len:
            # a prefill replica only submits prompt+1 (it never
            # decodes), so the engine's own exceeds_max_len guard
            # would miss the FULL request length — check it here, or
            # the fleet would pay a whole prefill + handoff before the
            # decode replica's admission rejects it
            return 400, {"error": "exceeds_max_len", "retriable": False}
        tenant = body.get("tenant")
        if tenant is not None:
            # bound client-supplied tenant labels: they key per-tenant
            # scheduler/telemetry state, which must not grow with
            # arbitrary client strings
            tenant = str(tenant)[:64]
        # catalog params, same discipline as the sampling params above:
        # unknown/malformed values are clean 400s on every replica —
        # the router filters by model BEFORE forwarding, so a mismatch
        # here means a stale scrape or a direct client; either way no
        # retry on this replica can succeed
        model = body.get("model")
        if model is not None:
            if not isinstance(model, str) or not model:
                return 400, {"error": "bad_request", "retriable": False}
            if model[:64] != self.model:
                return 400, {"error": "wrong_model", "retriable": False,
                             "model": self.model}
        adapter = body.get("adapter")
        if adapter is not None:
            if not isinstance(adapter, str) or not adapter:
                return 400, {"error": "bad_request", "retriable": False}
            adapter = adapter[:64]
            store = getattr(self.engine, "adapter_store", None)
            if store is None or not store.known(adapter):
                return 400, {"error": "unknown_adapter",
                             "retriable": False, "adapter": adapter}
        pull = body.get("kv_pull")
        if pull is not None:
            # router hint: a sibling advertises more of this prompt's
            # chain than we hold — pull it into the host tier before
            # admission so the radix walk hits it.  Strictly
            # best-effort: every failure arm degrades to recompute
            self._maybe_pull_chain(pull, prompt, salt=adapter)
        # a prefill-role replica runs admission + (chunked) prefill
        # only: max_new_tokens=1 makes the prefill pass's own sampled
        # token the request's last — it FINISHES at prefill end, its
        # blocks park published with K/V intact, and export_blocks
        # re-walks them by content.  The one emitted token is
        # discarded; the decode replica regenerates it when it
        # recomputes the final span (greedy — byte-identical)
        serve_new = 1 if prefill_only else max_new
        # a prefill replica never fans out: the decode replica serves
        # the n>1 group itself after the handoff (the shared prefix
        # travels once either way)
        serve_n = 1 if prefill_only else n

        def submit():
            return self.engine.submit(prompt, max_new_tokens=serve_new,
                                      deadline_s=deadline_s,
                                      tenant=tenant, trace_id=trace_id,
                                      handoff=handoff,
                                      temperature=temperature,
                                      top_p=top_p, top_k=top_k,
                                      n=serve_n, logprobs=logprobs,
                                      adapter_id=adapter)

        try:
            if request_id is not None:
                # reserve-or-attach is ONE atomic step: cache lookup,
                # in-flight lookup and engine submit all under _lock,
                # so two concurrent retries of the same id can never
                # both execute (engine.submit only takes the scheduler
                # lock — no inverse ordering exists)
                with self._lock:
                    cached = self._done_cache.get(request_id)
                    if cached is not None:
                        # retry of a completed id: same answer, no
                        # recompute
                        return 200, dict(cached, deduped=True)
                    req = self._inflight.get(request_id)
                    if req is None:
                        req = submit()
                        if req.status != REJECTED:
                            self._inflight[request_id] = req
            else:
                req = submit()
        except QueueFull:
            return 503, {"error": "queue_full", "retriable": True}
        except ValueError:
            # anything Request/engine validation still rejects is a
            # client error, not a replica failure
            return 400, {"error": "bad_request", "retriable": False}
        if req.status == REJECTED:
            return self._reject_response(req)
        self._work_evt.set()

        # a kill fault dies MID-STREAM: once the request has produced
        # about half its tokens — the worst moment (on a prefill-role
        # replica that is the moment prefill completes)
        kill_after = max(1, serve_new // 2) if kill else None
        while (not req.done
               or (req.samples
                   and any(not s.done for s in req.samples))):
            if kill_after is not None and len(req.tokens) >= kill_after:
                self._on_kill()
                return None
            if self._stop_evt.is_set():
                return None              # replica died under us: abort
            time.sleep(self.poll_s)
        if req.status != FINISHED:
            if request_id is not None:
                with self._lock:
                    self._inflight.pop(request_id, None)
            if req.status == REJECTED:
                return self._reject_response(req)
            return 503, {"error": req.status, "retriable": True}
        if prefill_only:
            # the prefill answer is a HANDOFF ENVELOPE, not tokens:
            # the prompt's cached chain as content-keyed wire records
            # (the router moves it to a decode replica).  Exported
            # under the step lock: the D2H gather must never race a
            # step dispatch that donates the cache buffers away
            with self._step_lock:
                records, nbytes = self._encode_records(
                    self.engine.blocks.export_blocks(req.rid, prompt,
                                                     salt=adapter))
            payload = {"handoff": {"records": records,
                                   "prefill_replica": self.replica_id,
                                   "cached_tokens": req.cached_prefix_len,
                                   "prefilled": int(req.cache_len)},
                       "rid": req.rid, "trace_id": req.trace_id,
                       "tenant": req.tenant,
                       "replica": self.replica_id}
        else:
            nbytes = 0
            payload = {"tokens": list(req.tokens), "rid": req.rid,
                       "trace_id": req.trace_id, "tenant": req.tenant,
                       "replica": self.replica_id,
                       "n_preemptions": req.n_preemptions}
            # sampling extras ride only-when-asked, so plain requests'
            # response payloads stay byte-identical
            if logprobs:
                payload["token_logprobs"] = list(req.token_logprobs)
                payload["top_logprobs"] = list(req.top_logprobs)
            if req.samples:
                payload["samples"] = [
                    dict({"tokens": list(s.tokens), "rid": s.rid},
                         **({"status": s.status}
                            if s.status != FINISHED else {}),
                         **({"token_logprobs": list(s.token_logprobs),
                             "top_logprobs": list(s.top_logprobs)}
                            if logprobs else {}))
                    for s in req.samples]
        with self._lock:
            # cache-write and in-flight pop are ONE locked step: a
            # retry arriving between them would miss both lookups and
            # re-execute.  When several handlers attached to one
            # in-flight request, only the first to land here counts it
            # served and writes the cache; the rest return the same
            # payload without double-counting.
            first = request_id is None or request_id not in self._done_cache
            if request_id is None:
                self._served += 1
            elif first:
                self._served += 1
                self._done_cache[request_id] = payload
                while len(self._done_cache) > _DONE_CACHE_SIZE:
                    self._done_cache.popitem(last=False)
            if request_id is not None:
                self._inflight.pop(request_id, None)
            if first and prefill_only:
                self._handoffs_exported += 1
                self._handoff_bytes_exported += nbytes
        if first and prefill_only:
            _handoff_bytes("exported").inc(nbytes)
        return 200, payload

    def _serve_handoff(self, body, trace_id, kill):
        """Ingest one prefill→decode handoff, then serve its decode.

        The KV records import into the host tier under their content
        keys; the request then runs like a plain prompt — the radix
        walk hits the imported chain, so only the final span (and
        whatever a failed/dropped/truncated import left uncovered)
        recomputes.  Degradation is always recompute-from-prompt,
        never an error: the body carries the prompt."""
        if self.state != READY:
            return 503, {"error": "draining", "retriable": True,
                         "state": self.state}
        if self.role == "prefill":
            return 503, {"error": "wrong_role", "retriable": True,
                         "role": self.role}
        if self._handoff_delay_s > 0:
            # chaos: simulated slow wire (pushed past the router's
            # per-hop timeout it exercises re-handoff on a sibling)
            time.sleep(self._handoff_delay_s)
            if self._stop_evt.is_set():
                return None
        request_id = body.get("request_id")
        if request_id is not None:
            with self._lock:
                done = request_id in self._done_cache
            if done:
                # a re-handoff of an id this replica already completed
                # (first delivery's response was lost): skip the whole
                # base64 decode + import — _serve_generate answers
                # from the done-cache either way
                return self._serve_generate(body, trace_id, kill,
                                            handoff=True)
        records = body.get("records") or []
        with self._lock:
            dropped = self._handoff_drops_left > 0 and bool(records)
            if dropped:
                self._handoff_drops_left -= 1
                self._handoff_drops += 1
        if dropped:
            records = []    # "arrived truncated": recompute from prompt
        imported = deduped = rejected = 0
        nbytes = 0
        with self._lock:
            self._handoff_ingesting += 1
        try:
            try:
                parsed, nbytes = self._decode_records(records)
                # the sender salted the chain with the request's
                # adapter id; verification needs the same root
                adp = body.get("adapter")
                adp = (adp[:64] if isinstance(adp, str) and adp
                       else None)
                imported, deduped, rejected = \
                    self.engine.blocks.import_blocks(parsed, salt=adp)
            except (KeyError, TypeError, ValueError):
                # malformed payload: the prompt is still fully
                # servable here — degrade to recompute, never a 400
                # (which the router would treat as permanent)
                rejected = len(records)
        finally:
            with self._lock:
                self._handoff_ingesting -= 1
                self._handoffs_received += 1
                self._handoff_imported += imported
                self._handoff_deduped += deduped
                self._handoff_rejected += rejected
                self._handoff_bytes_received += nbytes
        _handoff_bytes("received").inc(nbytes)
        if imported:
            _handoff_blocks("imported").inc(imported)
        if deduped:
            _handoff_blocks("deduped").inc(deduped)
        if rejected:
            _handoff_blocks("rejected").inc(rejected)
        return self._serve_generate(body, trace_id, kill, handoff=True)

    def _maybe_pull_chain(self, spec, prompt, salt=None):
        """Pull a sibling's cached KV chain for ``prompt`` into the
        local host tier — the peer-to-peer leg of the fleet KV fabric.

        ``spec`` is the router's ``kv_pull`` hint: ``{"peer": url,
        "tokens": advertised_prefix_tokens}``.  The pull POSTs the
        peer's ``/chain_export`` and lands the records through the
        SAME verified import path as a prefill→decode handoff
        (payload sha1 in ``_decode_records``, chain hash in
        ``import_blocks``) — so a shared prefix is prefilled once per
        fleet and shipped once per host.  Best-effort by contract:
        a malformed hint, an unreachable/slow peer
        (``MXTPU_ROUTE_PULL_TIMEOUT``), a corrupted payload, or a
        bloom false positive (the peer exports nothing) all degrade
        to recompute-from-prompt — never an error, never a wrong
        token.  Skipped outright when the local cache already covers
        at least the advertised span, or without a host tier to land
        the records in."""
        eng = self.engine
        if eng.blocks.host is None or not eng.blocks.prefix_cache:
            return
        try:
            peer = str(spec.get("peer") or "")
            tokens = int(spec.get("tokens") or 0)
        except (AttributeError, TypeError, ValueError):
            return
        if not peer.startswith("http") \
                or tokens < eng.blocks.block_size:
            return
        _, local = eng.blocks.prefix_probe(prompt, salt=salt)
        if local >= tokens:
            return            # already as warm as the peer advertises
        with self._lock:
            self._pull_attempts += 1
        try:
            pull_body = {"prompt": prompt}
            if salt is not None:
                # adapter-salted chains live in a disjoint key space;
                # the peer must export with the same salt
                pull_body["adapter"] = salt
            req = urllib.request.Request(
                f"{peer.rstrip('/')}/chain_export",
                data=json.dumps(pull_body).encode(),
                method="POST",
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(
                    req, timeout=self._pull_timeout_s) as resp:
                out = json.loads(resp.read())
            records = out.get("records") or []
            parsed, nbytes = self._decode_records(records)
            imported, deduped, rejected = \
                eng.ingest_pulled_blocks(parsed, salt=salt)
        except (OSError, KeyError, TypeError, ValueError):
            # transport failure, truncation, or digest mismatch: the
            # prompt is still fully servable here — recompute
            with self._lock:
                self._pull_failures += 1
            _pull_result("failed").inc()
            return
        # an empty export despite the advertisement is the bloom
        # false-positive arm (or the chain was evicted since the
        # scrape) — count it so the advertised FP bound is observable
        false_positive = not records
        with self._lock:
            self._pull_imported += imported
            self._pull_deduped += deduped
            self._pull_rejected += rejected
            self._pull_bytes_received += nbytes
            if false_positive:
                self._pull_false_positives += 1
        _handoff_bytes("pulled").inc(nbytes)
        _pull_result("false_positive" if false_positive else "ok").inc()

    def handle_chain_export(self, body):
        """``POST /chain_export``: serialize this replica's cached
        chain for a peer's prompt — the serving half of a peer-to-peer
        pull.  Read-only against the cache (D2H gather + host-pool
        peek, never a claim, never an index mutation) and never
        fault-injected: a pull is a bytes optimization, and chaos must
        exercise the PULLER's degrade path, not synthesize fake
        request arrivals here.  Exported under the step lock exactly
        like a prefill handoff: the gather must not race a step
        dispatch that donates the cache buffers away."""
        try:
            prompt = [int(t) for t in body["prompt"]]
        except (KeyError, TypeError, ValueError):
            return 400, {"error": "bad_request", "retriable": False}
        if not prompt:
            return 400, {"error": "bad_request", "retriable": False}
        adp = body.get("adapter")
        adp = adp[:64] if isinstance(adp, str) and adp else None
        with self._step_lock:
            records, nbytes = self._encode_records(
                self.engine.blocks.export_blocks(None, prompt,
                                                 salt=adp))
        with self._lock:
            self._chain_exports += 1
            self._chain_export_blocks += len(records)
            self._chain_export_bytes += nbytes
        _handoff_bytes("chain_exported").inc(nbytes)
        return 200, {"replica": self.replica_id, "records": records}

    def _encode_records(self, recs):
        """``export_blocks`` output -> JSON-ready wire records (raw
        K/V bytes base64'd, plus a payload digest — the chain hash
        covers keys/tokens only, so corruption of the K/V bytes
        themselves needs its own check).  Returns ``(records,
        payload_bytes)``."""
        import hashlib

        records, nbytes = [], 0
        for key, parent, tokens, arrays in recs:
            rec = {"key": key.hex(),
                   "parent": parent.hex() if parent is not None else None,
                   "tokens": tokens}
            digest = hashlib.sha1()
            for name, a in zip(("k", "v", "ksc", "vsc"), arrays):
                raw = np.ascontiguousarray(a).tobytes()
                digest.update(raw)
                rec[name] = base64.b64encode(raw).decode("ascii")
                nbytes += len(raw)
            rec["digest"] = digest.hexdigest()[:16]
            records.append(rec)
        return records, nbytes

    def _decode_records(self, records):
        """Wire records -> ``import_blocks`` input, every payload
        validated against the engine's host-block spec (shape x dtype
        bytes) AND its payload digest — the chain hash
        ``import_blocks`` re-verifies covers only keys/tokens, so
        same-length byte corruption needs the digest to be caught
        before wrong K/V can park under a valid content key.  A
        record without payload fields is a dedup-probe skip (the
        sender knows this replica already hosts the block)."""
        import hashlib

        specs = self.engine.host_block_spec()
        names = ("k", "v", "ksc", "vsc")[:len(specs)]
        parsed, nbytes = [], 0
        for rec in records:
            key = bytes.fromhex(rec["key"])
            parent = (bytes.fromhex(rec["parent"])
                      if rec.get("parent") else None)
            tokens = [int(t) for t in rec["tokens"]]
            arrays = None
            if all(n in rec for n in names):
                arrays = []
                digest = hashlib.sha1()
                for n, (shape, dt) in zip(names, specs):
                    raw = base64.b64decode(rec[n])
                    want = int(np.prod(shape)) * dt.itemsize
                    if len(raw) != want:
                        raise ValueError(
                            f"handoff record {n} holds {len(raw)} "
                            f"bytes, expected {want}")
                    digest.update(raw)
                    arrays.append(np.frombuffer(raw, dt).reshape(shape))
                    nbytes += len(raw)
                if rec.get("digest") is not None \
                        and digest.hexdigest()[:16] != rec["digest"]:
                    raise ValueError("handoff record payload digest "
                                     "mismatch (corrupted in transit)")
                arrays = tuple(arrays)
            parsed.append((key, parent, tokens, arrays))
        return parsed, nbytes

    # -- catalog management (supervisor rebalance surface) -------------------
    def _adapter_store_or_400(self, body):
        store = getattr(self.engine, "adapter_store", None)
        if store is None:
            return None, None, (400, {"error": "adapters_off",
                                      "retriable": False})
        adapter = body.get("adapter")
        if not isinstance(adapter, str) or not adapter:
            return None, None, (400, {"error": "bad_request",
                                      "retriable": False})
        return store, adapter[:64], None

    def handle_load_adapter(self, body):
        """Register an adapter at runtime: either an ``export_records``
        wire payload (sha1-verified per array) or a ``save_file`` host
        path.  Idempotent — re-loading registered content dedups by
        digest."""
        store, adapter, err = self._adapter_store_or_400(body)
        if err is not None:
            return err
        try:
            if body.get("records") is not None:
                store.import_records(adapter, body)
            elif body.get("path") is not None:
                store.load_file(adapter, str(body["path"]))
            else:
                return 400, {"error": "bad_request", "retriable": False}
        except (KeyError, OSError, TypeError, ValueError) as e:
            # a corrupt/oversized/malformed payload is the CALLER's
            # problem — never a 500 that opens breakers
            return 400, {"error": "bad_adapter", "retriable": False,
                         "detail": str(e)[:200]}
        return 200, {"adapter": adapter, "adapters": store.ids(),
                     "replica": self.replica_id}

    def handle_unload_adapter(self, body):
        """De-catalog an adapter (rebalance move-away).  An adapter
        pinned by running requests refuses retriable — the caller
        drains and retries."""
        store, adapter, err = self._adapter_store_or_400(body)
        if err is not None:
            return err
        try:
            removed = store.forget(adapter)
        except RuntimeError:
            return 503, {"error": "adapter_pinned", "retriable": True}
        if not removed:
            return 400, {"error": "unknown_adapter", "retriable": False,
                         "adapter": adapter}
        return 200, {"adapter": adapter, "adapters": store.ids(),
                     "replica": self.replica_id}

    def handle_adapter_export(self, body):
        """Serialize a registered adapter for a peer's /load_adapter
        (the rebalancer's copy half — adapters move replica-to-replica
        without a shared filesystem)."""
        store, adapter, err = self._adapter_store_or_400(body)
        if err is not None:
            return err
        if not store.known(adapter):
            return 400, {"error": "unknown_adapter", "retriable": False,
                         "adapter": adapter}
        payload = store.export_records(adapter)
        payload["replica"] = self.replica_id
        return 200, payload

    @property
    def waiting_handoffs(self):
        """Handoff ingests this replica has accepted but not yet
        admitted to prefill/decode (mid-import, or queued awaiting
        restore) — the /healthz load-signal component that stops the
        router's least-loaded pick from dog-piling a replica whose
        in-flight ingests haven't reached the running set yet."""
        with self._lock:
            ingesting = self._handoff_ingesting
        return ingesting + self.engine.scheduler.waiting_handoffs()

    # -- on-demand profiler capture (/profilez) ------------------------------
    _CAPTURE_KEEP = 8      # finished-capture metadata entries retained

    def _active_capture_locked(self):
        for cap in reversed(self._captures.values()):
            if cap["state"] == "running":
                return cap
        return None

    def handle_profilez(self, body):
        """``POST /profilez``: start a bounded-duration, process-global
        ``jax.profiler`` capture window and answer immediately with its
        capture id; the window runs out on a background thread and the
        artifact is served back by ``GET /profilez/<id>`` (metadata)
        and ``GET /profilez/<id>/trace`` (the gzip trace itself).

        One window at a time — a second POST answers a clean 409
        ``capture_in_progress`` (never the RuntimeError→500 that would
        trip router breakers) — and windows are rate-limited (429,
        ``MXTPU_PROFILEZ_INTERVAL_S``) with durations clamped to
        ``MXTPU_PROFILEZ_MAX_S``.  Draining or stopping the replica
        mid-window ends the capture cleanly (early stop, artifact
        kept).  Never fault-injected: control-plane, not traffic."""
        from .. import profiler as profiler_mod

        try:
            duration = float(body.get("duration_s", 1.0))
        except (TypeError, ValueError):
            return 400, {"error": "bad_request", "retriable": False}
        if not duration > 0.0:
            return 400, {"error": "bad_request", "retriable": False}
        duration = min(duration, self._profilez_max_s)
        reason = str(body.get("reason") or "on_demand")[:64]
        now = time.monotonic()
        with self._lock:
            active = self._active_capture_locked()
            if active is not None:
                return 409, {"error": "capture_in_progress",
                             "retriable": False, "id": active["id"],
                             "replica": self.replica_id}
            if self._last_capture_t is not None \
                    and now - self._last_capture_t \
                    < self._profilez_interval_s:
                retry = (self._profilez_interval_s
                         - (now - self._last_capture_t))
                return 429, {"error": "rate_limited", "retriable": True,
                             "retry_after_s": round(retry, 3),
                             "replica": self.replica_id}
            self._capture_seq += 1
            cap_id = f"{self.replica_id}-cap{self._capture_seq}"
            logdir = os.path.join(
                self._profilez_dir or os.path.join(
                    tempfile.gettempdir(),
                    f"mxtpu_profilez_{os.getpid()}"),
                cap_id)
            cap = {"id": cap_id, "state": "running", "reason": reason,
                   "duration_s": duration, "logdir": logdir,
                   # epoch stamp: capture_fleet aligns cross-replica
                   # windows (and timeline_report places the device
                   # events) on the wall clock
                   # mxtpu-lint: disable=wall-clock (cross-replica capture alignment stamp)
                   "started_epoch": time.time(),
                   "replica": self.replica_id, "trace_file": None,
                   "error": None}
            try:
                os.makedirs(logdir, exist_ok=True)
                profiler_mod.start(logdir)
            except profiler_mod.ProfilerActive as e:
                # someone else (another in-process replica, a bench
                # harness) holds the process-global profiler — the
                # same clean conflict as our own active window
                return 409, {"error": "capture_in_progress",
                             "retriable": False, "detail": str(e)[:200],
                             "replica": self.replica_id}
            except Exception as e:
                _errors("profilez_start").inc()
                return 500, {"error": "profiler_start_failed",
                             "retriable": True, "detail": str(e)[:200]}
            self._last_capture_t = now
            self._captures[cap_id] = cap
            while len(self._captures) > self._CAPTURE_KEEP:
                oldest = next(iter(self._captures))
                if self._captures[oldest]["state"] == "running":
                    break
                self._captures.pop(oldest)
        threading.Thread(
            target=self._finish_capture, args=(cap,), daemon=True,
            name=f"mxtpu-profilez-{self.port}").start()
        telemetry.counter("mxtpu_fleet_profilez_total",
                          "profiler capture requests by outcome",
                          ("outcome",)).labels(outcome="started").inc()
        return 200, {"id": cap_id, "state": "running",
                     "duration_s": duration, "logdir": logdir,
                     "started_epoch": cap["started_epoch"],
                     "replica": self.replica_id}

    def _finish_capture(self, cap):
        """Background tail of one capture window: wait out the bounded
        duration (early-out when the replica stops — drain/stop during
        a capture ends the window cleanly, keeping whatever was
        captured), stop the profiler, locate the artifact."""
        from .. import profiler as profiler_mod

        self._stop_evt.wait(cap["duration_s"])
        err = None
        try:
            profiler_mod.stop()
        except Exception as e:
            # a failed stop must not leave the entry "running" forever
            err = f"{type(e).__name__}: {e}"[:200]
        trace_file = None
        try:
            found = glob.glob(os.path.join(
                cap["logdir"], "plugins", "profile", "*",
                "*.trace.json.gz"))
            if found:
                trace_file = max(found, key=os.path.getmtime)
        except OSError:
            pass
        if err is None and trace_file is None:
            err = "no trace artifact written (capture aborted early?)"
        with self._lock:
            cap["trace_file"] = trace_file
            cap["error"] = err
            cap["state"] = "failed" if err else "done"
        telemetry.counter("mxtpu_fleet_profilez_total",
                          "profiler capture requests by outcome",
                          ("outcome",)).labels(
                              outcome="failed" if err else "done").inc()

    def handle_profilez_get(self, cap_id):
        """``GET /profilez/<id>``: capture metadata (state running/
        done/failed, logdir, trace file, epoch window)."""
        with self._lock:
            cap = self._captures.get(cap_id)
            if cap is None:
                return 404, {"error": "unknown_capture",
                             "retriable": False}
            return 200, dict(cap)

    def _reject_response(self, req):
        reason = req.reject_reason or "rejected"
        retriable = reason in RETRIABLE_REASONS
        return ((503 if retriable else 400),
                {"error": reason, "retriable": retriable,
                 "rid": req.rid, "trace_id": req.trace_id})

    # -- introspection -------------------------------------------------------
    def _health(self):
        state = self.state
        hk = self.engine.host_kv_stats()
        payload = {"status": "ok" if state == READY else state,
                "state": state,
                # the disaggregation role: the router routes prompts
                # to prefill-capable replicas and handoffs to
                # decode-capable ones
                "role": self.role,
                "in_flight": len(self._inflight),
                "queue_depth": self.engine.scheduler.queue_depth,
                # mid-chunked-prefill requests hold a batch slot too —
                # a replica grinding a long prefill must report the load
                "running": (len(self.engine.scheduler.running)
                            + len(self.engine.scheduler.prefilling)),
                # accepted handoff ingests not yet running: without
                # this a decode replica mid-ingest under-reports load
                # and attracts every next handoff
                "waiting_handoffs": self.waiting_handoffs,
                # host-DRAM KV tier occupancy (None with the tier off):
                # a saturated pool means further evictions re-pay
                # recompute, so the tier's headroom IS a load signal
                "host_kv_utilization": (hk["utilization"]
                                        if hk is not None else None),
                # the routable-cache advertisement (RadixSummary
                # snapshot; None with the prefix cache off).  Size-
                # bounded by construction: bloom_bits/8 bytes of
                # bitmap + top_k truncated-hex keys, ~1.2 KB at the
                # defaults, independent of cache size
                "kv_summary": self.engine.kv_summary()}
        # deploy identity is optional: untagged replicas keep the
        # pre-control-plane /healthz schema byte-for-byte
        if self.version is not None:
            payload["version"] = self.version
        # catalog advertisement, only-when-set for the same reason:
        # the carried checkpoint and the registered (routable) adapters
        if self.model is not None:
            payload["model"] = self.model
        store = getattr(self.engine, "adapter_store", None)
        if store is not None:
            payload["adapters"] = store.ids()
        return payload

    def _replica_state(self):
        """The router's balancing signal: readiness plus live load
        (queue depth, decode batch occupancy, KV occupancy)."""
        eng = self.engine
        with self._lock:
            state, served = self._state, self._served
            inflight = len(self._inflight)
        hk = eng.host_kv_stats()
        with self._lock:
            handoff = {"received": self._handoffs_received,
                       "exported": self._handoffs_exported,
                       "blocks_imported": self._handoff_imported,
                       "blocks_deduped": self._handoff_deduped,
                       "blocks_rejected": self._handoff_rejected,
                       "drops": self._handoff_drops,
                       "bytes_received": self._handoff_bytes_received,
                       "bytes_exported": self._handoff_bytes_exported}
            pull = {"attempts": self._pull_attempts,
                    "blocks_imported": self._pull_imported,
                    "blocks_deduped": self._pull_deduped,
                    "blocks_rejected": self._pull_rejected,
                    "false_positives": self._pull_false_positives,
                    "failures": self._pull_failures,
                    "bytes_received": self._pull_bytes_received,
                    "chain_exports": self._chain_exports,
                    "chain_export_blocks": self._chain_export_blocks,
                    "chain_export_bytes": self._chain_export_bytes}
        s = eng.stats()
        return {"replica": self.replica_id, "state": state,
                "role": self.role,
                "version": self.version,
                # catalog identity + adapter-store occupancy (None on
                # an uncataloged / adapters-off replica)
                "model": self.model,
                "adapters": (eng.adapter_info()
                             if hasattr(eng, "adapter_info") else None),
                "served": served, "in_flight": inflight,
                # the serving ground truth the fleet collector
                # aggregates (three-view agreement: fleet /fleetz ==
                # sum of these == the collector's registry series):
                # monotonic totals plus the local tail-latency SLO
                # inputs and per-tenant goodput counts
                "stats": {
                    "tokens_generated": s.tokens_generated,
                    "prompt_tokens": s.prompt_tokens,
                    "completed": s.completed,
                    "rejected": s.rejected,
                    "reject_reasons": dict(s.reject_reasons),
                    "preemptions": s.preemptions,
                    "decode_tok_per_sec": s.decode_tok_per_sec,
                    "total_tok_per_sec": s.total_tok_per_sec,
                    "ttft_ms_p50": s.ttft_ms_p50,
                    "ttft_ms_p99": s.ttft_ms_p99,
                    "tpot_ms_p50": s.tpot_ms_p50,
                    "tpot_ms_p99": s.tpot_ms_p99,
                    "decode_occupancy": s.decode_occupancy,
                    # prefix-cache goodput (the cache-aware router's
                    # A/B ground truth: hits split from LRU
                    # resurrections, plus the prefill compute the
                    # cache actually avoided)
                    "prefix_hits": s.prefix_hits,
                    "prefix_misses": s.prefix_misses,
                    "prefix_resurrections": s.prefix_resurrections,
                    "prefix_tokens_saved": s.prefix_tokens_saved,
                    "prefill_tokens_computed":
                        s.prefill_tokens_computed,
                    "tenants": {t: row.get("completed", 0)
                                for t, row in s.tenants.items()},
                    # per-adapter goodput (empty without adapter
                    # traffic — the collector's per-model/adapter
                    # /fleetz aggregation input)
                    "adapter_completed": {
                        a: row.get("completed", 0)
                        for a, row in s.adapters.items()},
                    "adapter_tokens": {
                        a: row.get("tokens", 0)
                        for a, row in s.adapters.items()},
                },
                "queue_depth": eng.scheduler.queue_depth,
                # running includes the chunked-prefill lane: those
                # requests occupy batch slots and the prefill budget,
                # so the router's load score must see them
                "running": (len(eng.scheduler.running)
                            + len(eng.scheduler.prefilling)),
                # in-flight handoff ingests count toward load too —
                # the router's least-loaded decode pick reads this
                "waiting_handoffs": self.waiting_handoffs,
                # prefill→decode handoff traffic (the disaggregation
                # observability: wire bytes, dedup hits, drop arms)
                "handoff": handoff,
                # peer-to-peer chain pull traffic (the fleet KV
                # fabric observability: hit/false-positive/failure
                # arms, wire bytes both directions)
                "pull": pull,
                # the routable-cache advertisement the affinity
                # router probes (None with the prefix cache off)
                "kv_summary": eng.kv_summary(),
                "max_batch": eng.max_batch,
                "kv_utilization": round(eng.blocks.utilization(), 4),
                # host-DRAM KV tier occupancy (None with the tier off)
                "host_kv_utilization": (hk["utilization"]
                                        if hk is not None else None),
                # per-program performance attribution (None with
                # MXTPU_PERF_ATTRIB=0, or on engines predating it):
                # the collector flattens this into role-keyed
                # MFU/goodput aggregates on /fleetz
                "perf": (eng.perf_summary()
                         if hasattr(eng, "perf_summary") else None),
                # per-step host-overhead fractions (None on engines
                # predating the step profiler, or a NOOP summary with
                # MXTPU_STEP_PROFILE=0)
                "step_profile": (eng._sprof.summary()
                                 if hasattr(eng, "_sprof") else None),
                "faults_fired": len(self.faults.fired)}

    def statusz_snapshot(self):
        """Global statusz plus THIS server's "replica" section (several
        in-process replicas share one global provider registry; the
        scraping router needs to know which one answered)."""
        snap = statusz_mod.snapshot()
        snap["replica"] = self._replica_state()
        return snap


# BaseHTTPRequestHandler at module scope (not a per-start() closure) so
# a process serving many replicas shares one handler class; per-replica
# state rides the server object (``self.server.replica``).
class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    @property
    def replica(self):
        return self.server.replica

    def _send_json(self, code, payload):
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _abort(self):
        """Close the connection without any response (the client sees
        a mid-request disconnect and treats it as retriable)."""
        try:
            self.close_connection = True
            self.connection.close()
        except OSError:
            _errors("abort").inc()

    def do_GET(self):
        if self.path == "/healthz":
            self._send_json(200, self.replica._health())
        elif self.path in ("/statusz.json", "/statusz"):
            self._send_json(200, self.replica.statusz_snapshot())
        elif self.path == "/metrics":
            # Prometheus text exposition of the process registry — the
            # fleet collector's second scrape target (empty until
            # MXTPU_TELEMETRY enables recording; the endpoint itself
            # costs nothing when the registry is empty)
            body = telemetry.to_prometheus_text(
                telemetry.registry()).encode()
            self.send_response(200)
            self.send_header("Content-Type",
                             "text/plain; version=0.0.4; charset=utf-8")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        elif self.path.startswith("/profilez/"):
            # /profilez/<id> (JSON metadata) or /profilez/<id>/trace
            # (the raw gzip xprof trace for timeline_report)
            parts = self.path.strip("/").split("/")
            cap_id = parts[1] if len(parts) > 1 else ""
            want_trace = len(parts) > 2 and parts[2] == "trace"
            code, payload = self.replica.handle_profilez_get(cap_id)
            if want_trace and code == 200:
                tf = payload.get("trace_file")
                if payload.get("state") != "done" or not tf:
                    self._send_json(409, {
                        "error": "capture_not_done",
                        "state": payload.get("state"),
                        "retriable": True})
                    return
                try:
                    with open(tf, "rb") as f:
                        data = f.read()
                except OSError:
                    self._send_json(404, {"error": "artifact_missing",
                                          "retriable": False})
                    return
                self.send_response(200)
                self.send_header("Content-Type", "application/gzip")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)
                return
            self._send_json(code, payload)
        else:
            self.send_error(404)

    def do_POST(self):
        if self.path == "/drain":
            try:                 # consume any body (keep-alive hygiene)
                self.rfile.read(int(self.headers.get("Content-Length",
                                                     0) or 0))
            except (ValueError, OSError):
                _errors("drain_body").inc()
            state = self.replica.drain()
            self._send_json(200, {"state": state,
                                  "queue_depth":
                                      self.replica.engine.scheduler
                                      .queue_depth})
            return
        if self.path == "/flight_dump":
            # fleet-triggered post-mortem: the collector's SLO layer
            # asks the OFFENDING replica to dump its flight-recorder
            # ring when a burn-rate alert fires.  Rides the recorder's
            # own per-reason rate limit (never force), so an alert
            # storm cannot fill this replica's disk; never
            # fault-injected (a post-mortem request is not traffic)
            try:
                n = int(self.headers.get("Content-Length", 0))
                body = json.loads(self.rfile.read(n) or b"{}")
            except (ValueError, OSError):
                body = {}
            from ..telemetry import flight as flight_mod

            reason = str(body.get("reason") or "fleet_request")[:64]
            extra = {"requested_by": "fleet",
                     "replica": self.replica.replica_id}
            if body.get("capture_id"):
                # a burn-triggered dump names the profiler capture
                # fired alongside it, so the post-mortem artifact
                # links straight to its device trace
                extra["capture_id"] = str(body["capture_id"])[:128]
            path = flight_mod.recorder().dump(reason, extra=extra)
            telemetry.counter(
                "mxtpu_fleet_flight_dump_requests_total",
                "fleet-triggered flight-dump requests",
                ("outcome",)).labels(
                    outcome="written" if path else "suppressed").inc()
            self._send_json(200, {"path": path,
                                  "replica": self.replica.replica_id})
            return
        if self.path == "/profilez":
            # on-demand profiler capture: control-plane like
            # /flight_dump — never fault-injected, and handler
            # exceptions map to retriable 500s (the 409/429 conflict
            # answers come back as clean JSON, not errors)
            try:
                n = int(self.headers.get("Content-Length", 0))
                body = json.loads(self.rfile.read(n) or b"{}")
            except (ValueError, OSError):
                body = {}
            try:
                result = self.replica.handle_profilez(body)
            except Exception:
                _errors("profilez").inc()
                result = 500, {"error": "internal", "retriable": True}
            try:
                self._send_json(*result)
            except OSError:
                _errors("respond").inc()
            return
        if self.path not in ("/generate", "/handoff", "/handoff_probe",
                             "/chain_export", "/load_adapter",
                             "/unload_adapter", "/adapter_export"):
            self.send_error(404)
            return
        try:
            n = int(self.headers.get("Content-Length", 0))
            body = json.loads(self.rfile.read(n) or b"{}")
        except (ValueError, OSError):
            self._send_json(400, {"error": "bad_json",
                                  "retriable": False})
            return
        if self.path == "/handoff_probe":
            # dedup probe: which of these content keys does this
            # replica already cache (either tier)?  The sender skips
            # those blocks' bytes on the wire.  Never fault-injected —
            # a probe is an optimization, not a request arrival
            try:
                keys = [bytes.fromhex(k) for k in body.get("keys") or []]
            except (TypeError, ValueError):
                self._send_json(400, {"error": "bad_request",
                                      "retriable": False})
                return
            have = set(self.replica.engine.blocks.has_blocks(keys))
            self._send_json(200, {"missing": [k.hex() for k in keys
                                              if k not in have]})
            return
        if self.path == "/chain_export":
            # peer-to-peer pull: serialize our cached chain for the
            # peer's prompt.  Never fault-injected (see
            # handle_chain_export)
            try:
                result = self.replica.handle_chain_export(body)
            except Exception:
                _errors("chain_export").inc()
                result = 500, {"error": "internal", "retriable": True}
            try:
                self._send_json(*result)
            except OSError:
                _errors("respond").inc()
            return
        if self.path in ("/load_adapter", "/unload_adapter",
                         "/adapter_export"):
            # catalog management: never fault-injected (a rebalance
            # move is control-plane, not traffic)
            fn = {"/load_adapter": self.replica.handle_load_adapter,
                  "/unload_adapter": self.replica.handle_unload_adapter,
                  "/adapter_export": self.replica.handle_adapter_export}[
                      self.path]
            try:
                result = fn(body)
            except Exception:
                _errors(self.path.lstrip("/")).inc()
                result = 500, {"error": "internal", "retriable": True}
            try:
                self._send_json(*result)
            except OSError:
                _errors("respond").inc()
            return
        trace_id = self.headers.get(TRACE_HEADER) or body.get("trace_id")
        handler = (self.replica.handle_handoff
                   if self.path == "/handoff"
                   else self.replica.handle_generate)
        try:
            result = handler(body, trace_id=trace_id)
        except Exception:
            # label by endpoint: a throwing handoff ingest path must
            # not send the operator to debug /generate
            _errors(self.path.lstrip("/")).inc()
            result = 500, {"error": "internal", "retriable": True}
        if result is None:
            self._abort()
            return
        code, payload = result
        try:
            self._send_json(code, payload)
        except OSError:
            _errors("respond").inc()  # client went away mid-response

    def log_message(self, *args):      # no stderr chatter per request
        pass
