"""The fleet front: SLO-aware routing with retries over N replicas.

The router turns "one excellent engine process" into a service a
client can trust: it picks the least-loaded READY replica (live queue
depth + decode occupancy + KV occupancy scraped from each replica's
``/statusz.json``), bounds each hop with a timeout, and retries
rejected/failed/timed-out requests on a sibling with capped
exponential backoff — so a single-replica failure (crash, drain,
back-pressure, hang) is invisible to the caller.  A replica whose hops
fail ``breaker_fails`` times consecutively at the TRANSPORT level
(timeout, disconnect, internal 500 — structured 503 back-pressure is a
healthy replica and never counts) trips a circuit breaker and leaves
rotation for ``breaker_reset_s`` (one half-open probe at a time
re-admits it), so a dying replica cannot eat every request's first
attempt.

Retries are safe because they are idempotent by construction: every
client request carries one ``request_id`` across all attempts (the
replica dedups on it) and one ``trace_id`` propagated in the
``X-MXTPU-Trace-Id`` header, so each hop's request-trace JSONL line
shares the id and ``tools/trace_report.py --stitch`` reassembles the
cross-replica story.

Disaggregated fleets: replicas advertise a ``role`` in their scraped
load signal ("both"/"prefill"/"decode" — replica.py).  Prompts route
least-loaded among prefill-capable replicas; when the chosen replica
is prefill-role its 200 answer is a *handoff envelope* (the prompt's
KV chain as content-keyed records) and the router moves it to the
least-loaded decode-capable replica via ``POST /handoff`` — after a
``/handoff_probe`` dedup round that skips the bytes of blocks the
target already caches (the radix key IS the transfer dedup).  The
handoff hop keeps every fleet guarantee: remaining end-to-end
deadline forwarded, same trace id (one stitched timeline across both
roles), same request-id idempotency, and retry-on-sibling — a handoff
that times out or lands on a dead decode replica is re-sent to
another one from the payload still in hand, and a payload that
arrives truncated degrades to recompute-from-prompt on the receiver
(token-identical either way).

Cache-aware routing (``MXTPU_ROUTE_AFFINITY`` > 0): every scrape also
captures the replica's advertised ``kv_summary`` (a RadixSummary —
counting bloom over its published KV block keys + top-K recent chain
keys).  The router hashes each prompt's block chain tokenizer-side
(``serve.kv_block_manager.chain_keys`` — the same
``H(parent, block_tokens)`` chain as the radix index, no model
loaded), probes each candidate's summary for the longest advertised
ancestor, and ranks on ``load − affinity × advertised_fraction`` —
sticky enough that a returning conversation lands on its prefix,
load-aware enough that a hot prefix doesn't melt one replica.  When
the pick holds less of the chain than a sibling advertises, the
``/generate`` body carries a ``kv_pull`` hint and the serving replica
pulls the chain peer-to-peer (``/chain_export``) into its host tier.
At affinity 0 (the default) all of this is byte-inert: no chain keys
computed, no summary probed, wire bodies and pick order identical to
the pre-affinity router.  Summaries older than
``MXTPU_ROUTE_SUMMARY_STALE`` scrape intervals score zero.

Pure stdlib (urllib + a persistent per-replica keep-alive scrape
connection); no background machinery unless ``start()`` is called
(the scrape thread).  All knobs take constructor arguments first,
``MXTPU_FLEET_*`` / ``MXTPU_ROUTE_*`` env defaults second.
"""

from __future__ import annotations

import http.client
import itertools
import json
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
import uuid

from .. import telemetry
from ..base import env_flag, env_float, env_int
from ..telemetry.request_trace import RequestTracer
from .replica import TRACE_HEADER

__all__ = ["Router", "RouterResult", "FleetError", "PermanentError",
           "NoReplicaAvailable"]


class FleetError(RuntimeError):
    """Base class for router-visible request failures."""


class PermanentError(FleetError):
    """The request can never succeed on any replica (e.g. longer than
    the model serves) — retrying would only burn capacity."""


class NoReplicaAvailable(FleetError):
    """Every attempt failed (replicas down/draining/rejecting) within
    the retry budget."""


class RouterResult:
    """One successful routed generation."""

    __slots__ = ("tokens", "replica", "trace_id", "request_id",
                 "attempts", "hops", "wall_s", "added_s", "samples",
                 "token_logprobs", "top_logprobs")

    def __init__(self, tokens, replica, trace_id, request_id, attempts,
                 hops, wall_s, added_s, samples=None,
                 token_logprobs=None, top_logprobs=None):
        self.tokens = tokens
        self.replica = replica
        self.trace_id = trace_id
        self.request_id = request_id
        self.attempts = attempts
        self.hops = hops           # [{"replica", "status", "wall_s"}]
        self.wall_s = wall_s
        self.added_s = added_s     # router-added latency (non-HTTP time)
        # per-request sampling extras (None unless the request asked):
        # n>1 sample list and the emitted tokens' logprob views
        self.samples = samples
        self.token_logprobs = token_logprobs
        self.top_logprobs = top_logprobs


class _ReplicaState:
    """Router-side view of one replica: scrape signal + breaker."""

    __slots__ = ("url", "name", "state", "role", "load",
                 "consecutive_failures", "open_until", "probing",
                 "last_scrape_t", "summary", "summary_t", "conn",
                 "scrape_lock", "connects", "model", "adapters")

    def __init__(self, url):
        self.url = url.rstrip("/")
        self.name = self.url
        self.state = "unknown"      # ready/draining/down/unknown
        # "both" until a scrape says otherwise: a legacy replica that
        # never advertises a role serves everything
        self.role = "both"
        # catalog advertisement: the carried checkpoint id and the
        # registered adapter ids.  None until a scrape says otherwise —
        # an uncataloged/legacy replica matches model-less requests
        # only, and a None adapter list never filters (the replica may
        # still know the adapter; its own 400 is the backstop)
        self.model = None
        self.adapters = None
        self.load = 0.0
        self.consecutive_failures = 0
        self.open_until = None      # breaker-open deadline (monotonic)
        self.probing = False        # half-open probe in flight
        self.last_scrape_t = None
        # cache-aware routing: the replica's advertised RadixSummary
        # snapshot and the scrape time it was captured (None until a
        # scrape sees one; a summary past the staleness cap scores
        # zero affinity — the PR 16 stale-data rule)
        self.summary = None
        self.summary_t = None
        # persistent scrape connection (keep-alive: the affinity
        # probe raises scrape frequency, so per-poll TCP connects
        # would be pure overhead); `connects` counts socket setups —
        # the connection-reuse regression pin reads it
        self.conn = None
        # non-blocking ownership of `conn`: an overlapping scrape
        # pass (a blackholed sibling can make passes overlap) skips a
        # replica whose connection is still mid-request rather than
        # interleaving two HTTP exchanges on one socket
        self.scrape_lock = threading.Lock()
        self.connects = 0


class Router:
    """Load-balancing, retrying front over replica URLs.

    Args (env default in parens):
      replicas: iterable of base URLs (``http://host:port``).
      timeout_s: per-hop HTTP timeout (``MXTPU_FLEET_TIMEOUT``, 30).
      retries: max attempts per request across replicas
        (``MXTPU_FLEET_RETRIES``, 3; the first try counts).
      backoff_s / backoff_max_s: capped exponential backoff between
        attempts (``MXTPU_FLEET_BACKOFF`` 0.05 /
        ``MXTPU_FLEET_BACKOFF_MAX`` 1.0) — attempt k (k >= 2) sleeps
        ``min(backoff_max_s, backoff_s * 2**(k-2))`` first.
      breaker_fails: consecutive hop failures that open a replica's
        circuit breaker (``MXTPU_FLEET_BREAKER_FAILS``, 3).
      breaker_reset_s: how long an open breaker keeps the replica out
        of rotation before one probe request may re-close it
        (``MXTPU_FLEET_BREAKER_RESET``, 5.0).
      scrape_interval_s: background statusz scrape period
        (``MXTPU_FLEET_SCRAPE_INTERVAL``, 0.5); ``start()`` launches
        the thread, or call ``scrape()`` manually (tests).
      affinity: cache-aware routing weight (``MXTPU_ROUTE_AFFINITY``,
        0.0 = byte-inert least-loaded): subtracts
        ``affinity × advertised_prefix_fraction`` from a candidate's
        load score.
      pull: attach ``kv_pull`` peer-hints when a sibling advertises
        more of the prompt's chain than the pick
        (``MXTPU_ROUTE_PULL``, on; effective only with affinity > 0).
      summary_stale: advertised summaries older than this many scrape
        intervals score zero affinity
        (``MXTPU_ROUTE_SUMMARY_STALE``, 3.0).
      clock: injectable monotonic clock (breaker/backoff tests).
      sleep: injectable sleep (backoff tests).
    """

    def __init__(self, replicas, timeout_s=None, retries=None,
                 backoff_s=None, backoff_max_s=None, breaker_fails=None,
                 breaker_reset_s=None, scrape_interval_s=None,
                 affinity=None, pull=None, summary_stale=None,
                 clock=time.monotonic, sleep=time.sleep):
        self.timeout_s = (float(timeout_s) if timeout_s is not None
                          else env_float("MXTPU_FLEET_TIMEOUT", 30.0))
        self.retries = (int(retries) if retries is not None
                        else env_int("MXTPU_FLEET_RETRIES", 3))
        self.backoff_s = (float(backoff_s) if backoff_s is not None
                          else env_float("MXTPU_FLEET_BACKOFF", 0.05))
        self.backoff_max_s = (
            float(backoff_max_s) if backoff_max_s is not None
            else env_float("MXTPU_FLEET_BACKOFF_MAX", 1.0))
        self.breaker_fails = (
            int(breaker_fails) if breaker_fails is not None
            else env_int("MXTPU_FLEET_BREAKER_FAILS", 3))
        self.breaker_reset_s = (
            float(breaker_reset_s) if breaker_reset_s is not None
            else env_float("MXTPU_FLEET_BREAKER_RESET", 5.0))
        self.scrape_interval_s = (
            float(scrape_interval_s) if scrape_interval_s is not None
            else env_float("MXTPU_FLEET_SCRAPE_INTERVAL", 0.5))
        # cache-aware routing weight: each candidate's score becomes
        # ``load - affinity * advertised_prefix_fraction`` (fraction
        # of the prompt's tokens the replica's RadixSummary says it
        # caches, 0..1 — same scale as one unit of load).  0 is the
        # BYTE-INERT default: no chain keys computed, no summary
        # probed, the pick identical to least-loaded by construction
        self.affinity = (float(affinity) if affinity is not None
                         else env_float("MXTPU_ROUTE_AFFINITY", 0.0))
        # peer-to-peer pull hints (effective only with affinity > 0):
        # when the pick holds less of the prompt's chain than the best
        # advertiser, the /generate body carries a kv_pull hint and
        # the serving replica pulls the chain from that sibling
        self.pull = (bool(pull) if pull is not None
                     else env_flag("MXTPU_ROUTE_PULL", True))
        # summaries older than this many scrape intervals contribute
        # ZERO affinity (the PR 16 stale-data rule: never route on
        # data the fleet stopped refreshing)
        self.summary_stale = (
            float(summary_stale) if summary_stale is not None
            else env_float("MXTPU_ROUTE_SUMMARY_STALE", 3.0))
        self.clock = clock
        self.sleep = sleep
        self._lock = threading.RLock()
        # membership + each entry's breaker/scrape fields are mutated
        # from request threads AND the scrape thread
        self._replicas = [_ReplicaState(u) for u in replicas]  # guarded-by: _lock
        self._rr = itertools.count()
        self._scrape_thread = None
        self._stop_evt = threading.Event()
        self._m_requests = telemetry.counter(
            "mxtpu_fleet_requests_total", "routed client requests",
            ("outcome",))
        self._m_hops = telemetry.counter(
            "mxtpu_fleet_hops_total", "per-replica attempt outcomes",
            ("replica", "status"))
        self._m_retries = telemetry.counter(
            "mxtpu_fleet_retries_total", "attempts after the first")
        self._m_breaker = telemetry.counter(
            "mxtpu_fleet_breaker_opens_total", "circuit-breaker trips",
            ("replica",))
        self._m_added = telemetry.histogram(
            "mxtpu_fleet_router_added_seconds",
            "router-added latency (request wall minus replica HTTP time)")
        self._m_handoffs = telemetry.counter(
            "mxtpu_fleet_handoffs_total",
            "prefill->decode KV handoffs routed", ("outcome",))
        self._m_handoff_dedup = telemetry.counter(
            "mxtpu_fleet_handoff_dedup_blocks_total",
            "handoff blocks whose bytes the dedup probe skipped")
        self._m_affinity = telemetry.counter(
            "mxtpu_fleet_affinity_picks_total",
            "affinity-routed picks by whether the chosen replica "
            "advertised any of the prompt's chain", ("outcome",))
        self._m_pull_hints = telemetry.counter(
            "mxtpu_fleet_pull_hints_total",
            "kv_pull hints attached to routed requests (a sibling "
            "advertised more of the chain than the pick)")
        # per-hop wall time by outcome: the stitched-view "router time"
        # a replica-side trace can never see (ok / reject = structured
        # 503 back-pressure / timeout / retry = transport failure that
        # moves to a sibling)
        self._m_hop_seconds = telemetry.histogram(
            "mxtpu_fleet_router_hop_seconds",
            "per-replica hop HTTP wall time by outcome", ("outcome",))
        self._m_breaker_state = telemetry.gauge(
            "mxtpu_fleet_breaker_state",
            "replica circuit breaker: 0 closed, 0.5 half-open probe, "
            "1 open", ("replica",))
        # router-side trace lines (the same MXTPU_REQUEST_TRACE /
        # MXTPU_TRACE_PUSH_URL opt-ins the serve engine honors): one
        # complete timeline per routed request — pick / hop / probe /
        # handoff events — under the SAME trace id as the replica-side
        # lines, so `trace_report --stitch` shows router time next to
        # replica time.  Inert (no events, no file, no pusher) when
        # neither knob is set.
        self._trace = RequestTracer(source="router")
        self._trace.identity = "router"
        self._trace_rid = itertools.count(1)

    # -- membership ----------------------------------------------------------
    def replicas(self):
        with self._lock:
            return list(self._replicas)

    def add_replica(self, url):
        with self._lock:
            self._replicas.append(_ReplicaState(url))

    def remove_replica(self, url):
        url = url.rstrip("/")
        with self._lock:
            self._replicas = [r for r in self._replicas if r.url != url]

    # -- scraping ------------------------------------------------------------
    def start(self):
        """Launch the background scrape thread (no-op when the
        interval is 0 — drive ``scrape()`` manually instead)."""
        if self.scrape_interval_s <= 0 or self._scrape_thread is not None:
            return self
        self._scrape_thread = threading.Thread(
            target=self._scrape_loop, daemon=True,
            name="mxtpu-fleet-router-scrape")
        self._scrape_thread.start()
        return self

    def stop(self):
        self._stop_evt.set()
        if self._scrape_thread is not None:
            self._scrape_thread.join(timeout=5)
            self._scrape_thread = None
        self._trace.close()
        for r in self.replicas():
            with self._lock:
                conn, r.conn = r.conn, None
            if conn is not None:
                try:
                    conn.close()
                except OSError:
                    pass

    def _scrape_loop(self):
        while not self._stop_evt.wait(self.scrape_interval_s):
            self.scrape()

    def scrape(self):
        """One pass over every replica's ``/statusz.json``: refresh
        readiness + load.  Unreachable replicas go ``down``.

        Replicas are scraped CONCURRENTLY (one short-lived thread
        each): a single blackholed replica eating its full probe
        timeout must not stall drain/down detection on every sibling
        past the scrape interval."""
        replicas = self.replicas()
        if not replicas:
            return self.snapshot()
        threads = [threading.Thread(target=self._scrape_one, args=(r,),
                                    daemon=True) for r in replicas]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=min(self.timeout_s, 5.0) + 1.0)
        return self.snapshot()

    def _scrape_one(self, r):
        """One replica's scrape over its PERSISTENT keep-alive
        connection (opened lazily, reused across passes — the
        affinity probe raises scrape frequency, and paying a TCP
        connect per poll per replica was pure overhead).  Any
        transport or parse failure closes the connection (it may be
        half-broken) and marks the replica down; the next pass
        reconnects.  Guarded by a non-blocking per-replica lock so an
        overlapping pass never interleaves two exchanges on one
        socket — it just skips this replica for one round."""
        if not r.scrape_lock.acquire(blocking=False):
            return                      # an older pass still owns conn
        try:
            conn = r.conn
            if conn is None:
                parsed = urllib.parse.urlsplit(r.url)
                conn = http.client.HTTPConnection(
                    parsed.hostname, parsed.port,
                    timeout=min(self.timeout_s, 5.0))
                with self._lock:
                    r.conn = conn
                    r.connects += 1
            conn.request("GET", "/statusz.json")
            resp = conn.getresponse()
            raw = resp.read()
            if resp.status != 200:
                raise OSError(f"statusz http {resp.status}")
            snap = json.loads(raw)
            sec = snap.get("replica") or {}
            summary = sec.get("kv_summary")
            with self._lock:
                r.state = ("ready" if sec.get("state") == "ready"
                           else sec.get("state") or "down")
                r.name = sec.get("replica") or r.name
                r.role = sec.get("role") or "both"
                r.model = sec.get("model")
                adp = sec.get("adapters")
                r.adapters = (list(adp.get("ids") or [])
                              if isinstance(adp, dict) else None)
                r.load = self._load_score(sec)
                r.last_scrape_t = self.clock()
                if isinstance(summary, dict):
                    r.summary = summary
                    r.summary_t = r.last_scrape_t
        except (OSError, ValueError, http.client.HTTPException):
            try:
                if r.conn is not None:
                    r.conn.close()
            except OSError:
                pass
            with self._lock:
                r.conn = None
                r.state = "down"
                r.last_scrape_t = self.clock()
        finally:
            r.scrape_lock.release()

    @staticmethod
    def _load_score(sec):
        """Scalar routing score from a replica's statusz section:
        queued work normalized by batch width plus KV occupancy — both
        saturate at ~1, so an idle replica scores ~0 and a saturated
        one ~2+.  In-flight handoff ingests (mid-import, not yet
        queued) count as queued work: a decode replica swallowing a
        large KV payload must not under-report and attract the next
        handoff too."""
        width = max(1, int(sec.get("max_batch") or 1))
        queued = (int(sec.get("queue_depth") or 0)
                  + int(sec.get("running") or 0)
                  + int(sec.get("waiting_handoffs") or 0))
        return queued / width + float(sec.get("kv_utilization") or 0.0)

    def snapshot(self):
        """Router-side fleet view (statusz provider shape)."""
        with self._lock:
            now = self.clock()
            return [{"url": r.url, "replica": r.name, "state": r.state,
                     "role": r.role,
                     "model": r.model,
                     "adapters": r.adapters,
                     "load": round(r.load, 4),
                     "consecutive_failures": r.consecutive_failures,
                     "breaker_open": bool(r.open_until is not None
                                          and r.open_until > now)}
                    for r in self._replicas]

    # -- cache-aware routing (affinity > 0 only) -----------------------------
    def _affinity_plan(self, prompt, salt=None):
        """Per-replica advertised-prefix match for ``prompt``: probe
        each FRESH ``kv_summary`` (stale ones score zero — the PR 16
        rule: never route on data the fleet stopped refreshing) for
        the longest advertised ancestor of the prompt's chain.  The
        chain keys are computed ONCE per distinct advertised
        block_size (the tokenizer-side ``chain_keys`` helper — same
        ``H(parent, block_tokens)`` hash as the radix index, no model
        loaded).  Returns ``{"scores": {url: {"tokens", "frac"}},
        "best": {...}}`` or None when nothing matched anywhere (the
        pick then degenerates to pure least-loaded).  Never called
        with ``affinity == 0`` — the byte-inert path skips it
        entirely."""
        from ..serve.kv_block_manager import RadixSummary, chain_keys

        now = self.clock()
        stale_after = (self.summary_stale
                       * max(self.scrape_interval_s, 1.0))
        with self._lock:
            rows = [(r.url, r.name, r.summary, r.summary_t)
                    for r in self._replicas]
        keys_by_bs = {}
        scores = {}
        best = None
        for url, name, summary, summary_t in rows:
            if not summary or summary_t is None:
                continue
            if now - summary_t > stale_after:
                continue                # stale: zero affinity
            bs = int(summary.get("block_size") or 0)
            if bs < 1:
                continue
            if bs not in keys_by_bs:
                # the replicas salt adapter chains (disjoint key
                # space per adapter); probe with the same salt or an
                # adapter request would score base-chain affinity
                keys_by_bs[bs] = chain_keys(prompt, bs, salt=salt)
            depth = RadixSummary.match(summary, keys_by_bs[bs])
            if depth <= 0:
                continue
            tokens = depth * bs
            scores[url] = {"tokens": tokens,
                           "frac": tokens / max(1, len(prompt))}
            if best is None or tokens > best["tokens"]:
                best = {"url": url, "name": name, "tokens": tokens}
        if not scores:
            return None
        return {"scores": scores, "best": best}

    def _pull_hint(self, plan, r):
        """The ``kv_pull`` hint for pick ``r`` under ``plan``: the
        best-advertising SIBLING's url + advertised token span, or
        None when the pick already matches the fleet's best (or pull
        is disabled).  The serving replica does the actual fetch —
        the router never moves KV bytes on this path."""
        best = plan["best"]
        if not self.pull or best is None or best["url"] == r.url:
            return None
        mine = plan["scores"].get(r.url)
        if mine is not None and mine["tokens"] >= best["tokens"]:
            return None
        return {"peer": best["url"], "tokens": int(best["tokens"])}

    # -- picking -------------------------------------------------------------
    def _pick(self, exclude, want=None, weights=None, model=None,
              adapter=None):
        """Least-loaded READY replica with a closed (or probe-ready)
        breaker, excluding already-tried ones; round-robin tiebreak.
        ``want`` filters by role capability: ``"prefill"`` skips
        decode-only replicas, ``"decode"`` skips prefill-only ones
        (role "both" — and never-scraped legacy replicas — serve
        either).  ``model`` filters by catalog identity: a model-tagged
        request only lands on replicas advertising that checkpoint
        (composing with role and affinity; model-less requests rank
        every replica, the historical pick).  ``adapter`` filters by
        advertised adapter ids when the replica advertises any — a
        replica with no advertisement passes (its own validation is
        the backstop).  ``weights`` (affinity routing) maps replica
        url -> score credit subtracted from its load before ranking;
        None — the affinity-off path — ranks on raw load,
        bit-identically to the pre-affinity router."""
        with self._lock:
            now = self.clock()
            rr = next(self._rr)
            n = max(1, len(self._replicas))
            ranked = []
            for i, r in enumerate(self._replicas):
                if r.url in exclude:
                    continue
                if r.state in ("draining", "down"):
                    continue
                if want == "prefill" and r.role == "decode":
                    continue
                if want == "decode" and r.role == "prefill":
                    continue
                if model is not None and r.model != model:
                    continue
                if (adapter is not None and r.adapters is not None
                        and adapter not in r.adapters):
                    continue
                if r.open_until is not None:
                    if r.open_until > now:
                        continue        # breaker open
                    if r.probing:
                        continue        # half-open: ONE probe at a time
                score = r.load
                if weights:
                    score -= weights.get(r.url, 0.0)
                ranked.append((score, (i - rr) % n, r))
            if not ranked:
                return None
            ranked.sort(key=lambda t: (t[0], t[1]))
            best = ranked[0][2]
            probing = best.open_until is not None
            if probing:
                best.probing = True     # this attempt IS the probe
        if probing:
            self._m_breaker_state.labels(replica=best.name).set(0.5)
        return best

    @staticmethod
    def _counts_for_breaker(code, payload):
        """Only TRANSPORT-level failures trip the breaker: timeouts,
        disconnects, garbage responses, and replica-internal 500s.  A
        structured 503 rejection (queue_full / tenant_share / draining
        / fault_refuse) is a healthy replica applying back-pressure —
        it must be retried on a sibling, but counting it as a failure
        would let one overload burst open EVERY breaker and take the
        whole fleet out for well-behaved clients."""
        if code in ("timeout", "disconnect", "bad_response"):
            return True
        return isinstance(code, int) and code >= 500 and code != 503

    def _hop_failed(self, r, status, breaker=True):
        with self._lock:
            r.probing = False
            if breaker:
                now = self.clock()
                r.consecutive_failures += 1
                # (re-)arm whenever the breaker is not CURRENTLY open:
                # a stale past deadline means a half-open probe just
                # failed, and the breaker must open again, not retire
                if r.consecutive_failures >= self.breaker_fails \
                        and (r.open_until is None or r.open_until <= now):
                    r.open_until = now + self.breaker_reset_s
                    self._m_breaker.labels(replica=r.name).inc()
            open_now = (r.open_until is not None
                        and r.open_until > self.clock())
        self._m_breaker_state.labels(replica=r.name).set(
            1.0 if open_now else 0.0)
        self._m_hops.labels(replica=r.name, status=status).inc()

    def _hop_ok(self, r, status="ok"):
        with self._lock:
            r.consecutive_failures = 0
            r.open_until = None
            r.probing = False
        self._m_breaker_state.labels(replica=r.name).set(0.0)
        self._m_hops.labels(replica=r.name, status=status).inc()

    @staticmethod
    def _hop_outcome(code):
        """The ``mxtpu_fleet_router_hop_seconds`` outcome label:
        structured rejections (503-class back-pressure and permanent
        400s) are ``reject``; transport failures that will move to a
        sibling are ``retry``; timeouts get their own bucket."""
        if code == 200:
            return "ok"
        if code == "timeout":
            return "timeout"
        if code == "rejected_permanent" or code == 503:
            return "reject"
        return "retry"

    def _observe_hop(self, code, wall_s):
        self._m_hop_seconds.labels(
            outcome=self._hop_outcome(code)).observe(wall_s)

    # -- router-side trace timeline (hop-level events) -----------------------
    def _trace_begin(self, prompt_len, max_new, tenant, trace_id):
        """Open a router-side timeline for one routed request (None
        when tracing is off — every hook below no-ops on None)."""
        if not self._trace.enabled:
            return None
        import types

        req = types.SimpleNamespace(
            rid=next(self._trace_rid), trace_id=trace_id, tenant=tenant,
            prompt=types.SimpleNamespace(size=int(prompt_len)),
            max_new_tokens=int(max_new), tokens=[], n_preemptions=0)
        self._trace.submitted(req)
        return req

    def _trace_ev(self, rt, name, **args):
        if rt is not None:
            self._trace.event(rt, name, **args)

    def _trace_end(self, rt, name, **args):
        # terminal names: "finished" for a served request, "cancelled"
        # for a router-level failure — never "rejected", which would
        # double-count mxtpu_serve_rejections_total against the
        # replica-side line that already owns the rejection
        if rt is not None:
            self._trace.terminal(rt, name, **args)

    # -- the request path ----------------------------------------------------
    def generate(self, prompt, max_new_tokens=64, deadline_s=None,
                 tenant=None, request_id=None, trace_id=None,
                 temperature=None, top_p=None, top_k=None, n=None,
                 logprobs=None, model=None, adapter=None):
        """Route one generation; returns :class:`RouterResult`.

        ``temperature``/``top_p``/``top_k``/``n``/``logprobs`` are the
        per-request sampling params — forwarded to the serving replica
        verbatim (and re-forwarded on a prefill→decode handoff, which
        reuses the same base body), only-when-set so plain requests'
        wire bodies stay byte-identical to pre-sampling releases.
        ``model``/``adapter`` (catalog params) ride the same rule, and
        additionally FILTER the pick: a model id no scraped replica
        advertises is a :class:`PermanentError` before any hop —
        routing it anywhere could only produce per-replica 400s.

        Raises :class:`PermanentError` for requests no replica can
        serve and :class:`NoReplicaAvailable` once the retry budget is
        exhausted."""
        request_id = request_id or uuid.uuid4().hex
        trace_id = trace_id or f"fleet-{uuid.uuid4().hex[:16]}"
        if model is not None:
            model = str(model)[:64]
            with self._lock:
                known = any(r.model == model for r in self._replicas)
            if not known:
                self._m_requests.labels(outcome="permanent").inc()
                raise PermanentError(
                    f"unknown model: {model!r} (no replica in the "
                    "fleet advertises it)")
        if adapter is not None:
            adapter = str(adapter)[:64]
        base = {"prompt": [int(t) for t in prompt],
                "max_new_tokens": int(max_new_tokens),
                "deadline_s": deadline_s, "tenant": tenant,
                "request_id": request_id}
        for key, val in (("temperature", temperature), ("top_p", top_p),
                         ("top_k", top_k), ("n", n),
                         ("logprobs", logprobs), ("model", model),
                         ("adapter", adapter)):
            if val is not None:
                base[key] = val
        body = json.dumps(base).encode()
        t0 = time.perf_counter()
        rt = self._trace_begin(len(base["prompt"]), max_new_tokens,
                               tenant, trace_id)
        # cache-aware routing: with affinity ON, score every fresh
        # advertised summary against this prompt's chain ONCE (not per
        # attempt — the fleet view only changes at scrape cadence).
        # With affinity 0 this whole plane is byte-inert: no chain
        # keys, no weights, no body growth, the pre-affinity pick
        plan = weights = None
        if self.affinity > 0:
            plan = self._affinity_plan(base["prompt"], salt=adapter)
            if plan is not None:
                weights = {u: self.affinity * s["frac"]
                           for u, s in plan["scores"].items()}
        hops = []
        tried = set()
        last_error = "no_replica"
        remaining = None
        for attempt in range(1, max(1, self.retries) + 1):
            if attempt > 1:
                self._m_retries.inc()
                self._trace_ev(rt, "retry", attempt=attempt,
                               last_error=last_error)
                self.sleep(min(self.backoff_max_s,
                               self.backoff_s * 2 ** (attempt - 2)))
            if deadline_s is not None:
                # the deadline is an END-TO-END SLO: each hop gets the
                # REMAINING budget, not a fresh one — and once it is
                # spent, retrying anywhere is pointless
                remaining = deadline_s - (time.perf_counter() - t0)
                if remaining <= 0:
                    self._m_requests.labels(outcome="deadline").inc()
                    self._trace_end(rt, "cancelled", reason="deadline")
                    raise PermanentError(
                        f"deadline_s={deadline_s} exhausted after "
                        f"{attempt - 1} attempt(s) (last error: "
                        f"{last_error})")
                body = json.dumps(dict(base,
                                       deadline_s=remaining)).encode()
            r = self._pick(tried, want="prefill", weights=weights,
                           model=model, adapter=adapter)
            if r is None and tried:
                # every replica tried once: second pass may retry one
                # (it may have recovered / stopped rejecting)
                tried = set()
                r = self._pick(tried, want="prefill", weights=weights,
                               model=model, adapter=adapter)
            if r is None:
                last_error = "no_replica"
                continue
            tried.add(r.url)
            send_body = body
            if plan is not None:
                sc = plan["scores"].get(r.url)
                mine = sc["tokens"] if sc else 0
                self._m_affinity.labels(
                    outcome="hit" if mine else "cold").inc()
                hint = self._pull_hint(plan, r)
                if hint is not None:
                    extra = dict(base, kv_pull=hint)
                    if deadline_s is not None:
                        extra["deadline_s"] = remaining
                    send_body = json.dumps(extra).encode()
                    self._m_pull_hints.inc()
                self._trace_ev(
                    rt, "pick", replica=r.name, attempt=attempt,
                    affinity_tokens=mine,
                    **({"pull_peer": hint["peer"],
                        "pull_tokens": hint["tokens"]}
                       if hint is not None else {}))
            else:
                self._trace_ev(rt, "pick", replica=r.name,
                               attempt=attempt)
            h0 = time.perf_counter()
            code, payload = self._post(r, send_body, trace_id)
            hop_wall = time.perf_counter() - h0
            self._observe_hop(code, hop_wall)
            self._trace_ev(rt, "hop", replica=r.name, status=str(code),
                           wall_ms=round(hop_wall * 1e3, 3))
            hops.append({"replica": r.name, "status": code,
                         "wall_s": round(hop_wall, 6)})
            if code == 200 and "handoff" in payload:
                # a prefill-role replica answered with the KV handoff
                # envelope, not tokens: move it (and the remaining
                # deadline + the same trace id) to a decode replica
                self._hop_ok(r, status="prefill_ok")
                return self._route_handoff(
                    payload["handoff"], base, request_id, trace_id,
                    deadline_s, t0, hops, attempt, rt=rt)
            if code == 200:
                self._hop_ok(r)
                wall = time.perf_counter() - t0
                added = max(0.0, wall - sum(h["wall_s"] for h in hops))
                self._m_added.observe(added)
                self._m_requests.labels(outcome="ok").inc()
                if rt is not None:
                    rt.tokens = list(payload.get("tokens") or [])
                    self._trace_end(rt, "finished",
                                    replica=payload.get("replica"),
                                    attempts=attempt)
                return RouterResult(
                    tokens=payload["tokens"], replica=payload["replica"],
                    trace_id=trace_id, request_id=request_id,
                    attempts=attempt, hops=hops, wall_s=wall,
                    added_s=added, samples=payload.get("samples"),
                    token_logprobs=payload.get("token_logprobs"),
                    top_logprobs=payload.get("top_logprobs"))
            if code == "rejected_permanent":
                # the replica is ALIVE and answered correctly — clear
                # its breaker state before giving the caller its 400
                self._hop_ok(r, status="rejected_permanent")
                self._m_requests.labels(outcome="permanent").inc()
                self._trace_end(rt, "cancelled", reason="permanent",
                                error=str(payload.get("error")))
                raise PermanentError(
                    f"request rejected as unservable: "
                    f"{payload.get('error')} (replica {r.name})")
            # retriable: 503-class rejection, timeout, disconnect
            last_error = (payload or {}).get("error", str(code))
            self._hop_failed(r, str(code),
                             breaker=self._counts_for_breaker(code,
                                                              payload))
            if last_error == "draining":
                # fast rotation exit — don't wait for the next scrape
                with self._lock:
                    r.state = "draining"
        self._m_requests.labels(outcome="exhausted").inc()
        self._trace_end(rt, "cancelled", reason="exhausted",
                        error=str(last_error))
        raise NoReplicaAvailable(
            f"request {request_id} failed after {self.retries} attempts "
            f"(last error: {last_error}); hops: "
            + ", ".join(f"{h['replica']}:{h['status']}" for h in hops))

    def _route_handoff(self, ho, base, request_id, trace_id,
                       deadline_s, t0, hops, attempts, rt=None):
        """Move one prefill replica's handoff envelope to a decode
        replica and return the completed generation.

        Own sibling-retry loop: the KV payload stays in the router's
        hand, so a handoff that times out, disconnects, or lands on a
        dead/draining decode replica is simply re-sent to another one
        (request-id idempotency makes the re-send safe, content-keyed
        records make a partial first delivery harmless).  The deadline
        is the SAME end-to-end budget the prefill hop was already
        drawing down; each attempt first runs the ``/handoff_probe``
        dedup round and skips the bytes of blocks the target already
        caches."""
        records = list(ho.get("records") or [])
        keys = [rec.get("key") for rec in records]
        # catalog params ride `base`, so they re-forward on this hop
        # automatically; the decode pick must honor them too
        model = base.get("model")
        adapter = base.get("adapter")
        tried = set()
        last_error = "no_decode_replica"
        for attempt in range(1, max(1, self.retries) + 1):
            if attempt > 1:
                self._m_retries.inc()
                self._trace_ev(rt, "retry", attempt=attempt,
                               hop="handoff", last_error=last_error)
                self.sleep(min(self.backoff_max_s,
                               self.backoff_s * 2 ** (attempt - 2)))
            remaining = None
            if deadline_s is not None:
                remaining = deadline_s - (time.perf_counter() - t0)
                if remaining <= 0:
                    self._m_requests.labels(outcome="deadline").inc()
                    self._m_handoffs.labels(outcome="deadline").inc()
                    self._trace_end(rt, "cancelled", reason="deadline")
                    raise PermanentError(
                        f"deadline_s={deadline_s} exhausted during "
                        f"handoff after {attempt - 1} attempt(s) "
                        f"(last error: {last_error})")
            r = self._pick(tried, want="decode", model=model,
                           adapter=adapter)
            if r is None and tried:
                tried = set()
                r = self._pick(tried, want="decode", model=model,
                               adapter=adapter)
            if r is None:
                last_error = "no_decode_replica"
                continue
            tried.add(r.url)
            self._trace_ev(rt, "pick", replica=r.name, attempt=attempt,
                           hop="handoff")
            send = records
            if keys and all(keys):
                missing = self._probe_handoff(r, keys)
                if missing is not None:
                    miss = set(missing)
                    skipped = sum(1 for k in keys if k not in miss)
                    if skipped:
                        self._m_handoff_dedup.inc(skipped)
                    self._trace_ev(rt, "probe", replica=r.name,
                                   skipped=skipped,
                                   missing=len(miss))
                    # the radix key IS the dedup: blocks the target
                    # already caches travel as key+tokens only (the
                    # receiver re-verifies the chain either way)
                    send = [rec if rec["key"] in miss else
                            {k: rec[k]
                             for k in ("key", "parent", "tokens")}
                            for rec in records]
            body = json.dumps(dict(base, records=send,
                                   deadline_s=remaining)).encode()
            h0 = time.perf_counter()
            code, payload = self._post(r, body, trace_id,
                                       path="/handoff")
            hop_wall = time.perf_counter() - h0
            self._observe_hop(code, hop_wall)
            self._trace_ev(rt, "handoff", replica=r.name,
                           status=str(code),
                           wall_ms=round(hop_wall * 1e3, 3),
                           records=len(send))
            hops.append({"replica": r.name, "status": code,
                         "wall_s": round(hop_wall, 6),
                         "hop": "handoff"})
            if code == 200:
                self._hop_ok(r)
                wall = time.perf_counter() - t0
                added = max(0.0, wall - sum(h["wall_s"] for h in hops))
                self._m_added.observe(added)
                self._m_requests.labels(outcome="ok").inc()
                self._m_handoffs.labels(outcome="ok").inc()
                if rt is not None:
                    rt.tokens = list(payload.get("tokens") or [])
                    self._trace_end(rt, "finished",
                                    replica=payload.get("replica"),
                                    attempts=attempts + attempt)
                return RouterResult(
                    tokens=payload["tokens"],
                    replica=payload["replica"], trace_id=trace_id,
                    request_id=request_id, attempts=attempts + attempt,
                    hops=hops, wall_s=wall, added_s=added,
                    samples=payload.get("samples"),
                    token_logprobs=payload.get("token_logprobs"),
                    top_logprobs=payload.get("top_logprobs"))
            if code == "rejected_permanent":
                self._hop_ok(r, status="rejected_permanent")
                self._m_requests.labels(outcome="permanent").inc()
                self._m_handoffs.labels(outcome="permanent").inc()
                self._trace_end(rt, "cancelled", reason="permanent",
                                error=str(payload.get("error")))
                raise PermanentError(
                    f"handoff rejected as unservable: "
                    f"{payload.get('error')} (replica {r.name})")
            last_error = (payload or {}).get("error", str(code))
            self._hop_failed(r, str(code),
                             breaker=self._counts_for_breaker(code,
                                                              payload))
            if last_error == "draining":
                with self._lock:
                    r.state = "draining"
        self._m_requests.labels(outcome="exhausted").inc()
        self._m_handoffs.labels(outcome="exhausted").inc()
        self._trace_end(rt, "cancelled", reason="exhausted",
                        error=str(last_error))
        raise NoReplicaAvailable(
            f"handoff for {request_id} failed after {self.retries} "
            f"attempt(s) (last error: {last_error}); hops: "
            + ", ".join(f"{h['replica']}:{h['status']}" for h in hops))

    def _probe_handoff(self, r, keys):
        """``/handoff_probe`` dedup round: the subset of ``keys`` the
        target does NOT cache (those need their bytes).  None when the
        probe itself fails — the probe is purely a bytes optimization,
        so failure means "send everything", never an error."""
        req = urllib.request.Request(
            f"{r.url}/handoff_probe",
            data=json.dumps({"keys": keys}).encode(), method="POST",
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(
                    req, timeout=min(self.timeout_s, 5.0)) as resp:
                out = json.loads(resp.read())
            missing = out.get("missing")
            return missing if isinstance(missing, list) else None
        except (OSError, ValueError):
            return None

    def _post(self, r, body, trace_id, path="/generate"):
        """One hop.  Returns ``(200, payload)``,
        ``("rejected_permanent", payload)`` for a 400-class rejection,
        or ``(status_label, payload_or_None)`` for retriable failures
        (503 rejections, timeouts, disconnects)."""
        req = urllib.request.Request(
            f"{r.url}{path}", data=body, method="POST",
            headers={"Content-Type": "application/json",
                     TRACE_HEADER: trace_id})
        try:
            with urllib.request.urlopen(req,
                                        timeout=self.timeout_s) as resp:
                return 200, json.loads(resp.read())
        except urllib.error.HTTPError as e:
            try:
                payload = json.loads(e.read())
            except ValueError:
                payload = {"error": f"http_{e.code}"}
            if e.code == 400 or not payload.get("retriable", True):
                return "rejected_permanent", payload
            return e.code, payload
        except TimeoutError:
            return "timeout", {"error": "timeout"}
        except (urllib.error.URLError, OSError) as e:
            # URLError wraps socket timeouts on some Python versions
            reason = getattr(e, "reason", e)
            if isinstance(reason, TimeoutError) or "timed out" in str(e):
                return "timeout", {"error": "timeout"}
            return "disconnect", {"error": f"disconnect: {e}"}
        except ValueError:
            return "bad_response", {"error": "bad_response"}
