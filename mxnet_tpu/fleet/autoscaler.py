"""Role-aware fleet autoscaler: the policy half of the control plane.

PR 14 built the sensor plane (``FleetCollector``'s role-keyed
``/fleetz`` aggregates + burn-rate SLO alerts) and PR 8/13 the
actuators (``Supervisor`` spawn/drain, prefill/decode role split);
this module closes the loop.  A DistServe-shaped disaggregated fleet
saturates its two pools on *different* signals — prefill replicas on
prompt queue depth and TTFT, decode replicas on pending handoff
ingests and KV/host-KV headroom and TPOT — so the autoscaler scales
each role's pool independently on its own signals, within per-role
min/max bounds.

Spec grammar (``MXTPU_AUTOSCALE_SPEC``)::

  spec     := entry (";" entry)*
  entry    := role "=" min ":" max        # a managed pool's bounds
            | knob "=" number             # policy knob
  role     := "both" | "prefill" | "decode"
  knob     := "up_queue"      # queued prompts per fresh replica that
                              #   mean "underprovisioned" (default 8)
            | "up_handoffs"   # waiting handoff ingests per fresh
                              #   decode replica (default 4)
            | "up_kv"         # mean device-KV occupancy (default 0.85)
            | "up_host_kv"    # mean host-KV occupancy (default 0.85)
            | "down_idle_s"   # quiet seconds before ONE scale-down
                              #   (default 30)
            | "cooldown_s"    # min seconds between actuations per
                              #   role, either direction (default 15)

Example: ``prefill=1:4;decode=1:8;up_queue=16;down_idle_s=30``.  Only
roles named in the spec are managed — an unlisted pool is never
touched, which is also what keeps prefill pressure from ever growing
the decode pool.

Hysteresis is deliberately asymmetric: scale-UP happens on the first
pressured evaluation (underprovisioning costs user latency *now*),
scale-DOWN only after ``down_idle_s`` of consecutively quiet windows
(capacity is cheap to keep for a beat, and load is bursty).  A
per-role cooldown bounds actuation frequency in both directions so a
chaos restart — which briefly looks like pressure (its queue drains
on siblings) then like idleness — cannot oscillate the fleet.

Staleness: pressure is computed over FRESH replicas only (the
collector's role aggregates already exclude replicas past the scrape
age cap), and a role with zero fresh replicas is held as-is — the
autoscaler never scales on dead data.

Every actuation increments
``mxtpu_fleet_scale_events_total{role,direction,reason}``, lands on
the collector's fleet timeline, and flight-dumps the surrounding
telemetry ring (``MXTPU_FLIGHT_DIR``) for post-mortems.
"""

from __future__ import annotations

import os
import threading
import time

from .. import telemetry
from ..telemetry import flight as flight_mod

__all__ = ["Autoscaler", "parse_autoscale_spec", "ENV_SPEC"]

ENV_SPEC = "MXTPU_AUTOSCALE_SPEC"

_ROLES = ("both", "prefill", "decode")
_KNOB_DEFAULTS = {
    "up_queue": 8.0,
    "up_handoffs": 4.0,
    "up_kv": 0.85,
    "up_host_kv": 0.85,
    "down_idle_s": 30.0,
    "cooldown_s": 15.0,
}


def parse_autoscale_spec(spec):
    """Parse the declarative autoscale spec (grammar above) into
    ``{"bounds": {role: (min, max)}, <knob>: float, ...}``.  Raises
    ``ValueError`` on malformed entries — a half-understood scaling
    policy must never run."""
    cfg = {"bounds": {}}
    cfg.update(_KNOB_DEFAULTS)
    for entry in str(spec).split(";"):
        entry = entry.strip()
        if not entry:
            continue
        key, sep, value = entry.partition("=")
        key, value = key.strip(), value.strip()
        if not sep or not value:
            raise ValueError(
                f"malformed autoscale entry {entry!r}: expected "
                "role=min:max or knob=number")
        if key in _ROLES:
            lo, sep2, hi = value.partition(":")
            try:
                lo, hi = int(lo), int(hi)
            except ValueError as e:
                raise ValueError(
                    f"malformed autoscale bounds {entry!r}: "
                    "expected role=min:max") from e
            if not sep2 or lo < 0 or hi < lo:
                raise ValueError(
                    f"bad autoscale bounds {entry!r}: need "
                    "0 <= min <= max")
            if key in cfg["bounds"]:
                raise ValueError(f"duplicate role in spec: {key!r}")
            cfg["bounds"][key] = (lo, hi)
        elif key in _KNOB_DEFAULTS:
            try:
                cfg[key] = float(value)
            except ValueError as e:
                raise ValueError(
                    f"malformed autoscale knob {entry!r}") from e
            if cfg[key] < 0:
                raise ValueError(f"negative autoscale knob {entry!r}")
        else:
            raise ValueError(
                f"unknown autoscale key {key!r} (roles: {_ROLES}; "
                f"knobs: {tuple(_KNOB_DEFAULTS)})")
    if not cfg["bounds"]:
        raise ValueError(
            f"autoscale spec {spec!r} names no role bounds "
            "(nothing to manage)")
    return cfg


def _objective_firing(slo_section, prefix):
    """True when any firing SLO objective's key starts with
    ``prefix`` (e.g. ``"ttft"``) — the burn-rate input per role."""
    if not slo_section:
        return False
    return any(o.get("firing") and str(o.get("objective", "")
                                       ).startswith(prefix)
               for o in slo_section.get("objectives") or ())


class Autoscaler:
    """The policy loop: read ``collector.fleet_view()``, scale each
    managed role's ``Supervisor`` pool.

    Args:
      collector: the ``FleetCollector`` whose role aggregates (and SLO
        section) drive the policy.
      pools: ``{role: Supervisor}`` — the per-role actuators (a bare
        ``Supervisor`` is accepted as ``{"both": sup}``).
      spec: the declarative policy — a spec string, a parsed dict from
        :func:`parse_autoscale_spec`, or None to read
        ``MXTPU_AUTOSCALE_SPEC`` (required: no spec, no autoscaler).
      interval_s: background-loop period (:meth:`start`); tests drive
        :meth:`evaluate` manually.
      clock: injectable monotonic clock (tests).
    """

    def __init__(self, collector, pools, spec=None, interval_s=2.0,
                 clock=time.monotonic):
        if spec is None:
            spec = os.environ.get(ENV_SPEC)
        if spec is None:
            raise ValueError(
                "no autoscale spec (pass spec= or set "
                f"{ENV_SPEC}, e.g. 'prefill=1:4;decode=1:8')")
        self.cfg = (spec if isinstance(spec, dict)
                    else parse_autoscale_spec(spec))
        if hasattr(pools, "add_slot"):     # a bare Supervisor
            pools = {"both": pools}
        self.pools = dict(pools)
        for role in self.cfg["bounds"]:
            if role not in self.pools:
                raise ValueError(
                    f"spec bounds name role {role!r} but no such "
                    f"pool was passed (pools: {tuple(self.pools)})")
        self.collector = collector
        self.interval_s = float(interval_s)
        self.clock = clock
        self._lock = threading.Lock()
        self._quiet_since = {}       # guarded-by: _lock — role -> t
        self._last_action_t = {}     # guarded-by: _lock — role -> t
        self._loop = None
        self._stop_evt = threading.Event()
        self._m_events = telemetry.counter(
            "mxtpu_fleet_scale_events_total",
            "autoscaler actuations by role, direction and reason",
            ("role", "direction", "reason"))

    # -- signals -------------------------------------------------------------
    def _pressure(self, role, agg, slo_section):
        """Scale-up reason for one role's FRESH aggregate, or None.
        Prefill saturates on prompt backlog + TTFT burn; decode on
        pending handoff ingests + KV headroom + TPOT burn; a classic
        "both" pool on any of them."""
        fresh = agg["replicas"] - agg["stale"]
        if fresh <= 0:
            return None              # dead data: never scale on it
        cfg = self.cfg
        if role in ("prefill", "both"):
            if agg["queue_depth"] / fresh >= cfg["up_queue"]:
                return "queue"
            if _objective_firing(slo_section, "ttft"):
                return "ttft_burn"
        if role in ("decode", "both"):
            if agg["waiting_handoffs"] / fresh >= cfg["up_handoffs"]:
                return "handoffs"
            kv = agg.get("kv_utilization_mean")
            if kv is not None and kv >= cfg["up_kv"]:
                return "kv"
            hkv = agg.get("host_kv_utilization_mean")
            if hkv is not None and hkv >= cfg["up_host_kv"]:
                return "host_kv"
            if _objective_firing(slo_section, "tpot"):
                return "tpot_burn"
        return None

    def _quiet(self, role, agg, slo_section):
        """True when the role carries no load at all — the only state
        that accrues scale-down credit."""
        if agg["replicas"] - agg["stale"] <= 0:
            return False             # unknown load is not "idle"
        if agg["queue_depth"] or agg["running"] \
                or agg["waiting_handoffs"]:
            return False
        if _objective_firing(slo_section, ""):
            return False             # any firing objective: not quiet
        return True

    # -- the policy step -----------------------------------------------------
    def evaluate(self, now=None):
        """One policy pass: at most ONE actuation per managed role.
        Returns ``[(role, direction, reason), ...]`` for what fired."""
        now = self.clock() if now is None else now
        view = self.collector.fleet_view()
        roles = view.get("roles") or {}
        slo_section = view.get("slo")
        actions = []
        for role, (lo, hi) in self.cfg["bounds"].items():
            sup = self.pools[role]
            size = sup.pool_size()
            agg = roles.get(role)
            with self._lock:
                last_t = self._last_action_t.get(role)
            in_cooldown = (last_t is not None
                           and now - last_t < self.cfg["cooldown_s"])
            if size < lo and not in_cooldown:
                # below the floor (e.g. a first pass, or bounds raised
                # live): restore minimum capacity before any policy
                self._actuate(sup, role, "up", "min_bound", now)
                actions.append((role, "up", "min_bound"))
                continue
            if agg is None:
                continue             # role not scraped yet: hold
            reason = self._pressure(role, agg, slo_section)
            if reason is not None:
                with self._lock:
                    self._quiet_since.pop(role, None)
                if size < hi and not in_cooldown:
                    self._actuate(sup, role, "up", reason, now)
                    actions.append((role, "up", reason))
                continue
            if not self._quiet(role, agg, slo_section):
                with self._lock:
                    self._quiet_since.pop(role, None)
                continue
            with self._lock:
                since = self._quiet_since.setdefault(role, now)
            if now - since < self.cfg["down_idle_s"]:
                continue             # quiet, but not for long enough
            if size > lo and not in_cooldown:
                self._actuate(sup, role, "down", "idle", now)
                actions.append((role, "down", "idle"))
        return actions

    def _actuate(self, sup, role, direction, reason, now):
        """One scaling action: spawn a fresh slot or drain out the
        newest one, then stamp the cooldown + observability trail."""
        if direction == "up":
            slot = sup.add_slot()
        else:
            slot = sup.active_slots()[-1]
            sup.remove_slot(slot)
        with self._lock:
            self._last_action_t[role] = now
            # an actuation resets the idle ledger either way: the next
            # scale-down needs a full fresh quiet window
            self._quiet_since.pop(role, None)
        self._m_events.labels(role=role, direction=direction,
                              reason=reason).inc()
        size = sup.pool_size()
        try:
            self.collector.annotate(
                "autoscale", role=role, direction=direction,
                reason=reason, slot=slot, pool_size=size)
        # mxtpu-lint: disable=swallowed-exception (the timeline is
        # observability; a broken collector endpoint must never abort
        # a scaling actuation mid-flight)
        except Exception:
            pass
        flight_mod.recorder().dump(
            f"autoscale_{direction}_{role}",
            extra={"role": role, "direction": direction,
                   "reason": reason, "slot": slot, "pool_size": size})
        if direction == "up" and hasattr(sup, "rebalance_catalog"):
            # a scaled-up replica starts with an empty adapter store:
            # one catalog pass spreads the hot adapters onto the pool
            # (no-op without an attached rebalancer — and the fresh
            # replica converges on later passes once it is scraped)
            sup.rebalance_catalog(reason=f"scale_up_{role}")

    # -- background loop -----------------------------------------------------
    def start(self):
        """Background policy thread pumping :meth:`evaluate` every
        ``interval_s`` (errors counted, never fatal — a flaky scrape
        must not kill the control loop)."""
        if self._loop is not None:
            return self
        self._stop_evt.clear()

        def loop():
            while not self._stop_evt.wait(self.interval_s):
                try:
                    self.evaluate()
                except Exception:
                    telemetry.counter(
                        "mxtpu_fleet_autoscaler_errors_total",
                        "autoscaler evaluate() failures").inc()

        self._loop = threading.Thread(
            target=loop, daemon=True, name="mxtpu-fleet-autoscaler")
        self._loop.start()
        return self

    def stop(self):
        self._stop_evt.set()
        if self._loop is not None:
            self._loop.join(timeout=5)
            self._loop = None

    def statusz(self):
        """Policy state for dashboards: bounds, knobs, per-role idle
        ledger and cooldown stamps."""
        with self._lock:
            return {
                "bounds": {r: list(b)
                           for r, b in self.cfg["bounds"].items()},
                "knobs": {k: self.cfg[k] for k in _KNOB_DEFAULTS},
                "pool_size": {r: self.pools[r].pool_size()
                              for r in self.cfg["bounds"]},
                "quiet_since": dict(self._quiet_since),
                "last_action_t": dict(self._last_action_t),
            }
