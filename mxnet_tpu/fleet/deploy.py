"""Rolling weight-reload deploys with SLO-gated automatic rollback.

The deploy half of the fleet control plane (``autoscaler.py`` is the
scaling half): given a *factory* that spawns replicas on the new
checkpoint, replace the fleet's slots drain-by-drain — one slot at a
time per role, riding ``Supervisor.replace_slot``'s ``_rolling``
exclusive claim so a deploy never races the crash monitor — and gate
every replacement behind a **token-parity probe**: a canary prompt
set served greedily by the old fleet before the rollout starts, then
re-served by each replacement directly after it spawns.  A
weight-*reload* (re-exported/re-sharded checkpoint, config rollout of
identical weights) must serve byte-identical tokens; a mismatch means
the new checkpoint is NOT the weights it claims to be, and the whole
rollout rolls back automatically.  The second rollback trigger is the
SLO plane: any burn-rate alert firing mid-rollout aborts and restores
the old factory the same way.

Per-role canary signatures:

  both     POST /generate          -> greedy token lists
  decode   POST /handoff (no KV)   -> degrades to recompute-from-
                                      prompt, returns token lists
  prefill  POST /generate          -> handoff envelope; the signature
                                      is the per-record KV payload
                                      digests (weight-dependent —
                                      prefill replicas never emit
                                      client tokens)

Old and new versions COEXIST mid-rollout — the router already
tolerates mixed fleets (membership-driven, per-replica scrape), and
``/fleetz`` surfaces per-slot ``version`` so an operator watching
``tools/fleet_report.py`` sees the rollout front move.  Rollback
replays the same drain-by-drain replacement with the old factory, so
it is exactly as zero-downtime as the rollout itself.

Counters: ``mxtpu_deploy_slots_replaced_total`` /
``mxtpu_deploy_rollbacks_total``; every replacement and rollback also
lands on the collector timeline and flight-dumps the telemetry ring.

Env knobs: ``MXTPU_DEPLOY_CANARY_NEW`` (canary max_new_tokens, 8) and
``MXTPU_DEPLOY_PROBE_TIMEOUT`` (per-probe HTTP timeout seconds, 30).
"""

from __future__ import annotations

import json
import time
import urllib.request

from .. import telemetry
from ..base import env_float, env_int
from ..telemetry import flight as flight_mod

__all__ = ["Deployer", "ENV_CANARY_NEW", "ENV_PROBE_TIMEOUT"]

ENV_CANARY_NEW = "MXTPU_DEPLOY_CANARY_NEW"
ENV_PROBE_TIMEOUT = "MXTPU_DEPLOY_PROBE_TIMEOUT"

# small deterministic default canary set (token ids valid for every
# vocab the smoke models use); callers with a real tokenizer pass
# their own prompts
_DEFAULT_CANARY = ((1, 2, 3, 4), (5, 3, 7), (2, 9, 4, 6, 8))


def _post_json(url, path, body, timeout_s):
    req = urllib.request.Request(
        f"{url.rstrip('/')}{path}", data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    with urllib.request.urlopen(req, timeout=timeout_s) as resp:
        return json.loads(resp.read())


class Deployer:
    """Rolling weight-reload over one or more role pools.

    Args:
      supervisors: ``{role: Supervisor}`` (a bare ``Supervisor`` is
        accepted as ``{"both": sup}``) — the pools to roll.
      collector: optional ``FleetCollector`` — supplies the SLO
        burn-rate rollback trigger and the timeline annotations.
      canary_prompts: token-id lists for the parity probe (default: a
        small deterministic built-in set).
      canary_max_new: greedy tokens per canary prompt
        (``MXTPU_DEPLOY_CANARY_NEW``, 8).
      probe_timeout_s: per-probe HTTP timeout
        (``MXTPU_DEPLOY_PROBE_TIMEOUT``, 30).
      clock: injectable monotonic clock (tests).
    """

    def __init__(self, supervisors, collector=None,
                 canary_prompts=None, canary_max_new=None,
                 probe_timeout_s=None, clock=time.monotonic):
        if hasattr(supervisors, "add_slot"):   # a bare Supervisor
            supervisors = {"both": supervisors}
        self.pools = dict(supervisors)
        self.collector = collector
        self.canary_prompts = tuple(
            tuple(p) for p in (canary_prompts or _DEFAULT_CANARY))
        self.canary_max_new = (
            int(canary_max_new) if canary_max_new is not None
            else env_int(ENV_CANARY_NEW, 8))
        self.probe_timeout_s = (
            float(probe_timeout_s) if probe_timeout_s is not None
            else env_float(ENV_PROBE_TIMEOUT, 30.0))
        self.clock = clock
        self._m_replaced = telemetry.counter(
            "mxtpu_deploy_slots_replaced_total",
            "slots moved to a new version by rolling deploys")
        self._m_rollbacks = telemetry.counter(
            "mxtpu_deploy_rollbacks_total",
            "rolling deploys aborted and rolled back")

    # -- probes --------------------------------------------------------------
    def probe(self, url, role):
        """The canary signature of one replica: a tuple per canary
        prompt — greedy tokens ("both"/"decode") or the handoff
        envelope's per-record KV digests ("prefill").  Raises
        ``OSError``/``ValueError`` when the replica cannot answer —
        an unanswerable replacement fails the gate."""
        sig = []
        for prompt in self.canary_prompts:
            body = {"prompt": list(prompt),
                    "max_new_tokens": self.canary_max_new}
            if role == "decode":
                # a decode-role replica only serves /handoff; with no
                # KV records it degrades to recompute-from-prompt and
                # returns tokens — exactly the weight probe we need
                body["records"] = []
                payload = _post_json(url, "/handoff", body,
                                     self.probe_timeout_s)
            else:
                payload = _post_json(url, "/generate", body,
                                     self.probe_timeout_s)
            if role == "prefill":
                recs = (payload.get("handoff") or {}).get(
                    "records") or ()
                if not recs:
                    raise ValueError("prefill canary exported no "
                                     "KV records")
                sig.append(tuple(r.get("digest") for r in recs))
            else:
                tokens = payload.get("tokens")
                if not tokens:
                    raise ValueError(f"canary returned no tokens: "
                                     f"{payload.get('error')}")
                sig.append(tuple(tokens))
        return sig

    def _reference(self):
        """Probe ONE live replica per pool before anything is
        replaced — the old version's canary signature that every
        replacement must match."""
        refs = {}
        for role, sup in self.pools.items():
            for slot in sup.active_slots():
                h = sup.handles()[slot]
                if h is not None and h.url:
                    refs[role] = self.probe(h.url, role)
                    break
        return refs

    def _burning(self):
        """True when any SLO objective is firing right now — the
        burn-rate rollback trigger (False without an SLO plane)."""
        if self.collector is None or self.collector.slo is None:
            return False
        try:
            return any(o.get("firing") for o in
                       self.collector.slo.statusz().get(
                           "objectives") or ())
        # mxtpu-lint: disable=swallowed-exception (a broken SLO
        # evaluator must not be able to veto OR force a rollback; the
        # parity gate still protects the rollout)
        except Exception:
            return False

    def _annotate(self, kind, **fields):
        if self.collector is None:
            return
        try:
            self.collector.annotate(kind, **fields)
        # mxtpu-lint: disable=swallowed-exception (the timeline is
        # observability; it must never abort a rollout step)
        except Exception:
            pass

    # -- the rollout ---------------------------------------------------------
    def rollout(self, factory, version=None, old_factory=None):
        """Roll every pool onto ``factory`` (``factory(slot) ->
        handle`` on the new checkpoint), one slot at a time per role,
        parity-probing each replacement; on a parity failure, an
        unanswerable replacement, or an SLO burn alert, roll every
        already-replaced slot back via ``old_factory`` (default: each
        supervisor's own spawn — the old version).  Returns a report
        dict (``status`` "ok" | "rolled_back")."""
        t0 = self.clock()
        report = {"version": version, "status": "ok", "reason": None,
                  "replaced": 0, "rolled_back": 0, "refs": {}}
        self._annotate("deploy_rollout", phase="start",
                       version=version)
        refs = self._reference()
        report["refs"] = {role: len(sig) for role, sig in refs.items()}
        replaced = []                   # (role, sup, slot) — in order
        failure = None
        for role, sup in self.pools.items():
            if failure:
                break
            ref = refs.get(role)
            for slot in sup.active_slots():
                handle = sup.replace_slot(slot, factory,
                                          reason="deploy")
                replaced.append((role, sup, slot))
                if handle is None or not handle.url:
                    failure = "spawn_failed"
                else:
                    self._m_replaced.inc()
                    report["replaced"] += 1
                    try:
                        sig = self.probe(handle.url, role)
                        if ref is not None and sig != ref:
                            failure = "parity"
                    except (OSError, ValueError):
                        failure = "probe_error"
                if failure is None and self._burning():
                    failure = "slo_burn"
                self._annotate("deploy_slot", role=role, slot=slot,
                               version=version,
                               ok=failure is None,
                               reason=failure)
                if failure:
                    break
        if failure:
            self._m_rollbacks.inc()
            report["status"] = "rolled_back"
            report["reason"] = failure
            self._annotate("deploy_rollback", phase="start",
                           reason=failure, version=version)
            flight_mod.recorder().dump(
                f"deploy_rollback_{failure}",
                extra={"version": version, "reason": failure,
                       "replaced": report["replaced"]})
            for role, sup, slot in replaced:
                sup.replace_slot(slot, old_factory, reason="rollback")
                report["rolled_back"] += 1
            self._annotate("deploy_rollback", phase="done",
                           slots=report["rolled_back"],
                           version=version)
        self._annotate("deploy_rollout", phase="done",
                       status=report["status"], version=version,
                       wall_s=round(self.clock() - t0, 3))
        flight_mod.recorder().dump(
            f"deploy_{report['status']}",
            extra={"version": version, "status": report["status"],
                   "replaced": report["replaced"]})
        return report
