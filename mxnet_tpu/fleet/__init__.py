"""Fleet layer: one engine process -> a service that survives losing it.

The reference framework's production story was a *process fleet*
(ps-lite's ZMQ node groups, dmlc-core's cluster tracker); the serving
analog here (ROADMAP item 3) is this package:

- ``replica``   — ``ReplicaServer``: a stdlib-HTTP front over one
  ``serve.Engine`` (``/generate``, ``/healthz``, ``/drain``,
  ``/statusz.json``, ``/handoff``), idempotent on client request ids;
  runnable as a process via ``tools/serve_replica.py``.  With
  ``role="prefill"|"decode"`` (``MXTPU_FLEET_ROLE``) the fleet splits
  DistServe-style: prefill replicas export a prompt's KV chain as
  content-keyed records and decode replicas ingest them through the
  host-RAM tier — decode iterations never share an engine with long
  prefills (docs/how_to/fleet.md "Disaggregated prefill/decode").
- ``router``    — ``Router``: least-loaded routing on scraped statusz
  signals (queue depth + KV occupancy + in-flight handoff ingests),
  per-hop timeout, capped exponential backoff, retry-on-sibling,
  per-replica circuit breaker, trace-id propagation so
  ``tools/trace_report.py --stitch`` reassembles a request's hops
  across replicas, and prefill→decode handoff orchestration
  (``/handoff_probe`` dedup + re-handoff on sibling).  With
  ``MXTPU_ROUTE_AFFINITY`` > 0 it becomes cache-aware: each scrape
  carries the replica's radix-cache advertisement (top-K chain keys
  + counting bloom) and the router scores candidates by longest
  advertised prompt-prefix ancestry, attaching a peer pull hint so
  a cold sibling fetches the missing KV chain over the handoff
  import path instead of recomputing it (the fleet-global KV
  fabric; docs/how_to/fleet.md "Cache-aware routing").
- ``supervisor``— ``Supervisor``: spawn/monitor/restart N replica
  slots, crash-restart with backoff, and drain -> AOT-warm restart
  rolling restarts (zero client-visible failures; PR 4's warm start is
  what makes this cheap).
- ``faults``    — ``FaultInjector``: the deterministic chaos hook
  (``MXTPU_FAULT_SPEC``: kill/delay/refuse/hang at request k) that the
  chaos gates in tests/test_fleet.py and tools/fleet_bench.py replay.
- ``collector`` — ``FleetCollector``: the live observability plane —
  scrapes every replica's ``/statusz.json`` + ``/metrics`` into
  per-replica time series (failures isolated per replica), aggregates
  a role-keyed fleet view at ``GET /fleetz``(+``.json``), receives
  pushed terminal request-trace lines (``MXTPU_TRACE_PUSH_URL``) for
  live cross-role stitched timelines, and carries the fleet timeline
  annotations (supervisor lifecycle, SLO alerts).  Rendered by
  ``tools/fleet_report.py``; the sensor half of autoscaling.
- ``autoscaler``— ``Autoscaler``: the policy half of the control
  plane (``MXTPU_AUTOSCALE_SPEC``, e.g. ``prefill=1:4;decode=1:8;
  up_queue=16``): scales each role's pool independently on its own
  signals (prefill: queue depth + TTFT burn; decode: waiting
  handoffs + KV/host-KV headroom + TPOT burn) with per-role bounds,
  asymmetric hysteresis and an oscillation cooldown; actuates via
  ``Supervisor.add_slot``/``remove_slot`` (AOT-warm spawns), router
  membership follows.
- ``catalog``   — ``CatalogRebalancer``: the model-catalog actuator —
  compares per-adapter traffic (the collector's per-model goodput)
  against placement (each replica's advertised adapter ids) and moves
  hot LoRA adapters replica-to-replica over ``/adapter_export`` →
  ``/load_adapter``; invoked by ``Supervisor.rebalance_catalog``
  (manually, or by the autoscaler after a scale-up so a fresh replica
  picks up the hot adapters).
- ``deploy``    — ``Deployer``: rolling weight-reload — replace
  slots drain-by-drain behind a token-parity canary probe, mixed
  versions coexist mid-rollout, automatic whole-rollout rollback on
  parity failure or SLO burn alert.
- ``slo``       — declarative objectives (``MXTPU_SLO_SPEC``, e.g.
  ``ttft_p99_ms=500;availability=0.999``) with SRE-workbook
  fast/slow multi-window burn-rate alerting: a firing alert counts
  ``mxtpu_slo_burning{objective}``, annotates the fleet timeline and
  flight-dumps the offending replicas.

Docs: docs/how_to/fleet.md.  Benchmark: ``tools/fleet_bench.py``
(FLEET_BENCH.json artifact — availability under one injected kill plus
rolling-restart downtime).
"""

from .autoscaler import Autoscaler, parse_autoscale_spec
from .catalog import CatalogRebalancer
from .collector import FleetCollector
from .deploy import Deployer
from .faults import Fault, FaultInjector, parse_fault_spec
from .replica import (DEAD, DRAINING, READY, ROLES, STARTING,
                      ReplicaServer, TRACE_HEADER)
from .router import (FleetError, NoReplicaAvailable, PermanentError,
                     Router, RouterResult)
from .slo import Objective, SLOEvaluator, parse_slo_spec
from .supervisor import ProcessReplica, Supervisor, probe_health

__all__ = ["ReplicaServer", "Router", "RouterResult", "Supervisor",
           "ProcessReplica", "FaultInjector", "Fault",
           "parse_fault_spec", "probe_health", "FleetError",
           "PermanentError", "NoReplicaAvailable", "TRACE_HEADER",
           "ROLES", "STARTING", "READY", "DRAINING", "DEAD",
           "FleetCollector", "SLOEvaluator", "Objective",
           "parse_slo_spec", "Autoscaler", "parse_autoscale_spec",
           "Deployer", "CatalogRebalancer"]
