"""Declarative SLOs with multi-window burn-rate alerting.

The Google SRE workbook's alerting recipe, applied to the fleet
collector's live series: an *objective* declares what fraction of
requests must be good ("99% of requests first-token within 500ms",
"99.9% of requests served at all"), which implies an **error budget**
(the tolerated bad fraction).  The *burn rate* is how fast the fleet
is spending that budget right now:

    burn = (bad requests / all requests, over a window) / budget

``burn == 1`` spends exactly the budget; ``burn == 14.4`` exhausts a
30-day budget in ~2 days.  Alerting on ONE window is the classic
trap — a short window pages on blips, a long one pages an hour late —
so an alert here fires only when BOTH a fast window (is it happening
*now*?) and a slow window (is it *sustained*?) burn past their
thresholds.  The textbook pairing (5m/1h at 14.4x) assumes a 30-day
budget; the defaults below are scaled to a serving fleet's timescale
and every knob is an env/constructor setting.

Spec grammar (``MXTPU_SLO_SPEC``)::

  spec       := objective (";" objective)*
  objective  := "availability" "=" fraction          # good = finished
              | metric "_p" QQ "_ms" "=" millis      # latency tail
  metric     := "ttft" | "tpot" | "total"
  QQ         := "50" | "90" | "99" | "99_9" | ...    # pNN[_N]

``ttft_p99_ms=500`` reads "99% of finished requests reach their first
token within 500ms" — budget 1%, a request counts *bad* when its TTFT
exceeds 500ms.  ``availability=0.999`` reads "99.9% of requests
finish" — budget 0.1%, a request counts bad when it terminates
rejected/cancelled.  Example: ``MXTPU_SLO_SPEC="ttft_p99_ms=500;
availability=0.999;tpot_p99_ms=80"``.

The per-request good/bad events come from the terminal request-trace
lines replicas push to the collector (``MXTPU_TRACE_PUSH_URL``), so
the math is exact request counting, never percentile-of-percentiles.
One CLIENT request can push several lines — the serving engine's, the
router's, and (disaggregated) the prefill replica's — so the burn math
first groups lines by trace id (:func:`group_requests`) and judges ONE
verdict per request (:meth:`Objective.judge`): the router line is the
client truth for availability when present; latency takes the worst
value any line observed.  Without grouping a total decode outage would
read as ~1/3 bad and an alert could sleep through it.

A FIRING alert (evaluated after every collector scrape pass):

* increments ``mxtpu_slo_burning{objective}`` (registry-direct — it
  must count even without ``MXTPU_TELEMETRY``, like the numeric
  watchdog),
* annotates the fleet timeline (visible at ``/fleetz`` next to the
  series that explain it), and
* triggers a rate-limited flight-recorder dump **on the offending
  replicas** — the replicas that served the bad requests in the fast
  window — so the post-mortem ring is captured while the incident is
  live, not after someone ssh'd in.

Chaos-provable: tests/test_fleet_obs.py injects kill/delay faults
(``MXTPU_FAULT_SPEC``) under a fake clock and pins that the alert
fires — and stays silent on a clean run.
"""

from __future__ import annotations

import re
import threading
import time

from ..base import env_flag, env_float, env_int

__all__ = ["Objective", "SLOEvaluator", "parse_slo_spec",
           "group_requests", "request_failed", "ENV_SPEC",
           "ENV_FAST_WINDOW", "ENV_SLOW_WINDOW", "ENV_FAST_BURN",
           "ENV_SLOW_BURN", "ENV_MIN_REQUESTS"]

ENV_SPEC = "MXTPU_SLO_SPEC"
ENV_FAST_WINDOW = "MXTPU_SLO_FAST_WINDOW"
ENV_SLOW_WINDOW = "MXTPU_SLO_SLOW_WINDOW"
ENV_FAST_BURN = "MXTPU_SLO_FAST_BURN"
ENV_SLOW_BURN = "MXTPU_SLO_SLOW_BURN"
ENV_MIN_REQUESTS = "MXTPU_SLO_MIN_REQUESTS"
ENV_BURN_CAPTURE = "MXTPU_PROFILEZ_ON_BURN"
ENV_BURN_CAPTURE_S = "MXTPU_PROFILEZ_BURN_S"

_LATENCY_KEY = re.compile(r"^(ttft|tpot|total)_p(\d+(?:_\d+)?)_ms$")
# trace-summary field each latency metric reads
_METRIC_FIELD = {"ttft": "ttft_s", "tpot": "tpot_s", "total": "total_s"}


def group_requests(records):
    """Group trace-line summaries into CLIENT requests by trace id (a
    line without one is its own request).  One request retried across
    replicas — or split across prefill/decode roles, or observed by
    both its serving engine and the router — is ONE unit of SLO
    accounting, not several."""
    groups, solo = {}, []
    for rec in records:
        tid = rec.get("trace_id")
        if tid is None:
            solo.append([rec])
        else:
            groups.setdefault(tid, []).append(rec)
    return list(groups.values()) + solo


def request_failed(group):
    """Client-level failure verdict for one request's trace lines:
    the router's line is the client truth when present (it saw the
    final outcome across every retry/handoff hop); otherwise any
    rejected/cancelled line fails the request.  None = no terminal
    signal usable for availability (nothing to count)."""
    router = [r for r in group if r.get("source") == "router"]
    if router:
        return any(r["status"] != "finished" for r in router)
    if any(r["status"] in ("rejected", "cancelled") for r in group):
        return True
    if any(r["status"] == "finished" for r in group):
        return False
    return None


class Objective:
    """One parsed objective: its key, kind, target and error budget."""

    __slots__ = ("key", "kind", "metric", "q", "target", "budget")

    def __init__(self, key, kind, target, metric=None, q=None):
        self.key = key
        self.kind = kind              # "availability" | "latency"
        self.target = float(target)
        self.metric = metric          # "ttft"/"tpot"/"total" (latency)
        self.q = q                    # the percentile (latency)
        if kind == "availability":
            if not 0.0 < self.target < 1.0:
                raise ValueError(
                    f"availability target must be in (0, 1) "
                    f"(got {target})")
            self.budget = 1.0 - self.target
        else:
            if self.target <= 0:
                raise ValueError(
                    f"{key}: latency target must be > 0 ms "
                    f"(got {target})")
            self.budget = 1.0 - q

    def is_bad(self, rec):
        """Whether ONE trace line spends error budget — None when the
        record carries no signal for this objective (e.g. a rejected
        request has no TTFT).  Line-level: offender attribution reads
        this; the burn math itself judges whole requests
        (:meth:`judge`)."""
        if self.kind == "availability":
            return rec["status"] != "finished"
        if rec["status"] != "finished":
            return None
        v = rec.get(_METRIC_FIELD[self.metric])
        if v is None:
            return None
        return v * 1e3 > self.target

    def judge(self, group):
        """One verdict per CLIENT request (a ``group_requests`` group):
        availability follows :func:`request_failed`; latency takes the
        WORST value any of the request's lines observed (the router's
        total includes retries and handoff hops; the engine lines
        carry TTFT/TPOT).  None = no signal for this objective."""
        if self.kind == "availability":
            return request_failed(group)
        field = _METRIC_FIELD[self.metric]
        vals = [r[field] for r in group
                if r["status"] == "finished"
                and r.get(field) is not None]
        if not vals:
            return None
        return max(vals) * 1e3 > self.target

    def __repr__(self):
        return f"Objective({self.key}={self.target})"


def parse_slo_spec(spec):
    """Parse the ``MXTPU_SLO_SPEC`` grammar into ``[Objective, ...]``.
    Raises ``ValueError`` on anything unrecognized — an SLO spec with
    a typo silently guarding nothing would be worse than a crash (the
    fault-spec philosophy)."""
    objectives = []
    for entry in (spec or "").split(";"):
        entry = entry.strip()
        if not entry:
            continue
        if "=" not in entry:
            raise ValueError(
                f"malformed SLO objective {entry!r}: expected key=value")
        key, _, value = entry.partition("=")
        key = key.strip()
        try:
            target = float(value)
        except ValueError as e:
            raise ValueError(
                f"malformed SLO objective {entry!r}: {e}") from e
        if key == "availability":
            objectives.append(Objective(key, "availability", target))
            continue
        m = _LATENCY_KEY.match(key)
        if not m:
            raise ValueError(
                f"unknown SLO objective {key!r} (use availability= or "
                f"<ttft|tpot|total>_p<NN>_ms=)")
        q = float(m.group(2).replace("_", ".")) / 100.0
        if not 0.0 < q < 1.0:
            raise ValueError(f"{key}: percentile must be in (0, 100)")
        objectives.append(Objective(key, "latency", target,
                                    metric=m.group(1), q=q))
    if len({o.key for o in objectives}) != len(objectives):
        raise ValueError(f"duplicate objective in {spec!r}")
    return objectives


class SLOEvaluator:
    """Multi-window burn-rate evaluation over a collector's records.

    Args (env default in parens):
      objectives: ``[Objective]`` (``parse_slo_spec``).
      collector: anything with ``trace_records(window_s, now=)``,
        ``annotate(kind, **f)``, ``url_for_replica(name)`` and
        ``request_flight_dump(url, reason)`` — in practice the
        ``FleetCollector`` that owns this evaluator.
      fast_s / slow_s: the two windows (``MXTPU_SLO_FAST_WINDOW`` 60 /
        ``MXTPU_SLO_SLOW_WINDOW`` 300 seconds).
      fast_burn / slow_burn: firing thresholds
        (``MXTPU_SLO_FAST_BURN`` 10 / ``MXTPU_SLO_SLOW_BURN`` 5) —
        an alert fires only when BOTH windows burn at or past their
        threshold.
      min_requests: fewest fast-window requests worth judging
        (``MXTPU_SLO_MIN_REQUESTS`` 10) — burn math over three
        requests is noise, not signal.
      dump_interval_s: per-objective floor between offender flight
        dumps (30) on top of each replica's own per-reason limit.
      clock: injectable monotonic clock (fake-clock chaos tests).
    """

    def __init__(self, objectives, collector, fast_s=None, slow_s=None,
                 fast_burn=None, slow_burn=None, min_requests=None,
                 dump_interval_s=30.0, clock=time.monotonic):
        self.objectives = list(objectives)
        self.collector = collector
        self.fast_s = (float(fast_s) if fast_s is not None
                       else env_float(ENV_FAST_WINDOW, 60.0))
        self.slow_s = (float(slow_s) if slow_s is not None
                       else env_float(ENV_SLOW_WINDOW, 300.0))
        self.fast_burn = (float(fast_burn) if fast_burn is not None
                          else env_float(ENV_FAST_BURN, 10.0))
        self.slow_burn = (float(slow_burn) if slow_burn is not None
                          else env_float(ENV_SLOW_BURN, 5.0))
        self.min_requests = (int(min_requests)
                             if min_requests is not None
                             else env_int(ENV_MIN_REQUESTS, 10))
        self.dump_interval_s = float(dump_interval_s)
        # fast-burn auto-profiling: alongside each offender's flight
        # dump, open a short /profilez capture window on it so the
        # page links straight to a device trace of the burn
        # (MXTPU_PROFILEZ_ON_BURN=0 keeps dumps only)
        self.capture_on_burn = env_flag(ENV_BURN_CAPTURE, True)
        self.capture_s = env_float(ENV_BURN_CAPTURE_S, 0.5)
        self.clock = clock
        self._lock = threading.Lock()
        # objective key -> {"firing", "since", "fired_total", ...}
        self._state = {o.key: {"firing": False, "since": None,
                               "fired_total": 0, "transitions": 0}
                       for o in self.objectives}   # guarded-by: _lock
        self._last_dump_t = {}                     # guarded-by: _lock
        self._last_eval = []                       # guarded-by: _lock

    # -- burn math -----------------------------------------------------------
    def _window_burn(self, obj, window_s, now):
        """(burn_rate, bad, total) over one trailing window — the bad
        fraction divided by the objective's error budget.  Counted per
        CLIENT request (lines grouped by trace id), so a request that
        pushed three lines is one unit; requests that carry no signal
        for the objective are excluded from its denominator."""
        bad = total = 0
        for group in group_requests(
                self.collector.trace_records(window_s, now=now)):
            verdict = obj.judge(group)
            if verdict is None:
                continue
            total += 1
            if verdict:
                bad += 1
        if total == 0:
            return 0.0, 0, 0
        return (bad / total) / obj.budget, bad, total

    def _offenders(self, obj, now):
        """Replica names of the fast window's bad requests, worst
        first — where the flight dumps go."""
        counts = {}
        for rec in self.collector.trace_records(self.fast_s, now=now):
            if obj.is_bad(rec) and rec.get("replica"):
                counts[rec["replica"]] = counts.get(rec["replica"], 0) + 1
        return [name for name, _ in
                sorted(counts.items(), key=lambda kv: -kv[1])]

    # -- the evaluation pass (collector runs this after each scrape) ---------
    def evaluate(self, now=None):
        """One evaluation pass; returns the per-objective state list
        (also kept for :meth:`statusz`).  A FIRING objective counts
        ``mxtpu_slo_burning{objective}`` every pass (the counter's
        growth rate IS the burn duration), annotates the fleet
        timeline on each transition, and flight-dumps the offenders
        (rate-limited)."""
        now = self.clock() if now is None else now
        out = []
        for obj in self.objectives:
            burn_fast, bad_f, total_f = self._window_burn(
                obj, self.fast_s, now)
            burn_slow, bad_s, total_s = self._window_burn(
                obj, self.slow_s, now)
            firing = (total_f >= self.min_requests
                      and burn_fast >= self.fast_burn
                      and burn_slow >= self.slow_burn)
            with self._lock:
                st = self._state[obj.key]
                transition = firing != st["firing"]
                st["firing"] = firing
                if firing:
                    st["fired_total"] += 1
                    if transition:
                        st["since"] = now
                        st["transitions"] += 1
                elif transition:
                    st["since"] = None
                    st["transitions"] += 1
            if firing:
                self._count_burning(obj.key)
            if transition:
                self.collector.annotate(
                    "slo_alert", objective=obj.key,
                    state="firing" if firing else "resolved",
                    burn_fast=round(burn_fast, 3),
                    burn_slow=round(burn_slow, 3),
                    bad_fast=bad_f, total_fast=total_f)
            if firing:
                self._dump_offenders(obj, now)
            out.append({
                "objective": obj.key, "kind": obj.kind,
                "target": obj.target, "budget": round(obj.budget, 6),
                "burn_fast": round(burn_fast, 4),
                "burn_slow": round(burn_slow, 4),
                "bad_fast": bad_f, "total_fast": total_f,
                "bad_slow": bad_s, "total_slow": total_s,
                "firing": firing})
        with self._lock:
            self._last_eval = out
        return out

    @staticmethod
    def _count_burning(objective):
        # registry-direct (not the enabled-gated accessor): an SLO
        # burning must count even when MXTPU_TELEMETRY is unset — the
        # same rule the numeric watchdog follows
        from mxnet_tpu import telemetry

        telemetry.registry().counter(
            "mxtpu_slo_burning",
            "evaluation passes with this objective's burn-rate alert "
            "firing", ("objective",)).labels(objective=objective).inc()

    def _dump_offenders(self, obj, now):
        with self._lock:
            last = self._last_dump_t.get(obj.key)
            if last is not None \
                    and now - last < self.dump_interval_s:
                return []
            self._last_dump_t[obj.key] = now
        dumped = []
        for name in self._offenders(obj, now):
            url = self.collector.url_for_replica(name)
            if url is None:
                continue
            # capture first: the flight dump then embeds the capture
            # id (and the last step-decomposition ring entries ride
            # the dump's statusz snapshot), so one page links alert →
            # post-mortem → device trace.  The replica's own 409/429
            # policy bounds profiling cost; a refused capture degrades
            # to a plain dump (capture_id None)
            capture_id = None
            request_capture = getattr(
                self.collector, "request_profile_capture", None)
            if self.capture_on_burn and request_capture is not None:
                cap = request_capture(
                    url, duration_s=self.capture_s,
                    reason=f"slo_burn_{obj.key}")
                capture_id = (cap or {}).get("id")
            if capture_id is None:
                # positional call keeps pre-capture collector doubles
                # (and subclasses with the old signature) working
                path = self.collector.request_flight_dump(
                    url, f"slo_burn_{obj.key}")
            else:
                path = self.collector.request_flight_dump(
                    url, f"slo_burn_{obj.key}", capture_id=capture_id)
            dumped.append({"replica": name, "path": path,
                           "capture_id": capture_id})
        if dumped:
            self.collector.annotate("slo_flight_dump",
                                    objective=obj.key, dumps=dumped)
        return dumped

    # -- introspection -------------------------------------------------------
    def statusz(self):
        """The ``/fleetz`` ``slo`` section: objectives with their last
        evaluated burn rates and firing state."""
        with self._lock:
            last = {e["objective"]: e for e in self._last_eval}
            out = []
            for obj in self.objectives:
                st = self._state[obj.key]
                row = {"objective": obj.key, "kind": obj.kind,
                       "target": obj.target,
                       "budget": round(obj.budget, 6),
                       "firing": st["firing"],
                       "firing_since": st["since"],
                       "fired_total": st["fired_total"]}
                row.update({k: v for k, v in
                            (last.get(obj.key) or {}).items()
                            if k.startswith(("burn_", "bad_",
                                             "total_"))})
                out.append(row)
        return {"fast_window_s": self.fast_s,
                "slow_window_s": self.slow_s,
                "fast_burn": self.fast_burn,
                "slow_burn": self.slow_burn,
                "min_requests": self.min_requests,
                "objectives": out}
