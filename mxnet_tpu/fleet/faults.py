"""Deterministic fault injection for the fleet chaos tests.

Chaos testing is only worth anything when the chaos is reproducible:
"the replica died at some point and things mostly recovered" proves
nothing, "the replica dies exactly at its 5th request, mid-stream, and
every client request still completes with identical tokens" is a gate.
The injector triggers on the ARRIVAL INDEX of ``/generate`` requests at
one replica (1-based, counted by that replica's injector), so a fault
spec plus a deterministic workload pins the exact failure point.

Spec grammar (``MXTPU_FAULT_SPEC``, or the ``spec=`` argument)::

  spec    := rule (";" rule)*
  rule    := action "@" k [":" arg]
  action  := "kill" | "delay" | "refuse" | "hang"
  k       := 1-based /generate arrival index at this replica
  arg     :=  delay: seconds to sleep before serving (default 0.05)
              refuse: how many consecutive requests to 503 (default 1)
              hang: seconds to hold the connection without answering
                    (default 3600 — practically forever)
              kill: ignored

Examples::

  kill@5                die (hard process exit / in-process hard stop)
                        while serving the 5th request, mid-stream
  delay@2:0.25          sleep 250ms before serving request 2
  refuse@3:2            503 requests 3 and 4 (retriable rejection)
  hang@7:30             hold request 7 open unanswered for 30s
  refuse@1;kill@9       rules compose; first matching rule wins

The supervisor/bench inject a spec into ONE replica's environment; the
others run clean.  An empty/unset spec parses to an injector that never
fires, so the hook can stay unconditionally wired in the replica.

Host-KV restore-delay fault (not arrival-indexed)
-------------------------------------------------

``MXTPU_FAULT_HOST_RESTORE_DELAY=<seconds>`` simulates a slow
DRAM→HBM copy on every host-KV-tier restore claim inside the serve
engine (``serve.kv_block_manager.HostKVPool``).  With
``MXTPU_SERVE_HOST_KV_RESTORE_BUDGET`` set, a delay past the budget
DEGRADES that radix hit to recompute — the entry stays hosted, the
engine prefills the span as if it missed — instead of stalling the
step loop on the copy; the pool's ``degraded`` counter and the
replica's ``host_kv_utilization`` load signal make the degradation
observable fleet-wide.  Read at pool construction (engine start), so
the chaos harness sets it in the target replica's environment like
``MXTPU_FAULT_SPEC``.

Handoff faults (disaggregated prefill/decode fleets)
----------------------------------------------------

Two chaos knobs target the prefill→decode KV handoff a role-split
fleet rides (docs/how_to/fleet.md "Disaggregated prefill/decode"):

``MXTPU_FAULT_HANDOFF_DELAY=<seconds>`` sleeps that long at the START
of every ``/handoff`` arrival at the target replica — a simulated slow
wire.  Pushed past the router's per-hop timeout it exercises the
retry-on-sibling re-handoff path (the router still holds the payload).

``MXTPU_FAULT_HANDOFF_DROP=<n>`` discards the KV records of the first
``n`` handoff arrivals at the target replica before import — the
payload "arrives truncated".  The receiving replica degrades to
recompute-from-prompt (the handoff body always carries the prompt),
so tokens stay byte-identical to a role="both" run; only the prefill
compute is re-paid and the replica's ``handoff`` counters show zero
imports.

Both are read at ``ReplicaServer`` construction (constructor arguments
``handoff_delay_s=`` / ``handoff_drop=`` win), set per target replica
like ``MXTPU_FAULT_SPEC``.  The arrival-indexed grammar above also
covers ``/handoff``: the injector counts handoff arrivals through the
same ``on_request`` hook, so ``kill@2`` on a decode replica kills it
mid-stream while serving its 2nd handoff.
"""

from __future__ import annotations

import threading

__all__ = ["Fault", "FaultInjector", "parse_fault_spec", "ENV_SPEC",
           "ENV_HOST_RESTORE_DELAY", "ENV_HOST_RESTORE_BUDGET",
           "ENV_HANDOFF_DELAY", "ENV_HANDOFF_DROP", "ACTIONS"]

ENV_SPEC = "MXTPU_FAULT_SPEC"

# declared as plain strings (NOT imported from serve.kv_block_manager,
# whose module also names them — that import would drag the whole
# serve/jax chain into this deliberately stdlib-only module); the
# canonical reader is serve.kv_block_manager.HostKVPool
ENV_HOST_RESTORE_DELAY = "MXTPU_FAULT_HOST_RESTORE_DELAY"
ENV_HOST_RESTORE_BUDGET = "MXTPU_SERVE_HOST_KV_RESTORE_BUDGET"

# prefill→decode handoff chaos (canonical reader: replica.ReplicaServer)
ENV_HANDOFF_DELAY = "MXTPU_FAULT_HANDOFF_DELAY"
ENV_HANDOFF_DROP = "MXTPU_FAULT_HANDOFF_DROP"

ACTIONS = ("kill", "delay", "refuse", "hang")

_DEFAULT_ARGS = {"delay": 0.05, "refuse": 1.0, "hang": 3600.0}


class Fault:
    """One parsed rule: ``action`` at arrival index ``at`` with ``arg``."""

    __slots__ = ("action", "at", "arg")

    def __init__(self, action, at, arg=None):
        if action not in ACTIONS:
            raise ValueError(f"unknown fault action {action!r} "
                             f"(one of {', '.join(ACTIONS)})")
        self.action = action
        self.at = int(at)
        if self.at < 1:
            raise ValueError(f"fault index must be >= 1 (got {at})")
        self.arg = float(_DEFAULT_ARGS.get(action, 0.0)
                         if arg is None else arg)

    def matches(self, index):
        """Whether this rule fires for the ``index``-th request.
        ``refuse`` covers a RANGE (``arg`` consecutive requests);
        everything else is a single index."""
        if self.action == "refuse":
            return self.at <= index < self.at + max(1, int(self.arg))
        return index == self.at

    def __repr__(self):
        return f"Fault({self.action}@{self.at}:{self.arg})"


def parse_fault_spec(spec):
    """Parse the ``MXTPU_FAULT_SPEC`` grammar into ``[Fault, ...]``.
    Raises ``ValueError`` on malformed rules — a chaos run with a typo'd
    spec silently testing nothing would be worse than a crash."""
    faults = []
    for rule in (spec or "").split(";"):
        rule = rule.strip()
        if not rule:
            continue
        if "@" not in rule:
            raise ValueError(
                f"malformed fault rule {rule!r}: expected action@k[:arg]")
        action, _, rest = rule.partition("@")
        at, _, arg = rest.partition(":")
        try:
            faults.append(Fault(action.strip(), int(at),
                                float(arg) if arg else None))
        except ValueError as e:
            raise ValueError(f"malformed fault rule {rule!r}: {e}") from e
    return faults


class FaultInjector:
    """Thread-safe arrival counter + rule matcher for one replica.

    ``spec=None`` reads ``MXTPU_FAULT_SPEC`` (unset -> no faults).  The
    replica calls :meth:`on_request` once per ``/generate`` arrival and
    interprets the returned :class:`Fault` (or ``None``); the injector
    itself never sleeps or kills — policy stays in one place, the
    replica, where the test can also stub it in-process.
    """

    def __init__(self, spec=None):
        if spec is None:
            import os

            spec = os.environ.get(ENV_SPEC, "")
        self.faults = (list(spec) if isinstance(spec, (list, tuple))
                       else parse_fault_spec(spec))
        self._lock = threading.Lock()
        self._count = 0            # guarded-by: _lock
        self.fired = []            # guarded-by: _lock

    @property
    def count(self):
        with self._lock:
            return self._count

    def on_request(self):
        """Count one arrival; return the first matching ``Fault`` (and
        record it in :attr:`fired`) or ``None``."""
        with self._lock:
            self._count += 1
            index = self._count
            for f in self.faults:
                if f.matches(index):
                    self.fired.append((index, f))
                    return f
        return None
