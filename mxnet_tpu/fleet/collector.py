"""FleetCollector: the fleet's live sensor plane.

Until now every fleet-level signal was post-hoc: per-replica request
traces were stitched from JSONL files after the run, router hop
latency existed only inside a bench payload, and nobody could read
"total queue depth across the decode pool right now" anywhere.  The
collector turns the fleet from benchmarkable into operable — and is
deliberately the *sensor* half of autoscaling (ROADMAP 2(a)): the
follow-up autoscaler reads this plane and is pure policy.

One ``FleetCollector`` (owned by whoever owns the Router/Supervisor)
does four things:

* **Scrapes** every replica's ``GET /statusz.json`` (the ``replica``
  section: queue depth, running, ``waiting_handoffs``, KV + host-KV
  utilization, and the ``stats`` ground truth — token/reject totals,
  TTFT/TPOT percentiles, per-tenant completions) and ``GET /metrics``
  (Prometheus text) on an interval into one bounded
  :class:`~mxnet_tpu.telemetry.timeseries.TimeSeriesRing` per replica.
  A scrape failure is isolated to ITS replica — counted, marked stale
  after ``stale_after`` missed intervals, never holing a sibling's
  series.
* **Aggregates** a fleet view keyed by role (prefill / decode / both):
  summed queue depth and token/reject totals, windowed tokens/sec,
  mean KV and host-KV utilization, ``waiting_handoffs``, per-tenant
  goodput — served at ``GET /fleetz`` (HTML) + ``GET /fleetz.json``
  and rendered by ``tools/fleet_report.py``.  Stale replicas are
  listed but EXCLUDED from totals (a dead replica's last scrape must
  not count as live queue depth forever).
* **Receives** pushed terminal request-trace lines (replicas set
  ``MXTPU_TRACE_PUSH_URL`` to this collector's ``/trace``), so
  cross-role stitched timelines — and the SLO layer's per-request
  good/bad events — exist live instead of only from files.
* **Annotates** a fleet timeline: supervisor lifecycle events
  (crash-restart, drain, rolling-restart phases) and firing SLO
  alerts land as annotations next to the series they explain.

With ``MXTPU_SLO_SPEC`` set (see ``fleet/slo.py``) the collector owns
an :class:`~mxnet_tpu.fleet.slo.SLOEvaluator` and evaluates it after
every scrape pass.

Fully inert when unconfigured: nothing in the serving stack constructs
a collector — no object, no thread, no endpoint — and replicas answer
scrapes with the same bytes whether a collector exists or not.  Pure
stdlib (urllib + http.server), like the rest of the fleet layer.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.request
from collections import deque

from .. import telemetry
from ..base import env_float, env_int
from ..telemetry import flight as flight_mod
from ..telemetry.timeseries import (TimeSeriesRing, nearest_rank,
                                    parse_prometheus_text)
from .slo import group_requests, request_failed

__all__ = ["FleetCollector", "ENV_INTERVAL", "ENV_PORT"]

ENV_INTERVAL = "MXTPU_FLEET_COLLECT_INTERVAL"
ENV_PORT = "MXTPU_FLEET_COLLECT_PORT"

# scraped statusz "replica"-section fields recorded verbatim into the
# per-replica ring (gauges: current level each sample)
_GAUGE_FIELDS = ("queue_depth", "running", "in_flight",
                 "waiting_handoffs", "kv_utilization",
                 "host_kv_utilization", "max_batch")
# "stats" ground-truth fields (mixed: monotonic totals + percentiles)
_STATS_FIELDS = ("tokens_generated", "prompt_tokens", "completed",
                 "rejected", "preemptions", "decode_tok_per_sec",
                 "total_tok_per_sec", "ttft_ms_p50", "ttft_ms_p99",
                 "tpot_ms_p50", "tpot_ms_p99", "decode_occupancy",
                 "prefix_hits", "prefix_misses",
                 "prefix_resurrections", "prefix_tokens_saved",
                 "prefill_tokens_computed")


class _ReplicaView:
    """Collector-side view of one replica: identity + its ring."""

    __slots__ = ("url", "name", "role", "state", "version", "model",
                 "adapters", "ring",
                 "last_attempt_t", "last_success_t",
                 "consecutive_failures", "total_failures", "scrapes")

    def __init__(self, url, ring_capacity, clock):
        self.url = url.rstrip("/")
        self.name = self.url
        self.role = "both"
        self.state = "unknown"
        self.version = None
        # catalog identity: carried checkpoint + registered adapter
        # ids (None until a scrape advertises them)
        self.model = None
        self.adapters = None
        self.ring = TimeSeriesRing(ring_capacity, clock=clock)
        self.last_attempt_t = None
        self.last_success_t = None
        self.consecutive_failures = 0
        self.total_failures = 0
        self.scrapes = 0


class FleetCollector:
    """Scrape + aggregate + ingest + serve; see the module docstring.

    Args (env default in parens):
      urls: replica base URLs to scrape (grow/shrink later with
        ``add_replica``/``remove_replica``).
      router: optional ``fleet.Router`` — membership then FOLLOWS the
        router's (supervisor respawns propagate automatically).
      interval_s: scrape period (``MXTPU_FLEET_COLLECT_INTERVAL``, 1.0).
        ``start()`` launches the scrape thread; tests drive
        ``scrape()`` manually.
      port: HTTP port for ``/fleetz`` + ``/trace``
        (``MXTPU_FLEET_COLLECT_PORT``; 0 = ephemeral — read ``.port``;
        None/unset = no server).
      timeout_s: per-replica scrape timeout (2.0) — one hung replica
        costs its own thread this much, never the pass.
      ring_capacity: samples kept per replica (256).
      stale_after: missed intervals before a replica's series is
        marked stale and excluded from totals (3.0).
      rate_window_s: trailing window for the windowed rates (30.0).
      slo_spec: ``MXTPU_SLO_SPEC`` override; a non-empty spec attaches
        an ``SLOEvaluator`` evaluated after every scrape pass.
      clock: injectable monotonic clock (fake-clock tests drive
        staleness, windows and burn rates deterministically).
    """

    def __init__(self, urls=(), router=None, interval_s=None, port=None,
                 timeout_s=2.0, ring_capacity=256, stale_after=3.0,
                 rate_window_s=30.0, trace_capacity=4096,
                 annotation_capacity=512, slo_spec=None,
                 clock=time.monotonic):
        self.interval_s = (float(interval_s) if interval_s is not None
                           else env_float(ENV_INTERVAL, 1.0))
        if port is None:
            env_port = env_int(ENV_PORT, -1)
            port = env_port if env_port >= 0 else None
        self._requested_port = port
        self.port = None
        self.timeout_s = float(timeout_s)
        self.ring_capacity = int(ring_capacity)
        self.stale_after = float(stale_after)
        self.rate_window_s = float(rate_window_s)
        self.router = router
        self.clock = clock
        self._lock = threading.RLock()
        self._views = {}                     # guarded-by: _lock
        self._scrape_passes = 0              # guarded-by: _lock
        self._traces = deque(maxlen=int(trace_capacity))  # guarded-by: _lock
        self._traces_received = 0            # guarded-by: _lock
        self._traces_bad = 0                 # guarded-by: _lock
        self._annotations = deque(maxlen=int(annotation_capacity))  # guarded-by: _lock
        for u in urls:
            self._views[u.rstrip("/")] = _ReplicaView(
                u, self.ring_capacity, clock)
        self._server = None
        self._scrape_thread = None
        self._stop_evt = threading.Event()
        self._m_scrape_failures = telemetry.counter(
            "mxtpu_fleet_scrape_failures_total",
            "per-replica collector scrape failures", ("replica",))
        self._m_traces = telemetry.counter(
            "mxtpu_fleet_collector_traces_total",
            "request-trace lines received on /trace", ("outcome",))
        # SLO layer (fleet/slo.py): attached when a spec is configured
        self.slo = None
        if slo_spec is None:
            import os

            slo_spec = os.environ.get("MXTPU_SLO_SPEC") or ""
        if slo_spec:
            from .slo import SLOEvaluator, parse_slo_spec

            self.slo = SLOEvaluator(parse_slo_spec(slo_spec), self,
                                    clock=clock)

    # -- membership ----------------------------------------------------------
    def add_replica(self, url):
        with self._lock:
            url = url.rstrip("/")
            if url not in self._views:
                self._views[url] = _ReplicaView(url, self.ring_capacity,
                                                self.clock)

    def remove_replica(self, url):
        with self._lock:
            self._views.pop(url.rstrip("/"), None)

    def views(self):
        with self._lock:
            return list(self._views.values())

    def _sync_membership(self):
        """With a router attached, membership follows ITS replica list
        (supervisor respawns propagate without extra wiring)."""
        if self.router is None:
            return
        urls = {r.url for r in self.router.replicas()}
        with self._lock:
            for u in urls - set(self._views):
                self._views[u] = _ReplicaView(u, self.ring_capacity,
                                              self.clock)
            for u in set(self._views) - urls:
                del self._views[u]

    # -- lifecycle -----------------------------------------------------------
    def start(self):
        """Launch the HTTP endpoint (when a port is configured) and
        the background scrape thread."""
        if self._requested_port is not None and self._server is None:
            self._server = _serve(self)
            self.port = self._server.server_address[1]
        if self.interval_s > 0 and self._scrape_thread is None:
            self._scrape_thread = threading.Thread(
                target=self._scrape_loop, daemon=True,
                name="mxtpu-fleet-collector")
            self._scrape_thread.start()
        return self

    def stop(self):
        self._stop_evt.set()
        if self._scrape_thread is not None:
            self._scrape_thread.join(timeout=5)
            self._scrape_thread = None
        server, self._server = self._server, None
        if server is not None:
            threading.Thread(target=server.shutdown,
                             daemon=True).start()
            try:
                server.server_close()
            except OSError:
                pass  # mxtpu-lint: disable=swallowed-exception (port
                # already torn down; nothing to record at shutdown)

    @property
    def url(self):
        return (f"http://127.0.0.1:{self.port}"
                if self.port is not None else None)

    def _scrape_loop(self):
        while not self._stop_evt.wait(self.interval_s):
            try:
                self.scrape()
            except Exception:
                telemetry.counter(
                    "mxtpu_fleet_collector_errors_total",
                    "collector scrape-pass failures").inc()

    # -- scraping ------------------------------------------------------------
    def scrape(self):
        """One concurrent pass over every replica (each isolated in
        its own thread + try block: a hung replica burns its own
        timeout, a broken one only its own series), then refresh the
        aggregate gauges and — when configured — evaluate the SLOs.
        Returns ``{"replicas": n, "ok": n, "failed": n}``."""
        self._sync_membership()
        views = self.views()
        results = {}
        threads = [threading.Thread(target=self._scrape_one,
                                    args=(v, results), daemon=True)
                   for v in views]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=self.timeout_s + 1.0)
        with self._lock:
            self._scrape_passes += 1
        self._update_agg_gauges()
        if self.slo is not None:
            self.slo.evaluate()
        ok = sum(1 for v in results.values() if v)
        return {"replicas": len(views), "ok": ok,
                "failed": len(views) - ok}

    def _scrape_one(self, view, results):
        now = self.clock()
        with self._lock:
            view.last_attempt_t = now
        try:
            with urllib.request.urlopen(f"{view.url}/statusz.json",
                                        timeout=self.timeout_s) as resp:
                snap = json.loads(resp.read())
            sec = snap.get("replica") or {}
            values = self._flatten_replica(sec)
        except (OSError, ValueError):
            with self._lock:
                view.consecutive_failures += 1
                view.total_failures += 1
            self._m_scrape_failures.labels(replica=view.name).inc()
            results[view.url] = False
            return
        # /metrics is best-effort on top: a replica predating the
        # endpoint (or with an empty registry) must not fail the
        # statusz scrape that carries the ground truth
        try:
            with urllib.request.urlopen(f"{view.url}/metrics",
                                        timeout=self.timeout_s) as resp:
                values.update(parse_prometheus_text(
                    resp.read().decode("utf-8", "replace")))
        except (OSError, ValueError):
            pass  # mxtpu-lint: disable=swallowed-exception (optional
            # second endpoint; the statusz scrape above already
            # succeeded and failures there ARE counted)
        view.ring.append(values, now=self.clock())
        with self._lock:
            view.name = sec.get("replica") or view.name
            view.role = sec.get("role") or "both"
            view.state = sec.get("state") or "unknown"
            view.version = sec.get("version")
            view.model = sec.get("model")
            adp = sec.get("adapters")
            view.adapters = (list(adp.get("ids") or [])
                             if isinstance(adp, dict) else None)
            view.consecutive_failures = 0
            view.last_success_t = self.clock()
            view.scrapes += 1
        results[view.url] = True

    @staticmethod
    def _flatten_replica(sec):
        """Flatten one scraped ``replica`` statusz section into ring
        series (same ``name{label=value}`` keying the registry
        flattener uses)."""
        values = {}
        for f in _GAUGE_FIELDS:
            v = sec.get(f)
            if v is not None:
                values[f] = v
        stats = sec.get("stats") or {}
        for f in _STATS_FIELDS:
            v = stats.get(f)
            if v is not None:
                values[f] = v
        for reason, n in (stats.get("reject_reasons") or {}).items():
            values[f"rejected{{reason={reason}}}"] = n
        for tenant, done in (stats.get("tenants") or {}).items():
            values[f"tenant_completed{{tenant={tenant}}}"] = done
        # per-adapter goodput (catalog traffic attribution — rows
        # exist only for requests that carried an adapter id; the
        # replica wire schema pre-flattens the engine's nested
        # ``adapters`` rows into these two series)
        for a, done in (stats.get("adapter_completed") or {}).items():
            values[f"adapter_completed{{adapter={a}}}"] = done
        for a, toks in (stats.get("adapter_tokens") or {}).items():
            values[f"adapter_tokens{{adapter={a}}}"] = toks
        for k, v in (sec.get("handoff") or {}).items():
            if isinstance(v, (int, float)):
                values[f"handoff_{k}"] = v
        # performance-attribution summary (replicas predating it, or
        # running MXTPU_PERF_ATTRIB=0, ship no section — None-skipped
        # like every other absent field)
        for k, v in (sec.get("perf") or {}).items():
            if isinstance(v, (int, float)):
                values[f"perf_{k}"] = v
        # fleet KV fabric: peer-to-peer pull counters plus the size of
        # the radix summary the replica is advertising to the router
        # (replicas predating the fabric — or running with the prefix
        # cache off — ship neither section)
        for k, v in (sec.get("pull") or {}).items():
            if isinstance(v, (int, float)):
                values[f"pull_{k}"] = v
        summary = sec.get("kv_summary")
        if isinstance(summary, dict) \
                and isinstance(summary.get("keys"), (int, float)):
            values["summary_keys"] = summary["keys"]
        return values

    def is_stale(self, view, now=None):
        """A replica is stale once ``stale_after`` intervals passed
        without a successful scrape (or it never answered one).
        Manually-driven collectors (``interval_s=0``, tests/benches)
        measure staleness against a 1-second floor."""
        now = self.clock() if now is None else now
        if view.last_success_t is None:
            return view.last_attempt_t is not None
        return now - view.last_success_t > self.stale_after * \
            max(self.interval_s, 1.0)

    # -- pushed request traces ----------------------------------------------
    def on_trace_line(self, rec):
        """Ingest one terminal request-trace line (the JSONL record
        shape ``telemetry/request_trace.py`` writes).  Returns True
        when the record parsed into a usable summary."""
        try:
            summary = _trace_summary(rec, self.clock())
        except (TypeError, ValueError, KeyError, AttributeError):
            with self._lock:
                self._traces_bad += 1
            self._m_traces.labels(outcome="bad").inc()
            return False
        with self._lock:
            self._traces.append(summary)
            self._traces_received += 1
        self._m_traces.labels(outcome="ok").inc()
        return True

    def trace_records(self, window_s=None, now=None):
        """Trace summaries received within the trailing window (all
        when ``window_s`` is None), oldest first."""
        now = self.clock() if now is None else now
        with self._lock:
            if window_s is None:
                return list(self._traces)
            cutoff = now - window_s
            return [t for t in self._traces if t["t"] >= cutoff]

    # -- fleet timeline annotations ------------------------------------------
    def annotate(self, kind, **fields):
        """Append one annotation to the fleet timeline (supervisor
        lifecycle events, firing SLO alerts).  Also lands in the
        process flight-recorder ring, so post-mortems see it."""
        ev = dict(fields)
        ev["kind"] = str(kind)
        # operators correlate annotations with their logs by wall time;
        # the monotonic stamp drives windowing
        # mxtpu-lint: disable=wall-clock (display timestamp)
        ev["time"] = round(time.time(), 3)
        ev["t"] = self.clock()
        with self._lock:
            self._annotations.append(ev)
        flight_mod.recorder().record(
            "fleet_annotation", annotation=str(kind),
            **{k: v for k, v in ev.items()
               if k not in ("kind", "t", "time")})
        return ev

    def annotations(self, limit=50):
        with self._lock:
            return list(self._annotations)[-int(limit):]

    # -- SLO support ---------------------------------------------------------
    def request_flight_dump(self, url, reason, capture_id=None):
        """Ask one replica to dump its flight-recorder ring (``POST
        /flight_dump`` — the replica's recorder rate-limits per
        reason).  ``capture_id`` names a profiler capture fired
        alongside, so the dump links to its device trace.  Returns
        the remote path or None; never raises."""
        body = {"reason": reason}
        if capture_id:
            body["capture_id"] = capture_id
        try:
            req = urllib.request.Request(
                f"{url.rstrip('/')}/flight_dump",
                data=json.dumps(body).encode(),
                method="POST",
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req,
                                        timeout=self.timeout_s) as resp:
                return json.loads(resp.read()).get("path")
        except (OSError, ValueError):
            return None

    def request_profile_capture(self, url, duration_s=1.0,
                                reason="fleet_capture"):
        """Ask one replica to open a bounded profiler capture window
        (``POST /profilez``).  Returns the response payload (carrying
        the capture ``id``) on 200, None on any refusal (409 conflict,
        429 rate limit) or wire failure; never raises — the SLO layer
        calls this from its evaluation loop."""
        try:
            req = urllib.request.Request(
                f"{url.rstrip('/')}/profilez",
                data=json.dumps({"duration_s": float(duration_s),
                                 "reason": str(reason)[:64]}).encode(),
                method="POST",
                headers={"Content-Type": "application/json"})
            # profiler cold-start can take seconds before the 200 comes
            # back, so don't reuse the (tight) scrape timeout here
            with urllib.request.urlopen(
                    req, timeout=max(self.timeout_s, 15.0)) as resp:
                return json.loads(resp.read())
        except (OSError, ValueError):
            return None

    def capture_fleet(self, duration_s=1.0, roles=None,
                      reason="fleet_capture"):
        """Open wall-clock-aligned capture windows across the fleet:
        one concurrent ``POST /profilez`` per (optionally role-
        filtered) replica, so every accepted window starts within one
        request round-trip of the others and each capture's
        ``started_epoch`` places it on the shared timeline.

        Returns ``{replica_name: payload-or-None}`` — None marks a
        replica that refused (active window, rate limit) or failed;
        accepted payloads carry the capture ``id`` to poll via ``GET
        /profilez/<id>``.  The fleet annotation ring records the sweep
        so /fleetz readers see which captures belong together."""
        with self._lock:
            targets = [(v.name, v.url) for v in self._views.values()
                       if roles is None or v.role in roles]
        results = {}
        threads = []

        def one(name, url):
            results[name] = self.request_profile_capture(
                url, duration_s=duration_s, reason=reason)

        for name, url in targets:
            t = threading.Thread(target=one, args=(name, url),
                                 daemon=True)
            t.start()
            threads.append(t)
        for t in threads:
            t.join(timeout=max(self.timeout_s, 15.0) + float(duration_s))
        self.annotate(
            "fleet_capture", reason=str(reason)[:64],
            duration_s=float(duration_s),
            captures=[{"replica": n,
                       "id": (r or {}).get("id"),
                       "accepted": r is not None}
                      for n, r in sorted(results.items())])
        return results

    def url_for_replica(self, name):
        """Replica name -> base URL (trace lines carry names; flight
        dumps need URLs)."""
        with self._lock:
            for v in self._views.values():
                if v.name == name:
                    return v.url
        return None

    # -- aggregation ---------------------------------------------------------
    def _replica_row(self, view, now):
        ring = view.ring
        row = {"url": view.url, "replica": view.name, "role": view.role,
               "state": view.state,
               "version": view.version,
               "model": view.model,
               "adapters": view.adapters,
               "stale": self.is_stale(view, now),
               "consecutive_failures": view.consecutive_failures,
               "total_failures": view.total_failures,
               "scrapes": view.scrapes,
               "age_s": (round(now - view.last_success_t, 3)
                         if view.last_success_t is not None else None),
               "samples": len(ring)}
        if row["stale"]:
            # a stale replica's last-scraped load signals are dead
            # data: past the age cap the row keeps identity/failure
            # fields only, so neither the role aggregates nor a policy
            # reader (the autoscaler) can scale on a corpse's queue
            return row
        latest = {f: ring.latest(f) for f in _GAUGE_FIELDS}
        totals = {f: ring.latest(f)
                  for f in ("tokens_generated", "completed", "rejected")}
        row.update({k: v for k, v in latest.items() if v is not None})
        row.update({k: int(v) for k, v in totals.items()
                    if v is not None})
        rate = ring.rate("tokens_generated", self.rate_window_s,
                         now=now)
        if rate is not None:
            row["tok_per_sec"] = round(rate, 3)
        for f in ("ttft_ms_p99", "tpot_ms_p99", "perf_mfu",
                  "perf_achieved_tflops", "perf_tok_flops",
                  "perf_cost_per_1k_tokens_s", "perf_sampled",
                  "prefix_hits", "prefix_resurrections",
                  "prefix_tokens_saved", "summary_keys",
                  "pull_attempts", "pull_blocks_imported"):
            v = ring.latest(f)
            if v is not None:
                row[f] = v
        return row

    def fleet_view(self):
        """The ``/fleetz.json`` payload: per-replica rows, per-role and
        whole-fleet aggregates (fresh replicas only — stale ones are
        listed and counted but never summed), SLO state, the recent
        annotation tail and the pushed-trace window summary."""
        now = self.clock()
        # ONE membership snapshot for the whole assembly: the scrape
        # thread may add/remove replicas concurrently, and a row built
        # from one snapshot must never be looked up in another
        views = self.views()
        by_url = {v.url: v for v in views}
        rows = [self._replica_row(v, now) for v in views]
        roles = {}
        # model-catalog aggregates: per-model traffic/goodput across
        # fresh replicas carrying that checkpoint tag, plus adapter
        # placement counts (how many replicas host each adapter id)
        models = {}
        for row in rows:
            tag = row.get("model")
            if tag is not None:
                m = models.setdefault(tag, {
                    "replicas": 0, "stale": 0, "completed": 0,
                    "tokens_generated": 0, "tok_per_sec": 0.0,
                    "adapters": {}, "adapter_goodput": {},
                    "adapter_tokens": {}})
                m["replicas"] += 1
                if row["stale"]:
                    m["stale"] += 1
                else:
                    m["completed"] += int(row.get("completed") or 0)
                    m["tokens_generated"] += \
                        int(row.get("tokens_generated") or 0)
                    m["tok_per_sec"] = round(
                        m["tok_per_sec"]
                        + (row.get("tok_per_sec") or 0.0), 3)
                    for a in row.get("adapters") or []:
                        m["adapters"][a] = m["adapters"].get(a, 0) + 1
                    mview = by_url[row["url"]]
                    for key in mview.ring.names():
                        for series, out in (
                                ("adapter_completed", "adapter_goodput"),
                                ("adapter_tokens", "adapter_tokens")):
                            pre = f"{series}{{adapter="
                            if key.startswith(pre):
                                a = key[len(pre):-1]
                                m[out][a] = m[out].get(a, 0) \
                                    + int(mview.ring.latest(key) or 0)
            agg = roles.setdefault(row["role"], {
                "replicas": 0, "stale": 0, "queue_depth": 0,
                "running": 0, "waiting_handoffs": 0,
                "tokens_generated": 0, "completed": 0, "rejected": 0,
                "tok_per_sec": 0.0, "achieved_tflops": 0.0,
                "_kv": [], "_hkv": [],
                "_ttft": [], "_tpot": [], "_mfu": [],
                "tenant_goodput": {}, "versions": {}})
            agg["replicas"] += 1
            if row["stale"]:
                agg["stale"] += 1
                continue
            if row.get("version"):
                # fresh replicas by deploy tag: >1 key mid-rollout
                agg["versions"][row["version"]] = \
                    agg["versions"].get(row["version"], 0) + 1
            for f in ("queue_depth", "running", "waiting_handoffs",
                      "tokens_generated", "completed", "rejected"):
                agg[f] += int(row.get(f) or 0)
            agg["tok_per_sec"] = round(
                agg["tok_per_sec"] + (row.get("tok_per_sec") or 0.0), 3)
            if row.get("kv_utilization") is not None:
                agg["_kv"].append(row["kv_utilization"])
            if row.get("host_kv_utilization") is not None:
                agg["_hkv"].append(row["host_kv_utilization"])
            if row.get("ttft_ms_p99") is not None:
                agg["_ttft"].append(row["ttft_ms_p99"])
            if row.get("tpot_ms_p99") is not None:
                agg["_tpot"].append(row["tpot_ms_p99"])
            # role-keyed goodput: MFU averages over the role's fresh
            # replicas, achieved TFLOP/s sums to the role's delivered
            # compute rate (both absent until a replica has sampled)
            if row.get("perf_mfu") is not None:
                agg["_mfu"].append(row["perf_mfu"])
            agg["achieved_tflops"] = round(
                agg["achieved_tflops"]
                + (row.get("perf_achieved_tflops") or 0.0), 6)
            view = by_url[row["url"]]
            for key in view.ring.names():
                if key.startswith("tenant_completed{tenant="):
                    tenant = key[len("tenant_completed{tenant="):-1]
                    agg["tenant_goodput"][tenant] = \
                        agg["tenant_goodput"].get(tenant, 0) \
                        + int(view.ring.latest(key) or 0)
        for agg in roles.values():
            agg["kv_utilization_mean"] = _mean(agg.pop("_kv"))
            agg["host_kv_utilization_mean"] = _mean(agg.pop("_hkv"))
            ttfts, tpots = agg.pop("_ttft"), agg.pop("_tpot")
            agg["ttft_ms_p99_max"] = max(ttfts) if ttfts else None
            agg["tpot_ms_p99_max"] = max(tpots) if tpots else None
            agg["mfu_mean"] = _mean(agg.pop("_mfu"))
        totals = {"replicas": len(rows),
                  "stale": sum(1 for r in rows if r["stale"])}
        for f in ("queue_depth", "running", "waiting_handoffs",
                  "tokens_generated", "completed", "rejected"):
            totals[f] = sum(a[f] for a in roles.values())
        totals["tok_per_sec"] = round(
            sum(a["tok_per_sec"] for a in roles.values()), 3)
        with self._lock:
            passes = self._scrape_passes
            received, bad = self._traces_received, self._traces_bad
        window = self._trace_window_summary(now)
        return {
            # mxtpu-lint: disable=wall-clock (display timestamp)
            "time": round(time.time(), 3),
            "interval_s": self.interval_s,
            "scrape_passes": passes,
            "rate_window_s": self.rate_window_s,
            "replicas": rows,
            "roles": roles,
            "models": models,
            "totals": totals,
            "slo": None if self.slo is None else self.slo.statusz(),
            "annotations": self.annotations(),
            "traces": dict(received=received, bad=bad, **window),
        }

    def _trace_window_summary(self, now):
        """Trailing-window request summary — counted per CLIENT
        request (lines grouped by trace id, the SLO layer's unit),
        never per line: one request observed by its engine AND the
        router is one request."""
        recs = self.trace_records(self.rate_window_s, now=now)
        verdicts = [request_failed(g) for g in group_requests(recs)]
        finished = sum(1 for v in verdicts if v is False)
        failed = sum(1 for v in verdicts if v is True)
        ttfts = sorted(r["ttft_s"] for r in recs
                       if r["status"] == "finished"
                       and r.get("ttft_s") is not None)
        tpots = sorted(r["tpot_s"] for r in recs
                       if r["status"] == "finished"
                       and r.get("tpot_s") is not None)
        return {
            "window_requests": len(verdicts),
            "window_finished": finished,
            "window_rejected": failed,
            "window_availability": (
                round(finished / (finished + failed), 4)
                if finished + failed else None),
            "window_ttft_p99_ms": _p99_ms(ttfts),
            "window_tpot_p99_ms": _p99_ms(tpots),
        }

    def _update_agg_gauges(self):
        """Mirror the per-role aggregates into the collector process's
        metrics registry — the third face of the three-view agreement
        (fleet view == sum of replica ground truth == registry
        series).  No-ops unless MXTPU_TELEMETRY is on."""
        view = self.fleet_view()
        for role, agg in view["roles"].items():
            for field, value in (
                    ("queue_depth", agg["queue_depth"]),
                    ("running", agg["running"]),
                    ("waiting_handoffs", agg["waiting_handoffs"]),
                    ("tokens_generated", agg["tokens_generated"]),
                    ("completed", agg["completed"]),
                    ("rejected", agg["rejected"]),
                    ("tok_per_sec", agg["tok_per_sec"]),
                    ("replicas", agg["replicas"]),
                    ("stale", agg["stale"]),
                    ("achieved_tflops", agg["achieved_tflops"]),
                    ("mfu_mean", agg["mfu_mean"])):
                if value is None:     # no replica has sampled yet
                    continue
                telemetry.gauge(
                    f"mxtpu_fleet_agg_{field}",
                    f"fleet-aggregated {field} by role",
                    ("role",)).labels(role=role).set(value)

    def statusz(self):
        """Compact collector self-description (registered nowhere by
        default; embedders may hook it onto their /statusz)."""
        with self._lock:
            return {"replicas": len(self._views),
                    "scrape_passes": self._scrape_passes,
                    "traces_received": self._traces_received,
                    "interval_s": self.interval_s,
                    "port": self.port,
                    "slo": None if self.slo is None
                    else [o.key for o in self.slo.objectives]}


def _mean(vals):
    return round(sum(vals) / len(vals), 4) if vals else None


def _p99_ms(sorted_vals):
    v = nearest_rank(sorted_vals, 0.99)
    return None if v is None else round(v * 1e3, 3)


def _trace_summary(rec, now):
    """Fold one pushed trace line into the collector's summary shape:
    terminal status, reason, replica identity, TTFT and mean TPOT.

    TTFT is ``submitted -> first prefill_end`` (the engine emits the
    request's first token at prefill end); TPOT is the decode span
    divided by the tokens it emitted."""
    events = rec.get("events") or []
    status = str(rec.get("status"))
    reason = None
    t0 = events[0]["t"] if events else None
    first_tok_t = None
    last_decode_t = None
    replica = rec.get("replica")
    for ev in events:
        name = ev.get("ev")
        if name == "prefill_end" and first_tok_t is None:
            first_tok_t = ev["t"]
        elif name == "decode":
            last_decode_t = ev["t"]
        elif name == "rejected":
            reason = ev.get("reason")
        if name in ("finished", "rejected", "cancelled") \
                and ev.get("replica"):
            # a router-side line attributes its terminal to the replica
            # that actually served the request — SLO offenders must be
            # the serving replica, never the literal string "router"
            replica = ev["replica"]
    ttft = (first_tok_t - t0
            if first_tok_t is not None and t0 is not None else None)
    generated = int(rec.get("generated") or 0)
    tpot = None
    if (first_tok_t is not None and last_decode_t is not None
            and generated > 1):
        tpot = max(0.0, (last_decode_t - first_tok_t) / (generated - 1))
    total = (events[-1]["t"] - t0 if len(events) > 1 else None)
    return {"t": now, "trace_id": rec.get("trace_id"),
            "rid": rec.get("rid"), "replica": replica,
            # which tracer wrote the line: "serve" (an engine — its
            # own schema omits the field) vs "router" (the client-
            # truth line the SLO availability verdict prefers)
            "source": rec.get("source") or "serve",
            "tenant": rec.get("tenant"), "status": status,
            "reason": reason, "generated": generated,
            "ttft_s": ttft, "tpot_s": tpot, "total_s": total}


# -- the /fleetz + /trace HTTP front ----------------------------------------
def _serve(collector):
    """Start the collector's stdlib HTTP server (daemon thread)."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class Handler(BaseHTTPRequestHandler):
        def _send(self, code, body, ctype="application/json"):
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            if self.path in ("/fleetz.json", "/fleetz"):
                view = collector.fleet_view()
                if self.path.endswith(".json"):
                    self._send(200, json.dumps(view,
                                               default=str).encode())
                else:
                    self._send(200, render_fleetz_html(view).encode(),
                               "text/html; charset=utf-8")
            elif self.path == "/healthz":
                self._send(200, json.dumps(
                    {"status": "ok",
                     "replicas": len(collector.views())}).encode())
            else:
                self.send_error(404)

        def do_POST(self):
            if self.path not in ("/trace", "/annotate"):
                self.send_error(404)
                return
            try:
                n = int(self.headers.get("Content-Length", 0))
                raw = self.rfile.read(n)
            except (ValueError, OSError):
                self._send(400, b'{"error": "bad_body"}')
                return
            if self.path == "/annotate":
                try:
                    rec = json.loads(raw or b"{}")
                    kind = str(rec.pop("kind", "external"))
                except (ValueError, AttributeError):
                    self._send(400, b'{"error": "bad_json"}')
                    return
                collector.annotate(kind, **{str(k): v
                                            for k, v in rec.items()})
                self._send(200, b'{"ok": true}')
                return
            ok = bad = 0
            # /trace accepts one JSON object per line (NDJSON) — one
            # malformed line counts bad without dropping its batch
            for line in (raw or b"").splitlines():
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    bad += 1
                    continue
                if collector.on_trace_line(rec):
                    ok += 1
                else:
                    bad += 1
            self._send(200, json.dumps({"ok": ok, "bad": bad}).encode())

        def log_message(self, *args):       # no stderr chatter
            pass

    server = ThreadingHTTPServer(("127.0.0.1",
                                  collector._requested_port), Handler)
    thread = threading.Thread(target=server.serve_forever, daemon=True,
                              name="mxtpu-fleet-collector-http")
    thread.start()
    return server


def render_fleetz_html(view):
    """Dependency-free HTML rendering of :meth:`fleet_view` — one
    section per region, JSON pretty-printed (the statusz style)."""
    import html as _html

    parts = ["<!doctype html><html><head><title>mxtpu /fleetz</title>",
             "<style>body{font-family:monospace;margin:1em}",
             "h2{border-bottom:1px solid #999;margin:1em 0 .2em}",
             "pre{margin:.2em 0 .8em;white-space:pre-wrap}</style>",
             "</head><body><h1>mxtpu /fleetz</h1>"]
    for name in ("totals", "roles", "slo", "replicas", "traces",
                 "annotations"):
        parts.append(f"<h2>{_html.escape(name)}</h2>")
        parts.append("<pre>"
                     + _html.escape(json.dumps(view.get(name), indent=2,
                                               default=str))
                     + "</pre>")
    parts.append("</body></html>")
    return "".join(parts)
