"""Replica supervisor: spawn N replicas, restart crashes, roll restarts.

The supervisor owns the fleet's process story the way dmlc-core's
tracker owned the reference's cluster jobs: it spawns N replica slots,
probes their ``/healthz``, restarts a crashed slot with capped
exponential backoff, and performs the drain -> checkpointless warm
restart sequence that makes a rolling restart of the whole fleet
invisible to clients:

  1. POST /drain — the replica stops admitting (router retries those
     rejections on siblings) and finishes its in-flight work
     token-identically;
  2. wait until ``/healthz`` reports the drain complete (no queued, no
     running, no in-flight handler work);
  3. terminate the process and spawn the replacement — which starts
     WARM: the AOT export store + warmup manifest
     (``MXTPU_AOT_DIR`` / ``MXTPU_WARMUP_MANIFEST``, PR 4) rebuild
     every bucket program without a fresh trace, so the slot is back
     in rotation at ~0.26x the cold-start cost;
  4. next slot.

The supervisor is deliberately transport-agnostic: a *handle* is
anything with ``poll() -> None | returncode``, ``terminate()`` and a
``url``.  :class:`ProcessReplica` is the real one
(``tools/serve_replica.py`` subprocesses); tests drive the same
supervisor with in-process handles, so the restart/drain logic is
tier-1-testable without process spawn latency.
"""

from __future__ import annotations

import json
import subprocess
import sys
import threading
import time
import urllib.request

from .. import telemetry
from ..base import env_float, env_int

__all__ = ["Supervisor", "ProcessReplica", "probe_health"]


def probe_health(url, timeout=2.0):
    """GET ``<url>/healthz`` -> dict, or None when unreachable (the
    liveness probe — rides the cheap endpoint, never /statusz)."""
    try:
        with urllib.request.urlopen(f"{url.rstrip('/')}/healthz",
                                    timeout=timeout) as resp:
            return json.loads(resp.read())
    except (OSError, ValueError):
        return None


class ProcessReplica:
    """One replica subprocess (``tools/serve_replica.py``).

    The child prints a single ``{"ready": true, "port": N, ...}`` JSON
    line once serving; :meth:`wait_ready` blocks on it.  Stdout is
    drained by a daemon thread so the child can never block on a full
    pipe; the last lines are kept for post-mortems.
    """

    def __init__(self, args, env=None):
        self.args = list(args)
        self.proc = subprocess.Popen(
            self.args, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True, env=env)
        self.url = None
        self.port = None
        self._lock = threading.Lock()
        self._lines = []           # guarded-by: _lock
        self._ready = threading.Event()
        self._reader = threading.Thread(target=self._drain, daemon=True)
        self._reader.start()

    def _drain(self):
        for line in self.proc.stdout:
            line = line.rstrip("\n")
            with self._lock:
                self._lines.append(line)
                del self._lines[:-50]
            if not self._ready.is_set() and line.startswith("{"):
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if rec.get("ready"):
                    self.port = int(rec["port"])
                    host = rec.get("host", "127.0.0.1")
                    self.url = f"http://{host}:{self.port}"
                    self._ready.set()

    def wait_ready(self, timeout_s=120.0):
        """Block until the child printed its ready line (-> url) or
        died; returns the url or raises RuntimeError with the tail of
        its output."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if self._ready.wait(0.1):
                return self.url
            if self.proc.poll() is not None:
                break
        with self._lock:
            tail = "\n".join(self._lines[-15:])
        raise RuntimeError(
            f"replica process not ready (rc={self.proc.poll()}):\n{tail}")

    def poll(self):
        return self.proc.poll()

    def terminate(self, grace_s=10.0):
        if self.proc.poll() is None:
            self.proc.terminate()
            try:
                self.proc.wait(timeout=grace_s)
            except subprocess.TimeoutExpired:
                self.proc.kill()
                self.proc.wait(timeout=grace_s)

    def output_tail(self):
        with self._lock:
            return "\n".join(self._lines[-50:])


def replica_command(port=0, extra_args=(), python=None, repo=None):
    """argv for one ``tools/serve_replica.py`` child (the default
    :class:`Supervisor` spawn target)."""
    import os

    repo = repo or os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    return ([python or sys.executable,
             os.path.join(repo, "tools", "serve_replica.py"),
             "--port", str(port)] + list(extra_args))


class Supervisor:
    """Spawn/monitor/restart N replica slots.

    Args (env default in parens):
      spawn: ``spawn(slot) -> handle`` (poll/terminate/url — see
        module docstring).  For processes, wrap :class:`ProcessReplica`
        and call ``wait_ready`` inside your spawn.
      n: number of slots.
      restart_backoff_s / restart_backoff_max_s: capped exponential
        backoff between a slot's crash-restarts
        (``MXTPU_FLEET_RESTART_BACKOFF`` 0.5 /
        ``MXTPU_FLEET_RESTART_BACKOFF_MAX`` 30).
      drain_timeout_s: max wait for a drain to complete before the
        slot is restarted anyway (``MXTPU_FLEET_DRAIN_TIMEOUT``, 120).
      router: optional ``fleet.Router`` whose membership follows
        respawns (old url out, new url in).
      collector: optional ``fleet.FleetCollector`` — lifecycle events
        (crash-restart, drain, respawn, rolling-restart phases) are
        pushed as annotations onto its fleet timeline, so ``/fleetz``
        explains a load dip ("slot 2 was rolling") without log
        archaeology.
      clock/sleep: injectable (tests).
    """

    def __init__(self, spawn, n, restart_backoff_s=None,
                 restart_backoff_max_s=None, drain_timeout_s=None,
                 router=None, collector=None, catalog=None,
                 clock=time.monotonic, sleep=time.sleep):
        self.spawn = spawn
        self.n = int(n)
        # optional CatalogRebalancer: the adapter-placement actuator
        # behind rebalance_catalog() (wired once at construction,
        # read-only afterwards — no lock needed)
        self.catalog = catalog
        self.restart_backoff_s = (
            float(restart_backoff_s) if restart_backoff_s is not None
            else env_float("MXTPU_FLEET_RESTART_BACKOFF", 0.5))
        self.restart_backoff_max_s = (
            float(restart_backoff_max_s)
            if restart_backoff_max_s is not None
            else env_float("MXTPU_FLEET_RESTART_BACKOFF_MAX", 30.0))
        self.drain_timeout_s = (
            float(drain_timeout_s) if drain_timeout_s is not None
            else env_float("MXTPU_FLEET_DRAIN_TIMEOUT", 120.0))
        self.router = router
        self.collector = collector
        self.clock = clock
        self.sleep = sleep
        self._lock = threading.RLock()
        self._handles = [None] * self.n      # guarded-by: _lock
        self._restarts = [0] * self.n        # guarded-by: _lock
        self._next_restart_t = [0.0] * self.n  # guarded-by: _lock
        # slots mid-drain_and_restart: the crash monitor must not also
        # respawn them (it would see the intentionally-terminated
        # handle as a crash and double-spawn an orphan replica)
        self._rolling = set()                # guarded-by: _lock
        # slots drained out by scale-down.  Indices are NEVER reused —
        # a slot keeps its identity in metrics/annotations forever, so
        # "slot 3 restarted twice" stays meaningful across pool resizes
        self._retired = set()                # guarded-by: _lock
        self._monitor = None
        self._stop_evt = threading.Event()
        self._m_restarts = telemetry.counter(
            "mxtpu_fleet_restarts_total",
            "replica restarts by slot and reason (crash / rolling)",
            ("slot", "reason"))

    def _annotate(self, kind, **fields):
        """Push one lifecycle event onto the fleet timeline (no-op
        without a collector; a broken collector must never take the
        supervisor down with it)."""
        if self.collector is None:
            return
        try:
            self.collector.annotate(kind, **fields)
        except Exception:
            telemetry.counter(
                "mxtpu_fleet_supervisor_errors_total",
                "supervisor monitor failures").inc()

    # -- membership ----------------------------------------------------------
    def handles(self):
        with self._lock:
            return list(self._handles)

    def urls(self):
        return [h.url for h in self.handles() if h is not None]

    def active_slots(self):
        """Slot indices currently backing the pool (retired scale-down
        slots excluded)."""
        with self._lock:
            return [s for s in range(self.n) if s not in self._retired]

    def pool_size(self):
        return len(self.active_slots())

    def start(self):
        """Spawn every slot (serially — replica startup may compile)."""
        for slot in range(self.n):
            self._spawn_slot(slot)
        return self

    def _spawn_slot(self, slot, factory=None):
        handle = (factory or self.spawn)(slot)
        with self._lock:
            old = self._handles[slot]
            self._handles[slot] = handle
        if self.router is not None:
            if old is not None and old.url:
                self.router.remove_replica(old.url)
            if handle.url:
                self.router.add_replica(handle.url)
        return handle

    # -- crash monitoring ----------------------------------------------------
    def check(self):
        """One monitor pass: restart every crashed slot whose backoff
        window has elapsed.  Returns the slots restarted."""
        restarted = []
        now = self.clock()
        for slot in range(self.n):
            with self._lock:
                h = self._handles[slot]
                due = self._next_restart_t[slot] <= now
                rolling = slot in self._rolling
            if rolling or h is None or h.poll() is None:
                continue
            if not due:
                continue            # crashed, but inside backoff
            with self._lock:
                # claim the slot for the duration of the (slow) spawn:
                # a drain_and_restart that starts meanwhile must wait
                # rather than double-spawn an orphan replica
                if slot in self._rolling:
                    continue
                self._rolling.add(slot)
                self._restarts[slot] += 1
                n_restarts = self._restarts[slot]
                backoff = min(self.restart_backoff_max_s,
                              self.restart_backoff_s
                              * 2 ** (n_restarts - 1))
                self._next_restart_t[slot] = now + backoff
            self._m_restarts.labels(slot=str(slot), reason="crash").inc()
            self._annotate("replica_crash_restart", slot=slot,
                           url=getattr(h, "url", None),
                           restarts=n_restarts,
                           backoff_s=round(backoff, 3))
            try:
                handle = self._spawn_slot(slot)
                self._annotate("replica_respawn", slot=slot,
                               url=getattr(handle, "url", None),
                               reason="crash")
            finally:
                with self._lock:
                    self._rolling.discard(slot)
            restarted.append(slot)
        return restarted

    def note_healthy(self, slot):
        """Reset a slot's crash-backoff (call once its replacement
        serves traffic again)."""
        with self._lock:
            self._restarts[slot] = 0
            self._next_restart_t[slot] = 0.0

    def run(self, interval_s=1.0):
        """Background monitor thread pumping :meth:`check`."""
        if self._monitor is not None:
            return self
        self._stop_evt.clear()

        def loop():
            while not self._stop_evt.wait(interval_s):
                try:
                    self.check()
                except Exception:
                    telemetry.counter(
                        "mxtpu_fleet_supervisor_errors_total",
                        "supervisor monitor failures").inc()

        self._monitor = threading.Thread(
            target=loop, daemon=True, name="mxtpu-fleet-supervisor")
        self._monitor.start()
        return self

    def stop(self, terminate=True):
        self._stop_evt.set()
        if self._monitor is not None:
            self._monitor.join(timeout=5)
            self._monitor = None
        if terminate:
            for h in self.handles():
                if h is not None:
                    h.terminate()

    # -- drain / rolling restart ---------------------------------------------
    def drain(self, slot):
        """POST /drain to one slot; returns True when accepted."""
        h = self.handles()[slot]
        if h is None or not h.url:
            return False
        try:
            req = urllib.request.Request(f"{h.url}/drain", data=b"",
                                         method="POST")
            with urllib.request.urlopen(req, timeout=5.0):
                return True
        except (OSError, ValueError):
            return False

    def wait_drained(self, slot, timeout_s=None):
        """Poll the slot's /healthz until its drain completed (state
        draining, nothing queued/running/in flight).  True on success,
        False on timeout or replica death (either way the caller may
        terminate — a dead replica has nothing left to finish)."""
        timeout_s = (self.drain_timeout_s if timeout_s is None
                     else timeout_s)
        h = self.handles()[slot]
        if h is None:
            return False            # empty/retired slot: nothing to wait on
        deadline = self.clock() + timeout_s
        while self.clock() < deadline:
            if h.poll() is not None:
                return False        # died mid-drain
            hz = probe_health(h.url)
            if hz is not None and hz.get("state") == "draining" \
                    and not hz.get("in_flight") \
                    and not hz.get("queue_depth") \
                    and not hz.get("running") \
                    and not hz.get("waiting_handoffs"):
                # waiting_handoffs: a decode replica mid-KV-ingest has
                # work the queue/running counts don't show yet — a
                # drain is not complete until those land or resolve
                # (absent on legacy replicas: falsy, same decision)
                return True
            self.sleep(0.05)
        return False

    def _claim(self, slot):
        """Claim a slot EXCLUSIVELY: if the crash monitor is mid-spawn
        on it (it holds the claim across its slow spawn), wait for it
        to finish rather than replacing a handle it is about to set
        (which would orphan the monitor's live replacement process)."""
        while True:
            with self._lock:
                if slot not in self._rolling:
                    self._rolling.add(slot)
                    return
            self.sleep(0.05)

    def replace_slot(self, slot, factory=None, reason="rolling"):
        """The zero-downtime slot replacement: drain -> wait ->
        terminate -> spawn-with-``factory`` (default: this
        supervisor's own ``spawn``, i.e. a plain restart — warm via
        the AOT/warmup env the spawn command carries) under the
        ``_rolling`` exclusive claim, so the deployer never races the
        crash monitor.  Returns the replacement handle, or None for a
        retired slot."""
        with self._lock:
            if slot in self._retired:
                return None
        t0 = self.clock()
        kind = ("rolling_restart_slot" if reason == "rolling"
                else "deploy_replace_slot")
        self._claim(slot)
        try:
            self._annotate(kind, slot=slot, phase="drain")
            self.drain(slot)
            self.wait_drained(slot)
            h = self.handles()[slot]
            if h is not None:
                self._annotate(kind, slot=slot, phase="terminate",
                               url=getattr(h, "url", None))
                h.terminate()
            handle = self._spawn_slot(slot, factory)
            self._m_restarts.labels(slot=str(slot),
                                    reason=reason).inc()
            self._annotate(kind, slot=slot, phase="respawned",
                           url=getattr(handle, "url", None),
                           wall_s=round(self.clock() - t0, 3))
        finally:
            with self._lock:
                self._rolling.discard(slot)
        self.note_healthy(slot)
        telemetry.histogram(
            "mxtpu_fleet_slot_restart_seconds",
            "drain-to-ready wall time of rolling-restart slots"
        ).observe(self.clock() - t0)
        return handle

    def drain_and_restart(self, slot):
        """The zero-downtime slot restart (same-factory
        :meth:`replace_slot`).  Returns the replacement handle."""
        return self.replace_slot(slot)

    def rolling_restart(self):
        """Drain-and-restart every slot, one at a time — the fleet
        never loses more than one replica of capacity, and the router
        retries each drain's rejections on the live siblings."""
        slots = self.active_slots()
        self._annotate("rolling_restart", phase="start",
                       slots=len(slots))
        for slot in slots:
            self.drain_and_restart(slot)
        self._annotate("rolling_restart", phase="done",
                       slots=len(slots))
        return self.urls()

    # -- pool resizing (the autoscaler's actuations) -------------------------
    def add_slot(self, factory=None):
        """Grow the pool by one slot: append a fresh slot index and
        spawn it (claimed in ``_rolling`` for the duration so the
        crash monitor never touches a half-born slot).  Returns the
        new slot index."""
        with self._lock:
            slot = self.n
            self.n += 1
            self._handles.append(None)
            self._restarts.append(0)
            self._next_restart_t.append(0.0)
            self._rolling.add(slot)
        try:
            handle = self._spawn_slot(slot, factory)
        except Exception:
            with self._lock:
                # a slot whose first spawn failed never joined the
                # pool; retire it so monitors/rolls skip the stub
                self._retired.add(slot)
            raise
        finally:
            with self._lock:
                self._rolling.discard(slot)
        self._annotate("scale_up_slot", slot=slot,
                       url=getattr(handle, "url", None))
        return slot

    def remove_slot(self, slot):
        """Shrink the pool by one slot: drain -> wait -> terminate,
        then RETIRE the index (router membership follows).  Returns
        True when the slot was removed, False when already retired."""
        with self._lock:
            if slot in self._retired:
                return False
        self._claim(slot)
        try:
            with self._lock:
                if slot in self._retired:
                    return False
            self._annotate("scale_down_slot", slot=slot, phase="drain")
            self.drain(slot)
            self.wait_drained(slot)
            with self._lock:
                h = self._handles[slot]
                self._handles[slot] = None
                self._retired.add(slot)
            if h is not None:
                if self.router is not None and h.url:
                    self.router.remove_replica(h.url)
                h.terminate()
            self._annotate("scale_down_slot", slot=slot,
                           phase="terminated",
                           url=getattr(h, "url", None))
        finally:
            with self._lock:
                self._rolling.discard(slot)
        return True

    def rebalance_catalog(self, reason="manual"):
        """Catalog-rebalance actuator: one plan+apply pass of the
        attached ``CatalogRebalancer`` (adapter placement follows
        traffic — see fleet/catalog.py).  Invoked manually or by the
        autoscaler after a scale-up so a fresh replica picks up the
        hot adapters.  No-op (empty list) without an attached
        rebalancer; a failing pass is annotated, never raised — the
        catalog converging late must not take the pool down."""
        if self.catalog is None:
            return []
        try:
            results = self.catalog.rebalance()
        except Exception:
            telemetry.counter(
                "mxtpu_fleet_supervisor_errors_total",
                "supervisor monitor failures").inc()
            self._annotate("catalog_rebalance_failed", reason=reason)
            return []
        if results:
            self._annotate("catalog_rebalance", reason=reason,
                           applied=len(results),
                           ok=sum(1 for r in results if r["ok"]))
        return results
