"""Runtime user kernels.

Rebuild of the reference MXRtc (src/common/mxrtc.cc, python/mxnet/rtc.py):
there the user hands CUDA source to NVRTC at runtime; here the user hands
a **Pallas kernel** (or any JAX-traceable function), which is compiled
for TPU by Mosaic and pushed like any other op.  Same capability —
user-supplied custom kernels without rebuilding the framework.
"""

from __future__ import annotations

import jax

from .ndarray import NDArray

__all__ = ["Rtc", "PallasKernel"]


class PallasKernel:
    """Wrap a pallas_call-building function into an NDArray-callable op.

    Example::

        import jax.numpy as jnp
        from jax.experimental import pallas as pl

        def scale_kernel(x_ref, o_ref):
            o_ref[:] = x_ref[:] * 2.0

        def build(x):
            return pl.pallas_call(
                scale_kernel,
                out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype))(x)

        k = PallasKernel(build)
        y = k(x_nd)
    """

    def __init__(self, build_fn, name="pallas_kernel"):
        self.name = name
        self._fn = jax.jit(build_fn)

    def __call__(self, *inputs):
        ctx = inputs[0].context
        raw = self._fn(*[x._data for x in inputs])
        if isinstance(raw, (tuple, list)):
            return [NDArray(r, ctx) for r in raw]
        return NDArray(raw, ctx)


class Rtc:
    """API-compatible shim for mx.rtc.Rtc(name, inputs, outputs, kernel).

    The reference takes CUDA C source; on TPU pass a python function
    ``kernel(inputs) -> outputs`` built from jnp/pallas instead.  Passing
    CUDA source raises with a pointer to PallasKernel.
    """

    def __init__(self, name, inputs, outputs, kernel):
        if isinstance(kernel, str):
            raise TypeError(
                "CUDA source kernels are not supported on TPU; pass a "
                "JAX/Pallas callable (see mxnet_tpu.rtc.PallasKernel)")
        self.name = name
        self._kernel = PallasKernel(kernel, name)

    def push(self, inputs, outputs, grid_dims=None, block_dims=None):
        results = self._kernel(*inputs)
        if not isinstance(results, list):
            results = [results]
        for dst, src in zip(outputs, results):
            dst[:] = src
