"""Optimizers (rebuild of python/mxnet/optimizer.py + src/optimizer/sgd-inl.h).

The registry/update-count/lr-wd-multiplier structure mirrors the
reference; every ``update`` body is a jitted JAX function operating
directly on device buffers with donated weight/state inputs, which is the
TPU equivalent of the reference's engine-scheduled C++ ``ccSGD`` fused
update (src/optimizer/sgd-inl.h) — no host round-trips in the hot loop.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .base import MXNetError
from .ndarray import NDArray, zeros
from .registry import Registry

__all__ = ["Optimizer", "SGD", "NAG", "SGLD", "ccSGD", "Adam", "AdamW", "AdaGrad",
           "RMSProp", "AdaDelta", "Test", "create", "get_updater", "register"]

OPT_REGISTRY = Registry("optimizer")
register = OPT_REGISTRY.register


def _donate(*argnums):
    """Donate buffers only where XLA supports it (TPU); CPU backend would
    warn and ignore."""
    return argnums if jax.default_backend() == "tpu" else ()


def _dispatch_inc(owner, kind):
    """Count one compiled-program dispatch on
    ``mxtpu_train_dispatches_total{kind=...}`` — the counter the fused
    train step's O(1)-vs-O(num_params) claim is asserted against
    (tests/test_fused_step.py).  The labeled child is cached on
    ``owner`` per kind and re-resolved when telemetry enablement flips,
    so the hot path pays dict lookups, not a registry lock per
    dispatch; like every instance-cached handle, it detaches from
    snapshots across a ``telemetry.reset()`` (metrics.Registry.clear
    contract — count by snapshot delta, as tools/train_bench.py does)."""
    from . import telemetry

    cache = getattr(owner, "_tel_dispatch", None)
    if cache is None:
        cache = owner._tel_dispatch = {}
    enabled = telemetry.enabled()
    cached = cache.get(kind)
    if cached is None or cached[0] is not enabled:
        child = telemetry.counter(
            "mxtpu_train_dispatches_total",
            "compiled-program dispatches issued by the training stack",
            ("kind",)).labels(kind=kind)
        cached = cache[kind] = (enabled, child)
    cached[1].inc()


def _state_leaves(state):
    """Raw jax arrays of an optimizer state (None / NDArray / tuple of
    NDArrays) — the representation ``step_param`` operates on."""
    if state is None:
        return None
    if isinstance(state, (tuple, list)):
        return tuple(s._data for s in state)
    return state._data


def _state_commit(state, new_leaves):
    """Write ``step_param`` result leaves back into the NDArray state."""
    if state is None:
        return
    if isinstance(state, (tuple, list)):
        for s, v in zip(state, new_leaves):
            s._set(v)
    else:
        state._set(new_leaves)


class Optimizer:
    """Base optimizer (reference optimizer.py:20-233)."""

    def __init__(self, rescale_grad=1.0, param_idx2name=None, wd=0.0,
                 clip_gradient=None, learning_rate=0.01, lr_scheduler=None,
                 sym=None, begin_num_update=0):
        self.rescale_grad = rescale_grad
        self.lr = learning_rate
        self.lr_scheduler = lr_scheduler
        if lr_scheduler is not None:
            self.lr_scheduler.base_lr = learning_rate
        self.wd = wd
        self.clip_gradient = clip_gradient
        self.begin_num_update = begin_num_update
        self.num_update = begin_num_update
        self._index_update_count = {}
        self.idx2name = dict(param_idx2name or {})
        self.lr_mult = {}
        self.wd_mult = {}
        self.sym = sym
        if sym is not None:
            attrs = sym.attr_dict()
            for name in sym.list_arguments():
                a = attrs.get(name, {})
                if "__lr_mult__" in a:
                    self.lr_mult[name] = float(a["__lr_mult__"])
                if "__wd_mult__" in a:
                    self.wd_mult[name] = float(a["__wd_mult__"])
        # jit is lazy (attributes are read at first trace), so building
        # here works even though subclass __init__ sets its knobs after
        # this returns
        self._build_steps()

    # -- pickling ----------------------------------------------------------
    # Optimizers are pickled to dist-kvstore servers (reference
    # kvstore.py:231-256) and into checkpoint states; jitted step
    # kernels are not picklable, so they are dropped and rebuilt.
    def _build_steps(self):
        """Recreate the jitted per-param update kernel around
        :meth:`step_param`; optimizers with a custom update (SGLD's RNG
        operand) override."""
        if not self.supports_step_tree:
            self._step = None
            return

        def kernel(w, g, state, lr, wd, t):
            # dispatch through self at trace time so attribute values
            # (momentum, betas, clip) are read when the kernel compiles
            return self.step_param(w, g, state, lr, wd, t)

        self._step = jax.jit(kernel, donate_argnums=_donate(0, 2))

    def __getstate__(self):
        # jitted kernels and the cached telemetry child (it holds a
        # threading.Lock) are process-local; dropped and rebuilt
        return {k: v for k, v in self.__dict__.items()
                if not k.startswith("_step") and k != "_tel_dispatch"}

    def __setstate__(self, state):
        self.__dict__.update(state)
        self._build_steps()

    # -- state -------------------------------------------------------------
    def create_state(self, index, weight):
        return None

    # -- functional update surface ----------------------------------------
    # ``step_param`` is THE update rule: a pure, traceable function over
    # raw jax arrays.  The per-param ``update`` below jits it with
    # donated weight/state buffers; the fused whole-pytree train step
    # (module/fused_step.py) traces it through ``step_tree`` inside one
    # donated XLA program — numerics are shared by construction.
    #
    #   w, g           weight / gradient arrays
    #   state          raw state leaves (None / array / tuple of arrays,
    #                  matching ``create_state``'s structure)
    #   lr, wd, t      per-param learning rate / weight decay and the
    #                  update count, passed as traced scalars so a
    #                  schedule change never recompiles
    step_param = None  # overridden by every fusable optimizer

    @property
    def supports_step_tree(self):
        """Whether this optimizer exposes the pure functional update the
        fused train step requires."""
        return callable(getattr(self, "step_param", None))

    def step_tree(self, params, grads, states, lr_tree, wd_tree, num_update):
        """Apply :meth:`step_param` across a whole ``name -> array``
        pytree (traceable; the body of the fused train step's optimizer
        stage).  Entries with no gradient pass through unchanged."""
        new_params, new_states = {}, {}
        for name, w in params.items():
            g = grads.get(name)
            if g is None:
                new_params[name] = w
                new_states[name] = states.get(name)
                continue
            new_params[name], new_states[name] = self.step_param(
                w, g, states.get(name), lr_tree[name], wd_tree[name],
                num_update)
        return new_params, new_states

    def update(self, index, weight, grad, state):
        """One per-parameter update through the jitted ``step_param``
        kernel (the reference's engine-scheduled fused update; the
        fallback path when the whole-pytree fused step is ineligible)."""
        if getattr(self, "_step", None) is None:
            raise NotImplementedError(
                f"{type(self).__name__} defines neither step_param nor a "
                "custom update")
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        t = self._index_update_count[index]
        _dispatch_inc(self, "per_param_update")
        w, new_state = self._step(weight._data, grad._data,
                                  _state_leaves(state), jnp.float32(lr),
                                  jnp.float32(wd), jnp.int32(t))
        weight._set(w)
        _state_commit(state, new_state)

    # -- multipliers / schedules (optimizer.py:120-233) ---------------------
    def set_lr_mult(self, args_lr_mult):
        self.lr_mult = dict(args_lr_mult)

    def set_wd_mult(self, args_wd_mult):
        self.wd_mult = dict(args_wd_mult)

    def _update_count(self, index):
        if index not in self._index_update_count:
            self._index_update_count[index] = self.begin_num_update
        self._index_update_count[index] += 1
        self.num_update = max(self._index_update_count[index], self.num_update)

    def _get_lr(self, index):
        lr = self.lr_scheduler(self.num_update) if self.lr_scheduler else self.lr
        name = self.idx2name.get(index, index)
        if name in self.lr_mult:
            lr *= self.lr_mult[name]
        return lr

    def _get_wd(self, index):
        wd = self.wd
        name = self.idx2name.get(index, index)
        # bias / gamma / beta default to wd_mult 0 in reference Module flows
        if name in self.wd_mult:
            wd *= self.wd_mult[name]
        elif isinstance(name, str) and name.endswith(("_bias", "_gamma", "_beta")):
            wd *= 0.0
        return wd

    def _preprocess(self, grad):
        g = grad * self.rescale_grad
        if self.clip_gradient is not None:
            g = jnp.clip(g, -self.clip_gradient, self.clip_gradient)
        return g

    @staticmethod
    def create_optimizer(name, rescale_grad=1.0, **kwargs):
        return OPT_REGISTRY.get(name)(rescale_grad=rescale_grad, **kwargs)

    @staticmethod
    def register(klass):
        """Register an optimizer class under its lowercased name
        (reference optimizer.py:17-28; usable as a decorator).  Like
        the reference, an existing name is OVERRIDDEN with a warning —
        users replace built-ins this way."""
        import warnings

        name = klass.__name__.lower()
        prev = OPT_REGISTRY._entries.get(name)
        if prev is not None and prev is not klass:
            warnings.warn(
                f"New optimizer {klass.__module__}.{klass.__name__} is "
                f"overriding existing optimizer {prev.__module__}."
                f"{prev.__name__}")
            OPT_REGISTRY._entries[name] = klass
        else:
            OPT_REGISTRY.register(name)(klass)
        return klass

    def set_lr_scale(self, args_lrscale):
        """Deprecated since the reference itself (optimizer.py:126-128);
        use ``set_lr_mult``."""
        raise DeprecationWarning("set_lr_scale is deprecated; use "
                                 "set_lr_mult")


create = Optimizer.create_optimizer


@register("sgd")
class SGD(Optimizer):
    """SGD with momentum / weight decay / grad clipping (optimizer.py:234)."""

    def __init__(self, momentum=0.0, **kwargs):
        self.momentum = momentum
        super().__init__(**kwargs)

    def step_param(self, w, g, m, lr, wd, t):
        g = self._preprocess(g) + wd * w
        if m is None:
            return (w - lr * g).astype(w.dtype), None
        m_new = self.momentum * m - lr * g
        return (w + m_new).astype(w.dtype), m_new.astype(m.dtype)

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return zeros(weight.shape, weight.context, dtype=weight.dtype)


@register("ccsgd")
class ccSGD(SGD):
    """Alias of SGD: the reference's C++-backed fused update
    (optimizer.py:426, src/optimizer/sgd.cc) — here every optimizer is
    already a fused on-device program."""


@register("nag")
class NAG(Optimizer):
    """Nesterov accelerated gradient (optimizer.py:313)."""

    def __init__(self, momentum=0.0, **kwargs):
        self.momentum = momentum
        super().__init__(**kwargs)

    def step_param(self, w, g, m, lr, wd, t):
        g = self._preprocess(g) + wd * w
        m_new = self.momentum * m + g
        g_eff = g + self.momentum * m_new
        return (w - lr * g_eff).astype(w.dtype), m_new.astype(m.dtype)

    def create_state(self, index, weight):
        return zeros(weight.shape, weight.context, dtype=weight.dtype)


@register("sgld")
class SGLD(Optimizer):
    """Stochastic Gradient Langevin Dynamics (optimizer.py:361).

    Keeps a custom ``update`` (the noise draw needs an RNG key operand);
    no ``step_param``, so the fused train step falls back to the
    per-param loop for it."""

    def _build_steps(self):
        def step(w, g, lr, wd, key):
            g = self._preprocess(g) + wd * w
            noise = jax.random.normal(key, w.shape, jnp.float32) * jnp.sqrt(lr)
            return (w - 0.5 * lr * g + noise.astype(w.dtype)).astype(w.dtype)

        self._step = jax.jit(step, donate_argnums=_donate(0))

    def update(self, index, weight, grad, state):
        from . import random as _random

        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        weight._set(self._step(weight._data, grad._data, jnp.float32(lr),
                               jnp.float32(wd), _random.next_key()))


@register("adam")
class Adam(Optimizer):
    """Adam (optimizer.py:504) with the reference's bias-corrected lr.

    The bias correction is computed inside the traced kernel from the
    update count ``t`` (a traced scalar), so neither the per-param nor
    the fused path recompiles as ``t`` advances."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, **kwargs):
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon
        super().__init__(learning_rate=learning_rate, **kwargs)

    def _bias_corrected_lr(self, lr, t):
        tf = jnp.asarray(t, jnp.float32)
        coef1 = 1.0 - jnp.power(jnp.float32(self.beta1), tf)
        coef2 = 1.0 - jnp.power(jnp.float32(self.beta2), tf)
        return lr * jnp.sqrt(coef2) / coef1

    def step_param(self, w, g, mv, lr, wd, t):
        m, v = mv
        g = self._preprocess(g) + wd * w
        m_new = self.beta1 * m + (1 - self.beta1) * g
        v_new = self.beta2 * v + (1 - self.beta2) * jnp.square(g)
        lr_t = self._bias_corrected_lr(lr, t)
        w_new = w - lr_t * m_new / (jnp.sqrt(v_new) + self.epsilon)
        return w_new.astype(w.dtype), (m_new.astype(m.dtype),
                                       v_new.astype(v.dtype))

    def create_state(self, index, weight):
        return (zeros(weight.shape, weight.context, dtype=weight.dtype),
                zeros(weight.shape, weight.context, dtype=weight.dtype))


@register("adagrad")
class AdaGrad(Optimizer):
    """AdaGrad (optimizer.py:605)."""

    def __init__(self, eps=1e-7, **kwargs):
        self.float_stable_eps = eps
        super().__init__(**kwargs)

    def step_param(self, w, g, h, lr, wd, t):
        g = self._preprocess(g)
        h_new = h + jnp.square(g)
        w_new = w - lr * (g / jnp.sqrt(h_new + self.float_stable_eps) + wd * w)
        return w_new.astype(w.dtype), h_new.astype(h.dtype)

    def create_state(self, index, weight):
        return zeros(weight.shape, weight.context, dtype=weight.dtype)


@register("rmsprop")
class RMSProp(Optimizer):
    """RMSProp, Tieleman & Hinton variant with momentum-of-gradient terms
    (optimizer.py:654: gamma1, gamma2)."""

    def __init__(self, learning_rate=0.002, gamma1=0.95, gamma2=0.9,
                 epsilon=1e-4, **kwargs):
        self.gamma1, self.gamma2, self.epsilon = gamma1, gamma2, epsilon
        super().__init__(learning_rate=learning_rate, **kwargs)

    def step_param(self, w, g, state, lr, wd, t):
        n, gavg, delta = state
        g = self._preprocess(g) + wd * w
        n_new = (1 - self.gamma1) * jnp.square(g) + self.gamma1 * n
        gavg_new = (1 - self.gamma1) * g + self.gamma1 * gavg
        denom = jnp.sqrt(n_new - jnp.square(gavg_new) + self.epsilon)
        delta_new = self.gamma2 * delta - lr * g / denom
        return ((w + delta_new).astype(w.dtype),
                (n_new.astype(n.dtype), gavg_new.astype(gavg.dtype),
                 delta_new.astype(delta.dtype)))

    def create_state(self, index, weight):
        z = lambda: zeros(weight.shape, weight.context, dtype=weight.dtype)
        return (z(), z(), z())


@register("adadelta")
class AdaDelta(Optimizer):
    """AdaDelta (optimizer.py:730)."""

    def __init__(self, rho=0.90, epsilon=1e-5, **kwargs):
        self.rho, self.epsilon = rho, epsilon
        super().__init__(**kwargs)

    def step_param(self, w, g, state, lr, wd, t):
        acc_g, acc_delta = state
        g = self._preprocess(g)
        acc_g_new = self.rho * acc_g + (1 - self.rho) * jnp.square(g)
        delta = (jnp.sqrt(acc_delta + self.epsilon)
                 / jnp.sqrt(acc_g_new + self.epsilon)) * g
        acc_delta_new = self.rho * acc_delta + (1 - self.rho) * jnp.square(delta)
        w_new = w - delta - wd * w
        return w_new.astype(w.dtype), (acc_g_new.astype(acc_g.dtype),
                                       acc_delta_new.astype(acc_delta.dtype))

    def create_state(self, index, weight):
        return (zeros(weight.shape, weight.context, dtype=weight.dtype),
                zeros(weight.shape, weight.context, dtype=weight.dtype))


@register("test")
class Test(Optimizer):
    """Trivial optimizer for unit tests (optimizer.py:784)."""

    def create_state(self, index, weight):
        return zeros(weight.shape, weight.context, dtype=weight.dtype)

    def update(self, index, weight, grad, state):
        weight._set(weight._data + grad._data * self.rescale_grad)
        state._set(weight._data)


def get_updater(optimizer: Optimizer):
    """Closure over per-index states (reference optimizer.py:803);
    this is the object pickled to dist-kvstore servers."""
    states = {}

    def updater(index, grad, weight):
        if index not in states:
            states[index] = optimizer.create_state(index, weight)
        optimizer.update(index, weight, grad, states[index])

    updater.states = states
    updater.optimizer = optimizer
    return updater


@register("adamw")
class AdamW(Adam):
    """Adam with decoupled weight decay (Loshchilov & Hutter; beyond
    the 2016 reference — the standard transformer-training optimizer).

    ``wd`` is applied directly to the weights, scaled by the schedule
    lr, instead of being folded into the gradient."""

    def step_param(self, w, g, mv, lr, wd, t):
        m, v = mv
        g = self._preprocess(g)
        m_new = self.beta1 * m + (1 - self.beta1) * g
        v_new = self.beta2 * v + (1 - self.beta2) * jnp.square(g)
        lr_t = self._bias_corrected_lr(lr, t)
        # decoupled decay: the weight shrinks by the schedule-lr-scaled
        # wd, independent of the moments
        w_new = (w * (1.0 - lr * wd)
                 - lr_t * m_new / (jnp.sqrt(v_new) + self.epsilon))
        return w_new.astype(w.dtype), (m_new.astype(m.dtype),
                                       v_new.astype(v.dtype))


@register("lars")
class LARS(SGD):
    """Layer-wise Adaptive Rate Scaling (You et al. 2017; beyond the
    2016 reference — the standard large-batch ResNet optimizer on TPU
    pods).  SGD+momentum whose per-layer lr is scaled by the trust
    ratio ``eta * ||w|| / (||g|| + wd * ||w||)``.  The adaptation is
    applied only to matrix/conv weights (ndim > 1); biases and norm
    scales update as plain SGD — the standard exclusion that keeps
    BatchNorm/bias updates from being crushed by their tiny norms."""

    def __init__(self, *, trust_coefficient=0.001, epsilon=1e-9, **kwargs):
        # keyword-only: LARS(0.9) must not silently set a 900x trust
        # coefficient when SGD's first positional is momentum
        self.trust_coefficient = trust_coefficient
        self.epsilon = epsilon
        super().__init__(**kwargs)

    def step_param(self, w, g, m, lr, wd, t):
        if w.ndim <= 1:
            # bias/gamma/beta: plain SGD(+momentum) step, state kept
            return SGD.step_param(self, w, g, m, lr, wd, t)
        eta, eps = self.trust_coefficient, self.epsilon
        g = self._preprocess(g)
        wf = w.astype(jnp.float32)
        gf = g.astype(jnp.float32)
        w_norm = jnp.sqrt(jnp.sum(jnp.square(wf)))
        g_norm = jnp.sqrt(jnp.sum(jnp.square(gf)))
        ratio = jnp.where(
            (w_norm > 0) & (g_norm > 0),
            eta * w_norm / (g_norm + wd * w_norm + eps), 1.0)
        gf = gf + wd * wf
        m_new = self.momentum * m + lr * ratio * gf
        return (wf - m_new).astype(w.dtype), m_new.astype(m.dtype)

    def create_state(self, index, weight):
        # momentum buffer always exists (the trust-ratio step needs it)
        return zeros(weight.shape, weight.context, dtype=weight.dtype)


@register("lamb")
class LAMB(Adam):
    """Layer-wise Adaptive Moments (You et al. 2019; beyond the 2016
    reference — the large-batch BERT/transformer optimizer).  Adam
    moments; the final update direction ``r = m̂/(sqrt(v̂)+eps) + wd*w``
    is rescaled per layer by ``||w|| / ||r||`` (matrix weights only)."""

    def __init__(self, *, epsilon=1e-6, **kwargs):
        # paper default 1e-6 — also keeps this surface numerically
        # identical to the functional lamb_opt in parallel/trainer.py.
        # Keyword-only: a positional first arg must not silently land
        # in epsilon when Adam's first positional is learning_rate.
        super().__init__(epsilon=epsilon, **kwargs)

    def step_param(self, w, g, mv, lr, wd, t):
        m, v = mv
        tf = jnp.asarray(t, jnp.float32)
        coef1 = 1.0 - jnp.power(jnp.float32(self.beta1), tf)
        coef2 = 1.0 - jnp.power(jnp.float32(self.beta2), tf)
        g = self._preprocess(g)
        m_new = self.beta1 * m + (1 - self.beta1) * g
        v_new = self.beta2 * v + (1 - self.beta2) * jnp.square(g)
        m_hat = m_new / coef1
        v_hat = v_new / coef2
        wf = w.astype(jnp.float32)
        r = m_hat / (jnp.sqrt(v_hat) + self.epsilon) + wd * wf
        w_norm = jnp.sqrt(jnp.sum(jnp.square(wf)))
        r_norm = jnp.sqrt(jnp.sum(jnp.square(r)))
        ratio = jnp.where((w_norm > 0) & (r_norm > 0),
                          w_norm / r_norm, 1.0)
        if w.ndim <= 1:
            ratio = 1.0  # bias/norm params: no layer adaptation
        w_new = wf - lr * ratio * r
        return w_new.astype(w.dtype), (m_new.astype(m.dtype),
                                       v_new.astype(v.dtype))
