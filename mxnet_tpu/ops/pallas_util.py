"""Shared helpers for the Pallas TPU kernels."""

from __future__ import annotations

import functools

import jax.numpy as jnp

__all__ = ["idx32"]


def idx32(fn):
    """Wrap a BlockSpec index map so every returned index is int32.

    The package enables ``jax_enable_x64`` for float64 parity with the
    reference's mshadow type switch, and under x64 a Python int literal
    in an index map traces as a weak int64 constant.  Mosaic cannot
    legalize an i64 ``func.return`` (grid indices stay i32, so mixed
    tuples fail too) and TPU compilation of the kernel dies with
    "failed to legalize operation 'func.return'".  Casting every
    component restores the x64-independent contract.
    """
    @functools.wraps(fn)
    def wrapped(*g):
        return tuple(jnp.asarray(v, jnp.int32) for v in fn(*g))
    return wrapped
