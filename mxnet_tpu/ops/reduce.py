"""Reduction operators (src/operator/broadcast_reduce_op.cc rebuild)."""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..param import Params, field, tuple_of
from .op import register_simple_op


class ReduceAxisParam(Params):
    axis = field(tuple_of(int), default=None, doc="axes to reduce; None = all")
    keepdims = field(bool, default=False)


def _reduce_shape(params, in_shapes):
    shp = in_shapes[0]
    if shp is None:
        raise ValueError("reduce: input shape unknown")
    axis = params.axis
    if axis is None:
        out = (1,) if params.keepdims else ()
        return in_shapes, out if out else (1,)
    axis = tuple(a % len(shp) for a in axis)
    if params.keepdims:
        out = tuple(1 if i in axis else d for i, d in enumerate(shp))
    else:
        out = tuple(d for i, d in enumerate(shp) if i not in axis)
        out = out if out else (1,)
    return in_shapes, out


def _make_reduce(name, jfn, aliases=()):
    def fn(p, x):
        out = jfn(x, axis=p.axis, keepdims=p.keepdims)
        if out.ndim == 0:
            out = out.reshape(1)
        return out

    register_simple_op(name, fn, nin=1, param_cls=ReduceAxisParam,
                       shape_rule=_reduce_shape, aliases=aliases)


_make_reduce("sum", jnp.sum, aliases=("sum_axis",))
_make_reduce("max", jnp.max, aliases=("max_axis",))
_make_reduce("min", jnp.min, aliases=("min_axis",))
_make_reduce("mean", jnp.mean)
_make_reduce("prod", jnp.prod)


def _norm_fn(x):
    return jnp.sqrt(jnp.sum(jnp.square(x))).reshape(1)


register_simple_op("norm", _norm_fn, nin=1,
                   shape_rule=lambda p, s: (s, (1,)))


class ArgmaxParam(Params):
    axis = field(int, default=None, doc="axis; None reduces all")
    keepdims = field(bool, default=False)


def _arg_shape(params, in_shapes):
    shp = in_shapes[0]
    if params.axis is None:
        return in_shapes, (1,)
    ax = params.axis % len(shp)
    if params.keepdims:
        return in_shapes, tuple(1 if i == ax else d for i, d in enumerate(shp))
    out = tuple(d for i, d in enumerate(shp) if i != ax)
    return in_shapes, out if out else (1,)


def _make_arg(name, jfn):
    def fn(p, x):
        out = jfn(x, axis=p.axis, keepdims=p.keepdims).astype(x.dtype)
        if out.ndim == 0:
            out = out.reshape(1)
        return out

    register_simple_op(name, fn, nin=1, param_cls=ArgmaxParam, shape_rule=_arg_shape)


_make_arg("argmax", jnp.argmax)
_make_arg("argmin", jnp.argmin)


def _argmax_channel(x):
    """argmax over axis 1 (reference argmax_channel, broadcast_reduce_op)."""
    return jnp.argmax(x, axis=1).astype(x.dtype)


register_simple_op("argmax_channel", _argmax_channel, nin=1,
                   shape_rule=lambda p, s: (s, (s[0][0],) + tuple(s[0][2:])))


class BroadcastAxisParam(Params):
    axis = field(tuple_of(int), default=(), doc="axes to broadcast (must be size 1)")
    size = field(tuple_of(int), default=(), doc="target sizes per axis")


def _broadcast_axis_shape(params, in_shapes):
    shp = in_shapes[0]
    if shp is None:
        raise ValueError("broadcast_axis: input shape unknown")
    if len(params.axis) != len(params.size):
        raise ValueError("broadcast_axis: axis and size must have equal length")
    out = list(shp)
    for ax, sz in zip(params.axis, params.size):
        ax = ax % len(out)
        if out[ax] != 1:
            raise ValueError(f"broadcast_axis: axis {ax} has size {out[ax]}, "
                             "can only broadcast size-1 axes")
        out[ax] = sz
    return in_shapes, tuple(out)


def _broadcast_axis(p, x):
    out = list(x.shape)
    for ax, sz in zip(p.axis, p.size):
        out[ax % x.ndim] = sz
    return jnp.broadcast_to(x, tuple(out))


register_simple_op("broadcast_axis", _broadcast_axis, nin=1,
                   param_cls=BroadcastAxisParam, shape_rule=_broadcast_axis_shape)


class BroadcastToParam(Params):
    shape = field(tuple_of(int), required=True,
                  doc="target shape; 0 keeps the input size on that axis")


def _broadcast_to_shape(params, in_shapes):
    shp = in_shapes[0]
    if shp is None:
        raise ValueError("broadcast_to: input shape unknown")
    if len(params.shape) != len(shp):
        raise ValueError("broadcast_to: shape ndim mismatch")
    out = tuple(d if t == 0 else t for d, t in zip(shp, params.shape))
    for d, t in zip(shp, out):
        if d != t and d != 1:
            raise ValueError(f"broadcast_to: cannot broadcast {shp} to {out}")
    return in_shapes, out


def _broadcast_to(p, x):
    out = tuple(d if t == 0 else t for d, t in zip(x.shape, p.shape))
    return jnp.broadcast_to(x, out)


register_simple_op("broadcast_to", _broadcast_to, nin=1,
                   param_cls=BroadcastToParam, shape_rule=_broadcast_to_shape)
