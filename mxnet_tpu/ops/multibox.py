"""SSD multibox operators.

Rebuild of the reference SSD example's native CUDA/C++ operators
(example/ssd/operator/multibox_{prior,target,detection}-inl.h + .cu):
anchor generation, training-target matching and detection decoding/NMS —
all as static-shape vectorized JAX so they fuse into the SSD graph.

Box format: corner (xmin, ymin, xmax, ymax), normalized to [0, 1].
Ground-truth label rows: [class_id, xmin, ymin, xmax, ymax]; class −1
pads invalid rows (reference convention).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..param import Params, field, tuple_of
from .op import OpDef, register_op


def _iou(a, b):
    """IOU matrix between (A, 4) and (B, 4) corner boxes."""
    ix1 = jnp.maximum(a[:, None, 0], b[None, :, 0])
    iy1 = jnp.maximum(a[:, None, 1], b[None, :, 1])
    ix2 = jnp.minimum(a[:, None, 2], b[None, :, 2])
    iy2 = jnp.minimum(a[:, None, 3], b[None, :, 3])
    iw = jnp.maximum(ix2 - ix1, 0)
    ih = jnp.maximum(iy2 - iy1, 0)
    inter = iw * ih
    area_a = jnp.maximum((a[:, 2] - a[:, 0]) * (a[:, 3] - a[:, 1]), 0)
    area_b = jnp.maximum((b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1]), 0)
    union = area_a[:, None] + area_b[None, :] - inter
    return jnp.where(union > 0, inter / union, 0.0)


# -- MultiBoxPrior -----------------------------------------------------------
class MultiBoxPriorParam(Params):
    sizes = field(tuple_of(float), default=(1.0,))
    ratios = field(tuple_of(float), default=(1.0,))
    clip = field(bool, default=False)
    steps = field(tuple_of(float), default=None)
    offsets = field(tuple_of(float), default=(0.5, 0.5))


@register_op("MultiBoxPrior", aliases=("_contrib_MultiBoxPrior",))
class MultiBoxPriorOp(OpDef):
    """Anchor boxes per feature-map cell (multibox_prior-inl.h):
    num_anchors = len(sizes) + len(ratios) - 1."""

    param_cls = MultiBoxPriorParam

    def _num_anchors(self, params):
        return len(params.sizes) + len(params.ratios) - 1

    def infer_shape(self, params, in_shapes):
        d = in_shapes[0]
        n_anchor = self._num_anchors(params)
        return list(in_shapes), [(1, d[2] * d[3] * n_anchor, 4)], []

    def forward(self, params, inputs, aux, train, key):
        H, W = inputs[0].shape[2], inputs[0].shape[3]
        # steps / offsets are (y, x), reference multibox_prior-inl.h order
        step_y = params.steps[0] if params.steps else 1.0 / H
        step_x = params.steps[1] if params.steps else 1.0 / W
        oy, ox = params.offsets
        cy = (jnp.arange(H) + oy) * step_y
        cx = (jnp.arange(W) + ox) * step_x
        # anchor (w, h) list: all sizes with ratio[0], then ratios[1:] with
        # sizes[0] (reference enumeration)
        whs = []
        r0 = np.sqrt(params.ratios[0])
        for s in params.sizes:
            whs.append((s * r0, s / r0))
        for r in params.ratios[1:]:
            sr = np.sqrt(r)
            whs.append((params.sizes[0] * sr, params.sizes[0] / sr))
        whs = jnp.asarray(whs)  # (A, 2)
        gy, gx = jnp.meshgrid(cy, cx, indexing="ij")  # (H, W)
        centers = jnp.stack([gx, gy], axis=-1).reshape(-1, 1, 2)  # (HW,1,2)
        half = whs.reshape(1, -1, 2) / 2.0
        boxes = jnp.concatenate([centers - half, centers + half], axis=-1)
        boxes = boxes.reshape(1, -1, 4)
        if params.clip:
            boxes = jnp.clip(boxes, 0.0, 1.0)
        return [boxes.astype(inputs[0].dtype)], []


# -- MultiBoxTarget ----------------------------------------------------------
class MultiBoxTargetParam(Params):
    overlap_threshold = field(float, default=0.5)
    ignore_label = field(float, default=-1.0)
    negative_mining_ratio = field(float, default=-1.0)
    negative_mining_thresh = field(float, default=0.5)
    minimum_negative_samples = field(int, default=0)
    variances = field(tuple_of(float), default=(0.1, 0.1, 0.2, 0.2))


@register_op("MultiBoxTarget", aliases=("_contrib_MultiBoxTarget",))
class MultiBoxTargetOp(OpDef):
    """Match anchors to ground truth, emit regression targets + masks +
    classification targets (multibox_target-inl.h).

    inputs: anchors (1, A, 4), labels (N, M, 5), cls_preds (N, cls+1, A)
    outputs: loc_target (N, A*4), loc_mask (N, A*4), cls_target (N, A)
    """

    param_cls = MultiBoxTargetParam
    is_loss = True  # matching is not differentiated

    def list_arguments(self, params):
        return ["anchor", "label", "cls_pred"]

    def list_outputs(self, params):
        return ["loc_target", "loc_mask", "cls_target"]

    def infer_shape(self, params, in_shapes):
        anchor, label, cls_pred = in_shapes
        A = anchor[1]
        N = label[0]
        return list(in_shapes), [(N, A * 4), (N, A * 4), (N, A)], []

    def infer_dtype(self, params, in_dtypes):
        dt = in_dtypes[0] or np.dtype(np.float32)
        return [dt] * 3, [dt] * 3, []

    def forward(self, params, inputs, aux, train, key):
        anchors = inputs[0][0]  # (A, 4)
        labels = inputs[1]  # (N, M, 5)
        cls_preds = inputs[2]  # (N, cls+1, A)
        # pin to the input dtype: a bare asarray of the python-float
        # tuple becomes f64 under the package's x64 default and leaks
        # into the outputs (infer_dtype promises the input dtype)
        variances = jnp.asarray(params.variances, dtype=anchors.dtype)
        A = anchors.shape[0]

        def encode(anchor, gt):
            aw = anchor[:, 2] - anchor[:, 0]
            ah = anchor[:, 3] - anchor[:, 1]
            acx = (anchor[:, 0] + anchor[:, 2]) / 2
            acy = (anchor[:, 1] + anchor[:, 3]) / 2
            gw = jnp.maximum(gt[:, 2] - gt[:, 0], 1e-8)
            gh = jnp.maximum(gt[:, 3] - gt[:, 1], 1e-8)
            gcx = (gt[:, 0] + gt[:, 2]) / 2
            gcy = (gt[:, 1] + gt[:, 3]) / 2
            tx = (gcx - acx) / jnp.maximum(aw, 1e-8) / variances[0]
            ty = (gcy - acy) / jnp.maximum(ah, 1e-8) / variances[1]
            tw = jnp.log(gw / jnp.maximum(aw, 1e-8)) / variances[2]
            th = jnp.log(gh / jnp.maximum(ah, 1e-8)) / variances[3]
            return jnp.stack([tx, ty, tw, th], axis=-1)

        def one_sample(label, cls_pred):
            valid = label[:, 0] >= 0  # (M,)
            gt_boxes = label[:, 1:5]
            iou = _iou(anchors, gt_boxes)  # (A, M)
            iou = jnp.where(valid[None, :], iou, -1.0)
            best_gt = jnp.argmax(iou, axis=1)  # (A,)
            best_iou = jnp.max(iou, axis=1)
            assigned = best_iou >= params.overlap_threshold
            # bipartite: each valid gt claims its best anchor; padding rows
            # (class -1) are routed to a sentinel index and dropped so they
            # can't clobber a real gt's claim
            best_anchor = jnp.argmax(iou, axis=0)  # (M,)
            best_anchor = jnp.where(valid, best_anchor, A)
            claim = jnp.zeros((A,), bool).at[best_anchor].set(
                True, mode="drop")
            claimed_gt = jnp.zeros((A,), jnp.int32).at[best_anchor].set(
                jnp.arange(label.shape[0], dtype=jnp.int32), mode="drop")
            gt_idx = jnp.where(claim, claimed_gt, best_gt)
            pos = assigned | claim
            matched = gt_boxes[gt_idx]  # (A, 4)
            loc_t = encode(anchors, matched)
            loc_t = jnp.where(pos[:, None], loc_t, 0.0).reshape(-1)
            loc_m = jnp.repeat(pos, 4).astype(loc_t.dtype)
            cls_t = jnp.where(pos, label[gt_idx, 0] + 1, 0.0)  # 0 = background
            if params.negative_mining_ratio > 0:
                # hard negatives: highest background loss (= max non-bg
                # score) first, keep ratio * num_pos
                neg_score = jnp.max(cls_pred[1:], axis=0) - cls_pred[0]
                neg_score = jnp.where(pos, -jnp.inf, neg_score)
                num_pos = jnp.sum(pos)
                num_neg = jnp.maximum(
                    (params.negative_mining_ratio * num_pos).astype(jnp.int32),
                    params.minimum_negative_samples)
                order = jnp.argsort(-neg_score)
                rank = jnp.zeros((A,), jnp.int32).at[order].set(
                    jnp.arange(A, dtype=jnp.int32))
                keep_neg = (~pos) & (rank < num_neg)
                cls_t = jnp.where(pos | keep_neg, cls_t, params.ignore_label)
            return loc_t, loc_m, cls_t

        loc_t, loc_m, cls_t = jax.vmap(one_sample)(labels, cls_preds)
        return [lax.stop_gradient(loc_t), lax.stop_gradient(loc_m),
                lax.stop_gradient(cls_t)], []

    def backward(self, params, out_grads, inputs, outputs):
        return [jnp.zeros_like(x) for x in inputs]


# -- MultiBoxDetection -------------------------------------------------------
class MultiBoxDetectionParam(Params):
    clip = field(bool, default=True)
    threshold = field(float, default=0.01)
    background_id = field(int, default=0)
    nms_threshold = field(float, default=0.5)
    force_suppress = field(bool, default=False)
    variances = field(tuple_of(float), default=(0.1, 0.1, 0.2, 0.2))
    nms_topk = field(int, default=-1)


@register_op("MultiBoxDetection", aliases=("_contrib_MultiBoxDetection",))
class MultiBoxDetectionOp(OpDef):
    """Decode predictions + per-class NMS (multibox_detection-inl.h).

    inputs: cls_prob (N, cls+1, A), loc_pred (N, A*4), anchors (1, A, 4)
    output: (N, A, 6) rows [class_id, score, x1, y1, x2, y2]; class −1
    marks suppressed/invalid entries.
    """

    param_cls = MultiBoxDetectionParam
    is_loss = True

    def list_arguments(self, params):
        return ["cls_prob", "loc_pred", "anchor"]

    def infer_shape(self, params, in_shapes):
        cls_prob = in_shapes[0]
        A = in_shapes[2][1]
        return list(in_shapes), [(cls_prob[0], A, 6)], []

    def forward(self, params, inputs, aux, train, key):
        cls_prob, loc_pred, anchors = inputs
        anchors = anchors[0]
        variances = jnp.asarray(params.variances, dtype=anchors.dtype)
        N = cls_prob.shape[0]
        A = anchors.shape[0]

        aw = anchors[:, 2] - anchors[:, 0]
        ah = anchors[:, 3] - anchors[:, 1]
        acx = (anchors[:, 0] + anchors[:, 2]) / 2
        acy = (anchors[:, 1] + anchors[:, 3]) / 2

        def one(probs, locs):
            t = locs.reshape(A, 4)
            cx = t[:, 0] * variances[0] * aw + acx
            cy = t[:, 1] * variances[1] * ah + acy
            w = jnp.exp(t[:, 2] * variances[2]) * aw
            h = jnp.exp(t[:, 3] * variances[3]) * ah
            boxes = jnp.stack([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2],
                              axis=-1)
            if params.clip:
                boxes = jnp.clip(boxes, 0.0, 1.0)
            # best non-background class per anchor
            fg = jnp.concatenate(
                [probs[:params.background_id], probs[params.background_id + 1:]],
                axis=0)
            # class ids are foreground-relative (reference convention:
            # original class minus the background slot)
            best = jnp.argmax(fg, axis=0)
            cls_id = best.astype(jnp.float32)
            score = jnp.max(fg, axis=0)
            keep = score > params.threshold
            cls_id = jnp.where(keep, cls_id, -1.0)
            score = jnp.where(keep, score, 0.0)
            # NMS: greedy over score order.  Only the top nms_topk survive,
            # so the IoU matrix is topk x topk, not A x A (at SSD300 scale
            # A=8732 the full matrix would be ~300 MB per image).
            order = jnp.argsort(-score)
            boxes_o = boxes[order]
            cls_o = cls_id[order]
            score_o = score[order]
            topk = min(params.nms_topk, A) if params.nms_topk > 0 else A
            boxes_k = boxes_o[:topk]
            cls_k = cls_o[:topk]
            iou = _iou(boxes_k, boxes_k)
            same = (cls_k[:, None] == cls_k[None, :]) | params.force_suppress
            sup_matrix = (iou > params.nms_threshold) & same

            def body(i, alive_k):
                is_alive = alive_k[i] & (cls_k[i] >= 0)
                kill = sup_matrix[i] & (jnp.arange(topk) > i) & is_alive
                return alive_k & ~kill

            alive_k = lax.fori_loop(0, topk, body, jnp.ones((topk,), bool))
            alive = jnp.zeros((A,), bool).at[:topk].set(alive_k)
            alive = alive & (cls_o >= 0)
            cls_f = jnp.where(alive, cls_o, -1.0)
            out = jnp.concatenate([cls_f[:, None], score_o[:, None], boxes_o],
                                  axis=-1)
            return out

        return [lax.stop_gradient(jax.vmap(one)(cls_prob, loc_pred))], []

    def backward(self, params, out_grads, inputs, outputs):
        return [jnp.zeros_like(x) for x in inputs]
