"""Random sampling operators (src/operator/sample_op.cc rebuild).

Samplers consume PRNG keys threaded through the executor / the global
imperative key (mxnet_tpu.random), replacing the reference's per-device
mshadow::Random resource (src/resource.cc:144-176).
"""

from __future__ import annotations

import jax

from ..param import Params, field, tuple_of
from .op import register_simple_op


class UniformParam(Params):
    low = field(float, default=0.0)
    high = field(float, default=1.0)
    shape = field(tuple_of(int), default=None)


class NormalParam(Params):
    loc = field(float, default=0.0)
    scale = field(float, default=1.0)
    shape = field(tuple_of(int), default=None)


def _sample_shape(p, in_shapes):
    if p.shape is None:
        raise ValueError("sample op: shape required")
    return in_shapes, tuple(p.shape)


def _uniform(p, key=None):
    return jax.random.uniform(key, p.shape, minval=p.low, maxval=p.high)


def _normal(p, key=None):
    return p.loc + p.scale * jax.random.normal(key, p.shape)


register_simple_op("_sample_uniform", _uniform, nin=0, param_cls=UniformParam,
                   shape_rule=_sample_shape, need_rng=True,
                   aliases=("uniform", "_random_uniform"))
register_simple_op("_sample_normal", _normal, nin=0, param_cls=NormalParam,
                   shape_rule=_sample_shape, need_rng=True,
                   aliases=("normal", "_random_normal"))
